"""Synthetic latency firehose: on-device sample generation -> dense
aggregation -> per-interval export replay (BASELINE.json configs[4]:
"1B-sample/sec synthetic latency firehose -> OpenTSDB submitter replay").

Host->device transfer cannot carry 1B samples/s, so the firehose
generates samples *on device* inside the jitted step (Zipf-skewed metric
ids via inverse-CDF searchsorted, lognormal latencies), fuses generation
with compress+scatter-add, and only the per-interval statistics leave the
device.  Each interval's ProcessedMetricSet is serialized with the
OpenTSDB protocol and either written to a sink address or summarized to
stdout.

CLI: python -m loghisto_tpu.firehose --metrics 10000 --seconds 5
     [--sink host:port] [--batch 4194304]
"""

from __future__ import annotations

import datetime as _dt
import functools
import sys
import time
from typing import Optional

import numpy as np

from loghisto_tpu.config import DEFAULT_PERCENTILES, MetricConfig
from loghisto_tpu.metrics import ProcessedMetricSet
from loghisto_tpu.opentsdb import opentsdb_protocol


def zipf_cdf(num_metrics: int, s: float = 1.3) -> np.ndarray:
    weights = 1.0 / np.arange(1, num_metrics + 1, dtype=np.float64) ** s
    cdf = np.cumsum(weights)
    return (cdf / cdf[-1]).astype(np.float32)


def _make_sample_generator(
    num_metrics: int, mean: float, sigma: float
):
    """Shared synthetic workload: Zipf-skewed metric ids (inverse-CDF
    searchsorted) + lognormal latencies.  Used by both the single-device
    and the mesh firehose steps so the distributions can never diverge."""
    import jax
    import jax.numpy as jnp

    cdf = zipf_cdf(num_metrics)

    def generate(key, n: int):
        k1, k2 = jax.random.split(key)
        u = jax.random.uniform(k1, (n,), dtype=jnp.float32)
        ids = jnp.searchsorted(jnp.asarray(cdf), u).astype(jnp.int32)
        values = jnp.exp(
            mean + sigma * jax.random.normal(k2, (n,), dtype=jnp.float32)
        )
        return ids, values

    return generate


def make_firehose_step(
    num_metrics: int,
    batch: int,
    config: MetricConfig,
    mean: float = 10.0,
    sigma: float = 2.0,
    ingest_path: str = "auto",
):
    """Jitted (acc, key) -> (acc', key'): generate one batch on device and
    accumulate it.  Generation fuses into the ingest program, so HBM
    traffic is accumulator-only.  The accumulation kernel is the
    auto-dispatched one for this configuration (sort-dedup at high metric
    cardinality on TPU — the duplicate-heavy Zipf batches the firehose
    generates are exactly the regime where plain scatter serializes)."""
    import jax

    from loghisto_tpu.ops.dispatch import ingest_step_fn, resolve_ingest_path

    ingest_path = resolve_ingest_path(
        ingest_path, num_metrics, config.num_buckets,
        jax.default_backend(), batch_size=batch,
    )
    accumulate = ingest_step_fn(ingest_path)
    generate = _make_sample_generator(num_metrics, mean, sigma)

    @functools.partial(jax.jit, donate_argnums=0)
    def step(acc, key):
        key, sub = jax.random.split(key)
        ids, values = generate(sub, batch)
        acc = accumulate(
            acc, ids, values, config.bucket_limit, config.precision
        )
        return acc, key

    return step


def make_mesh_firehose_interval_step(
    mesh,
    num_metrics: int,
    batch: int,
    config: MetricConfig,
    mean: float = 10.0,
    sigma: float = 2.0,
    ingest_path: str = "auto",
):
    """Interval-amortized distributed firehose (the firehose twin of
    aggregator.make_interval_distributed_step): each device generates its
    own sample shard (keys split per stream index) and folds it into its
    (stream, metric) partial block with ZERO collectives; the stream-axis
    psum — the BASELINE configs[2] '8-way psum merge' — runs once per
    collect, into the metric-sharded accumulator.

    Returns (ingest, collect, make_partial):
      ingest(partial, key) -> (partial, key)   collective-free batch
      collect(acc, partial) -> (acc, fresh_partial)  one psum/interval
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    from loghisto_tpu.ops.dispatch import ingest_step_fn, resolve_ingest_path
    from loghisto_tpu.ops.ingest import sanitize_ids
    from loghisto_tpu.parallel.mesh import METRIC_AXIS, STREAM_AXIS, shard_map

    n_stream = mesh.shape[STREAM_AXIS]
    n_metric = mesh.shape[METRIC_AXIS]
    if num_metrics % n_metric or batch % n_stream:
        raise ValueError("metrics/batch must divide the mesh axes")
    rows = num_metrics // n_metric
    local_batch = batch // n_stream
    ingest_path = resolve_ingest_path(
        ingest_path, num_metrics, config.num_buckets,
        mesh.devices.flat[0].platform, batch_size=local_batch, mesh=True,
    )
    generate = _make_sample_generator(num_metrics, mean, sigma)

    def local_ingest(partial_local, key):
        si = jax.lax.axis_index(STREAM_AXIS)
        mi = jax.lax.axis_index(METRIC_AXIS)
        ids, values = generate(jax.random.fold_in(key[0], si), local_batch)
        local_ids = sanitize_ids(ids - mi * rows)
        folded = ingest_step_fn(ingest_path)(
            partial_local[0], local_ids, values,
            config.bucket_limit, config.precision,
        )
        return folded[None]

    ingest_inner = shard_map(
        local_ingest, mesh=mesh,
        in_specs=(P(STREAM_AXIS, METRIC_AXIS, None), P()),
        out_specs=P(STREAM_AXIS, METRIC_AXIS, None),
    )

    @functools.partial(jax.jit, donate_argnums=0)
    def ingest(partial, key):
        key, sub = jax.random.split(key)
        return ingest_inner(partial, sub[None]), key

    def local_collect(acc_local, partial_local):
        merged = jax.lax.psum(partial_local[0], STREAM_AXIS)
        return acc_local + merged, jnp.zeros_like(partial_local)

    collect = jax.jit(
        shard_map(
            local_collect, mesh=mesh,
            in_specs=(
                P(METRIC_AXIS, None),
                P(STREAM_AXIS, METRIC_AXIS, None),
            ),
            out_specs=(
                P(METRIC_AXIS, None),
                P(STREAM_AXIS, METRIC_AXIS, None),
            ),
        ),
        donate_argnums=(0, 1),
    )

    def make_partial() -> jnp.ndarray:
        sharding = NamedSharding(mesh, P(STREAM_AXIS, METRIC_AXIS, None))
        return jax.device_put(
            jnp.zeros(
                (n_stream, num_metrics, config.num_buckets),
                dtype=jnp.int32,
            ),
            sharding,
        )

    return ingest, collect, make_partial


def run_firehose(
    num_metrics: int = 10_000,
    batch: int = 1 << 22,
    seconds: float = 5.0,
    interval: float = 1.0,
    sink: Optional[tuple[str, int]] = None,
    config: Optional[MetricConfig] = None,
    mesh=None,
    out=sys.stdout,
    max_inflight: int = 8,
    ingest_path: str = "auto",
    max_interval_samples: Optional[int] = None,
    recorder=None,
) -> dict:
    """Run the firehose; returns a summary dict (samples/s, intervals).
    With `mesh`, generation+aggregation run SPMD with psum merges.
    `max_interval_samples` overrides the int32-exactness early-close
    budget (default 2^31 - batch; see the guard below).  ``recorder``
    (an obs.SpanRecorder) records a span per dispatch step, per
    interval, and per export — the contender knob behind
    benchmarks/obs_overhead.py's < 2%% recorder-cost criterion."""
    import jax
    import jax.numpy as jnp

    from loghisto_tpu.obs.spans import NULL_RECORDER
    from loghisto_tpu.ops.stats import dense_stats

    rec = recorder if recorder is not None else NULL_RECORDER
    config = config or MetricConfig()
    ingest = collect = partial = None
    if mesh is not None:
        # interval-amortized SPMD: per-batch folds are collective-free;
        # the stream-axis psum runs once per interval at collect
        ingest, collect, make_partial = make_mesh_firehose_interval_step(
            mesh, num_metrics, batch, config, ingest_path=ingest_path
        )
    else:
        step = make_firehose_step(
            num_metrics, batch, config, ingest_path=ingest_path
        )
    stats_fn = jax.jit(
        functools.partial(
            dense_stats,
            bucket_limit=config.bucket_limit,
            precision=config.precision,
        )
    )
    labels, ps = zip(*(
        (label, p) for label, p in DEFAULT_PERCENTILES.items()
        if 0.0 <= p <= 1.0
    ))
    ps = np.asarray(ps, dtype=np.float32)

    if mesh is not None:
        from loghisto_tpu.parallel.aggregator import make_sharded_accumulator

        acc = make_sharded_accumulator(mesh, num_metrics, config.num_buckets)
        partial = make_partial()
        key = jax.random.key(0)
        partial, key = ingest(partial, key)  # compile both programs
        acc, partial = collect(acc, partial)
        jax.block_until_ready(acc)
        acc = jnp.zeros_like(acc)  # discard warm-up samples
    else:
        acc = jnp.zeros((num_metrics, config.num_buckets), dtype=jnp.int32)
        key = jax.random.key(0)
        acc, key = step(acc, key)  # compile
        jax.block_until_ready(acc)
        acc = jnp.zeros_like(acc)  # discard warm-up samples from interval 1

    # int32-exactness budget: the dense accumulator (and mesh partials)
    # are int32, and the worst case concentrates every sample of an
    # interval in one cell.  At TPU-scale rates (1e9/s) a >2s interval
    # would cross 2^31 — stop dispatching and close the interval early
    # instead of silently wrapping (TPUAggregator spills to host int64
    # for the same reason; the firehose's synthetic load just closes the
    # interval, which is exact).
    if max_interval_samples is None:
        max_interval_samples = (1 << 31) - batch

    total_samples = 0
    intervals = 0
    t_start = time.perf_counter()
    while time.perf_counter() - t_start < seconds:
        rec.begin_interval()
        t_int_ns = time.perf_counter_ns()
        t_int = time.perf_counter()
        interval_samples = 0
        inflight = 0
        while time.perf_counter() - t_int < interval:
            if interval_samples >= max_interval_samples:
                out.write(
                    "interval closing early: int32 accumulator budget "
                    f"({interval_samples:,} samples)\n"
                )
                break
            step_ns = time.perf_counter_ns()
            if mesh is not None:
                partial, key = ingest(partial, key)
            else:
                acc, key = step(acc, key)
            rec.record("firehose.step", step_ns, time.perf_counter_ns())
            interval_samples += batch
            # bound the async dispatch queue: without this, a dispatcher
            # that runs ahead of the device (or of a slow link) enqueues
            # thousands of steps inside one wall-clock interval and the
            # stats sync below then drains them for minutes — the
            # interval's sample count must reflect work the device kept
            # up with, not a backlog
            inflight += 1
            if inflight >= max_inflight:
                jax.block_until_ready(partial if mesh is not None else acc)
                inflight = 0
        if mesh is not None:
            acc, partial = collect(acc, partial)
        stats = stats_fn(acc, ps)
        counts = np.asarray(stats["counts"])
        pcts = np.asarray(stats["percentiles"])
        sums = np.asarray(stats["sums"])
        acc = jnp.zeros_like(acc)
        intervals += 1
        total_samples += interval_samples

        # serialize the hottest metrics for the export replay
        with rec.span("firehose.export"):
            metrics = {}
            hot = np.argsort(counts)[::-1][:16]
            for mid in hot:
                if counts[mid] == 0:
                    continue
                name = f"firehose_{mid}"
                metrics[f"{name}_count"] = float(counts[mid])
                metrics[f"{name}_sum"] = float(sums[mid])
                for label, value in zip(labels, pcts[mid]):
                    metrics[label % name] = float(value)
            pms = ProcessedMetricSet(
                time=_dt.datetime.now(tz=_dt.timezone.utc), metrics=metrics
            )
            payload = opentsdb_protocol(pms)
            if sink is not None:
                from loghisto_tpu.submitter import send_once

                err = send_once("tcp", sink, payload)
                status = "sent" if err is None else f"error: {err}"
            else:
                status = f"{len(payload)} bytes serialized"
        rec.record("firehose.interval", t_int_ns, time.perf_counter_ns())
        rate = interval_samples / (time.perf_counter() - t_int)
        out.write(
            f"interval {intervals}: {interval_samples:,} samples "
            f"({rate/1e6:.1f}M/s), export {status}\n"
        )
        out.flush()

    elapsed = time.perf_counter() - t_start
    summary = {
        "samples_per_s": total_samples / elapsed,
        "total_samples": total_samples,
        "intervals": intervals,
        "platform": jax.devices()[0].platform,
    }
    out.write(
        f"firehose: {summary['samples_per_s']/1e6:.1f}M samples/s over "
        f"{intervals} intervals on {summary['platform']}\n"
    )
    return summary


def main(argv=None) -> None:
    import argparse

    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--metrics", type=int, default=10_000)
    parser.add_argument("--batch", type=int, default=1 << 22)
    parser.add_argument("--seconds", type=float, default=5.0)
    parser.add_argument("--interval", type=float, default=1.0)
    parser.add_argument("--sink", default=None,
                        help="host:port OpenTSDB sink (optional)")
    parser.add_argument("--mesh", action="store_true",
                        help="run SPMD over all devices (psum merges)")
    parser.add_argument("--mesh-metric", type=int, default=1,
                        help="metric-axis size of the mesh")
    args = parser.parse_args(argv)
    sink = None
    if args.sink:
        host, port = args.sink.rsplit(":", 1)
        sink = (host, int(port))
    mesh = None
    if args.mesh:
        from loghisto_tpu.parallel.mesh import make_mesh

        mesh = make_mesh(metric=args.mesh_metric)
    run_firehose(
        num_metrics=args.metrics, batch=args.batch, seconds=args.seconds,
        interval=args.interval, sink=sink, mesh=mesh,
    )


if __name__ == "__main__":
    main()
