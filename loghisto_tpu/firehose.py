"""Synthetic latency firehose: on-device sample generation -> dense
aggregation -> per-interval export replay (BASELINE.json configs[4]:
"1B-sample/sec synthetic latency firehose -> OpenTSDB submitter replay").

Host->device transfer cannot carry 1B samples/s, so the firehose
generates samples *on device* inside the jitted step (Zipf-skewed metric
ids via inverse-CDF searchsorted, lognormal latencies), fuses generation
with compress+scatter-add, and only the per-interval statistics leave the
device.  Each interval's ProcessedMetricSet is serialized with the
OpenTSDB protocol and either written to a sink address or summarized to
stdout.

CLI: python -m loghisto_tpu.firehose --metrics 10000 --seconds 5
     [--sink host:port] [--batch 4194304]
"""

from __future__ import annotations

import datetime as _dt
import functools
import sys
import time
from typing import Optional

import numpy as np

from loghisto_tpu.config import DEFAULT_PERCENTILES, MetricConfig
from loghisto_tpu.metrics import ProcessedMetricSet
from loghisto_tpu.opentsdb import opentsdb_protocol


def zipf_cdf(num_metrics: int, s: float = 1.3) -> np.ndarray:
    weights = 1.0 / np.arange(1, num_metrics + 1, dtype=np.float64) ** s
    cdf = np.cumsum(weights)
    return (cdf / cdf[-1]).astype(np.float32)


def make_firehose_step(
    num_metrics: int,
    batch: int,
    config: MetricConfig,
    mean: float = 10.0,
    sigma: float = 2.0,
):
    """Jitted (acc, key) -> (acc', key'): generate one batch on device and
    accumulate it.  Generation fuses into the ingest program, so HBM
    traffic is accumulator-only."""
    import jax
    import jax.numpy as jnp

    from loghisto_tpu.ops.ingest import ingest_batch

    cdf = zipf_cdf(num_metrics)

    @functools.partial(jax.jit, donate_argnums=0)
    def step(acc, key):
        key, k1, k2 = jax.random.split(key, 3)
        u = jax.random.uniform(k1, (batch,), dtype=jnp.float32)
        ids = jnp.searchsorted(jnp.asarray(cdf), u).astype(jnp.int32)
        values = jnp.exp(
            mean + sigma * jax.random.normal(k2, (batch,), dtype=jnp.float32)
        )
        acc = ingest_batch(
            acc, ids, values, config.bucket_limit, config.precision
        )
        return acc, key

    return step


def run_firehose(
    num_metrics: int = 10_000,
    batch: int = 1 << 22,
    seconds: float = 5.0,
    interval: float = 1.0,
    sink: Optional[tuple[str, int]] = None,
    config: Optional[MetricConfig] = None,
    out=sys.stdout,
) -> dict:
    """Run the firehose; returns a summary dict (samples/s, intervals)."""
    import jax
    import jax.numpy as jnp

    from loghisto_tpu.ops.stats import dense_stats

    config = config or MetricConfig()
    step = make_firehose_step(num_metrics, batch, config)
    stats_fn = jax.jit(
        functools.partial(
            dense_stats,
            bucket_limit=config.bucket_limit,
            precision=config.precision,
        )
    )
    labels, ps = zip(*(
        (label, p) for label, p in DEFAULT_PERCENTILES.items()
        if 0.0 <= p <= 1.0
    ))
    ps = np.asarray(ps, dtype=np.float32)

    acc = jnp.zeros((num_metrics, config.num_buckets), dtype=jnp.int32)
    key = jax.random.key(0)
    acc, key = step(acc, key)  # compile
    jax.block_until_ready(acc)
    acc = jnp.zeros_like(acc)  # discard warm-up samples from interval 1

    total_samples = 0
    intervals = 0
    t_start = time.perf_counter()
    while time.perf_counter() - t_start < seconds:
        t_int = time.perf_counter()
        interval_samples = 0
        while time.perf_counter() - t_int < interval:
            acc, key = step(acc, key)
            interval_samples += batch
        stats = stats_fn(acc, ps)
        counts = np.asarray(stats["counts"])
        pcts = np.asarray(stats["percentiles"])
        sums = np.asarray(stats["sums"])
        acc = jnp.zeros_like(acc)
        intervals += 1
        total_samples += interval_samples

        # serialize the hottest metrics for the export replay
        metrics = {}
        hot = np.argsort(counts)[::-1][:16]
        for mid in hot:
            if counts[mid] == 0:
                continue
            name = f"firehose_{mid}"
            metrics[f"{name}_count"] = float(counts[mid])
            metrics[f"{name}_sum"] = float(sums[mid])
            for label, value in zip(labels, pcts[mid]):
                metrics[label % name] = float(value)
        pms = ProcessedMetricSet(
            time=_dt.datetime.now(tz=_dt.timezone.utc), metrics=metrics
        )
        payload = opentsdb_protocol(pms)
        if sink is not None:
            from loghisto_tpu.submitter import send_once

            err = send_once("tcp", sink, payload)
            status = "sent" if err is None else f"error: {err}"
        else:
            status = f"{len(payload)} bytes serialized"
        rate = interval_samples / (time.perf_counter() - t_int)
        out.write(
            f"interval {intervals}: {interval_samples:,} samples "
            f"({rate/1e6:.1f}M/s), export {status}\n"
        )
        out.flush()

    elapsed = time.perf_counter() - t_start
    summary = {
        "samples_per_s": total_samples / elapsed,
        "total_samples": total_samples,
        "intervals": intervals,
        "platform": jax.devices()[0].platform,
    }
    out.write(
        f"firehose: {summary['samples_per_s']/1e6:.1f}M samples/s over "
        f"{intervals} intervals on {summary['platform']}\n"
    )
    return summary


def main(argv=None) -> None:
    import argparse

    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--metrics", type=int, default=10_000)
    parser.add_argument("--batch", type=int, default=1 << 22)
    parser.add_argument("--seconds", type=float, default=5.0)
    parser.add_argument("--interval", type=float, default=1.0)
    parser.add_argument("--sink", default=None,
                        help="host:port OpenTSDB sink (optional)")
    args = parser.parse_args(argv)
    sink = None
    if args.sink:
        host, port = args.sink.rsplit(":", 1)
        sink = (host, int(port))
    run_firehose(
        num_metrics=args.metrics, batch=args.batch, seconds=args.seconds,
        interval=args.interval, sink=sink,
    )


if __name__ == "__main__":
    main()
