"""TPUMetricSystem: the fully wired product in one object.

A drop-in MetricSystem whose aggregation also runs on device: it
constructs a TPUAggregator, attaches it behind the subscription boundary
(the north-star architecture — callers keep using counter/histogram/
start_timer unchanged), registers the TPU gauges, and exposes the
device-side statistics.

    ms = TPUMetricSystem(interval=1.0, num_metrics=10_000)
    ms.start()
    ms.histogram("rpc_latency", 1234.5)        # host path, as ever
    ms.record_batch(ids, values)               # firehose path, batched
    pms = ms.device_metrics()                  # percentiles computed on TPU
"""

from __future__ import annotations

from typing import Mapping

import numpy as np

from loghisto_tpu.config import DEFAULT_PERCENTILES, MetricConfig
from loghisto_tpu.metrics import MetricSystem, ProcessedMetricSet
from loghisto_tpu.parallel.aggregator import TPUAggregator


class TPUMetricSystem(MetricSystem):
    def __init__(
        self,
        interval: float = 60.0,
        sys_stats: bool = True,
        config: MetricConfig = MetricConfig(),
        num_metrics: int = 1024,
        percentiles: Mapping[str, float] = DEFAULT_PERCENTILES,
        mesh=None,
        native_staging: bool = False,
        fast_ingest: bool = False,
    ):
        super().__init__(
            interval=interval, sys_stats=sys_stats, config=config,
            fast_ingest=fast_ingest,
        )
        self.aggregator = TPUAggregator(
            num_metrics=num_metrics,
            config=config,
            percentiles=percentiles,
            mesh=mesh,
            native_staging=native_staging,
        )
        self.aggregator.attach(self)
        self.aggregator.register_device_gauges(self)

    def record_batch(self, ids: np.ndarray, values: np.ndarray) -> None:
        """Batched firehose ingestion straight to the device accumulator
        (bypasses the host sparse tier; ids come from metric_id())."""
        self.aggregator.record_batch(ids, values)

    def metric_id(self, name: str) -> int:
        """Dense row id for `name` (registers on first use)."""
        return self.aggregator.registry.id_for(name)

    def device_metrics(self, reset: bool = True) -> ProcessedMetricSet:
        """Device-side statistics for everything aggregated so far."""
        return self.aggregator.collect(reset=reset)

    def start(self) -> None:
        # restartable like the base class: re-attach the device bridge if a
        # previous stop() detached it
        if self.aggregator._attached is None:
            self.aggregator.attach(self)
        super().start()

    def stop(self) -> None:
        self.aggregator.detach()
        super().stop()
