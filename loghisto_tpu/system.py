"""TPUMetricSystem: the fully wired product in one object.

A drop-in MetricSystem whose aggregation also runs on device: it
constructs a TPUAggregator, attaches it behind the subscription boundary
(the north-star architecture — callers keep using counter/histogram/
start_timer unchanged), registers the TPU gauges, and exposes the
device-side statistics.

    ms = TPUMetricSystem(interval=1.0, num_metrics=10_000)
    ms.start()
    ms.histogram("rpc_latency", 1234.5)        # host path, as ever
    ms.record_batch(ids, values)               # firehose path, batched
    pms = ms.device_metrics()                  # percentiles computed on TPU

With ``retention=`` a TimeWheel subscribes alongside the aggregator,
keeping sliding-window history on device and powering the rule engine:

    ms = TPUMetricSystem(interval=1.0, retention=True)
    ms.start()
    ms.query_window("rpc_latency", window=300)          # p99 over 5m
    ms.add_rule(SloBurnRateRule("api_slo", "errors", "requests",
                                objective=0.999, long_window=3600,
                                short_window=300))
    ms.subscribe_to_alerts(ch)                          # Alert events
"""

from __future__ import annotations

from typing import Iterable, Mapping, Optional, Sequence

import numpy as np

from loghisto_tpu.channel import Channel
from loghisto_tpu.config import DEFAULT_PERCENTILES, MetricConfig
from loghisto_tpu.labels import LabelIndex
from loghisto_tpu.metrics import MetricSystem, ProcessedMetricSet, RawMetricSet
from loghisto_tpu.parallel.aggregator import TPUAggregator


class TPUMetricSystem(MetricSystem):
    def __init__(
        self,
        interval: float = 60.0,
        sys_stats: bool = True,
        config: MetricConfig = MetricConfig(),
        num_metrics: int = 1024,
        percentiles: Mapping[str, float] = DEFAULT_PERCENTILES,
        mesh=None,
        native_staging: bool = False,
        fast_ingest: bool = False,
        retention=None,
        commit: str = "auto",
        lifecycle=None,
        anomaly=None,
        transport: str = "auto",
        storage: str = "auto",
        paged_config=None,
        observability=None,
        resilience=None,
        federation=None,
    ):
        """``retention`` turns on the windowed retention tier:
        ``True`` builds a TimeWheel with the default 60x1 / 60x60 /
        24x3600 tiers, a sequence of ``(slots, res)`` pairs builds one
        with those tiers, and a ready ``TimeWheel`` instance is attached
        as-is (it must share this system's registry for consistent row
        ids).  The wheel subscribes behind the same raw boundary as the
        aggregator and shares its registry and mesh.

        ``commit`` picks the interval-commit pipeline when retention is
        on: "fused" runs ONE donated-carry program per interval for the
        aggregator fold plus every tier's open-slot scatter behind a
        single subscription (loghisto_tpu.commit.IntervalCommitter);
        "fanout" keeps the per-consumer bridges; "auto" (default)
        follows the capture-overridable switch in ops/dispatch.py.
        Sharded state (``mesh=``) runs the fused program under
        ``shard_map`` — capability-resolved, degrading to the fan-out
        only when the shape genuinely can't shard.  Without retention the
        aggregator is the only device consumer, so the fan-out IS one
        dispatch already and ``commit`` is moot.

        ``lifecycle`` takes a ``lifecycle.LifecycleConfig`` and turns on
        the metric lifecycle subsystem: per-interval activity tracking
        rides the fused commit (zero extra dispatches), TTL/idle and
        cardinality policies retire churned series into catch-all
        overflow metrics (count-exact), freed device rows are reused and
        periodically compacted, and a ``lifecycle.*`` gauge family
        reports the churn.  Requires retention + the fused commit path
        (the subsystem's clock and activity signal ARE the committed
        intervals).

        ``anomaly`` takes an ``anomaly.AnomalyConfig`` and turns on the
        distribution drift engine: per-metric EWMA baseline bucket
        profiles ride the fused commit (zero extra dispatches), one
        fused divergence dispatch per interval scores every metric's
        live window CDF against its baseline (KS / JSD / bucket-space
        EMD), ``DistributionDriftRule``s alert on the scores through
        the normal rule engine, and ``anomaly.<metric>.{ks,jsd,emd}``
        gauges ride every exporter.  Requires retention + the fused
        commit path, like ``lifecycle``.

        ``transport`` passes through to the TPUAggregator's host->device
        transport selection ("auto" / "raw" / "preagg" / "sparse"; see
        TPUAggregator.__init__).

        ``storage`` picks the accumulator backend ("auto" / "dense" /
        "paged"; PR 14): "paged" replaces the dense ``[M, B]`` device
        tensor with an occupancy-tracked page pool + host page table
        and per-metric variable-resolution codecs — HBM and commit H2D
        cost scale with occupied buckets, not capacity, which is what
        makes 1M live metric rows fit one chip.  "auto" follows
        ``ops.dispatch.resolve_storage_path`` (dense below the
        PAGED_MIN_METRICS crossover, or whenever a mesh / non-sparse
        transport rules paging out; ``aggregator.storage_reason`` says
        why).  ``paged_config`` takes a ``paging.PagedStoreConfig``
        (pool size, codec policy, overflow row).  Paged storage keeps
        no dense carry, so it composes with the fan-out commit, not the
        fused committer — ``commit="auto"`` degrades, explicit
        ``commit="fused"`` raises with the reason.

        ``observability`` takes an ``obs.ObsConfig`` (or ``True`` for
        the defaults) and turns on the self-observability subsystem
        (ISSUE 9): a lock-free span ring records interval-scoped stage
        timings across the whole pipeline, closed spans are re-ingested
        as ``obs.<stage>.LatencyUs`` histograms through the normal
        ingest path, a health watchdog exports ``health.*`` gauges and
        the Prometheus endpoint's ``/healthz`` JSON, and the span ring
        dumps as Perfetto-compatible Chrome trace JSON
        (``obs.dump_perfetto(ms.obs, path)``).  ``debug_dump()`` works
        with or without it.

        ``resilience`` takes a ``resilience.ResilienceConfig`` (or
        ``True`` for the defaults) and turns on the resilience subsystem
        (ISSUE 10): pipeline bridge threads (reaper, committer bridge,
        aggregator bridge, time-wheel bridge) restart with capped
        exponential backoff instead of silently dying; repeated device
        failures trip a circuit breaker that pins the fan-out/spill
        commit path; with ``checkpoint_path``/``journal_path`` set, the
        committer bridge checkpoints every N intervals (stamped with the
        interval seq watermark) and ``recover()`` restores + replays the
        journal past the watermark — at most one interval lost across a
        crash.  A ``fault_injector`` in the config scripts deterministic
        chaos faults through the pipeline's hook sites; left None, every
        hook is a single attribute test.

        ``federation`` takes a ``federation.FederationConfig`` (or
        ``True`` for the defaults) and turns this system into the
        aggregator pod of a federation tier (ISSUE 11): a TCP
        ``FederationReceiver`` listens on ``(host, port)`` (port 0 binds
        an ephemeral one, read back from ``ms.federation.port``) for
        framed packed-triple deltas from ``FederationEmitter``s running
        in other processes, interns their metric names through this
        system's registry, deduplicates frames by per-emitter sequence
        number, and drains the triples into the same staged ingest and
        fused commit local samples take — so the federated aggregate is
        bit-identical to a single process recording everything.  The
        accept/decode threads run supervised when ``resilience`` is on,
        ``federation.*`` gauges ride every exporter, and with
        ``observability`` the health report gains the
        ``emitter_starvation`` / ``fed_decode_errors`` invariants."""
        super().__init__(
            interval=interval, sys_stats=sys_stats, config=config,
            fast_ingest=fast_ingest,
        )

        # -- resilience (ISSUE 10), resolved FIRST so every component
        # below is constructed/attached already wired ------------------- #
        self.resilience = None
        self.fault_injector = None
        self.supervisor = None     # the reaper's start() picks this up
        self.device_breaker = None
        self.recovery = None
        self._recovered = False
        if resilience is not None and resilience is not False:
            from loghisto_tpu.resilience import (
                CircuitBreaker, ResilienceConfig, ThreadSupervisor,
            )

            rcfg = (
                ResilienceConfig() if resilience is True else resilience
            )
            self.resilience = rcfg
            self.fault_injector = rcfg.fault_injector
            if rcfg.supervise:
                self.supervisor = ThreadSupervisor(
                    base_backoff_s=rcfg.restart_backoff_s,
                    max_backoff_s=rcfg.restart_backoff_cap_s,
                )
            self.device_breaker = CircuitBreaker(
                threshold=rcfg.breaker_threshold,
                window_s=rcfg.breaker_window_s,
                open_s=rcfg.breaker_open_s,
            )

        self.aggregator = TPUAggregator(
            num_metrics=num_metrics,
            config=config,
            percentiles=percentiles,
            mesh=mesh,
            native_staging=native_staging,
            transport=transport,
            storage=storage,
            paged_config=paged_config,
        )
        self.aggregator.register_device_gauges(self)
        # label layer (ISSUE 16): one inverted index over the shared
        # registry serves selector queries and the labels.* gauges; the
        # retention wheel (below) routes brace-syntax patterns to it
        self.label_index = LabelIndex(self.aggregator.registry)
        self.label_index.register_gauges(self)
        if self.resilience is not None:
            # before attach: the bridge/xfer threads must spawn supervised
            self.aggregator.supervisor = self.supervisor
            self.aggregator.device_breaker = self.device_breaker
            self.aggregator.fault_injector = self.fault_injector

        self.retention = None
        self.rule_engine = None
        self.committer = None
        if retention is not None and retention is not False:
            from loghisto_tpu.window import (
                DEFAULT_TIERS, RuleEngine, TimeWheel,
            )

            if isinstance(retention, TimeWheel):
                self.retention = retention
            else:
                tiers = (
                    DEFAULT_TIERS if retention is True else retention
                )
                self.retention = TimeWheel(
                    num_metrics=num_metrics,
                    config=config,
                    interval=interval,
                    tiers=tiers,
                    registry=self.aggregator.registry,
                    mesh=mesh,
                )
            self.retention.label_index = self.label_index
            if self.resilience is not None:
                self.retention.supervisor = self.supervisor
                self.retention.fault_injector = self.fault_injector
            self.rule_engine = RuleEngine(self.retention)
            self.rule_engine.attach()
            # query-engine self-metrics (commit.query_* family): snapshot
            # age, plan-cache hits, sparse readback volume
            self.retention.register_query_gauges(self)

        import jax

        from loghisto_tpu.ops.dispatch import (
            mesh_commit_incapability, resolve_commit_path,
        )

        platform = (
            mesh.devices.flat[0].platform
            if mesh is not None
            else jax.default_backend()
        )
        self.commit_path = resolve_commit_path(
            commit, platform, mesh=mesh,
            num_metrics=self.aggregator.num_metrics,
        )
        self.lifecycle = None
        self.anomaly = None
        if lifecycle is not None and self.retention is None:
            raise ValueError(
                "lifecycle needs retention: construct with "
                "TPUMetricSystem(retention=True, lifecycle=...)"
            )
        if anomaly is not None and self.retention is None:
            raise ValueError(
                "the drift engine needs retention: construct with "
                "TPUMetricSystem(retention=True, anomaly=...)"
            )
        if self.commit_path == "fused" and self.retention is not None:
            from loghisto_tpu.commit import (
                IntervalCommitter, commit_incompatibility,
            )

            reason = commit_incompatibility(self.aggregator, self.retention)
            if reason is None:
                if lifecycle is not None:
                    from loghisto_tpu.lifecycle import LifecycleManager

                    self.lifecycle = LifecycleManager(
                        self.aggregator, self.retention, lifecycle,
                        metric_system=self,
                    )
                    self.lifecycle.register_gauges(self)
                if anomaly is not None:
                    from loghisto_tpu.anomaly import AnomalyManager

                    self.anomaly = AnomalyManager(
                        self.aggregator, self.retention, anomaly,
                        metric_system=self,
                    )
                    self.anomaly.register_gauges(self)
                    if self.lifecycle is not None:
                        # evictions zero bank rows, compaction permutes
                        # them — inside the lifecycle's own critical
                        # sections
                        self.lifecycle.anomaly = self.anomaly
                # ONE subscription pays both consumers: neither the
                # aggregator bridge nor the wheel bridge attaches
                self.committer = IntervalCommitter(
                    self.aggregator, self.retention,
                    lifecycle=self.lifecycle,
                    anomaly=self.anomaly,
                )
                if self.resilience is not None:
                    self.committer.supervisor = self.supervisor
                    self.committer.breaker = self.device_breaker
                    self.committer.fault_injector = self.fault_injector
                self.committer.attach(self)
                self.committer.register_gauges(self)
            elif commit == "fused":
                # the user explicitly demanded fused; an incompatible
                # pair must fail loudly, not silently fan out
                raise ValueError(f"fused commit unavailable: {reason}")
            else:
                self.commit_path = "fanout"
        else:
            if self.commit_path == "fused":
                # no retention: the aggregator is the only consumer, so
                # the "fan-out" is already a single dispatch per interval
                self.commit_path = "fanout"
        if self.committer is None:
            # mesh-sharded state takes the fused path too (the sharded
            # shard_map commit); only a genuine fan-out resolution —
            # explicit commit="fanout", the capture switch, or a shape
            # that can't shard — lacks the donated carries
            if lifecycle is not None:
                raise ValueError(
                    "lifecycle rides the fused interval commit; this "
                    f"configuration resolved commit={self.commit_path!r}"
                    " (the fan-out pipeline doesn't carry the activity "
                    "vector)"
                )
            if anomaly is not None:
                raise ValueError(
                    "the drift engine rides the fused interval commit; "
                    "this configuration resolved "
                    f"commit={self.commit_path!r} (the fan-out pipeline "
                    "doesn't carry the baseline banks)"
                )
            self.aggregator.attach(self)
            if self.retention is not None:
                self.retention.attach(self)

        if self.resilience is not None:
            from loghisto_tpu.resilience import (
                RecoveryManager, register_resilience_gauges,
            )

            rcfg = self.resilience
            if (rcfg.checkpoint_path is not None
                    or rcfg.journal_path is not None):
                self.recovery = RecoveryManager(
                    self,
                    aggregator=self.aggregator,
                    committer=self.committer,
                    lifecycle=self.lifecycle,
                    anomaly=self.anomaly,
                    checkpoint_path=rcfg.checkpoint_path,
                    journal_path=rcfg.journal_path,
                    checkpoint_every_intervals=(
                        rcfg.checkpoint_every_intervals
                    ),
                    fault_injector=self.fault_injector,
                )
                if self.committer is not None:
                    # the bridge thread drives the checkpoint cadence
                    self.committer.recovery = self.recovery
                elif self.retention is not None:
                    # fan-out path: the wheel's interval hook is the
                    # per-interval heartbeat instead
                    self.retention.add_interval_hook(
                        lambda raw, _rec=self.recovery: _rec.on_commit(raw)
                    )
            register_resilience_gauges(
                self,
                supervisor=self.supervisor,
                breaker=self.device_breaker,
                recovery=self.recovery,
                injector=self.fault_injector,
            )

        # -- federation tier (ISSUE 11) --------------------------------- #
        self.federation = None
        self.federation_config = None
        if federation is not None and federation is not False:
            from loghisto_tpu.federation import FederationConfig
            from loghisto_tpu.federation.receiver import FederationReceiver

            fcfg = (
                FederationConfig() if federation is True else federation
            )
            self.federation_config = fcfg
            self.federation = FederationReceiver(
                self.aggregator,
                host=fcfg.host,
                port=fcfg.port,
                journal_path=fcfg.journal_path,
                replay_on_start=fcfg.replay_on_start,
                expected_emitters=fcfg.expected_emitters,
                supervisor=self.supervisor,
                fault_injector=self.fault_injector,
            )
            self.federation.register_gauges(self)
            # fleet observability (ISSUE 12): freshness/rollup knobs
            self.federation.starvation_s = (
                fcfg.starvation_intervals * self.interval
            )
            self.federation.skew_tolerance_s = fcfg.skew_tolerance_s
            # record→queryable freshness completes at snapshot publish
            # when a commit path exists; headless/fanout systems fall
            # back to the wheel's interval hook; otherwise samples
            # complete at frame-apply time (has_publisher stays False)
            if self.committer is not None:
                self.committer.freshness_hook = self.federation.note_publish
                self.federation.has_publisher = True
            elif self.retention is not None:
                fed = self.federation
                self.retention.add_interval_hook(
                    lambda raw: fed.note_publish(getattr(raw, "seq", None))
                )
                self.federation.has_publisher = True

        # -- self-observability (ISSUE 9) ------------------------------- #
        self.obs = None            # the SpanRecorder (None when off)
        self.obs_config = None
        self.health = None         # the HealthWatchdog (None when off)
        self.self_observer = None
        self.commit_path_reason = (
            mesh_commit_incapability(
                mesh, num_metrics=self.aggregator.num_metrics
            )
            if mesh is not None and self.commit_path != "fused" else None
        )
        if observability is not None and observability is not False:
            from loghisto_tpu.obs import (
                HealthWatchdog, ObsConfig, SelfObserver, SpanRecorder,
            )

            cfg = ObsConfig() if observability is True else observability
            self.obs_config = cfg
            rec = SpanRecorder(cfg.capacity)
            self.obs = rec
            # ring saturation signal (ISSUE 12): dropped > 0 means the
            # ring wrapped faster than exporters drained it
            self.register_gauge_func(
                "obs.SpansDropped", lambda: float(rec.dropped)
            )
            # hand the ring to every instrumentation site
            self.obs_recorder = rec          # reaper broadcast span
            self.aggregator.obs_recorder = rec
            if self.retention is not None:
                self.retention.obs_recorder = rec
            if self.lifecycle is not None:
                self.lifecycle.obs_recorder = rec
            if self.anomaly is not None:
                self.anomaly.obs_recorder = rec
            if self.federation is not None:
                self.federation.obs_recorder = rec
            if self.committer is not None:
                self.committer.obs_recorder = rec
                if cfg.dogfood:
                    self.self_observer = SelfObserver(self, rec)
                    self.committer.self_observer = self.self_observer
            if cfg.health:
                self.health = HealthWatchdog(
                    self.committer, self.aggregator,
                    interval=self.interval,
                    stall_intervals=cfg.stall_intervals,
                    backpressure_fraction=cfg.backpressure_fraction,
                    commit_path=self.commit_path,
                    commit_path_reason=self.commit_path_reason,
                    wheel=self.retention,
                    supervisor=self.supervisor,
                    breaker=self.device_breaker,
                    recovery=self.recovery,
                    federation=self.federation,
                    federation_starvation_intervals=(
                        self.federation_config.starvation_intervals
                        if self.federation_config is not None else 3.0
                    ),
                    federation_skew_tolerance_s=(
                        self.federation_config.skew_tolerance_s
                        if self.federation_config is not None else 1.0
                    ),
                )
                if self.committer is not None:
                    self.committer.watchdog = self.health
                self.health.register_gauges(self)

    def debug_dump(self) -> dict:
        """One introspection snapshot of the whole pipeline: registry
        occupancy and free-list depth, the resolved commit path (with
        the mesh-incapability reason when it degraded), query/result
        cache hit counters, mesh layout, transfer/staging ring depths,
        span-ring state, and the current health report.  Pure reads —
        safe to call from any thread, any time."""
        agg = self.aggregator
        reg = agg.registry
        dump: dict = {
            "commit_path": self.commit_path,
            "commit_path_reason": self.commit_path_reason,
            "mesh": (
                {str(k): int(v) for k, v in agg.mesh.shape.items()}
                if agg.mesh is not None else None
            ),
            "registry": {
                "capacity": reg.capacity,
                "occupancy": len(reg),
                "free_count": reg.free_count(),
                "generation": reg.generation,
            },
            "rings": {
                "xfer_queued_samples": agg._xfer_queued_samples,
                "pending_samples": agg.pending_samples,
                "max_pending_samples": agg.max_pending_samples,
                "staging_depth": agg.staging_depth,
            },
            "transport": agg.transport_stats(),
        }
        wheel = self.retention
        if wheel is not None:
            dump["query"] = {
                "snapshot_hits": wheel.query_snapshot_hits,
                "fallbacks": wheel.query_fallbacks,
                "result_cache_hits": wheel.query_result_cache_hits,
                "rows_fetched": wheel.query_rows_fetched,
                "group_by_serves": wheel.query_group_serves,
                "plan_cache_hits": wheel.plan_cache.hits,
                "plan_cache_misses": wheel.plan_cache.misses,
                "snapshot_age_intervals": wheel.snapshot_age_intervals(),
            }
        # label layer: inverted-index size, selector-cache hit rate, and
        # live label cardinality per prefix — the operator's view of
        # which subsystem's label space is exploding (pair with the
        # lifecycle label_budgets and resolve_storage_path's crossover:
        # every label set is a registry row under the canonical
        # ``name;k1=v1`` encoding)
        li = self.label_index
        labels_dump = li.stats()
        labels_dump["cardinality_by_prefix"] = li.cardinality_by_prefix()
        dump["labels"] = labels_dump
        if self.committer is not None:
            dump["commit"] = {
                "intervals_committed": self.committer.intervals_committed,
                "fused_intervals": self.committer.fused_intervals,
                "fanout_intervals": self.committer.fanout_intervals,
                "staging_depth": self.committer._staging.depth,
            }
        dump["obs"] = {
            "enabled": self.obs is not None,
            "capacity": self.obs.capacity if self.obs else 0,
            "recorded": self.obs.recorded if self.obs else 0,
            "dropped": self.obs.dropped if self.obs else 0,
            "current_seq": self.obs.current_seq if self.obs else 0,
            "saturated": (
                bool(self.obs.recorded >= self.obs.capacity)
                if self.obs else False
            ),
        }
        if self.resilience is not None:
            dump["resilience"] = {
                "thread_restarts": (
                    dict(self.supervisor.restarts_by_name)
                    if self.supervisor is not None else {}
                ),
                "breaker_state": (
                    self.device_breaker.state
                    if self.device_breaker is not None else None
                ),
                "breaker_opened_total": (
                    self.device_breaker.opened_total
                    if self.device_breaker is not None else 0
                ),
                "checkpoints_taken": (
                    self.recovery.checkpoints_taken
                    if self.recovery is not None else 0
                ),
                "checkpoint_errors": (
                    self.recovery.checkpoint_errors
                    if self.recovery is not None else 0
                ),
                "last_checkpoint_seq": (
                    self.recovery.last_checkpoint_seq
                    if self.recovery is not None else None
                ),
                "recovery_in_progress": (
                    self.recovery.in_progress
                    if self.recovery is not None else False
                ),
                "faults_injected": (
                    self.fault_injector.faults_injected
                    if self.fault_injector is not None else 0
                ),
            }
        if self.federation is not None:
            dump["federation"] = self.federation.stats()
        dump["health"] = (
            self.health.report().as_dict() if self.health else None
        )
        return dump

    def record_batch(self, ids: np.ndarray, values: np.ndarray) -> None:
        """Batched firehose ingestion straight to the device accumulator
        (bypasses the host sparse tier; ids come from metric_id())."""
        self.aggregator.record_batch(ids, values)

    def metric_id(self, name: str) -> int:
        """Dense row id for `name` (registers on first use)."""
        return self.aggregator.registry.id_for(name)

    def device_metrics(self, reset: bool = True) -> ProcessedMetricSet:
        """Device-side statistics for everything aggregated so far."""
        return self.aggregator.collect(reset=reset)

    # ------------------------------------------------------------------ #
    # windowed retention & rules (requires retention=)
    # ------------------------------------------------------------------ #

    def _require_retention(self):
        if self.retention is None:
            raise RuntimeError(
                "windowed queries/rules need retention: construct with "
                "TPUMetricSystem(retention=True) (or tiers/a TimeWheel)"
            )
        return self.retention

    def query_window(
        self,
        pattern: str = "*",
        window: Optional[float] = None,
        percentiles: Optional[Sequence[float]] = None,
        tier: Optional[int] = None,
    ):
        """Sliding-window statistics over the retention wheel — served
        from the latest commit-time snapshot when one covers the window
        (one sparse gather dispatch, or zero when the epoch hasn't
        advanced); see TimeWheel.query."""
        return self._require_retention().query(
            pattern, window, percentiles, tier
        )

    def query(
        self,
        selector: str = "*",
        window: Optional[float] = None,
        percentiles: Optional[Sequence[float]] = None,
        tier: Optional[int] = None,
    ):
        """Selector-aware window query (ISSUE 16): ``selector`` is a
        label selector (``http.latency{route=/api,code=~5..}``) or a
        plain name glob — both resolve through the wheel's sparse
        row-id serve path.  Same serving guarantees as query_window
        (this method and query_window accept either syntax; query() is
        the labeled-era spelling)."""
        return self._require_retention().query(
            selector, window, percentiles, tier
        )

    def query_group_by(
        self,
        selector: str,
        by: Sequence[str],
        window: Optional[float] = None,
        percentiles: Optional[Sequence[float]] = None,
        tier: Optional[int] = None,
        depth: Optional[int] = None,
    ):
        """On-device group_by rollup: merge every row matching
        ``selector`` into one histogram per distinct value-tuple of the
        ``by`` label keys — one jitted gather + segment-sum dispatch,
        exact merges (see TimeWheel.query_group_by).  ``depth=k`` adds
        per-group equi-depth summaries (``edges``)."""
        return self._require_retention().query_group_by(
            selector, by, window=window, percentiles=percentiles,
            tier=tier, depth=depth,
        )

    def window_rate(self, name: str, window: float) -> float:
        """Counter rate (events/s) over the trailing window."""
        return self._require_retention().window_rate(name, window)

    def add_rule(self, rule):
        """Register an alerting rule (window.rules.*Rule), evaluated
        after every interval; its state gauges join this system's
        exporters immediately.  ``DistributionDriftRule``s are bound to
        this system's AnomalyManager automatically (requires
        ``anomaly=AnomalyConfig(...)``)."""
        self._require_retention()
        if getattr(rule, "kind", None) == "distribution_drift":
            if self.anomaly is None:
                raise ValueError(
                    "distribution_drift rules need the drift engine: "
                    "construct with TPUMetricSystem(retention=True, "
                    "anomaly=AnomalyConfig(...))"
                )
            rule.bind(self.anomaly)
        elif getattr(rule, "kind", None) == "freshness":
            if self.federation is None:
                raise ValueError(
                    "freshness rules read the federation receiver's "
                    "end-to-end latency ledger: construct with "
                    "TPUMetricSystem(federation=FederationConfig(...))"
                )
            rule.bind(self.federation)
        self.rule_engine.add(rule)
        self.rule_engine.register_gauges(self)
        return rule

    def subscribe_to_alerts(self, ch: Channel) -> None:
        self._require_retention()
        self.rule_engine.subscribe(ch)

    def unsubscribe_from_alerts(self, ch: Channel) -> None:
        if self.rule_engine is not None:
            self.rule_engine.unsubscribe(ch)

    def backfill_retention(self, intervals: Iterable[RawMetricSet]) -> int:
        """Replay journaled intervals (utils.journal.replay(path)) into
        the retention state — offline reconstruction of window state.
        On the fused commit path the replay runs through the interval
        committer (the system's single interval consumer), so lifecycle
        activity and drift baselines rebuild alongside the wheel and
        the aggregator sees the samples exactly as it would have live.
        Returns the number of intervals pushed."""
        self._require_retention()
        if self.committer is not None:
            n = 0
            for raw in intervals:
                self.committer.commit(raw)
                n += 1
            return n
        return self.retention.backfill(intervals)

    # ------------------------------------------------------------------ #

    def recover(self):
        """Restore the latest checkpoint and replay journaled intervals
        past its seq watermark (resilience.RecoveryManager.recover) —
        at most the one in-flight interval is lost across a crash.
        Returns the RecoveryReport.  Runs automatically on the first
        ``start()`` when ``ResilienceConfig.recover_on_start`` is set."""
        if self.recovery is None:
            raise RuntimeError(
                "crash recovery needs a checkpoint/journal path: "
                "construct with TPUMetricSystem(resilience="
                "ResilienceConfig(checkpoint_path=..., journal_path=...))"
            )
        self._recovered = True
        return self.recovery.recover()

    def start(self) -> None:
        # restartable like the base class: re-attach whichever commit
        # pipeline a previous stop() detached — the fused committer is
        # the single bridge when present, the per-consumer pair otherwise
        if self.committer is not None:
            if self.committer._thread is None:
                self.committer.attach(self)
        else:
            if self.aggregator._attached is None:
                self.aggregator.attach(self)
            if self.retention is not None and self.retention._thread is None:
                self.retention.attach(self)
        if self.recovery is not None:
            # recover BEFORE the reaper starts minting intervals: replay
            # runs through the normal commit path, then the seq counter
            # is advanced past the replayed watermark so live intervals
            # never collide with journaled ones
            if (self.resilience.recover_on_start
                    and not self._recovered):
                self._recovered = True
                self.recovery.recover()
            self.recovery.start()
        if self.federation is not None:
            # after recovery (a journal replay must land on restored
            # state), before the reaper: federated deltas are ordinary
            # staged ingest, safe as soon as the aggregator exists
            self.federation.start()
        super().start()

    def stop(self) -> None:
        if self.federation is not None:
            # first: stop accepting new deltas, then let the close()
            # below drain whatever already reached the transfer queue
            self.federation.stop()
        if self.committer is not None:
            self.committer.detach()
        else:
            self.aggregator.detach()
            if self.retention is not None:
                self.retention.detach()
        # drain the transfer pipeline fully (staging ring + queue) so a
        # shutdown never strands in-flight samples; the worker re-spawns
        # lazily if start() resumes ingestion
        self.aggregator.close()
        if self.recovery is not None:
            # after the bridges drained, before the reaper dies: the
            # final checkpoint captures every committed interval, so a
            # clean stop/start round trip replays nothing
            self.recovery.stop(final_checkpoint=True)
        super().stop()
