"""Device mesh construction for distributed histogram aggregation.

The reference has no distributed surface at all (SURVEY.md §2 census); this
module supplies the communication backbone the TPU design adds: a named
2-axis mesh

    ("stream", "metric")

where the *stream* axis shards the sample firehose (data parallelism — each
device buckets its own shard of samples, valid because histograms are
order-free and mergeable) and the *metric* axis shards the dense
``[num_metrics, num_buckets]`` accumulator rows (tensor parallelism — for
10k+ metric configs whose dense tensor shouldn't be replicated).  Merges
ride ``psum`` over the stream axis (ICI within a slice, DCN across
slices); percentile extraction then runs row-parallel on the metric axis.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

STREAM_AXIS = "stream"
METRIC_AXIS = "metric"

# jax moved shard_map out of jax.experimental at 0.6; every call site in
# the package routes through this name so both spellings work.
try:
    shard_map = jax.shard_map
except AttributeError:  # jax < 0.6
    from jax.experimental.shard_map import shard_map


# -- canonical carry shardings ---------------------------------------------- #
# Every device carry in the sharded commit pipeline uses one of these
# four layouts; the committer, the lifecycle/anomaly managers, and the
# checkpoint restore all build placements through them so the layouts
# cannot drift apart.

def row_vector_sharding(mesh: Mesh) -> NamedSharding:
    """int32 [M] carries (the lifecycle activity vector)."""
    return NamedSharding(mesh, PartitionSpec(METRIC_AXIS))


def acc_sharding(mesh: Mesh) -> NamedSharding:
    """[M, B] carries (accumulator, interval histogram)."""
    return NamedSharding(mesh, PartitionSpec(METRIC_AXIS, None))


def ring_sharding(mesh: Mesh) -> NamedSharding:
    """[S, M, B] / [K, M, B] carries (tier rings, baseline profiles)."""
    return NamedSharding(mesh, PartitionSpec(None, METRIC_AXIS, None))


def bank_weight_sharding(mesh: Mesh) -> NamedSharding:
    """f32 [K, M] carries (baseline bank weight mass)."""
    return NamedSharding(mesh, PartitionSpec(None, METRIC_AXIS))


def cell_sharding(mesh: Mesh) -> NamedSharding:
    """Staged interval cell chunks [N]: split over the stream axis so
    each device scatters its slice and ONE psum merges the deltas."""
    return NamedSharding(mesh, PartitionSpec(STREAM_AXIS))


def pool_sharding(mesh: Mesh) -> NamedSharding:
    """int32 [total_pages, page_size] page pools: each metric shard owns
    a contiguous arena of pool rows (its own zero page at the arena
    base), so the paged scatter runs shard-local under shard_map."""
    return NamedSharding(mesh, PartitionSpec(METRIC_AXIS, None))


def triple_sharding(mesh: Mesh) -> NamedSharding:
    """Translated commit triples [N, 3]: split over the stream axis
    like cell chunks — each device scatters its slice into a local pool
    delta and ONE psum merges them (int32 ⇒ order-independent)."""
    return NamedSharding(mesh, PartitionSpec(STREAM_AXIS, None))


def make_mesh(
    stream: Optional[int] = None,
    metric: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a ("stream", "metric") mesh.

    Defaults to all local devices on the stream axis — the right default
    for the firehose workload, where ingest bandwidth is the bottleneck.
    """
    devices = list(devices if devices is not None else jax.devices())
    if stream is None:
        if len(devices) % metric:
            raise ValueError(
                f"{len(devices)} devices not divisible by metric={metric}"
            )
        stream = len(devices) // metric
    n = stream * metric
    if n > len(devices):
        raise ValueError(
            f"mesh {stream}x{metric} needs {n} devices, have {len(devices)}"
        )
    grid = np.asarray(devices[:n]).reshape(stream, metric)
    return Mesh(grid, (STREAM_AXIS, METRIC_AXIS))
