"""Device mesh construction for distributed histogram aggregation.

The reference has no distributed surface at all (SURVEY.md §2 census); this
module supplies the communication backbone the TPU design adds: a named
2-axis mesh

    ("stream", "metric")

where the *stream* axis shards the sample firehose (data parallelism — each
device buckets its own shard of samples, valid because histograms are
order-free and mergeable) and the *metric* axis shards the dense
``[num_metrics, num_buckets]`` accumulator rows (tensor parallelism — for
10k+ metric configs whose dense tensor shouldn't be replicated).  Merges
ride ``psum`` over the stream axis (ICI within a slice, DCN across
slices); percentile extraction then runs row-parallel on the metric axis.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

STREAM_AXIS = "stream"
METRIC_AXIS = "metric"


def make_mesh(
    stream: Optional[int] = None,
    metric: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a ("stream", "metric") mesh.

    Defaults to all local devices on the stream axis — the right default
    for the firehose workload, where ingest bandwidth is the bottleneck.
    """
    devices = list(devices if devices is not None else jax.devices())
    if stream is None:
        if len(devices) % metric:
            raise ValueError(
                f"{len(devices)} devices not divisible by metric={metric}"
            )
        stream = len(devices) // metric
    n = stream * metric
    if n > len(devices):
        raise ValueError(
            f"mesh {stream}x{metric} needs {n} devices, have {len(devices)}"
        )
    grid = np.asarray(devices[:n]).reshape(stream, metric)
    return Mesh(grid, (STREAM_AXIS, METRIC_AXIS))
