"""Multi-host distributed aggregation (SURVEY.md §5.8 — the slot the
single-process reference leaves empty).

The whole design is already multi-host-shaped: histogram merge is an
elementwise add, which `psum` performs identically over ICI (within a
slice) and DCN (across slices/hosts) once JAX's global runtime is up.
This module provides the thin host-side pieces:

  * `initialize(...)` — wraps `jax.distributed.initialize`; after it,
    `jax.devices()` spans every host and `parallel.mesh.make_mesh()` built
    from those devices gives the global ("stream", "metric") mesh.  The
    shard_map step from `parallel.aggregator.make_distributed_step` then
    runs unchanged: GSPMD treats the global mesh uniformly, psum rides ICI
    within a slice and DCN across.
  * `local_sample_shard(...)` — helper for carving each host's sample
    stream out of a global batch axis (each host feeds only its local
    devices; no host ever materializes the global batch).
There is no bespoke RPC layer on purpose: the reference's TCP submitter is
one-way *export*, not coordination, and remains exactly that here; all
peer-to-peer communication is XLA collectives.
"""

from __future__ import annotations

from typing import Optional

import jax


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Bring up the global JAX runtime across hosts.

    On Cloud TPU pods all three arguments are auto-detected; pass them
    explicitly elsewhere.  Safe to call once per process, before any
    backend use."""
    kwargs = {}
    if coordinator_address is not None:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    jax.distributed.initialize(**kwargs)


def global_mesh(metric: int = 1):
    """The global ("stream","metric") mesh over every device of every
    host.  Call after initialize()."""
    from loghisto_tpu.parallel.mesh import make_mesh

    return make_mesh(metric=metric, devices=jax.devices())


def local_sample_shard(global_batch: int) -> tuple[int, int]:
    """(start, size) of this host's slice of a `global_batch`-sized sample
    axis, proportional to its local device count."""
    total = jax.device_count()
    local = jax.local_device_count()
    if global_batch % total:
        raise ValueError(
            f"global_batch={global_batch} not divisible by device count "
            f"{total}"
        )
    per_device = global_batch // total
    # Validate the contiguity assumption instead of silently overlapping:
    # this mapping requires local device ids to form a dense range.
    local_ids = sorted(d.id for d in jax.local_devices())
    if local_ids != list(range(local_ids[0], local_ids[0] + local)):
        raise RuntimeError(
            f"local device ids {local_ids} are not contiguous; derive the "
            "shard from a prefix sum of per-process device counts instead"
        )
    return local_ids[0] * per_device, local * per_device


def make_global_arrays(mesh, ids_local, values_local):
    """Assemble global sample arrays from per-host local shards using
    jax.make_array_from_process_local_data — each host supplies only its
    own samples; no host materializes the global batch."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from loghisto_tpu.parallel.mesh import STREAM_AXIS

    sharding = NamedSharding(mesh, P(STREAM_AXIS))
    ids = jax.make_array_from_process_local_data(sharding, ids_local)
    values = jax.make_array_from_process_local_data(sharding, values_local)
    return ids, values
