"""Multi-host distributed aggregation (SURVEY.md §5.8 — the slot the
single-process reference leaves empty).

The whole design is already multi-host-shaped: histogram merge is an
elementwise add, which `psum` performs identically over ICI (within a
slice) and DCN (across slices/hosts) once JAX's global runtime is up.
This module provides the thin host-side pieces:

  * `initialize(...)` — wraps `jax.distributed.initialize`; after it,
    `jax.devices()` spans every host and `parallel.mesh.make_mesh()` built
    from those devices gives the global ("stream", "metric") mesh.  The
    shard_map step from `parallel.aggregator.make_distributed_step` then
    runs unchanged: GSPMD treats the global mesh uniformly, psum rides ICI
    within a slice and DCN across.
  * `local_sample_shard(...)` — helper for carving each host's sample
    stream out of a global batch axis (each host feeds only its local
    devices; no host ever materializes the global batch).
There is no bespoke RPC layer on purpose: the reference's TCP submitter is
one-way *export*, not coordination, and remains exactly that here; all
peer-to-peer communication is XLA collectives.
"""

from __future__ import annotations

from typing import Optional

import jax


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Bring up the global JAX runtime across hosts.

    On Cloud TPU pods all three arguments are auto-detected; pass them
    explicitly elsewhere.  Safe to call once per process, before any
    backend use."""
    kwargs = {}
    if coordinator_address is not None:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    jax.distributed.initialize(**kwargs)


def global_mesh(metric: int = 1):
    """The global ("stream","metric") mesh over every device of every
    host.  Call after initialize()."""
    from loghisto_tpu.parallel.mesh import make_mesh

    return make_mesh(metric=metric, devices=jax.devices())


def local_sample_shard(global_batch: int) -> tuple[int, int]:
    """(start, size) of this host's slice of a `global_batch`-sized sample
    axis, proportional to its local device count.

    Positions come from this process's devices' indices in the
    ``jax.devices()`` GLOBAL ORDER — the same order make_mesh lays the
    mesh out in — never from ``device.id``: device ids are not dense
    across processes (virtual CPU devices in process 1 are numbered
    2048+), and an id-based offset silently produced an out-of-range,
    empty sample slice for every process but 0 (caught by the 2-process
    test, tests/multihost_worker.py)."""
    devs = jax.devices()
    total = len(devs)
    if global_batch % total:
        raise ValueError(
            f"global_batch={global_batch} not divisible by device count "
            f"{total}"
        )
    per_device = global_batch // total
    me = jax.process_index()
    positions = [i for i, d in enumerate(devs) if d.process_index == me]
    # Validate contiguity instead of silently overlapping: the mesh's
    # stream axis maps contiguous device positions to contiguous sample
    # slices, so a process's devices must form a dense position range.
    if positions != list(range(positions[0], positions[0] + len(positions))):
        raise RuntimeError(
            f"process {me}'s device positions {positions} are not "
            "contiguous in jax.devices() order; derive the shard from a "
            "prefix sum of per-process device counts instead"
        )
    return positions[0] * per_device, len(positions) * per_device


def global_put(host, sharding):
    """Place a host array onto a (possibly multi-process) sharding.

    Single-process this is ``jax.device_put``.  Across real
    ``jax.distributed`` processes a plain device_put onto a
    non-addressable sharding runs an assert-equal COLLECTIVE before
    committing — unavailable on the CPU backend the 2-process drill
    uses — so the global array is assembled collective-free via
    ``make_array_from_callback``, each process slicing its addressable
    shards out of the host value.  Callers guarantee every process
    passes the SAME host value (the paged store's translate step is
    deterministic from shared inputs, which is the whole multi-process
    design: identical host tables, no coordination)."""
    import numpy as np

    if getattr(sharding, "is_fully_addressable", True):
        return jax.device_put(host, sharding)
    host = np.asarray(host)
    return jax.make_array_from_callback(
        host.shape, sharding, lambda idx: host[idx]
    )


def host_gather(arr):
    """A host numpy copy of ``arr`` regardless of process span.

    Single-process (including virtual multi-device CPU meshes) this is
    plain ``np.asarray``.  Across real ``jax.distributed`` processes a
    sharded array is only partially addressable, so the copy rides
    ``process_allgather`` — every process receives the full global
    value.  The paged store's decode path (checkpoint gather-on-save,
    ``decode_dense``) funnels through here, which is what makes v3
    paged checkpoints writable from any process of a multi-host pod."""
    import numpy as np

    if getattr(arr, "is_fully_addressable", True):
        return np.asarray(arr)
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(arr, tiled=True))


def make_global_arrays(mesh, ids_local, values_local):
    """Assemble global sample arrays from per-host local shards — each
    host supplies only its own samples; no host materializes the global
    batch.

    Built on jax.make_array_from_callback keyed by each addressable
    device's GLOBAL stream slice.  (make_array_from_process_local_data
    is wrong here: with a metric axis > 1 the sample arrays are sharded
    over stream but REPLICATED over metric, and that API divides the
    process-local buffer across all local devices — every metric shard
    would silently see only 1/metric of the stream.  Caught by the
    2-process test, tests/multihost_worker.py.)"""
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from loghisto_tpu.parallel.mesh import STREAM_AXIS

    ids_local = np.asarray(ids_local)
    values_local = np.asarray(values_local)
    sharding = NamedSharding(mesh, P(STREAM_AXIS))
    n_local = ids_local.shape[0]
    global_n = n_local * jax.process_count()
    start, size = local_sample_shard(global_n)
    if size != n_local:
        raise ValueError(
            f"local shard has {n_local} samples but this process's share "
            f"of the global batch is {size} (equal per-process shards "
            "required)"
        )

    def build(local):
        def cb(index):
            sl = index[0]
            lo = 0 if sl.start is None else sl.start
            hi = global_n if sl.stop is None else sl.stop
            if lo < start or hi > start + size:
                raise RuntimeError(
                    f"addressable shard [{lo}:{hi}) falls outside this "
                    f"process's sample range [{start}:{start + size})"
                )
            return local[lo - start:hi - start]

        return jax.make_array_from_callback(
            (global_n,), sharding, cb
        )

    return build(ids_local), build(values_local)
