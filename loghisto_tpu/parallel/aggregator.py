"""TPU aggregation engine: dense device accumulators, distributed merges,
and the TPUAggregator runtime that gates them behind the subscription
boundary.

North-star architecture (BASELINE.json): host callers keep using
``MetricSystem``; a TPUAggregator ships raw samples (or pre-bucketed
interval histograms) to the device, where

  * ingest is a fused compress -> scatter-add (ops/ingest.py),
  * cross-stream / cross-host merge is a ``psum`` over the mesh's stream
    axis — the elementwise-additive merge the log-bucket representation
    makes exact,
  * percentile extraction is the CDF scan of ops/stats.py, row-parallel
    over the metric axis.

The distributed step below runs under ``shard_map`` on a
("stream", "metric") mesh: sample shards enter per device, local dense
histograms are psum-merged across the stream axis, folded into the
metric-sharded accumulator, and per-metric statistics come back sharded by
metric rows.  This is the §5.7/§5.8 slot of SURVEY.md — the capability the
reference (a single-process Go library) does not have.
"""

from __future__ import annotations

import functools
import threading
import time
from typing import Dict, Mapping, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from loghisto_tpu.config import DEFAULT_PERCENTILES, PRECISION, MetricConfig
from loghisto_tpu.metrics import MetricSystem, ProcessedMetricSet, RawMetricSet
from loghisto_tpu.channel import Channel, ChannelClosed
from loghisto_tpu.ops.ingest import (
    bucket_indices,
    make_ingest_fn,
    make_weighted_ingest_fn,
    sanitize_ids,
)
from loghisto_tpu.ops.stats import dense_stats
from loghisto_tpu.parallel.mesh import METRIC_AXIS, STREAM_AXIS
from loghisto_tpu.registry import MetricRegistry


def local_histogram_fold(
    acc_local: jnp.ndarray,
    ids: jnp.ndarray,
    values: jnp.ndarray,
    rows_per_shard: int,
    bucket_limit: int,
    precision: int = PRECISION,
) -> jnp.ndarray:
    """The sharded-ingest core, shared by every shard_map step: offset ids
    into this metric shard's row range (ids below it go negative, so
    sanitize before drop-mode scatter or they'd wrap to the last row),
    bucket the local sample shard, psum the dense histograms across the
    stream axis, and fold into the accumulator.  Must run inside
    shard_map on a ("stream", "metric") mesh."""
    shard = jax.lax.axis_index(METRIC_AXIS)
    local_ids = sanitize_ids(ids - shard * rows_per_shard)
    bidx = bucket_indices(values, bucket_limit, precision)
    hist = jnp.zeros_like(acc_local).at[local_ids, bidx].add(1, mode="drop")
    hist = jax.lax.psum(hist, STREAM_AXIS)
    return acc_local + hist


def make_distributed_step(
    mesh: Mesh,
    num_metrics: int,
    bucket_limit: int,
    percentile_values,
    precision: int = PRECISION,
):
    """Build the jitted full aggregation step over a ("stream", "metric")
    mesh.

    Returns f(acc, ids, values) -> (new_acc, stats) where
      acc    int32 [num_metrics, num_buckets], sharded over metric rows
      ids    int32 [N], sharded over the stream axis
      values float32 [N], sharded over the stream axis
      stats  {"counts": [M] (metric-sharded), "sums": [M],
              "percentiles": [M, P]}

    Per device: bucket the local sample shard into a local dense histogram
    (dropping ids outside this device's metric rows), psum across the
    stream axis, fold into the accumulator, then extract statistics for
    the local metric rows.  All collectives are XLA-native and ride ICI.
    """
    n_metric = mesh.shape[METRIC_AXIS]
    if num_metrics % n_metric:
        raise ValueError(
            f"num_metrics={num_metrics} not divisible by metric axis "
            f"size {n_metric}"
        )
    rows_per_shard = num_metrics // n_metric
    ps = jnp.asarray(percentile_values, dtype=jnp.float32)

    def local_step(acc_local, ids, values):
        acc_local = local_histogram_fold(
            acc_local, ids, values, rows_per_shard, bucket_limit, precision
        )
        stats = dense_stats(acc_local, ps, bucket_limit, precision)
        return acc_local, stats

    stats_specs = {
        "counts": P(METRIC_AXIS),
        "sums": P(METRIC_AXIS),
        "percentiles": P(METRIC_AXIS, None),
    }
    step = jax.shard_map(
        local_step,
        mesh=mesh,
        in_specs=(P(METRIC_AXIS, None), P(STREAM_AXIS), P(STREAM_AXIS)),
        out_specs=(P(METRIC_AXIS, None), stats_specs),
    )
    return jax.jit(step, donate_argnums=0)


def make_sharded_accumulator(
    mesh: Mesh, num_metrics: int, num_buckets: int
) -> jnp.ndarray:
    """Zero accumulator laid out metric-sharded, stream-replicated."""
    sharding = NamedSharding(mesh, P(METRIC_AXIS, None))
    return jax.device_put(
        jnp.zeros((num_metrics, num_buckets), dtype=jnp.int32), sharding
    )


class TPUAggregator:
    """Device-tier metric engine (the reference has no equivalent; this is
    the TPU execution backend the north star adds behind the subscription
    boundary).

    Two ways in:
      * `record_batch(ids, values)` / `record(name, value)` — direct
        firehose ingestion; batches buffer on host and flush to the device
        as fused compress+scatter-add steps.
      * `attach(metric_system)` — subscribe to the host MetricSystem's raw
        broadcast and merge each interval's pre-bucketed histograms into
        the device accumulator (weighted scatter-add), so existing callers
        get device-side percentile extraction without code changes.

    `collect()` extracts all statistics on device (one CDF-scan program),
    resets the accumulator, folds lifetime aggregates on host (python ints
    — immune to int32 overflow across intervals), and returns a
    ProcessedMetricSet with the standard naming scheme.
    """

    def __init__(
        self,
        num_metrics: int = 1024,
        config: MetricConfig = MetricConfig(),
        percentiles: Mapping[str, float] = DEFAULT_PERCENTILES,
        registry: Optional[MetricRegistry] = None,
        batch_size: int = 1 << 16,
        mesh: Optional[Mesh] = None,
        native_staging: bool = False,
        ingest_path: str = "scatter",
    ):
        """When `mesh` is given (a ("stream","metric") mesh from
        parallel.mesh.make_mesh), the dense accumulator is laid out
        metric-row-sharded across the mesh and every jitted step runs
        SPMD — XLA partitions the scatter-adds and the CDF scan row-wise
        and inserts the collectives.  num_metrics must divide evenly by
        the metric-axis size.

        `native_staging=True` stages record_batch samples in the C++
        lock-striped buffer (loghisto_tpu._native) instead of Python
        lists — writers release the GIL in the C call, and overflow sheds
        with an exposed drop counter.  Requires the native library; falls
        back (with a log line) when unavailable.

        `ingest_path` selects the device accumulation kernel:
          * "scatter"  — XLA scatter-add (default; works everywhere)
          * "matmul"   — one-hot MXU matmul (small metric counts)
          * "multirow" — metric-tiled Pallas kernel (sorted/block-padded;
            single-device only, TPU-targeted, interpret-mode elsewhere)
        All three are bit-identical (tests/test_fast_paths.py,
        tests/test_pallas_multirow.py); they differ only in speed per
        configuration — benchmarks/device_paths.py measures them."""
        self.config = config
        self.num_metrics = num_metrics
        # explicit None check: an empty registry is falsy (it has __len__),
        # so `registry or ...` would silently discard a caller's registry
        self.registry = (
            registry if registry is not None
            else MetricRegistry(capacity=num_metrics)
        )
        if self.registry.capacity > num_metrics:
            raise ValueError(
                f"registry capacity {self.registry.capacity} exceeds "
                f"num_metrics {num_metrics}: names beyond the accumulator "
                "rows could never be aggregated"
            )
        for label in percentiles:
            try:
                if not isinstance(label % "name", str):
                    raise TypeError("renders to non-string")
            except (TypeError, ValueError) as e:
                raise ValueError(
                    f"percentile label {label!r} is not a valid %-format "
                    f"template for a metric name: {e}"
                ) from None
        self.percentiles = dict(percentiles)
        self.batch_size = batch_size

        self._lock = threading.Lock()
        self._pending_ids: list[np.ndarray] = []
        self._pending_values: list[np.ndarray] = []
        self._pending_count = 0

        self._native_buf = None
        self._native_staged = 0
        # host-side retry buffer bound when the device is unreachable
        self.max_pending_samples = 32 * batch_size
        self.retry_cooldown = 1.0  # seconds between device retry attempts
        self._shed_samples = 0
        self._device_down_until = 0.0
        self._interval_ingested = 0  # samples in the live accumulator
        if native_staging:
            from loghisto_tpu import _native

            if _native.available():
                # 16 shards x 4*batch_size x 12B ~= 48 MB at the default
                # batch_size; scale with the workload, don't floor at 1M
                self._native_buf = _native.NativeIngestBuffer(
                    num_shards=16,
                    capacity_per_shard=max(batch_size * 4, 1 << 16),
                )
            else:
                import logging

                logging.getLogger("loghisto_tpu").warning(
                    "native staging requested but unavailable (%s); using "
                    "Python staging", _native.build_error(),
                )

        self.mesh = mesh
        if mesh is not None:
            n_metric = mesh.shape[METRIC_AXIS]
            if num_metrics % n_metric:
                raise ValueError(
                    f"num_metrics={num_metrics} not divisible by the mesh "
                    f"metric axis ({n_metric})"
                )
            self._acc = make_sharded_accumulator(
                mesh, num_metrics, config.num_buckets
            )
        else:
            self._acc = jnp.zeros(
                (num_metrics, config.num_buckets), dtype=jnp.int32
            )
        # identity for dense-layout paths; multirow slices its lane padding
        self._finalize_acc = lambda a: a
        # per-path zero-accumulator factory (layout differs by path)
        self._make_acc = self._fresh_dense_acc
        if ingest_path == "scatter":
            self._ingest = make_ingest_fn(
                config.bucket_limit, config.precision
            )
        elif ingest_path == "matmul":
            from loghisto_tpu.ops.matmul_hist import make_matmul_ingest_fn

            self._ingest = make_matmul_ingest_fn(
                config.bucket_limit, config.precision
            )
        elif ingest_path == "multirow":
            if mesh is not None:
                raise ValueError(
                    "ingest_path='multirow' is single-device (its dense "
                    "layout is lane-padded); use scatter with a mesh"
                )
            from loghisto_tpu.ops.pallas_multirow import make_multirow_ingest

            init, multirow_ingest, self._finalize_acc = make_multirow_ingest(
                num_metrics, config.bucket_limit, config.precision
            )
            self._ingest = multirow_ingest
            # lane-padded accumulator layout; the weighted host-bridge
            # ingest still works (dense buckets are the leading columns)
            self._make_acc = init
            self._acc = init()
        else:
            raise ValueError(
                f"unknown ingest_path {ingest_path!r}: expected 'scatter', "
                "'matmul', or 'multirow'"
            )
        self.ingest_path = ingest_path
        self._weighted_ingest = make_weighted_ingest_fn(config.bucket_limit)
        self._stats_fn = jax.jit(
            functools.partial(
                dense_stats,
                bucket_limit=config.bucket_limit,
                precision=config.precision,
            )
        )
        # lifetime aggregates on host: name id -> [sum, count]
        self._agg_lock = threading.Lock()
        self._agg: Dict[int, list] = {}
        self._last_aggregation_us = 0.0

        self._attached: Optional[tuple[MetricSystem, Channel, threading.Thread]] = None

    # -- direct ingestion ---------------------------------------------- #

    def record(self, name: str, value: float) -> None:
        self.record_batch(
            np.array([self.registry.id_for(name)], dtype=np.int32),
            np.array([value], dtype=np.float32),
        )

    def record_batch(self, ids: np.ndarray, values: np.ndarray) -> None:
        """Buffer a batch of (metric_id, value) samples; flushes to device
        when the buffered count reaches batch_size."""
        ids = np.asarray(ids, dtype=np.int32)
        values = np.asarray(values, dtype=np.float32)
        if ids.shape != values.shape:
            raise ValueError("ids and values must have the same shape")
        if self._native_buf is not None:
            accepted = self._native_buf.record_batch(
                ids, values.astype(np.float64)
            )
            # keep the documented auto-flush contract in the native path;
            # counted under the lock (an unsynchronized += can lose
            # updates and *miss* flushes) and only for accepted samples
            with self._lock:
                self._native_staged += accepted
                should_flush = self._native_staged >= self.batch_size
            if should_flush:
                self.flush()
            return
        with self._lock:
            self._pending_ids.append(ids)
            self._pending_values.append(values)
            self._pending_count += len(ids)
            # while the device is down (flush cooldown-gated), the buffer
            # must stay bounded
            self._bound_pending_locked()
            should_flush = self._pending_count >= self.batch_size
        if should_flush:
            self.flush()

    def _fresh_dense_acc(self) -> jnp.ndarray:
        if self.mesh is not None:
            return make_sharded_accumulator(
                self.mesh, self.num_metrics, self.config.num_buckets
            )
        return jnp.zeros(
            (self.num_metrics, self.config.num_buckets), dtype=jnp.int32
        )

    def _fresh_acc(self) -> jnp.ndarray:
        """Zero accumulator in THIS ingest path's layout (the multirow
        path is lane-padded; rebuilding the wrong shape after a device
        failure would permanently break ingestion)."""
        return self._make_acc()

    def _bound_pending_locked(self) -> None:
        """Enforce max_pending_samples by shedding the OLDEST samples,
        slicing partial arrays so no more than the overflow is dropped.
        Caller holds self._lock."""
        overflow = self._pending_count - self.max_pending_samples
        while overflow > 0 and self._pending_ids:
            head = self._pending_ids[0]
            if len(head) <= overflow:
                self._pending_ids.pop(0)
                self._pending_values.pop(0)
                self._pending_count -= len(head)
                self._shed_samples += len(head)
                overflow -= len(head)
            else:
                self._pending_ids[0] = head[overflow:]
                self._pending_values[0] = self._pending_values[0][overflow:]
                self._pending_count -= overflow
                self._shed_samples += overflow
                overflow = 0

    def flush(self, force: bool = False) -> None:
        """Push buffered samples to the device accumulator.

        Batches are shipped in fixed-size chunks (padding the tail with
        id -1, which the kernel drops) so the jitted ingest compiles for
        exactly one shape instead of one executable per batch length.

        Device failures follow SURVEY.md §5.3 shed-don't-block: samples
        buffer on host (bounded, oldest shed first) and retries are
        cooldown-gated so a down device costs one attempt per
        retry_cooldown, not one per record.  `force=True` (used by
        collect()) bypasses the cooldown."""
        if self._native_buf is not None:
            with self._lock:
                self._native_staged = 0
            nids, nvalues = self._native_buf.drain()
            if len(nids):
                with self._lock:
                    self._pending_ids.append(nids)
                    self._pending_values.append(nvalues.astype(np.float32))
                    self._pending_count += len(nids)
                    self._bound_pending_locked()
        with self._lock:
            if not self._pending_count:
                return
            if not force and time.monotonic() < self._device_down_until:
                return  # device cooling down; keep buffering
            ids = np.concatenate(self._pending_ids)
            values = np.concatenate(self._pending_values)
            self._pending_ids, self._pending_values = [], []
            self._pending_count = 0
            n = len(ids)
            bs = self.batch_size
            padded = (n + bs - 1) // bs * bs
            if padded != n:
                ids = np.concatenate(
                    [ids, np.full(padded - n, -1, dtype=np.int32)]
                )
                values = np.concatenate(
                    [values, np.zeros(padded - n, dtype=np.float32)]
                )
            for off in range(0, padded, bs):
                try:
                    self._acc = self._ingest(
                        self._acc, ids[off:off + bs], values[off:off + bs]
                    )
                    self._device_down_until = 0.0
                    self._interval_ingested += min(bs, n - off)
                except Exception:
                    import logging

                    logger = logging.getLogger("loghisto_tpu")
                    self._device_down_until = (
                        time.monotonic() + self.retry_cooldown
                    )
                    # The ingest donates the accumulator; a failure may
                    # have consumed the buffer.  Detect it — continuing to
                    # use a deleted array would brick every later flush.
                    if getattr(self._acc, "is_deleted", lambda: False)():
                        logger.error(
                            "device failure consumed the donated "
                            "accumulator; %d already-ingested samples of "
                            "this interval are lost",
                            self._interval_ingested,
                        )
                        self._shed_samples += self._interval_ingested
                        self._interval_ingested = 0
                        self._acc = self._fresh_acc()
                    tail = n - off  # real samples only, never the pad
                    logger.exception(
                        "device ingest failed; buffering %d samples for "
                        "retry (cooldown %.1fs)", max(tail, 0),
                        self.retry_cooldown,
                    )
                    if tail > 0:
                        self._pending_ids.append(ids[off:n])
                        self._pending_values.append(values[off:n])
                        self._pending_count += tail
                    self._bound_pending_locked()
                    break

    # -- host-tier bridge ----------------------------------------------- #

    def merge_raw(self, raw: RawMetricSet) -> None:
        """Merge one host-tier interval (sparse bucket maps) into the dense
        device accumulator via a weighted scatter-add."""
        ids, bidx, weights = [], [], []
        for name, bucket_counts in raw.histograms.items():
            mid = self.registry.id_for(name)
            for bucket, count in bucket_counts.items():
                ids.append(mid)
                bidx.append(bucket)  # codec bucket; kernel clips to range
                weights.append(count)
        if not ids:
            return
        # pad to a fixed chunk size (dropped id -1): one compiled
        # executable instead of one per distinct per-interval entry count
        # (which leaks compile-cache memory interval after interval)
        chunk = 4096
        n = len(ids)
        padded = (n + chunk - 1) // chunk * chunk
        ids_np = np.full(padded, -1, dtype=np.int32)
        bidx_np = np.zeros(padded, dtype=np.int32)
        weights_np = np.zeros(padded, dtype=np.int32)
        ids_np[:n] = ids
        bidx_np[:n] = bidx
        weights_np[:n] = weights
        with self._lock:
            for off in range(0, padded, chunk):
                self._acc = self._weighted_ingest(
                    self._acc,
                    ids_np[off:off + chunk],
                    bidx_np[off:off + chunk],
                    weights_np[off:off + chunk],
                )

    def attach(self, ms: MetricSystem, channel_capacity: int = 8) -> None:
        """Subscribe to a MetricSystem's raw broadcast; every interval's
        histograms are merged into the device accumulator on a bridge
        thread (the subscription boundary of the north star)."""
        if self._attached is not None:
            raise RuntimeError("already attached")
        ch = Channel(channel_capacity)
        ms.subscribe_to_raw_metrics(ch)

        def bridge():
            while True:
                try:
                    raw = ch.get()
                except ChannelClosed:
                    return
                try:
                    self.merge_raw(raw)
                except Exception:  # pragma: no cover - defensive
                    import logging

                    logging.getLogger("loghisto_tpu").exception(
                        "device merge failed for interval %s", raw.time
                    )

        t = threading.Thread(
            target=bridge, daemon=True, name="loghisto-tpu-bridge"
        )
        t.start()
        self._attached = (ms, ch, t)

    def detach(self) -> None:
        if self._attached is None:
            return
        ms, ch, t = self._attached
        ms.unsubscribe_from_raw_metrics(ch)
        ch.close()
        t.join(timeout=5.0)
        self._attached = None

    # -- collection ----------------------------------------------------- #

    def collect(self, reset: bool = True) -> ProcessedMetricSet:
        """Extract statistics for every registered metric on device and
        return them with the standard naming scheme."""
        self.flush(force=True)
        labels, ps = [], []
        for label, p in self.percentiles.items():
            if 0.0 <= p <= 1.0:
                labels.append(label)
                ps.append(p)
        t0 = time.perf_counter()
        # Only the snapshot/swap needs the ingest lock; the device stats
        # round-trip runs outside it so producers never stall on collection.
        # (With reset=False the accumulator keeps flowing, so it must be
        # copied under the lock — a later flush() would otherwise donate
        # the very buffer stats are reading.)
        with self._lock:
            acc = self._acc
            if reset:
                # zeros_like preserves the NamedSharding in mesh mode
                self._acc = jnp.zeros_like(acc)
                self._interval_ingested = 0
            else:
                acc = acc + 0  # defensive copy; donation-safe snapshot
        from loghisto_tpu.utils.trace import maybe_capture

        with maybe_capture("loghisto_collect"):
            stats = self._stats_fn(
                self._finalize_acc(acc), np.asarray(ps, dtype=np.float32)
            )
        counts = np.asarray(stats["counts"])
        sums = np.asarray(stats["sums"])
        pcts = np.asarray(stats["percentiles"])
        self._last_aggregation_us = (time.perf_counter() - t0) * 1e6

        names = self.registry.names()
        metrics: Dict[str, float] = {}
        with self._agg_lock:
            if reset:
                agg_view = self._agg  # interval closes: fold for real
            else:
                # peek: report lifetime+current without mutating, so
                # repeated collect(reset=False) can never double-fold
                agg_view = {
                    mid: list(entry) for mid, entry in self._agg.items()
                }
            for mid, name in enumerate(names):
                count = int(counts[mid])
                if count == 0:
                    continue
                total = float(sums[mid])
                metrics[f"{name}_count"] = float(count)
                metrics[f"{name}_sum"] = total
                metrics[f"{name}_avg"] = total / count
                for label, value in zip(labels, pcts[mid]):
                    metrics[label % name] = float(value)
                # int seed: go_compat accumulates exact integers like the
                # reference's uint64 store; float mode promotes naturally.
                entry = agg_view.setdefault(mid, [0, 0])
                if self.config.go_compat:
                    # same uint64 semantics as the host tier's store
                    from loghisto_tpu.metrics import _UINT64_MASK

                    entry[0] = (entry[0] + int(total)) & _UINT64_MASK
                else:
                    entry[0] += total
                entry[1] += count
            for mid, entry in agg_view.items():
                name = names[mid] if mid < len(names) else None
                if name is None or entry[1] <= 0:
                    continue
                if self.config.go_compat:
                    avg = float(int(entry[0]) // int(entry[1]))
                else:
                    avg = entry[0] / entry[1]
                metrics[f"{name}_agg_avg"] = avg
                metrics[f"{name}_agg_count"] = float(entry[1])
                metrics[f"{name}_agg_sum"] = float(entry[0])

        import datetime as _dt

        return ProcessedMetricSet(
            time=_dt.datetime.now(tz=_dt.timezone.utc), metrics=metrics
        )

    # -- gauges ---------------------------------------------------------- #

    def register_device_gauges(self, ms: MetricSystem) -> None:
        """Register TPU gauges on a MetricSystem: HBM use and the last
        device aggregation time (SURVEY.md §5.5)."""

        def hbm_bytes() -> float:
            try:
                stats = jax.devices()[0].memory_stats()
                return float((stats or {}).get("bytes_in_use", 0))
            except Exception:
                return 0.0

        ms.register_gauge_func("tpu.HbmBytesInUse", hbm_bytes)
        ms.register_gauge_func(
            "tpu.LastAggregationUs", lambda: self._last_aggregation_us
        )
        if self._native_buf is not None:
            ms.register_gauge_func(
                "tpu.StagingDropped",
                lambda: float(self._native_buf.dropped),
            )
        ms.register_gauge_func(
            "tpu.SamplesShed", lambda: float(self._shed_samples)
        )
