"""TPU aggregation engine: dense device accumulators, distributed merges,
and the TPUAggregator runtime that gates them behind the subscription
boundary.

North-star architecture (BASELINE.json): host callers keep using
``MetricSystem``; a TPUAggregator ships raw samples (or pre-bucketed
interval histograms) to the device, where

  * ingest is a fused compress -> scatter-add (ops/ingest.py),
  * cross-stream / cross-host merge is a ``psum`` over the mesh's stream
    axis — the elementwise-additive merge the log-bucket representation
    makes exact,
  * percentile extraction is the CDF scan of ops/stats.py, row-parallel
    over the metric axis.

The distributed step below runs under ``shard_map`` on a
("stream", "metric") mesh: sample shards enter per device, local dense
histograms are psum-merged across the stream axis, folded into the
metric-sharded accumulator, and per-metric statistics come back sharded by
metric rows.  This is the §5.7/§5.8 slot of SURVEY.md — the capability the
reference (a single-process Go library) does not have.
"""

from __future__ import annotations

import collections
import functools
import threading
import time
from typing import Dict, Mapping, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from loghisto_tpu.config import DEFAULT_PERCENTILES, PRECISION, MetricConfig
from loghisto_tpu.metrics import MetricSystem, ProcessedMetricSet, RawMetricSet
from loghisto_tpu.channel import Channel, ChannelClosed
from loghisto_tpu.obs.spans import NULL_RECORDER
from loghisto_tpu.ops.ingest import (
    make_ingest_fn,
    make_weighted_ingest_fn,
    sanitize_ids,
)
from loghisto_tpu.ops.dispatch import resolve_ingest_path
from loghisto_tpu.ops.stats import dense_stats, dense_stats_np
from loghisto_tpu.parallel.mesh import METRIC_AXIS, STREAM_AXIS, shard_map
from loghisto_tpu.registry import MetricRegistry, RegistryFullError

# Default registry-growth headroom: max_metrics = num_metrics * this when
# unspecified.  Shared with bench.py's path resolution so the benchmarked
# default kernel tracks the default-configured aggregator's exactly.
DEFAULT_GROWTH_FACTOR = 8

# Fixed launch width for weighted cell merges (bridge intervals, preagg
# flushes): one compiled executable serves every merge, and a 10k-metric
# interval is a handful of launches instead of the round-1 hundreds.
_MERGE_CHUNK = 1 << 16

# Minimum raw-item size the transport="auto" density probe runs on: the
# unique-cell ratio of a small batch says nothing about skew, and the
# probe itself (one host compress + unique over this prefix) must stay
# negligible next to shipping the batch.
_PROBE_SAMPLES = 1 << 16


class IngestStagingRing:
    """Depth-K reusable host staging slots for the transfer worker — the
    CellStagingRing idea (ops/commit.py) generalized to the raw
    (ids, values) wire.

    ``stage()`` copies a chunk into the next slot, pads the tail with id
    -1 (every ingest kernel drops it), and issues the async
    ``device_put`` — which returns before the H2D copy completes, so the
    upload of slot i overlaps the donated ingest dispatches still
    consuming slot i-1.  Before a slot is REUSED (depth stages later)
    its previous device arrays are ``block_until_ready``'d: a ready
    device array means its H2D copy has finished reading the host
    buffer, so overwriting the slot can never corrupt an in-flight
    transfer.  Depth 2 is the minimum for overlap; 3 keeps one slot
    filling, one in flight, one being consumed."""

    def __init__(self, slot_samples: int, depth: int = 3,
                 chunk_samples: Optional[int] = None):
        if depth < 2:
            raise ValueError(f"ring depth must be >= 2, got {depth}")
        if slot_samples < 1:
            raise ValueError(f"slot_samples must be >= 1, got {slot_samples}")
        self.slot_samples = int(slot_samples)
        # upload quantum: a partially-filled slot uploads only its prefix
        # rounded up to this (the dispatch loop consumes chunk_samples
        # slices) — a 1-batch item must not pay the full 8-batch slot on
        # the wire.  Default = whole slot.
        self.chunk_samples = int(chunk_samples or slot_samples)
        if not 1 <= self.chunk_samples <= self.slot_samples:
            raise ValueError(
                f"chunk_samples must be in [1, {self.slot_samples}]; "
                f"got {self.chunk_samples}"
            )
        self.depth = int(depth)
        self._ids = [
            np.full(self.slot_samples, -1, dtype=np.int32)
            for _ in range(depth)
        ]
        self._values = [
            np.zeros(self.slot_samples, dtype=np.float32)
            for _ in range(depth)
        ]
        self._inflight: list[Optional[tuple]] = [None] * depth
        self._next = 0
        self.uploads = 0
        self.bytes_uploaded = 0

    def stage(self, ids: np.ndarray, values: np.ndarray):
        """Copy one chunk (<= slot_samples) into the next slot and start
        its async upload; returns the (ids, values) device arrays."""
        n = len(ids)
        if n > self.slot_samples:
            raise ValueError(f"chunk of {n} exceeds slot {self.slot_samples}")
        i = self._next
        self._next = (i + 1) % self.depth
        prev = self._inflight[i]
        if prev is not None:
            self._inflight[i] = None
            for arr in prev:
                try:
                    arr.block_until_ready()
                except Exception:
                    # the old transfer errored — its batch was already
                    # requeued/shed by the failure path; the slot is free
                    pass
        slot_ids, slot_values = self._ids[i], self._values[i]
        slot_ids[:n] = ids
        slot_values[:n] = values
        chunk = self.chunk_samples
        padded = min(self.slot_samples, -(-n // chunk) * chunk)
        if n < padded:
            slot_ids[n:padded] = -1
            slot_values[n:padded] = 0.0
        # contiguous prefix view: only the chunk-rounded fill crosses the
        # wire, not the whole slot
        ids_dev = jax.device_put(slot_ids[:padded])
        values_dev = jax.device_put(slot_values[:padded])
        self._inflight[i] = (ids_dev, values_dev)
        self.uploads += 1
        self.bytes_uploaded += padded * (
            slot_ids.itemsize + slot_values.itemsize
        )
        return ids_dev, values_dev

    def drain(self) -> None:
        """Block until EVERY in-flight async upload has completed (or
        surfaced its failure), then release the slots.  ``stage()`` only
        waits for the slot it is about to reuse, so with the r13
        double-buffered dispatch loop up to ``depth`` uploads can still
        be in flight when the pipeline goes quiet — ``close()`` must
        drain them all before the final interval commits, or a host
        buffer could be torn down under a H2D copy still reading it.
        Failed transfers are swallowed like in ``stage()``: their batch
        was already requeued/shed by the failure path."""
        for i, prev in enumerate(self._inflight):
            if prev is None:
                continue
            self._inflight[i] = None
            for arr in prev:
                try:
                    arr.block_until_ready()
                except Exception:
                    pass


def local_histogram_fold(
    acc_local: jnp.ndarray,
    ids: jnp.ndarray,
    values: jnp.ndarray,
    rows_per_shard: int,
    bucket_limit: int,
    precision: int = PRECISION,
    ingest_path: str = "scatter",
) -> jnp.ndarray:
    """The sharded-ingest core, shared by every shard_map step: offset ids
    into this metric shard's row range (ids below it go negative, so
    sanitize before drop-mode scatter or they'd wrap to the last row),
    bucket the local sample shard, psum the dense histograms across the
    stream axis, and fold into the accumulator.  Must run inside
    shard_map on a ("stream", "metric") mesh.

    ``ingest_path`` names a CONCRETE per-batch kernel ("scatter", "sort",
    "hybrid", "matmul" — resolve "auto" outside the traced region): the
    duplicate-serialization economics that drive single-chip dispatch
    apply unchanged to the per-device local fold (a Zipf stream
    concentrates each shard's in-range samples on its hot rows), so the
    mesh path uses the same dispatched kernels.  Out-of-shard ids are
    sanitized far out of range, which every kernel drops."""
    from loghisto_tpu.ops.dispatch import ingest_step_fn

    shard = jax.lax.axis_index(METRIC_AXIS)
    local_ids = sanitize_ids(ids - shard * rows_per_shard)
    hist = ingest_step_fn(ingest_path)(
        jnp.zeros_like(acc_local), local_ids, values, bucket_limit,
        precision,
    )
    hist = jax.lax.psum(hist, STREAM_AXIS)
    return acc_local + hist


def make_distributed_step(
    mesh: Mesh,
    num_metrics: int,
    bucket_limit: int,
    percentile_values,
    precision: int = PRECISION,
    ingest_path: str = "auto",
    batch_size: int | None = None,
):
    """Build the jitted full aggregation step over a ("stream", "metric")
    mesh.

    Returns f(acc, ids, values) -> (new_acc, stats) where
      acc    int32 [num_metrics, num_buckets], sharded over metric rows
      ids    int32 [N], sharded over the stream axis
      values float32 [N], sharded over the stream axis
      stats  {"counts": [M] (metric-sharded), "sums": [M],
              "percentiles": [M, P]}

    Per device: bucket the local sample shard into a local dense histogram
    (dropping ids outside this device's metric rows), psum across the
    stream axis, fold into the accumulator, then extract statistics for
    the local metric rows.  All collectives are XLA-native and ride ICI.
    """
    n_metric = mesh.shape[METRIC_AXIS]
    if num_metrics % n_metric:
        raise ValueError(
            f"num_metrics={num_metrics} not divisible by metric axis "
            f"size {n_metric}"
        )
    rows_per_shard = num_metrics // n_metric
    ps = jnp.asarray(percentile_values, dtype=jnp.float32)
    # resolve dispatch OUTSIDE the traced region: choose on the global
    # metric count (duplicate-heaviness tracks global hotness), validate
    # on it too (stricter than the local shard shape, never looser).
    # mesh=True: auto must not pick pallas inside shard_map (ADVICE r2);
    # batch_size (the caller's per-step bound, when known) guards the
    # float32-exactness preconditions at selection time, not trace time.
    ingest_path = resolve_ingest_path(
        ingest_path, num_metrics,
        2 * bucket_limit + 1, mesh.devices.flat[0].platform,
        batch_size=batch_size, mesh=True,
    )

    def local_step(acc_local, ids, values):
        acc_local = local_histogram_fold(
            acc_local, ids, values, rows_per_shard, bucket_limit, precision,
            ingest_path=ingest_path,
        )
        stats = dense_stats(acc_local, ps, bucket_limit, precision)
        return acc_local, stats

    stats_specs = {
        "counts": P(METRIC_AXIS),
        "sums": P(METRIC_AXIS),
        "percentiles": P(METRIC_AXIS, None),
    }
    step = shard_map(
        local_step,
        mesh=mesh,
        in_specs=(P(METRIC_AXIS, None), P(STREAM_AXIS), P(STREAM_AXIS)),
        out_specs=(P(METRIC_AXIS, None), stats_specs),
    )
    return jax.jit(step, donate_argnums=0)


def make_sharded_accumulator(
    mesh: Mesh, num_metrics: int, num_buckets: int
) -> jnp.ndarray:
    """Zero accumulator laid out metric-sharded, stream-replicated
    (the canonical acc layout from parallel.mesh, shared with the
    sharded fused commit and checkpoint restore).  global_put keeps
    the placement collective-free when the mesh spans real
    jax.distributed processes (a plain device_put onto a
    non-addressable sharding runs an assert-equal collective the CPU
    drill backend lacks)."""
    import numpy as np

    from loghisto_tpu.parallel.mesh import acc_sharding
    from loghisto_tpu.parallel.multihost import global_put

    return global_put(
        np.zeros((num_metrics, num_buckets), dtype=np.int32),
        acc_sharding(mesh),
    )


def make_interval_distributed_step(
    mesh: Mesh,
    num_metrics: int,
    bucket_limit: int,
    percentile_values,
    precision: int = PRECISION,
    ingest_path: str = "auto",
    batch_size: int | None = None,
):
    """Interval-amortized distributed aggregation (VERDICT r3 item 3).

    ``make_distributed_step`` psums the full dense [rows, buckets]
    histogram across the stream axis EVERY batch — MESH_SCALE_r3.json
    measured that collective at 7.8x a single-device step for pure
    stream sharding.  But histogram merges are associative: nothing
    requires the cross-stream reduction before the interval boundary.
    Here each device folds batches into its own (stream, metric) partial
    with ZERO collectives, and the stream-axis psum runs once per
    ``collect`` — with B batches/interval the collective amortizes to
    1/B of the per-batch design's volume.

    Returns (ingest, collect, make_partial):

      make_partial() -> int32 [n_stream, num_metrics, num_buckets],
          sharded P(stream, metric, None) — each device owns one
          [1, rows_per_shard, num_buckets] block, so the partial costs
          one accumulator's worth of HBM per device, not n_stream.
      ingest(partial, ids, values) -> partial
          Collective-free per-batch fold (donated partial; ids/values
          stream-sharded like the per-batch design).
      collect(acc, partial) -> (acc, fresh_partial, stats)
          One psum over the stream axis, fold into the metric-sharded
          accumulator, stats on the merged rows; returns a zeroed
          partial so the caller just rebinds both carries.  r13: the
          collective is issued ASYNC — ``collect.start(acc, partial) ->
          (acc, stats)`` exposes the raw program, whose outputs no
          longer include the fresh partial, so folding the next batch
          into an independent ``make_partial()`` overlaps the psum
          instead of serializing behind it.

    Overflow contract (same int32 budget as the per-batch design): the
    partials and the accumulator are int32, and the worst case
    concentrates every sample in one cell — callers must collect before
    an interval ingests 2^31 samples globally (at the 1e9/s north-star
    rate that is a 2-second interval).  TPUAggregator enforces this with
    its host int64 spill; raw step-factory callers own the bound, like
    run_firehose's early-close guard.
    """
    n_metric = mesh.shape[METRIC_AXIS]
    n_stream = mesh.shape[STREAM_AXIS]
    if num_metrics % n_metric:
        raise ValueError(
            f"num_metrics={num_metrics} not divisible by metric axis "
            f"size {n_metric}"
        )
    rows_per_shard = num_metrics // n_metric
    ps = jnp.asarray(percentile_values, dtype=jnp.float32)
    ingest_path = resolve_ingest_path(
        ingest_path, num_metrics,
        2 * bucket_limit + 1, mesh.devices.flat[0].platform,
        batch_size=batch_size, mesh=True,
    )

    def local_ingest(partial_local, ids, values):
        from loghisto_tpu.ops.dispatch import ingest_step_fn

        shard = jax.lax.axis_index(METRIC_AXIS)
        local_ids = sanitize_ids(ids - shard * rows_per_shard)
        folded = ingest_step_fn(ingest_path)(
            partial_local[0], local_ids, values, bucket_limit, precision
        )
        return folded[None]

    ingest = jax.jit(
        shard_map(
            local_ingest,
            mesh=mesh,
            in_specs=(
                P(STREAM_AXIS, METRIC_AXIS, None),
                P(STREAM_AXIS),
                P(STREAM_AXIS),
            ),
            out_specs=P(STREAM_AXIS, METRIC_AXIS, None),
        ),
        donate_argnums=0,
    )

    def local_collect(acc_local, partial_local):
        merged = jax.lax.psum(partial_local[0], STREAM_AXIS)
        acc_local = acc_local + merged
        stats = dense_stats(acc_local, ps, bucket_limit, precision)
        return acc_local, stats

    stats_specs = {
        "counts": P(METRIC_AXIS),
        "sums": P(METRIC_AXIS),
        "percentiles": P(METRIC_AXIS, None),
    }
    # The psum program no longer RETURNS the fresh partial (pre-r13 it
    # zeroed the donated one inside the same program): a fresh partial
    # that is an output of the collect would make the next interval's
    # first fold a data-dependent consumer of the collective, so XLA
    # would serialize batch folds behind the psum.  Allocating it
    # independently (make_partial below) breaks that edge — issuing
    # ``collect_start`` and immediately folding the next batch overlaps
    # the stream-axis collective with shard-local work.  Bit-identity is
    # untouched: the int32 psum is order-independent (PR-8 invariant)
    # and a zero partial is a zero partial wherever it comes from.
    collect_start = jax.jit(
        shard_map(
            local_collect,
            mesh=mesh,
            in_specs=(
                P(METRIC_AXIS, None),
                P(STREAM_AXIS, METRIC_AXIS, None),
            ),
            out_specs=(
                P(METRIC_AXIS, None),
                stats_specs,
            ),
        ),
        donate_argnums=(0, 1),
    )

    def make_partial() -> jnp.ndarray:
        sharding = NamedSharding(mesh, P(STREAM_AXIS, METRIC_AXIS, None))
        return jax.device_put(
            jnp.zeros(
                (n_stream, num_metrics, 2 * bucket_limit + 1),
                dtype=jnp.int32,
            ),
            sharding,
        )

    def collect(acc, partial):
        """Compat form of the interval collect: issue the async psum
        program (donates acc and partial) and hand back the pre-r13
        (acc, fresh_partial, stats) triple.  The returned arrays are
        un-fetched jax futures; callers that want the r13 overlap use
        ``collect.start(acc, partial) -> (acc, stats)`` directly, grab a
        fresh partial from make_partial(), and fold the next batch while
        the collective is still in flight."""
        acc, stats = collect_start(acc, partial)
        return acc, make_partial(), stats

    collect.start = collect_start

    return ingest, collect, make_partial


class TPUAggregator:
    """Device-tier metric engine (the reference has no equivalent; this is
    the TPU execution backend the north star adds behind the subscription
    boundary).

    Two ways in:
      * `record_batch(ids, values)` / `record(name, value)` — direct
        firehose ingestion; batches buffer on host and flush to the device
        as fused compress+scatter-add steps.
      * `attach(metric_system)` — subscribe to the host MetricSystem's raw
        broadcast and merge each interval's pre-bucketed histograms into
        the device accumulator (weighted scatter-add), so existing callers
        get device-side percentile extraction without code changes.

    `collect()` extracts all statistics on device (one CDF-scan program),
    resets the accumulator, folds lifetime aggregates on host (python ints
    — immune to int32 overflow across intervals), and returns a
    ProcessedMetricSet with the standard naming scheme.
    """

    def __init__(
        self,
        num_metrics: int = 1024,
        config: MetricConfig = MetricConfig(),
        percentiles: Mapping[str, float] = DEFAULT_PERCENTILES,
        registry: Optional[MetricRegistry] = None,
        batch_size: int = 1 << 16,
        mesh: Optional[Mesh] = None,
        native_staging: bool = False,
        ingest_path: str = "auto",
        on_registry_full: str = "grow",
        max_metrics: Optional[int] = None,
        spill_threshold: int = 1 << 30,
        transport: str = "auto",
        storage: str = "auto",
        paged_config=None,
    ):
        """When `mesh` is given (a ("stream","metric") mesh from
        parallel.mesh.make_mesh), the dense accumulator is laid out
        metric-row-sharded across the mesh and every jitted step runs
        SPMD — XLA partitions the scatter-adds and the CDF scan row-wise
        and inserts the collectives.  num_metrics must divide evenly by
        the metric-axis size.

        `native_staging=True` stages record_batch samples in the C++
        lock-striped buffer (loghisto_tpu._native) instead of Python
        lists — writers release the GIL in the C call, and overflow sheds
        with an exposed drop counter.  Requires the native library; falls
        back (with a log line) when unavailable.

        `ingest_path` selects the device accumulation kernel:
          * "auto"     — (default) pick the measured-fastest kernel for
            (num_metrics, num_buckets, platform) via ops/dispatch.py
          * "scatter"  — XLA scatter-add (works everywhere)
          * "matmul"   — one-hot MXU matmul (small metric counts)
          * "sort"     — sort-deduplicated conflict-free scatter
            (ops/sort_ingest.py; built for TPU scatter semantics)
          * "multirow" — metric-tiled Pallas kernel (sorted/block-padded;
            single-device only, TPU-targeted, interpret-mode elsewhere)
        All three are bit-identical (tests/test_fast_paths.py,
        tests/test_pallas_multirow.py); they differ only in speed per
        configuration — benchmarks/device_paths.py measures them.

        `on_registry_full` defines the name-cardinality policy when a new
        name arrives with the registry at capacity (the reference admits
        new names forever, metrics.go:281-294):
          * "grow"  — (default) double the accumulator's metric rows (and
            the registry capacity) up to `max_metrics` (default 8x the
            initial num_metrics; doubling preserves mesh divisibility).
            Past max_metrics, samples for unseen names are shed with a
            counter (`tpu.RegistryShedSamples` gauge) — the library-wide
            shed-don't-block degradation (SURVEY.md §5.3).
          * "error" — raise RegistryFullError (round-1 behavior).

        `spill_threshold` bounds int32 accumulator overflow (SURVEY.md §7
        hard part (b)): once a single interval has ingested this many
        samples (the worst case concentrates ALL of them in one cell),
        the device accumulator is folded into a host int64 spill tensor
        and reset, without closing the interval.  collect() merges the
        spill back in and computes that interval's statistics in exact
        int64 on host.  The default (2^30) can never wrap: 2^30 ingested
        samples + one further flush round cannot reach 2^31 in any cell.

        `transport` picks how flush() ships staged samples to the device:
          * "raw"    — ship (id, value) pairs; the device kernel does the
            compression (8 bytes/sample on the wire).
          * "preagg" — compress + dedup on host first (C++ hash, the
            same codec bit-for-bit) and ship unique (id, bucket, count)
            cells via the weighted scatter — the wire carries O(unique
            cells) instead of O(samples), which for Zipf-shaped load is
            orders of magnitude less.  This is the same
            local-aggregate-before-network design as the multi-host psum
            merge, applied to the host->device hop.
          * "sparse" — ship raw staging unchanged, but fold each FLUSH
            on host (parallel native tier, NumPy fallback) into packed
            (id, bucket, count) triples and merge them with the weighted
            scatter — the raw transport's zero record-time cost with the
            preagg transport's O(unique cells) wire.  The fold runs on
            the transfer worker thread, overlapped with device work.
          * "auto"   — (default) start on "raw"; the transfer worker
            probes the first large batch's unique-cell density and
            switches to "sparse" when the load is skewed enough to pay
            for the fold (ops/dispatch.py SPARSE_DENSITY_CROSSOVER,
            capture-overridable).  "preagg" is never auto-picked: its
            record-time fold taxes producer threads, which only wins
            when producers aren't the bottleneck — a property no
            flush-side probe can observe.

        `storage` picks the accumulator backend (r14):
          * "dense" — the donated [M, B] int32 tensor (every row pays
            full bucket capacity in HBM and commit bytes).
          * "paged" — page pool + per-row page table + per-metric
            variable-resolution codecs (loghisto_tpu/paging.py): HBM
            and commit H2D track OCCUPIED buckets.  Requires the
            sparse packed-triple transport (pinned automatically when
            transport="auto"; explicit "raw"/"preagg" raises) and a
            single device (no mesh).
          * "auto"  — (default) resolve_storage_path: paged at high
            metric cardinality (PAGED_MIN_METRICS rows) where the
            dense tensor's HBM cost bites, dense below it — the
            declining reason lands in `storage_reason`.
        `paged_config` takes a paging.PagedStoreConfig (pool size,
        codec policy, overflow row)."""
        self.config = config
        self.num_metrics = num_metrics
        # explicit None check: an empty registry is falsy (it has __len__),
        # so `registry or ...` would silently discard a caller's registry
        self.registry = (
            registry if registry is not None
            else MetricRegistry(capacity=num_metrics)
        )
        if self.registry.capacity > num_metrics:
            raise ValueError(
                f"registry capacity {self.registry.capacity} exceeds "
                f"num_metrics {num_metrics}: names beyond the accumulator "
                "rows could never be aggregated"
            )
        for label in percentiles:
            try:
                if not isinstance(label % "name", str):
                    raise TypeError("renders to non-string")
            except (TypeError, ValueError) as e:
                raise ValueError(
                    f"percentile label {label!r} is not a valid %-format "
                    f"template for a metric name: {e}"
                ) from None
        self.percentiles = dict(percentiles)
        self.batch_size = batch_size

        # Two-lock split so producers never stall on device work
        # (SURVEY.md §7 hard part (c)):
        #   _lock     — host staging state (_pending_*, _native_staged);
        #               held only for list appends/drains, never across a
        #               device call.
        #   _dev_lock — device state (_acc, _spill, _interval_ingested,
        #               growth); held across device dispatches.
        # Never nested: every method releases one before taking the other,
        # so lock-ordering deadlocks are impossible by construction.
        self._lock = threading.Lock()
        self._dev_lock = threading.Lock()
        self._pending_ids: list[np.ndarray] = []
        self._pending_values: list[np.ndarray] = []
        self._pending_count = 0

        self._native_buf = None
        self._native_staged = 0
        # Worker-side re-buffer for batches a device failure (or the
        # retry cooldown) bounced back: appended chronologically by the
        # single FIFO transfer worker, so everything here is OLDER than
        # everything in _pending_* — flush drains requeue-first and the
        # oldest-first shed policy stays honest.  Guarded by _lock.
        self._requeue_ids: list[np.ndarray] = []
        self._requeue_values: list[np.ndarray] = []
        self._requeue_count = 0
        # Transfer pipeline (r6 tentpole): flush() is enqueue-only; this
        # FIFO + condition pair feeds a single transfer worker thread
        # that stages slots, issues async device_puts, and runs the
        # donated dispatches — so producers never block on device work,
        # and the upload of chunk k+1 overlaps the dispatch of chunk k.
        self._xfer_cv = threading.Condition()
        self._xfer_queue: collections.deque = collections.deque()
        self._xfer_queued_samples = 0  # samples sitting in the queue
        self._xfer_active = False  # worker is mid-item
        self._xfer_thread: Optional[threading.Thread] = None
        self._xfer_stop = False
        self._staging_ring: Optional[IngestStagingRing] = None
        self.staging_depth = 3
        # wire accounting for bytes/sample reporting (bench satellite)
        self._xfer_uploads = 0
        self._xfer_bytes = 0
        self._xfer_samples_shipped = 0
        # host-side retry buffer bound when the device is unreachable
        self.max_pending_samples = 32 * batch_size
        self.retry_cooldown = 1.0  # seconds between device retry attempts
        self._shed_samples = 0
        # guards _shed_samples, which is incremented from both the staging
        # side (_bound_pending_locked, under _lock) and the device side
        # (_on_device_failure_locked, under _dev_lock)
        self._shed_lock = threading.Lock()
        self._device_down_until = 0.0
        self._interval_ingested = 0  # samples in the live accumulator
        # immutable (epoch, cdf/counts/sums) handle over the live
        # accumulator, published by the fused committer's snapshot
        # dispatch; None whenever the accumulator was reset, grown,
        # spilled, or rebuilt — readers must treat None as "recompute"
        self.stats_snapshot = None
        # resilience (ISSUE 10), installed by TPUMetricSystem: the
        # supervisor ledgers bridge/worker restarts, the breaker counts
        # device failures (ONE count point: _on_device_failure_locked),
        # the injector scripts chaos faults (None = one attribute test
        # per hook site)
        self.supervisor = None
        self.device_breaker = None
        self.fault_injector = None
        # observability (ISSUE 9): flush/drain spans; swapped for a real
        # ring by TPUMetricSystem(observability=...)
        self.obs_recorder = NULL_RECORDER

        if on_registry_full not in ("grow", "error"):
            raise ValueError(
                f"on_registry_full={on_registry_full!r}: expected 'grow' "
                "or 'error'"
            )
        self.on_registry_full = on_registry_full
        self.max_metrics = (
            int(max_metrics) if max_metrics is not None
            else num_metrics * DEFAULT_GROWTH_FACTOR
        )
        if self.max_metrics < num_metrics:
            raise ValueError(
                f"max_metrics {self.max_metrics} < num_metrics {num_metrics}"
            )
        if not 0 < spill_threshold <= 1 << 30:
            raise ValueError(
                "spill_threshold must be in (0, 2^30]: the overflow "
                "guarantee needs threshold + one ingest chunk < 2^31"
            )
        if spill_threshold + batch_size >= 1 << 31:
            raise ValueError(
                f"spill_threshold {spill_threshold} + batch_size "
                f"{batch_size} >= 2^31: a single chunk between spill "
                "checks could wrap an int32 cell"
            )
        self.spill_threshold = int(spill_threshold)
        if ingest_path in ("sort", "sortscan", "matmul", "hybrid", "pallas"):
            # validate explicit choices BEFORE the accumulator allocation
            # below — the combined-key bound failing after a multi-GB
            # jnp.zeros is a worse failure mode than a raise inside the
            # traced ingest, which flush's shed-don't-block handling would
            # mask as a down device (platform is irrelevant here)
            resolve_ingest_path(
                ingest_path, num_metrics, config.num_buckets, "any",
                guard_metrics=self.max_metrics, batch_size=batch_size,
            )
        # int64 host fold of pre-spill interval counts (canonical dense
        # layout); engaged only when an interval exceeds spill_threshold
        self._spill: Optional[np.ndarray] = None
        self._spilled_samples = 0  # this interval's spilled count
        self._registry_shed_samples = 0  # lifetime, past-max_metrics names
        if native_staging:
            from loghisto_tpu import _native

            if _native.available():
                # 16 shards x 4*batch_size x 12B ~= 48 MB at the default
                # batch_size; scale with the workload, don't floor at 1M
                self._native_buf = _native.NativeIngestBuffer(
                    num_shards=16,
                    capacity_per_shard=max(batch_size * 4, 1 << 16),
                )
            else:
                import logging

                logging.getLogger("loghisto_tpu").warning(
                    "native staging requested but unavailable (%s); using "
                    "Python staging", _native.build_error(),
                )

        if transport not in ("auto", "raw", "preagg", "sparse"):
            raise ValueError(
                f"transport={transport!r}: expected 'auto', 'raw', "
                "'preagg', or 'sparse'"
            )
        # "auto" (r6): start on raw and let the transfer worker probe
        # the first large batch's cell density — skewed load switches to
        # the sparse transport at runtime (ops.dispatch.choose_transport
        # / SPARSE_DENSITY_CROSSOVER).  "preagg" stays an explicit
        # opt-in: its record-time fold trades producer-thread CPU for
        # flush latency, a workload property no flush-side probe sees.
        # storage backend (r14/r17): resolved BEFORE the transport
        # rewrite below because the storage choice pins the transport —
        # paged with the direct-to-paged fused kernel (r17) keeps RAW
        # (compress/encode/translate all run on device), paged without
        # it pins sparse (the page-table translate rides the host fold).
        from loghisto_tpu.ops.dispatch import (
            fused_paged_incapability,
            resolve_storage_path,
        )

        backend = jax.default_backend()
        self.fused_paged_reason = fused_paged_incapability(
            num_metrics, config.num_buckets, batch_size=batch_size,
            mesh=mesh is not None, transport=transport, platform=backend,
            crossover=(ingest_path == "auto"), mesh_obj=mesh,
        )
        fused_paged_ok = (
            self.fused_paged_reason is None
            and ingest_path in ("auto", "fused")
        )
        self.storage, self.storage_reason = resolve_storage_path(
            storage, num_metrics, config.num_buckets,
            backend, mesh=mesh is not None,
            transport=transport, fused_ok=fused_paged_ok,
            mesh_obj=mesh,
        )
        self.paged = None
        self.fused_paged = self.storage == "paged" and fused_paged_ok
        if self.storage == "paged":
            # fused path ingests raw; host-fold fallback pins sparse
            # (auto pins either way; incompatible explicit transports
            # raised inside resolve_storage_path)
            transport = "raw" if self.fused_paged else "sparse"
        self._transport_auto = transport == "auto"
        self.probe_density: Optional[float] = None
        if transport == "auto":
            transport = "raw"
        self.transport = transport
        self._cell_store = None
        # watermark: ship cells to the device mid-interval once the host
        # store holds this many (bounds host memory at ~16B/cell)
        self.max_host_cells = 1 << 22
        if transport == "preagg":
            from loghisto_tpu import _native as _nat

            # Sharded + double-buffered (VERDICT r2 item 2): producers
            # fold into per-thread shards at record time (the C fold runs
            # with the GIL released, so writer threads aggregate in
            # parallel), and draining swaps buffers per shard so the
            # O(capacity) scan never blocks ingest.  backend="auto"
            # degrades to the pure-NumPy store when no compiler built the
            # native library — preagg no longer requires one (r6).
            self._cell_store = _nat.ShardedCellStore(
                config.bucket_limit, config.precision, backend="auto"
            )
            if self._native_buf is not None:
                import logging

                logging.getLogger("loghisto_tpu").info(
                    "preagg transport folds samples into the cell store "
                    "at record time; the native staging buffer is unused"
                )
                self._native_buf = None

        self.mesh = mesh
        if mesh is not None:
            n_metric = mesh.shape[METRIC_AXIS]
            if num_metrics % n_metric:
                raise ValueError(
                    f"num_metrics={num_metrics} not divisible by the mesh "
                    f"metric axis ({n_metric})"
                )
        if self.storage == "paged":
            from loghisto_tpu.paging import PagedStore, PagedStoreConfig

            if ingest_path == "multirow":
                raise ValueError(
                    "ingest_path='multirow' needs the dense lane-padded "
                    "accumulator; paged storage keeps none (every paged "
                    "commit rides the packed sparse-triple scatter)"
                )
            # r18: a mesh shards the store itself — per-shard page
            # arenas, shard-local translate/scatter inside one
            # shard_map (the capability table's relaxed "mesh shape:"
            # edges pre-screened the divisibility constraints)
            self.paged = PagedStore(
                num_metrics,
                config.bucket_limit,
                config.precision,
                config=paged_config or PagedStoreConfig(),
                mesh=mesh,
            )
            # no dense [M, B] tensor exists in paged mode — the pool +
            # page table ARE the accumulator.  Every _acc touch below is
            # behind a `self.paged is not None` branch.
            self._acc = None
            if self.fused_paged:
                ingest_path = "fused_paged"
            elif ingest_path == "fused":
                raise ValueError(
                    "ingest_path='fused' with paged storage needs the "
                    "direct-to-paged fused kernel: "
                    f"{self.fused_paged_reason}"
                )
        elif mesh is not None:
            self._acc = make_sharded_accumulator(
                mesh, num_metrics, config.num_buckets
            )
        else:
            self._acc = jnp.zeros(
                (num_metrics, config.num_buckets), dtype=jnp.int32
            )
        if ingest_path == "auto":
            platform = (
                mesh.devices.flat[0].platform
                if mesh is not None
                else jax.default_backend()
            )
            # shared guard policy: growth can take the row space to
            # max_metrics, so auto validates shapes against the cap and
            # must not pick a kernel the grown shape would invalidate
            ingest_path = resolve_ingest_path(
                "auto", num_metrics, config.num_buckets, platform,
                guard_metrics=self.max_metrics, batch_size=batch_size,
                mesh=mesh is not None,
            )
        # identity for dense-layout paths; multirow slices its lane padding
        self._finalize_acc = lambda a: a
        # per-path zero-accumulator factory (layout differs by path)
        self._make_acc = self._fresh_dense_acc
        if ingest_path == "scatter":
            self._ingest = make_ingest_fn(
                config.bucket_limit, config.precision
            )
        elif ingest_path == "matmul":
            from loghisto_tpu.ops.matmul_hist import make_matmul_ingest_fn

            self._ingest = make_matmul_ingest_fn(
                config.bucket_limit, config.precision
            )
        elif ingest_path == "hybrid":
            from loghisto_tpu.ops.hybrid_hist import make_hybrid_ingest_fn

            self._ingest = make_hybrid_ingest_fn(
                config.bucket_limit, config.precision
            )
        elif ingest_path == "sort":
            # shape already validated (pre-allocation, against max_metrics)
            from loghisto_tpu.ops.sort_ingest import make_sort_ingest_fn

            self._ingest = make_sort_ingest_fn(
                config.bucket_limit, config.precision
            )
        elif ingest_path == "sortscan":
            from loghisto_tpu.ops.sort_ingest import make_sortscan_ingest_fn

            self._ingest = make_sortscan_ingest_fn(
                config.bucket_limit, config.precision
            )
        elif ingest_path == "pallas":
            self._ingest = self._make_dense_step_fn("pallas")
        elif ingest_path == "fused":
            # explicit selection: surface the correctness blockers with
            # their reason strings at construction (auto resolved them
            # above); the crossover is the operator's call here
            from loghisto_tpu.ops.dispatch import fused_ingest_incapability

            reason = fused_ingest_incapability(
                num_metrics, batch_size=batch_size,
                mesh=mesh is not None, crossover=False,
            )
            if reason is not None:
                raise ValueError(f"ingest_path='fused': {reason}")
            self._ingest = self._make_dense_step_fn("fused")
        elif ingest_path == "fused_paged":
            # direct-to-paged fused kernel (r17): dispatches run through
            # PagedStore.ingest_raw inside _dispatch_slot_locked — the
            # donated pool is the accumulator, so there is no dense
            # f(acc, ids, values) step fn to build here
            self._ingest = None
        elif ingest_path == "multirow":
            if mesh is not None:
                raise ValueError(
                    "ingest_path='multirow' is single-device (its dense "
                    "layout is lane-padded); use scatter with a mesh"
                )
            from loghisto_tpu.ops.pallas_multirow import make_multirow_ingest

            init, multirow_ingest, self._finalize_acc = make_multirow_ingest(
                num_metrics, config.bucket_limit, config.precision
            )
            self._ingest = multirow_ingest
            # lane-padded accumulator layout; the weighted host-bridge
            # ingest still works (dense buckets are the leading columns)
            self._make_acc = init
            self._acc = init()
        else:
            raise ValueError(
                f"unknown ingest_path {ingest_path!r}: expected 'auto', "
                "'scatter', 'matmul', 'sort', 'sortscan', 'hybrid', "
                "'fused', or 'multirow'"
            )
        self.ingest_path = ingest_path
        self._weighted_ingest = make_weighted_ingest_fn(config.bucket_limit)
        # Packed [n, 3] merge step — built unconditionally (not just for
        # preagg) because transport="auto" can switch to sparse at
        # runtime after the density probe; the kernel tier follows the
        # capture-overridable SPARSE_KERNEL switch.  Compilation is lazy
        # (first packed merge), so raw-only aggregators never pay for it.
        from loghisto_tpu.ops.sparse_ingest import make_sparse_ingest_fn

        self._packed_ingest = make_sparse_ingest_fn(config.bucket_limit)
        self._stats_fn = jax.jit(
            functools.partial(
                dense_stats,
                bucket_limit=config.bucket_limit,
                precision=config.precision,
            )
        )
        # lifetime aggregates on host: name id -> [sum, count]
        self._agg_lock = threading.Lock()
        self._agg: Dict[int, list] = {}
        self._last_aggregation_us = 0.0

        self._attached: Optional[tuple[MetricSystem, threading.Thread]] = None
        self._bridge_ch: Optional[Channel] = None
        self._bridge_stop = threading.Event()
        # serializes the bridge's eviction re-subscribe against detach():
        # without it, detach racing an eviction could strand a freshly
        # subscribed reader-less channel on the MetricSystem
        self._bridge_lock = threading.Lock()
        self._bridge_evictions = 0

    # -- direct ingestion ---------------------------------------------- #

    def record(self, name: str, value: float) -> None:
        self.record_batch(
            np.array([self._id_for(name)], dtype=np.int32),
            np.array([value], dtype=np.float32),
        )

    def _id_for(self, name: str, samples: int = 1) -> int:
        """Row id for a name, applying the on_registry_full policy: grow
        the row space geometrically up to max_metrics, then shed (-1 —
        every ingest kernel drops it) with a counter.  `samples` is how
        many samples ride on this lookup (merge_raw passes a histogram's
        whole interval count), so the shed gauge reports true loss."""
        try:
            return self.registry.id_for(name)
        except RegistryFullError:
            if self.on_registry_full == "error":
                raise
        with self._dev_lock:
            try:
                return self.registry.id_for(name)  # a racer may have grown
            except RegistryFullError:
                pass
            if self._grow_locked():
                return self.registry.id_for(name)
            first = self._registry_shed_samples == 0
            self._registry_shed_samples += samples
            if first:
                import logging

                logging.getLogger("loghisto_tpu").warning(
                    "metric registry exhausted at max_metrics=%d; samples "
                    "for further new names are shed (tpu.RegistryShedSamples"
                    " counts them)", self.max_metrics,
                )
            return -1

    def _make_dense_step_fn(self, path: str):
        """Jitted donated-accumulator wrapper over any dense-layout
        dispatched kernel (all paths share the [*, B] accumulator, so
        growth can swap kernels without touching the data)."""
        from loghisto_tpu.ops.dispatch import ingest_step_fn

        step = ingest_step_fn(path)
        bl, prec = self.config.bucket_limit, self.config.precision

        @functools.partial(jax.jit, donate_argnums=0)
        def ingest(acc, ids, values):
            return step(acc, ids, values, bl, prec)

        return ingest

    def _grow_row_unit(self) -> int:
        """Row-count granularity growth must preserve: the mesh metric
        axis (shard divisibility) or the multirow kernel's row tile."""
        if self.mesh is not None:
            return self.mesh.shape[METRIC_AXIS]
        if self.ingest_path == "multirow":
            return 8  # make_multirow_ingest's rows_tile default
        if self.ingest_path == "fused":
            return 8  # fused_ingest.ROWS_TILE: M must stay tile-divisible
        return 1

    def _grow_locked(self, target: Optional[int] = None) -> bool:
        """Grow the metric-row space in place (caller holds _dev_lock): pad
        the accumulator (and spill) with zero rows, re-shard in mesh mode,
        rebuild the shape-specialized multirow kernel (caller holds
        _dev_lock — growth mutates device state).  Returns False when
        no growth is possible (max_metrics reached, or the divisibility
        unit leaves no room).  All fallible work happens BEFORE any state
        is committed, so a failed grow leaves the aggregator untouched.
        Geometric growth bounds jit recompiles at log2(max/initial)."""
        old_m = self.num_metrics
        unit = self._grow_row_unit()
        new_m = min(
            target if target is not None else old_m * 2, self.max_metrics
        )
        new_m -= new_m % unit  # clamp may land off-grid; round down
        if new_m <= old_m:
            return False
        if self.paged is not None:
            # paged growth is a host-side page-table extension: no device
            # tensor is reallocated, no kernel is rebuilt, no data moves.
            self.paged.grow(new_m)
            self.num_metrics = new_m
            self.stats_snapshot = None
            self.registry.grow(new_m)
            return True
        # -- fallible section: build everything in locals first --
        make_acc, ingest, finalize = (
            self._make_acc, self._ingest, self._finalize_acc
        )
        new_path = self.ingest_path
        if self.ingest_path == "multirow":
            from loghisto_tpu.ops.pallas_multirow import make_multirow_ingest

            make_acc, ingest, finalize = make_multirow_ingest(
                new_m, self.config.bucket_limit, self.config.precision
            )
        elif self.ingest_path == "pallas":
            # the single-row kernel cannot cover more rows; swap to the
            # auto-dispatched dense-family kernel for the grown shape
            # (same [*, B] layout, so the data moves unchanged)
            platform = (
                self.mesh.devices.flat[0].platform
                if self.mesh is not None
                else jax.default_backend()
            )
            new_path = resolve_ingest_path(
                "auto", new_m, self.config.num_buckets, platform,
                guard_metrics=self.max_metrics, batch_size=self.batch_size,
                mesh=self.mesh is not None,
            )
            ingest = self._make_dense_step_fn(new_path)
        acc_np = np.asarray(self._acc)
        grown = np.zeros((new_m, acc_np.shape[1]), dtype=acc_np.dtype)
        grown[:old_m] = acc_np
        if self.mesh is not None:
            new_acc = jax.device_put(
                grown, NamedSharding(self.mesh, P(METRIC_AXIS, None))
            )
        else:
            new_acc = jnp.asarray(grown)
        # -- commit --
        self._make_acc, self._ingest, self._finalize_acc = (
            make_acc, ingest, finalize
        )
        self.ingest_path = new_path
        self._acc = new_acc
        self.num_metrics = new_m
        self.stats_snapshot = None  # row space changed; handle is stale
        self.registry.grow(new_m)
        if self._spill is not None:
            spill = np.zeros(
                (new_m, self._spill.shape[1]), dtype=self._spill.dtype
            )
            spill[:old_m] = self._spill
            self._spill = spill
        return True

    def _spill_fold_locked(self) -> None:
        """Fold the device accumulator into the host int64 spill tensor and
        reset it, WITHOUT closing the interval (caller holds _dev_lock).
        Keeps
        every per-cell device count below spill_threshold + one flush
        round — the int32 overflow guarantee."""
        if self.paged is not None:
            # decode pool -> host spill dict inside the store (exact:
            # spill cells keep native dense indices), zero the pool
            self.paged.spill_pool()
            self._spilled_samples += self._interval_ingested
            self._interval_ingested = 0
            self.stats_snapshot = None
            return
        acc_np = np.asarray(self._finalize_acc(self._acc), dtype=np.int64)
        if self._spill is None:
            self._spill = acc_np
        else:
            self._spill += acc_np
        self._acc = self._fresh_acc()
        self._spilled_samples += self._interval_ingested
        self._interval_ingested = 0
        self.stats_snapshot = None  # acc folded out; handle is stale

    def record_batch(self, ids: np.ndarray, values: np.ndarray) -> None:
        """Buffer a batch of (metric_id, value) samples; flushes to device
        when the buffered count reaches batch_size."""
        ids = np.asarray(ids, dtype=np.int32)
        values = np.asarray(values, dtype=np.float32)
        if ids.shape != values.shape:
            raise ValueError("ids and values must have the same shape")
        if self._cell_store is not None:
            # preagg direct fold (VERDICT r2 item 2): samples are touched
            # ONCE — compressed + deduped into this thread's cell shard
            # right here, with the GIL released inside the C fold.  No
            # staging lists, no concatenate, no second pass at flush; the
            # device sees one packed ship per interval (or watermark).
            self._preagg_record(ids, values)
            return
        if self._native_buf is not None:
            accepted = self._native_buf.record_batch(
                ids, values.astype(np.float64)
            )
            # keep the documented auto-flush contract in the native path;
            # counted under the lock (an unsynchronized += can lose
            # updates and *miss* flushes) and only for accepted samples
            with self._lock:
                self._native_staged += accepted
                should_flush = self._native_staged >= self.batch_size
            if should_flush:
                self.flush()
            return
        with self._lock:
            self._pending_ids.append(ids)
            self._pending_values.append(values)
            self._pending_count += len(ids)
            # while the device is down (flush cooldown-gated), the buffer
            # must stay bounded
            self._bound_pending_locked()
            should_flush = self._pending_count >= self.batch_size
        if should_flush:
            self.flush()

    def _fresh_dense_acc(self) -> jnp.ndarray:
        if self.mesh is not None:
            return make_sharded_accumulator(
                self.mesh, self.num_metrics, self.config.num_buckets
            )
        return jnp.zeros(
            (self.num_metrics, self.config.num_buckets), dtype=jnp.int32
        )

    def _fresh_acc(self) -> jnp.ndarray:
        """Zero accumulator in THIS ingest path's layout (the multirow
        path is lane-padded; rebuilding the wrong shape after a device
        failure would permanently break ingestion)."""
        return self._make_acc()

    def _buffered_samples(self) -> int:
        """Samples currently buffered on host awaiting a device attempt
        (requeued failures + fresh pending).  Unsynchronized sum — a
        monitoring/test convenience, exact whenever the transfer queue
        is idle."""
        return self._requeue_count + self._pending_count

    @property
    def pending_samples(self) -> int:
        """Public monitoring alias for the host-buffered sample count —
        the health watchdog's ingest-backpressure signal (compared
        against ``max_pending_samples``)."""
        return self._buffered_samples()

    def _bound_pending_locked(self) -> None:
        """Enforce max_pending_samples over the WHOLE host buffer
        (requeue + pending) by shedding the OLDEST samples — the requeue
        lists hold strictly older content than _pending (single FIFO
        worker), so they shed first.  Partial arrays are sliced so no
        more than the overflow is dropped.  Caller holds self._lock."""
        overflow = (
            self._requeue_count + self._pending_count
            - self.max_pending_samples
        )
        for ids_list, values_list, count_attr in (
            (self._requeue_ids, self._requeue_values, "_requeue_count"),
            (self._pending_ids, self._pending_values, "_pending_count"),
        ):
            while overflow > 0 and ids_list:
                head = ids_list[0]
                if len(head) <= overflow:
                    ids_list.pop(0)
                    values_list.pop(0)
                    setattr(
                        self, count_attr,
                        getattr(self, count_attr) - len(head),
                    )
                    with self._shed_lock:
                        self._shed_samples += len(head)
                    overflow -= len(head)
                else:
                    ids_list[0] = head[overflow:]
                    values_list[0] = values_list[0][overflow:]
                    setattr(
                        self, count_attr,
                        getattr(self, count_attr) - overflow,
                    )
                    with self._shed_lock:
                        self._shed_samples += overflow
                    overflow = 0

    def flush(self, force: bool = False) -> None:
        """Hand buffered samples to the transfer pipeline.

        flush() is ENQUEUE-ONLY (r6 tentpole): it drains host staging
        under _lock, enqueues one transfer item, and returns — the
        transfer worker thread stages ring slots, issues the async
        device_puts, and runs the donated dispatches, so producers never
        block on device work and the upload of chunk k+1 overlaps the
        dispatch of chunk k.  ``force=True`` (collect / checkpoint /
        close) additionally WAITS until the whole queue has drained —
        after a forced flush, device state reflects every prior record.

        Device failures follow SURVEY.md §5.3 shed-don't-block: the
        worker re-buffers the unapplied remainder on host (bounded,
        oldest shed first) and retries are cooldown-gated so a down
        device costs one attempt per retry_cooldown, not one per
        record."""
        with self.obs_recorder.span("ingest.flush"):
            self._flush_impl(force)

    def _flush_impl(self, force: bool) -> None:
        if self._cell_store is not None:
            # preagg: samples were folded at record time; flushing means
            # shipping the deduped cells.  Mid-interval ships happen only
            # past the watermark (the wire carries each interval's unique
            # cells once); `force` (collect/checkpoint) always ships.
            if not force and len(self._cell_store) < self.max_host_cells:
                return
            packed = self._cell_store.drain_packed_all()
            if len(packed):
                self._enqueue_xfer(("packed", packed, None, 0, force))
            if force:
                self.wait_transfers()
            return
        if self._native_buf is not None:
            with self._lock:
                self._native_staged = 0
            nids, nvalues = self._native_buf.drain()
            if len(nids):
                with self._lock:
                    self._pending_ids.append(nids)
                    self._pending_values.append(nvalues.astype(np.float32))
                    self._pending_count += len(nids)
                    self._bound_pending_locked()
        with self._lock:
            if not self._requeue_count and not self._pending_count:
                ids = values = None
            elif (
                not force
                and time.monotonic() < self._device_down_until
            ):
                # _device_down_until is written under _dev_lock; this read
                # is a benign race (cooldown is a heuristic, not an
                # invariant)
                return  # device cooling down; keep buffering
            elif (
                not force
                and self._xfer_queued_samples >= self.max_pending_samples
            ):
                # transfer queue saturated (device slower than producers):
                # leave samples in the bounded host buffer, where the
                # oldest-first shed policy applies, instead of growing
                # the queue without bound
                return
            else:
                # requeue first: strictly older than anything in _pending
                ids = np.concatenate(self._requeue_ids + self._pending_ids)
                values = np.concatenate(
                    self._requeue_values + self._pending_values
                )
                self._requeue_ids, self._requeue_values = [], []
                self._requeue_count = 0
                self._pending_ids, self._pending_values = [], []
                self._pending_count = 0
        if ids is not None:
            kind = "fold" if self.transport == "sparse" else "raw"
            self._enqueue_xfer((kind, ids, values, len(ids), force))
        if not force:
            return
        self.wait_transfers()
        # An item already in flight when we drained may have failed
        # DURING the wait and requeued its samples — invisible to the
        # drain above, yet recorded strictly before this flush, so the
        # forced barrier owes them one forced (cooldown-bypassing)
        # attempt, exactly as the synchronous flush gave them.  One extra
        # pass only: if that attempt also fails, the device is down and
        # the samples stay buffered (same bounded-attempts contract as
        # the worker path).
        with self._lock:
            if not self._requeue_count and not self._pending_count:
                return
            ids = np.concatenate(self._requeue_ids + self._pending_ids)
            values = np.concatenate(
                self._requeue_values + self._pending_values
            )
            self._requeue_ids, self._requeue_values = [], []
            self._requeue_count = 0
            self._pending_ids, self._pending_values = [], []
            self._pending_count = 0
        kind = "fold" if self.transport == "sparse" else "raw"
        self._enqueue_xfer((kind, ids, values, len(ids), True))
        self.wait_transfers()

    def merge_packed(self, packed: np.ndarray, wait: bool = False) -> None:
        """Public packed-triple ingest: merge an int32 ``[n, 3]``
        (row_id, codec_bucket, count) cell array — already in THIS
        aggregator's row-id space — through the transfer worker's packed
        path (same device merge, spill guarantees, and wire accounting
        as the sparse transport's fold).  The federation receiver's
        drain; scatter-adds are order-independent, so interleaving with
        local ingest cannot change the aggregate.  ``wait`` blocks until
        the transfer queue drains (tests; production callers pipeline)."""
        packed = np.ascontiguousarray(packed, dtype=np.int32)
        if packed.ndim != 2 or packed.shape[1] != 3:
            raise ValueError(
                f"packed cell array must be [n, 3] (id, bucket, count); "
                f"got shape {packed.shape}"
            )
        if len(packed):
            self._enqueue_xfer(("packed", packed, None, 0, False))
        if wait:
            self.wait_transfers()

    # -- transfer pipeline ---------------------------------------------- #

    def _enqueue_xfer(self, item: tuple) -> None:
        """Append one (kind, a, b, n_samples, force) item to the transfer
        queue, lazily (re)spawning the worker thread."""
        with self._xfer_cv:
            if self._xfer_thread is None or not self._xfer_thread.is_alive():
                if (
                    self._xfer_thread is not None
                    and not self._xfer_stop
                    and self.supervisor is not None
                ):
                    # the worker died abnormally (a clean close() sets
                    # _xfer_stop first); the lazy respawn below is its
                    # restart — count it on the shared ledger so the
                    # thread_restarted invariant sees it
                    self.supervisor.note_external_restart(
                        "loghisto-tpu-xfer"
                    )
                self._xfer_stop = False
                self._xfer_thread = threading.Thread(
                    target=self._xfer_worker,
                    daemon=True,
                    name="loghisto-tpu-xfer",
                )
                self._xfer_thread.start()
            self._xfer_queue.append(item)
            self._xfer_queued_samples += item[3]
            self._xfer_cv.notify_all()

    def wait_transfers(self, timeout: Optional[float] = None) -> bool:
        """Block until the transfer queue is empty AND the worker is idle
        (every enqueued flush has reached the device, the spill, or the
        requeue buffer).  The synchronization barrier behind
        flush(force=True); tests and checkpointing rely on it.  Returns
        False on timeout."""
        deadline = (
            None if timeout is None else time.monotonic() + timeout
        )
        with self._xfer_cv:
            while self._xfer_queue or self._xfer_active:
                remaining = (
                    None if deadline is None
                    else deadline - time.monotonic()
                )
                if remaining is not None and remaining <= 0:
                    return False
                self._xfer_cv.wait(remaining)
        return True

    def close(self) -> None:
        """Drain everything and stop the transfer worker, in two phases.
        flush(force) drains the host buffers and the transfer QUEUE —
        but NOT the staging ring: with the r13 double-buffered pipeline,
        up to ring-depth async uploads can still be in flight after the
        queue empties (stage() only waits for the slot it is about to
        reuse).  Phase two below drains those in-flight slots under
        _dev_lock (ring.drain()), restoring exact count conservation —
        nothing staged is dropped.  Then the worker is signalled down
        and joined.  The aggregator stays usable: a later flush lazily
        re-spawns the worker."""
        self.flush(force=True)
        # r13 double-buffering means up to ring-depth async uploads can
        # still be in flight after the queue drains (stage() only waits
        # for the slot it reuses, and a worker killed between items —
        # e.g. by an agg.xfer_worker chaos fault — leaves its staged
        # slot undispatched).  Drain them under _dev_lock so the final
        # interval commit can never race a live H2D copy.
        with self._dev_lock:
            ring = self._staging_ring
            if ring is not None:
                ring.drain()
        with self._xfer_cv:
            self._xfer_stop = True
            self._xfer_cv.notify_all()
            t = self._xfer_thread
        if t is not None:
            t.join(timeout=10.0)

    def _xfer_worker(self) -> None:
        while True:
            inj = self.fault_injector
            if inj is not None:
                # chaos hook BETWEEN items (no queue bookkeeping is in
                # flight here): a scripted crash kills the worker — the
                # next enqueue lazily respawns it, counted on the
                # supervisor ledger; a scripted wedge blocks it, backing
                # the queue up into the max_pending_samples shed bound
                inj.check("agg.xfer_worker")
            with self._xfer_cv:
                while not self._xfer_queue and not self._xfer_stop:
                    self._xfer_cv.wait()
                if not self._xfer_queue:  # stop requested, queue drained
                    self._xfer_active = False
                    self._xfer_cv.notify_all()
                    return
                item = self._xfer_queue.popleft()
                self._xfer_active = True
            try:
                with self.obs_recorder.span("ingest.drain"):
                    self._process_xfer_item(item)
            except Exception:  # pragma: no cover - defensive
                import logging

                logging.getLogger("loghisto_tpu").exception(
                    "transfer worker failed processing a %s item", item[0]
                )
            finally:
                with self._xfer_cv:
                    self._xfer_queued_samples -= item[3]
                    self._xfer_active = False
                    self._xfer_cv.notify_all()

    def _process_xfer_item(self, item: tuple) -> None:
        kind, a, b, n, force = item
        if kind == "packed":
            self._xfer_uploads += 1
            self._xfer_bytes += a.nbytes
            self._xfer_samples_shipped += int(a[:, 2].sum(dtype=np.int64))
            self._ship_packed(a)
            return
        # raw staging content ("raw" ships samples, "fold" packs first).
        # Cooldown gate runs HERE, per item: after a failure arms the
        # cooldown, queued non-forced items bounce straight to the
        # requeue buffer without a device attempt — one attempt per
        # cooldown window, in arrival order.
        if not force and time.monotonic() < self._device_down_until:
            self._requeue_raw(a, b)
            return
        if kind == "fold" or self._maybe_switch_sparse(a, b, n):
            self._process_fold(a, b, n)
            return
        self._process_raw(a, b, n)

    def _requeue_raw(self, ids: np.ndarray, values: np.ndarray) -> None:
        if not len(ids):
            return
        with self._lock:
            self._requeue_ids.append(ids)
            self._requeue_values.append(values)
            self._requeue_count += len(ids)
            self._bound_pending_locked()

    def _maybe_switch_sparse(
        self, ids: np.ndarray, values: np.ndarray, n: int
    ) -> bool:
        """transport="auto" density probe (runs once, on the worker, on
        the first raw item large enough to be representative): fold the
        WHOLE item to unique (row, bucket) cells with the host codec and
        switch to the sparse transport when the load is skewed past the
        crossover.  The fold must see the full item, not a prefix —
        PAGED_STORE_r14 measured the old 64Ki-prefix probe reading
        density 0.92 on a 100k-row skew because a prefix shorter than
        the interval cannot observe within-interval duplication (most
        prefix samples land on distinct cells even when every cell
        repeats hundreds of times across the batch).  Returns True when
        THIS item should already take the fold path."""
        if not self._transport_auto or self.probe_density is not None:
            return False
        if n < _PROBE_SAMPLES:
            return False
        from loghisto_tpu import _native
        from loghisto_tpu.ops import dispatch as _dispatch

        buckets = _native.compress_np_host(
            values, self.config.precision
        ).astype(np.int64)
        keep = ids >= 0
        kept = int(keep.sum())
        if not kept:
            return False
        keys = (ids[keep].astype(np.int64) << 16) | (
            buckets[keep] + 32768
        )
        self.probe_density = len(np.unique(keys)) / kept
        platform = (
            self.mesh.devices.flat[0].platform
            if self.mesh is not None
            else jax.default_backend()
        )
        chosen = _dispatch.choose_transport(
            platform, density=self.probe_density
        )
        if chosen != self.transport:
            import logging

            logging.getLogger("loghisto_tpu").info(
                "transport auto-probe: cell density %.3f <= crossover "
                "%.3f; switching to the sparse packed-triple transport",
                self.probe_density, _dispatch.SPARSE_DENSITY_CROSSOVER,
            )
            self.transport = chosen
        return self.transport == "sparse"

    def _process_fold(
        self, ids: np.ndarray, values: np.ndarray, n: int
    ) -> None:
        """Sparse transport: fold the raw batch into packed triples on
        this worker thread (GIL-released parallel native tier, NumPy
        fallback) and merge them via the packed scatter.  Failures past
        this point spill exactly (cells are finished aggregates — no
        retry queue needed)."""
        from loghisto_tpu import _native

        try:
            packed = _native.fold_packed(
                ids, values,
                bucket_limit=self.config.bucket_limit,
                precision=self.config.precision,
            )
        except MemoryError:
            # can't build the fold table: ship the batch raw instead of
            # losing it (same wire contract, just more bytes)
            self._process_raw(ids, values, n)
            return
        self._xfer_uploads += 1
        self._xfer_bytes += packed.nbytes
        self._xfer_samples_shipped += n
        self._ship_packed(packed)

    def _dispatch_slot_locked(self, slot: tuple) -> Optional[int]:
        """Consume one staged super-chunk (caller holds _dev_lock):
        wait for the slot's async upload, record its "ingest.upload"
        span (issue -> ready, i.e. the real H2D window — which overlaps
        the PREVIOUS slot's "ingest.dispatch" span when the pipeline is
        doing its job; benchmarks/fused_ingest_bench.py computes the
        overlap percentage from exactly these two span streams), then
        run the donated per-batch_size dispatches with the per-chunk
        spill check.  Returns the absolute sample offset where work
        failed, or None when the slot fully applied."""
        soff, send, ids_dev, values_dev, t_issue = slot
        bs = self.batch_size
        rec = self.obs_recorder
        try:
            ids_dev.block_until_ready()
            values_dev.block_until_ready()
        except Exception:
            self._on_device_failure_locked()
            return soff
        rec.record("ingest.upload", t_issue, time.perf_counter_ns())
        with rec.span("ingest.dispatch"):
            for off in range(soff, send, bs):
                lo = off - soff
                try:
                    inj = self.fault_injector
                    if inj is not None:
                        # chaos hook inside the per-chunk net: an
                        # injected device failure takes the organic
                        # recovery (cooldown + requeue remainder)
                        inj.check("agg.ingest")
                    if self.paged is not None:
                        # direct-to-paged (r17): ONE Pallas dispatch
                        # straight into the donated pool (the batch was
                        # page-prepared on the worker before staging)
                        self.paged.ingest_raw(
                            ids_dev[lo:lo + bs], values_dev[lo:lo + bs]
                        )
                    else:
                        self._acc = self._ingest(
                            self._acc,
                            ids_dev[lo:lo + bs],
                            values_dev[lo:lo + bs],
                        )
                    self._device_down_until = 0.0
                    self._interval_ingested += min(bs, send - off)
                    # int32 overflow guarantee: the check must run per
                    # chunk — a force-flush of a large host backlog
                    # could otherwise push a hot cell past 2^31
                    # (worst case all samples hit one cell; threshold
                    # + batch_size < 2^31 is validated at construction)
                    if self._interval_ingested >= self.spill_threshold:
                        self._spill_fold_locked()
                except Exception:
                    self._on_device_failure_locked()
                    return off
        return None

    def _process_raw(
        self, ids: np.ndarray, values: np.ndarray, n: int
    ) -> None:
        """Raw transport device loop (worker thread): a true
        double-buffered pipeline over the staging ring (r13).  Slot k+1
        is staged — its async ``device_put`` issued — BEFORE slot k's
        dispatches run, so the H2D copy of the next super-chunk proceeds
        while the donated ingest dispatches consume the current one; the
        per-slot "ingest.upload" / "ingest.dispatch" spans recorded by
        _dispatch_slot_locked prove the overlap.  Failures preserve
        exact sample conservation: everything before the failing offset
        was applied, everything from it on is requeued from the host
        arrays (which also covers a staged-but-undispatched next slot)."""
        if self.paged is not None and not self.fused_paged:
            # reached only through _process_fold's MemoryError fallback
            # (non-fused paged pins transport="sparse").  There is no
            # dense device loop to fall back to, and re-entering the
            # fold would repeat the failed allocation — compress on the
            # host and take the exact spill instead.  Rare by
            # construction; correctness over throughput.
            from loghisto_tpu._native import compress_np_host

            buckets = compress_np_host(
                values.astype(np.float64), self.config.precision
            )
            np.clip(
                buckets, -self.config.bucket_limit,
                self.config.bucket_limit, out=buckets,
            )
            with self._dev_lock:
                self._spill_add_cells_locked(
                    ids, buckets, np.ones(len(ids), dtype=np.int64)
                )
            self._xfer_samples_shipped += n
            return
        if self.paged is not None:
            # fused direct-to-paged (r17): assign codecs and map every
            # page this batch touches in one vectorized host pass on
            # THIS worker thread, BEFORE anything uploads — the
            # staged/dispatched loop below never consults the host page
            # table, so allocation can never block a dispatch.  ids come
            # back rewritten (saturation -> overflow row or -1 + exact
            # host spill), so a post-failure requeue of these arrays
            # stays count-exact: spilled counts were applied here
            # exactly once and their ids are already -1.
            with self._dev_lock:
                ids, _ = self.paged.prepare_batch(ids, values)
        bs = self.batch_size
        ring = self._staging_ring
        if ring is None or ring.slot_samples != 8 * bs:
            ring = self._staging_ring = IngestStagingRing(
                8 * bs, depth=self.staging_depth, chunk_samples=bs
            )
        super_bs = ring.slot_samples
        retry_off = None
        with self._dev_lock:
            pending: Optional[tuple] = None  # staged, not yet dispatched
            for soff in range(0, n, super_bs):
                send = min(soff + super_bs, n)
                t_issue = time.perf_counter_ns()
                try:
                    ids_dev, values_dev = ring.stage(
                        ids[soff:send], values[soff:send]
                    )
                    nxt = (soff, send, ids_dev, values_dev, t_issue)
                except Exception:
                    self._on_device_failure_locked()
                    nxt = None
                if pending is not None:
                    fail = self._dispatch_slot_locked(pending)
                    pending = None
                    if fail is not None:
                        retry_off = fail
                        break
                if nxt is None:
                    retry_off = soff
                    break
                pending = nxt
            if retry_off is None and pending is not None:
                retry_off = self._dispatch_slot_locked(pending)
        self._xfer_samples_shipped += (
            n if retry_off is None else retry_off
        )
        if retry_off is not None and retry_off < n:
            import logging

            # the traceback was already logged inside the except handler
            # (_on_device_failure_locked); this is just the retry notice
            logging.getLogger("loghisto_tpu").warning(
                "buffering %d samples for retry (cooldown %.1fs)",
                n - retry_off, self.retry_cooldown,
            )
            self._requeue_raw(ids[retry_off:n], values[retry_off:n])

    def transport_stats(self) -> dict:
        """Wire accounting for the active transport: uploads, bytes
        actually moved host->device (ring slots count their padded
        size — that IS what transfers), and samples those bytes carried.
        bench.py / benchmarks/h2d_bench.py derive bytes/sample from
        this."""
        ring = self._staging_ring
        return {
            "transport": self.transport,
            "probe_density": self.probe_density,
            "uploads": self._xfer_uploads + (ring.uploads if ring else 0),
            "bytes_uploaded": self._xfer_bytes
            + (ring.bytes_uploaded if ring else 0),
            "samples_shipped": self._xfer_samples_shipped,
        }

    def _preagg_record(self, ids: np.ndarray, values: np.ndarray) -> None:
        """Fold one batch into the calling thread's cell shard (the preagg
        hot path — native hash, the same codec bit-for-bit as the device
        kernel).  The device sees traffic only on force-flush (interval
        boundaries: collect/checkpoint) or past the max_host_cells
        watermark — so the wire carries each interval's UNIQUE cells
        once, however many samples they absorbed, and a thin host->device
        link no longer caps sample throughput.  On device failure the
        cells fold into the host int64 spill — they are already exact
        aggregates, so nothing needs a retry queue."""
        consumed = self._cell_store.add(ids, values)
        if consumed < len(ids):
            # shard table could not grow: the consumed prefix is folded
            # exactly once, so ship everything held (drained tables keep
            # their capacity, now at low load) and retry ONLY the
            # remainder — no double count
            self._ship_packed(self._cell_store.drain_packed_all())
            rest = self._cell_store.add(ids[consumed:], values[consumed:])
            if consumed + rest < len(ids):
                dropped = len(ids) - consumed - rest
                with self._shed_lock:
                    self._shed_samples += dropped
                import logging

                logging.getLogger("loghisto_tpu").error(
                    "cell store cannot grow even after draining; "
                    "shed %d samples", dropped,
                )
        if len(self._cell_store) >= self.max_host_cells:
            self.flush()

    def _ship_packed(self, packed: np.ndarray) -> None:
        """Merge drained packed cells into the device accumulator (one
        int32 [m, 3] (id, bucket, count) wire array; ingest.cpp
        lh_cells_drain_packed)."""
        if not len(packed):
            return
        # Hard guard on the wire contract BEFORE anything reaches the
        # kernel: a 2-column array would not raise under jit (static OOB
        # gathers clamp), it would silently misread keys as row ids —
        # the exact corruption the int32 [m, 3] format exists to prevent.
        if packed.ndim != 2 or packed.shape[1] != 3:
            raise ValueError(
                f"packed cell array must be [m, 3] (id, bucket, count); "
                f"got shape {packed.shape}"
            )
        if packed.dtype != np.int32:
            raise ValueError(
                f"packed cell array must be int32 (no-x64 JAX would "
                f"silently truncate int64); got {packed.dtype}"
            )
        with self._dev_lock:
            try:
                self._merge_packed_locked(packed)
            except Exception:
                # chunk-dispatch failures are handled (and partially
                # spilled) inside _merge_packed_locked; reaching here
                # means the merge failed BEFORE applying any cell (e.g.
                # the spill fold's device read) — spilling the full set
                # is exact, not a double count
                self._on_device_failure_locked()
                self._spill_add_packed_locked(packed)

    def _spill_add_packed_locked(self, packed: np.ndarray) -> None:
        from loghisto_tpu._native import unpack_cells

        uids, ubuckets, uweights = unpack_cells(packed)
        self._spill_add_cells_locked(
            uids, ubuckets.astype(np.int64), uweights
        )

    def _merge_packed_locked(self, packed: np.ndarray) -> None:
        """Packed twin of _merge_cells_locked: same spill guarantees and
        per-chunk accounting, one device transfer per chunk.  Caller
        holds _dev_lock."""
        n = len(packed)
        weights = packed[:, 2]
        total = int(weights.sum(dtype=np.int64))
        if (
            self._interval_ingested + total >= self.spill_threshold
            or (n and int(weights.max()) >= 1 << 30)
        ):
            self._spill_fold_locked()
            self._spill_add_packed_locked(packed)
            return
        if self.paged is not None:
            # the store translates (row, codec bucket, count) against the
            # page table and pads to COMMIT_CHUNK internally; cells that
            # can't get a page go to the store's exact host spill
            try:
                self._interval_ingested += self.paged.commit(packed)
            except Exception:
                self._on_device_failure_locked()
                self._spill_add_packed_locked(packed)
                return
            self._device_down_until = 0.0
            return
        for off in range(0, n, _MERGE_CHUNK):
            take = min(_MERGE_CHUNK, n - off)
            pad = np.empty((_MERGE_CHUNK, 3), dtype=np.int32)
            pad[:, 0] = -1  # negative id: dropped by sanitize_ids
            pad[:, 1] = 0
            pad[:, 2] = 0
            pad[:take] = packed[off:off + take]
            try:
                self._acc = self._packed_ingest(self._acc, pad)
            except Exception:
                self._on_device_failure_locked()
                self._spill_add_packed_locked(packed[off:])
                return
            # success-only reset, mirroring the raw flush loop
            self._device_down_until = 0.0
            self._interval_ingested += int(
                weights[off:off + take].sum(dtype=np.int64)
            )

    def _on_device_failure_locked(self) -> None:
        """Device-failure bookkeeping (caller holds _dev_lock, and must
        call from INSIDE the except handler so the traceback below is
        still live): log the failure, arm the retry cooldown, and recover
        the donated accumulator if the failed dispatch consumed it —
        continuing to use a deleted array would brick every later
        flush."""
        import logging

        logging.getLogger("loghisto_tpu").exception(
            "device ingest dispatch failed"
        )
        self._device_down_until = time.monotonic() + self.retry_cooldown
        if getattr(self._acc, "is_deleted", lambda: False)():
            logging.getLogger("loghisto_tpu").error(
                "device failure consumed the donated accumulator; %d "
                "already-ingested samples of this interval are lost",
                self._interval_ingested,
            )
            with self._shed_lock:
                self._shed_samples += self._interval_ingested
            self._interval_ingested = 0
            self._acc = self._fresh_acc()
        if self.paged is not None and self.paged.pool_deleted():
            logging.getLogger("loghisto_tpu").error(
                "device failure consumed the donated page pool; %d "
                "already-ingested samples of this interval are lost",
                self._interval_ingested,
            )
            with self._shed_lock:
                self._shed_samples += self._interval_ingested
            self._interval_ingested = 0
            self.paged.reset_pool()
        self.stats_snapshot = None
        if self.device_breaker is not None:
            # the SINGLE breaker count point per physical failure: the
            # committer's fused recovery, the bridge merge, and the
            # transfer worker all funnel through this handler, so the
            # consumer hooks fanning out from here must never count
            self.device_breaker.record_failure("aggregator")

    # -- host-tier bridge ----------------------------------------------- #

    def merge_raw(self, raw: RawMetricSet) -> None:
        """Merge one host-tier interval (sparse bucket maps) into the dense
        device accumulator via fixed-width weighted scatter launches.

        Cells are padded to _MERGE_CHUNK (dropped id -1) so ONE compiled
        executable — pre-warmed by _bridge_warmup — serves every merge;
        a typical interval is a single launch, a 10k-metric worst case a
        handful (the round-1 fixed-4096-chunk loop serialized ~hundreds
        under the ingest lock, VERDICT r1 item 9).

        Counts too large for the int32 device path (or intervals that
        would push a cell past the spill threshold) are folded directly
        into the int64 host spill instead — exact at any magnitude."""
        ids, bidx, weights = [], [], []
        for name, bucket_counts in raw.histograms.items():
            mid = self._id_for(name, samples=sum(bucket_counts.values()))
            if mid < 0:
                continue  # shed (already counted, with its true weight)
            for bucket, count in bucket_counts.items():
                ids.append(mid)
                bidx.append(bucket)  # codec bucket; clipped to range below
                weights.append(count)
        if not ids:
            return
        ids_np = np.asarray(ids, dtype=np.int32)
        bidx_np = np.asarray(bidx, dtype=np.int64)
        weights_np = np.asarray(weights, dtype=np.int64)
        with self._dev_lock:
            self._merge_cells_locked(ids_np, bidx_np, weights_np)

    def _spill_add_cells_locked(
        self,
        ids_np: np.ndarray,
        bidx_np: np.ndarray,
        weights_np: np.ndarray,
    ) -> None:
        """Add (id, codec bucket, weight) cells to the host int64 spill —
        exact at any magnitude.  Caller holds _dev_lock."""
        if self.paged is not None:
            # paged mode keeps its spill as a sparse host dict inside the
            # store (a dense [M, B] int64 tensor at 1M rows would defeat
            # the whole backend); same exactness contract
            keep = (ids_np >= 0) & (ids_np < self.num_metrics)
            dense_idx = (
                np.clip(
                    bidx_np[keep],
                    -self.config.bucket_limit,
                    self.config.bucket_limit,
                )
                + self.config.bucket_limit
            )
            self.paged.spill_cells(
                ids_np[keep].astype(np.int64), dense_idx, weights_np[keep]
            )
            self._spilled_samples += int(weights_np[keep].sum())
            return
        if self._spill is None:
            self._spill = np.zeros(
                (self.num_metrics, self.config.num_buckets), dtype=np.int64
            )
        keep = (ids_np >= 0) & (ids_np < self.num_metrics)
        dense_idx = (
            np.clip(
                bidx_np[keep],
                -self.config.bucket_limit,
                self.config.bucket_limit,
            )
            + self.config.bucket_limit
        )
        np.add.at(
            self._spill,
            (ids_np[keep].astype(np.int64), dense_idx),
            weights_np[keep],
        )
        self._spilled_samples += int(weights_np[keep].sum())

    def _merge_cells_locked(
        self,
        ids_np: np.ndarray,
        bidx_np: np.ndarray,
        weights_np: np.ndarray,
    ) -> None:
        """Merge weighted (id, codec bucket, count) cells into the device
        accumulator via ONE padded scatter launch, or the host spill when
        the int32 guarantee requires it.  Caller holds _dev_lock."""
        n = len(ids_np)
        total = int(weights_np.sum())
        if (
            self._interval_ingested + total >= self.spill_threshold
            or (n and int(weights_np.max()) >= 1 << 30)
        ):
            # giant merge: keep the int32 guarantee by applying it on
            # the host spill in exact int64
            self._spill_fold_locked()
            self._spill_add_cells_locked(ids_np, bidx_np, weights_np)
            return
        if self.paged is not None:
            # repack to the triple wire and ride the paged commit path.
            # int32 casts are safe here: the guard above bounds every
            # weight below 1 << 30 and ids/buckets are clipped in commit.
            packed = np.empty((n, 3), dtype=np.int32)
            packed[:, 0] = ids_np
            packed[:, 1] = np.clip(
                bidx_np, -self.config.bucket_limit, self.config.bucket_limit
            )
            packed[:, 2] = weights_np
            self._merge_packed_locked(packed)
            return
        # ONE fixed launch shape (not a power-of-two ladder): every merge
        # reuses the single executable _bridge_warmup pre-compiled, so no
        # interval — whatever its cell count — ever pays a cold XLA
        # compile mid-bridge.  Typical intervals fit one launch; a
        # 10k-metric worst case is a handful, not the round-1 hundreds.
        # Accounting is PER CHUNK and device failure is handled here:
        # chunks already applied stay counted in _interval_ingested (or
        # are shed with it if the failed dispatch consumed the donated
        # accumulator), and ONLY the unapplied remainder folds into the
        # exact host spill — no sample is ever lost or double-counted.
        for off in range(0, n, _MERGE_CHUNK):
            take = min(_MERGE_CHUNK, n - off)
            ids_pad = np.full(_MERGE_CHUNK, -1, dtype=np.int32)
            bidx_pad = np.zeros(_MERGE_CHUNK, dtype=np.int32)
            weights_pad = np.zeros(_MERGE_CHUNK, dtype=np.int32)
            ids_pad[:take] = ids_np[off:off + take]
            bidx_pad[:take] = bidx_np[off:off + take]
            weights_pad[:take] = weights_np[off:off + take]
            try:
                self._acc = self._weighted_ingest(
                    self._acc, ids_pad, bidx_pad, weights_pad
                )
            except Exception:
                self._on_device_failure_locked()
                self._spill_add_cells_locked(
                    ids_np[off:], bidx_np[off:], weights_np[off:]
                )
                return
            # success-only reset, mirroring the raw flush loop — a failed
            # chunk's cooldown must survive this merge returning normally
            self._device_down_until = 0.0
            self._interval_ingested += int(weights_np[off:off + take].sum())

    def _bridge_warmup(self) -> None:
        """Pre-compile the weighted-ingest executable at THE merge shape
        (all ids dropped — numerically a no-op).  _merge_cells_locked
        always launches exactly _MERGE_CHUNK-sized chunks, so this one
        compile covers every future merge: without it the bridge's FIRST
        merge_raw pays the cold XLA compile (tens of seconds) while the
        host reaper keeps ticking, fills the freshly subscribed channel,
        and strike-evicts it (metrics.go:565-581 semantics) before the
        bridge ever processes an interval."""
        if self.paged is not None:
            with self._dev_lock:
                self.paged.warmup()
                if self.fused_paged:
                    # one all-dropped compile at THE staging chunk shape
                    # — every fused dispatch launches exactly batch_size
                    # samples, so this covers all of them
                    self.paged.warmup_fused(self.batch_size)
            return
        ids = np.full(_MERGE_CHUNK, -1, dtype=np.int32)
        zeros = np.zeros(_MERGE_CHUNK, dtype=np.int32)
        with self._dev_lock:
            self._acc = self._weighted_ingest(self._acc, ids, zeros, zeros)

    def attach(self, ms: MetricSystem, channel_capacity: int = 8) -> None:
        """Subscribe to a MetricSystem's raw broadcast; every interval's
        histograms are merged into the device accumulator on a bridge
        thread (the subscription boundary of the north star).

        The bridge survives strike-eviction: if a long device stall fills
        the channel and the reaper closes it, queued intervals are still
        drained (Channel.get drains before raising), the stall's dropped
        intervals stay dropped (shed-don't-block), and the bridge
        re-subscribes on a fresh channel (`tpu.BridgeEvictions` counts
        occurrences) instead of dying silently."""
        if self._attached is not None:
            raise RuntimeError("already attached")
        self._bridge_warmup()
        stop = threading.Event()
        ch = Channel(channel_capacity)
        ms.subscribe_to_raw_metrics(ch)
        self._bridge_ch = ch
        self._bridge_stop = stop

        def bridge():
            nonlocal ch
            while not stop.is_set():
                try:
                    raw = ch.get()
                except ChannelClosed:
                    with self._bridge_lock:
                        # detach() sets stop BEFORE taking this lock, so
                        # checking under it guarantees we never subscribe
                        # a channel detach won't see
                        if stop.is_set():
                            return
                        self._bridge_evictions += 1
                        ch = Channel(channel_capacity)
                        ms.subscribe_to_raw_metrics(ch)
                        self._bridge_ch = ch
                    import logging

                    logging.getLogger("loghisto_tpu").warning(
                        "bridge channel was strike-evicted (device stall?);"
                        " re-subscribed (eviction #%d)",
                        self._bridge_evictions,
                    )
                    continue
                try:
                    self.merge_raw(raw)
                except Exception:  # pragma: no cover - defensive
                    import logging

                    logging.getLogger("loghisto_tpu").exception(
                        "device merge failed for interval %s", raw.time
                    )

        if self.supervisor is not None:
            # a crashed bridge restarts with capped backoff; the clean
            # stop-event return ends the thread for good
            t = self.supervisor.spawn(bridge, "loghisto-tpu-bridge")
        else:
            t = threading.Thread(
                target=bridge, daemon=True, name="loghisto-tpu-bridge"
            )
            t.start()
        self._attached = (ms, t)

    def detach(self) -> None:
        if self._attached is None:
            return
        ms, t = self._attached
        self._bridge_stop.set()
        with self._bridge_lock:
            ch = self._bridge_ch
            self._bridge_ch = None
        if ch is not None:
            ms.unsubscribe_from_raw_metrics(ch)
            ch.close()
        # a supervised handle also needs its restart loop stopped, or a
        # backoff nap could outlive the join below
        stop_fn = getattr(t, "stop", None)
        if stop_fn is not None:
            stop_fn()
        t.join(timeout=5.0)
        self._attached = None

    # -- collection ----------------------------------------------------- #

    def collect(self, reset: bool = True) -> ProcessedMetricSet:
        """Extract statistics for every registered metric on device and
        return them with the standard naming scheme."""
        self.flush(force=True)
        labels, ps = [], []
        for label, p in self.percentiles.items():
            if 0.0 <= p <= 1.0:
                labels.append(label)
                ps.append(p)
        t0 = time.perf_counter()
        # Only the snapshot/swap needs the ingest lock; the device stats
        # round-trip runs outside it so producers never stall on collection.
        # (With reset=False the accumulator keeps flowing, so it must be
        # copied under the lock — a later flush() would otherwise donate
        # the very buffer stats are reading.)
        if self.paged is not None:
            # the paged stats program runs the per-codec gathered
            # extraction inside the store (sparse_cells_stats —
            # percentiles are bit-identical to the dense selection), with
            # the store's exact host spill already folded in, so no dense
            # combine step exists on this branch
            with self._dev_lock:
                stats = self.paged.stats(
                    np.asarray(ps, dtype=np.float64), reset=reset
                )
                if reset:
                    self._interval_ingested = 0
                    self._spilled_samples = 0
                    self.stats_snapshot = None
        else:
            with self._dev_lock:
                acc = self._acc
                spill = self._spill
                if reset:
                    # zeros_like preserves the NamedSharding in mesh mode
                    self._acc = jnp.zeros_like(acc)
                    self._interval_ingested = 0
                    self._spill = None
                    self._spilled_samples = 0
                    self.stats_snapshot = None
                else:
                    acc = acc + 0  # defensive copy; donation-safe snapshot
                    spill = None if spill is None else spill.copy()
            from loghisto_tpu.utils.trace import maybe_capture

            if spill is not None:
                # overflow-spill interval: counts exceed int32 on device,
                # so the whole extraction runs in exact int64 on host
                combined = spill + np.asarray(
                    self._finalize_acc(acc), dtype=np.int64
                )
                stats = dense_stats_np(
                    combined,
                    np.asarray(ps, dtype=np.float64),
                    self.config.bucket_limit,
                    self.config.precision,
                )
            else:
                with maybe_capture("loghisto_collect"):
                    stats = self._stats_fn(
                        self._finalize_acc(acc),
                        np.asarray(ps, dtype=np.float32),
                    )
        counts = np.asarray(stats["counts"])
        sums = np.asarray(stats["sums"])
        pcts = np.asarray(stats["percentiles"])
        self._last_aggregation_us = (time.perf_counter() - t0) * 1e6

        names = self.registry.names()
        # a concurrent grow() may have registered names beyond the rows of
        # this snapshot; they belong to the next interval
        names = names[: len(counts)]
        metrics: Dict[str, float] = {}
        with self._agg_lock:
            if reset:
                agg_view = self._agg  # interval closes: fold for real
            else:
                # peek: report lifetime+current without mutating, so
                # repeated collect(reset=False) can never double-fold
                agg_view = {
                    mid: list(entry) for mid, entry in self._agg.items()
                }
            # Fold EVERY nonzero row into the lifetime store, named or
            # not: record_batch with raw unregistered ids is a supported
            # pattern (checkpoints identity-map such rows), so a reset
            # must not discard their history — it surfaces as soon as
            # the row's name is registered.  Reporting stays name-gated.
            for mid in np.nonzero(counts)[0]:
                mid = int(mid)
                count = int(counts[mid])
                total = float(sums[mid])
                if mid < len(names) and names[mid] is not None:
                    name = names[mid]
                    metrics[f"{name}_count"] = float(count)
                    metrics[f"{name}_sum"] = total
                    metrics[f"{name}_avg"] = total / count
                    for label, value in zip(labels, pcts[mid]):
                        metrics[label % name] = float(value)
                # int seed: go_compat accumulates exact integers like the
                # reference's uint64 store; float mode promotes naturally.
                entry = agg_view.setdefault(mid, [0, 0])
                if self.config.go_compat:
                    # same uint64 semantics as the host tier's store
                    from loghisto_tpu.metrics import _UINT64_MASK

                    entry[0] = (entry[0] + int(total)) & _UINT64_MASK
                else:
                    entry[0] += total
                entry[1] += count
            for mid, entry in agg_view.items():
                name = names[mid] if mid < len(names) else None
                if name is None or entry[1] <= 0:
                    continue
                if self.config.go_compat:
                    avg = float(int(entry[0]) // int(entry[1]))
                else:
                    avg = entry[0] / entry[1]
                metrics[f"{name}_agg_avg"] = avg
                metrics[f"{name}_agg_count"] = float(entry[1])
                metrics[f"{name}_agg_sum"] = float(entry[0])

        import datetime as _dt

        return ProcessedMetricSet(
            time=_dt.datetime.now(tz=_dt.timezone.utc), metrics=metrics
        )

    # -- gauges ---------------------------------------------------------- #

    def register_device_gauges(self, ms: MetricSystem) -> None:
        """Register TPU gauges on a MetricSystem: HBM use and the last
        device aggregation time (SURVEY.md §5.5)."""

        def hbm_bytes() -> float:
            try:
                stats = jax.devices()[0].memory_stats()
                return float((stats or {}).get("bytes_in_use", 0))
            except Exception:
                return 0.0

        ms.register_gauge_func("tpu.HbmBytesInUse", hbm_bytes)
        ms.register_gauge_func(
            "tpu.LastAggregationUs", lambda: self._last_aggregation_us
        )
        if self._native_buf is not None:
            ms.register_gauge_func(
                "tpu.StagingDropped",
                lambda: float(self._native_buf.dropped),
            )
        ms.register_gauge_func(
            "tpu.SamplesShed", lambda: float(self._shed_samples)
        )
        ms.register_gauge_func(
            "tpu.BridgeEvictions", lambda: float(self._bridge_evictions)
        )
        ms.register_gauge_func(
            "tpu.RegistryShedSamples",
            lambda: float(self._registry_shed_samples),
        )
        ms.register_gauge_func(
            "tpu.SpilledSamples", lambda: float(self._spilled_samples)
        )
        if self.paged is not None:
            ms.register_gauge_func(
                "tpu.PagedOccupiedPages",
                lambda: float(self.paged.occupied_pages),
            )
            ms.register_gauge_func(
                "tpu.PagedFreePages", lambda: float(self.paged.free_pages)
            )
            ms.register_gauge_func(
                "tpu.PagedHbmBytes", lambda: float(self.paged.hbm_bytes())
            )
            ms.register_gauge_func(
                "tpu.PagedSpilledCells",
                lambda: float(self.paged.spilled_cells),
            )
            ms.register_gauge_func(
                "tpu.PagedLastCommitH2DBytes",
                lambda: float(self.paged.last_h2d_bytes),
            )
            # paging.* family (ISSUE 18): the per-shard arena view the
            # /healthz pool_saturation invariant alerts on.  Saturation
            # is shard-local — one hot metric shard spills while the
            # pod-wide tpu.PagedFreePages still looks roomy
            ms.register_gauge_func(
                "paging.PoolSaturation",
                lambda: float(self.paged.pool_saturation()),
            )
            ms.register_gauge_func(
                "paging.ShardFreePagesMin",
                lambda: float(min(self.paged.shard_free_pages())),
            )
            for k in range(self.paged._n_shards):
                ms.register_gauge_func(
                    f"paging.Shard{k}Occupancy",
                    lambda k=k: float(self.paged.shard_occupancy()[k]),
                )
            ms.register_gauge_func(
                "paging.AllocatedPages",
                lambda: float(self.paged.allocated_pages),
            )

            def _alloc_rate(state={"n": 0, "t": None}):
                # pages/s since the previous scrape: cumulative counts
                # need dashboard-side deltas; the reaper cadence makes
                # this self-describing instead
                import time as _time

                now = _time.monotonic()
                n = int(self.paged.allocated_pages)
                last_n, last_t = state["n"], state["t"]
                state["n"], state["t"] = n, now
                if last_t is None or now <= last_t:
                    return 0.0
                return max(0.0, (n - last_n) / (now - last_t))

            ms.register_gauge_func("paging.PageAllocRate", _alloc_rate)
            ms.register_gauge_func(
                "paging.SpilledCells",
                lambda: float(self.paged.spilled_cells),
            )
