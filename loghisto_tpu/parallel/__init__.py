"""Distributed aggregation: device meshes, shard_map steps, the
TPUAggregator runtime, and multi-host initialization."""

from loghisto_tpu.parallel.aggregator import (
    TPUAggregator,
    make_distributed_step,
    make_interval_distributed_step,
    make_sharded_accumulator,
)
from loghisto_tpu.parallel.mesh import METRIC_AXIS, STREAM_AXIS, make_mesh

__all__ = [
    "METRIC_AXIS",
    "STREAM_AXIS",
    "TPUAggregator",
    "make_distributed_step",
    "make_interval_distributed_step",
    "make_mesh",
    "make_sharded_accumulator",
]
