"""Sketch model families: dense log-bucket histograms (the core model),
t-digest, and HyperLogLog — all mergeable, all expressed as static-shape
JAX ops so they jit and shard."""

from loghisto_tpu.models.loghist import LogHistogram
from loghisto_tpu.models import hll, moments, tdigest

__all__ = ["LogHistogram", "hll", "moments", "tdigest"]
