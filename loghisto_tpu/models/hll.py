"""HyperLogLog cardinality sketch as JAX ops (BASELINE.json configs[3]).

Estimates the number of *distinct* values in a stream — the one statistic
log-bucket histograms cannot provide.  Batch insertion is a hash +
segment-max over 2^p registers, so it jits, vectorizes, and (like the
histogram and t-digest) merges associatively: merge = elementwise register
max, which rides the same mesh collectives (pmax over the stream axis).

Uses a 32-bit murmur-style finalizer over the float bit pattern (JAX
default configs lack uint64), giving reliable estimates up to ~1e6
distinct values at the default p=14 (2^14 registers, ~0.8% relative
error); beyond that the 32-bit hash space itself starts to saturate.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class HLLConfig:
    p: int = 14  # 2^p registers

    def __post_init__(self):
        if not 4 <= self.p <= 18:
            raise ValueError("p must be in [4, 18]")

    @property
    def num_registers(self) -> int:
        return 1 << self.p


def empty(config: HLLConfig = HLLConfig()) -> jnp.ndarray:
    return jnp.zeros(config.num_registers, dtype=jnp.int32)


def _hash32(x: jnp.ndarray) -> jnp.ndarray:
    """Murmur3-finalizer-style avalanche over float32 bit patterns."""
    h = jax.lax.bitcast_convert_type(
        jnp.asarray(x, dtype=jnp.float32), jnp.uint32
    )
    h = h ^ (h >> 16)
    h = h * jnp.uint32(0x85EBCA6B)
    h = h ^ (h >> 13)
    h = h * jnp.uint32(0xC2B2AE35)
    h = h ^ (h >> 16)
    return h


@functools.partial(jax.jit, static_argnames=("p",))
def _insert(registers, values, p, n_valid):
    m = 1 << p
    h = _hash32(values)
    idx = (h & jnp.uint32(m - 1)).astype(jnp.int32)
    # padded entries (index >= n_valid) route to a scrap register
    valid = jnp.arange(values.shape[0]) < n_valid
    idx = jnp.where(valid, idx, m)
    rest = h >> p
    # rho: position of the first set bit in the remaining (32-p) bits,
    # counting from 1; all-zero rest gets the maximum 32-p+1.
    width = 32 - p
    bits = jnp.arange(width, dtype=jnp.uint32)
    set_at = (rest[:, None] >> bits[None, :]) & jnp.uint32(1)
    first = jnp.argmax(set_at, axis=1).astype(jnp.int32)
    any_set = set_at.any(axis=1)
    rho = jnp.where(any_set, first + 1, width + 1)
    maxes = jax.ops.segment_max(rho, idx, num_segments=m + 1)[:m]
    maxes = jnp.maximum(maxes, 0)  # segment_max fills empty with -inf/min
    return jnp.maximum(registers, maxes)


def insert(
    registers: jnp.ndarray, values, config: HLLConfig = HLLConfig()
) -> jnp.ndarray:
    """Add a batch of values to the sketch.  Batches pad to the next
    power of two (padding masked out), so arbitrary batch sizes reuse
    O(log N) compiled executables."""
    values = jnp.asarray(values, dtype=jnp.float32)
    n = values.shape[0]
    padded = 1 << max(0, (int(n) - 1).bit_length())
    if padded != n:
        values = jnp.concatenate(
            [values, jnp.zeros(padded - n, dtype=jnp.float32)]
        )
    return _insert(registers, values, config.p, n)


def merge(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Union of two sketches — elementwise max (use lax.pmax on a mesh)."""
    return jnp.maximum(a, b)


@jax.jit
def estimate(registers: jnp.ndarray) -> jnp.ndarray:
    """Distinct-count estimate with linear-counting small-range correction."""
    m = registers.shape[0]
    alpha = 0.7213 / (1.0 + 1.079 / m)
    inv = jnp.sum(jnp.exp2(-registers.astype(jnp.float32)))
    raw = alpha * m * m / inv
    zeros = jnp.sum(registers == 0)
    linear = m * jnp.log(m / jnp.maximum(zeros, 1).astype(jnp.float32))
    use_linear = (raw <= 2.5 * m) & (zeros > 0)
    return jnp.where(use_linear, linear, raw)
