"""t-digest sketch as static-shape JAX ops (BASELINE.json configs[3]).

A TPU-shaped reformulation of Dunning's merging t-digest: centroids live in
fixed-size arrays ``means[C], weights[C]`` (unused slots weight 0), and a
batch insert is

    concatenate -> sort by mean -> k-scale clustering -> segment-sum

which is fully vectorized and deterministic (no data-dependent loops, so it
jits and shards).  The k1 scale function ``k(q) = (delta / 2pi) *
asin(2q - 1)`` bounds cluster count by ~delta while keeping tail clusters
small — preserving extreme-percentile accuracy, the same design goal as the
log-bucket histogram codec.

Unlike the log-histogram (lossless counts, bounded relative error), the
t-digest trades exactness for adaptivity: it needs no a-priori value range.
Both sketches merge associatively, so the same psum/mesh machinery applies
(merge = insert the other digest's centroids as weighted samples).
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class TDigestConfig:
    # 512 centroid slots (static shape): 4 KB of state.  Raised from 256
    # with the power-law tail interpolation (VERDICT r2 item 8) — the
    # pair holds heavy-tail p9999 error under 10% (pareto a=1.5: 41%
    # at 256/linear -> ~6% at 512/power-law; ACCURACY.md), where the
    # extra slots buy tail clusters the k1 scale keeps small.
    capacity: int = 512
    # compression parameter; the k1 scale spans delta/2 clusters, so the
    # default fills ~80% of capacity (delta = 1.6 * capacity)
    delta: float = 0.0  # 0 -> derived from capacity

    def __post_init__(self):
        if self.capacity < 16:
            raise ValueError("capacity must be >= 16")
        if self.delta == 0.0:
            # fill ~80% of capacity, bounded so the two reserved extreme
            # singleton slots (+1 rounding slot) always fit
            object.__setattr__(
                self,
                "delta",
                min(1.6 * self.capacity, 2.0 * (self.capacity - 3)),
            )
        if self.delta < 8:
            raise ValueError("delta must be >= 8")
        if self.delta / 2 + 3 > self.capacity:
            raise ValueError(
                f"delta={self.delta} needs ~{int(self.delta // 2) + 3} "
                f"cluster slots, more than capacity={self.capacity}"
            )


def empty(config: TDigestConfig = TDigestConfig()):
    """(means, weights) of an empty digest."""
    return (
        jnp.zeros(config.capacity, dtype=jnp.float32),
        jnp.zeros(config.capacity, dtype=jnp.float32),
    )


def _k_scale(q: jnp.ndarray, delta: float) -> jnp.ndarray:
    q = jnp.clip(q, 0.0, 1.0)
    return (delta / (2.0 * jnp.pi)) * jnp.arcsin(2.0 * q - 1.0)


def _compress(means, weights, capacity: int, delta: float):
    """Cluster sorted centroids by k-scale index and segment-reduce.

    The lowest and highest populated entries are forced into their own
    singleton clusters (slots 0 and capacity-1) — Dunning's extreme-
    centroid rule.  Because a singleton's mean is the value itself, the
    digest's observed min and max survive every compression EXACTLY, and
    tail quantiles interpolate toward the true max instead of a smeared
    cluster mean (the p9999 accuracy fix; see ACCURACY.md)."""
    total = jnp.maximum(weights.sum(), 1e-30)
    # midpoint quantile of each centroid
    cum = jnp.cumsum(weights) - weights / 2.0
    q = cum / total
    k = _k_scale(q, delta)
    cluster = jnp.floor(k - _k_scale(jnp.float32(0.0), delta)).astype(jnp.int32)
    # interior clusters live in [1, capacity-2]; 0 and capacity-1 are the
    # reserved extreme singletons
    cluster = jnp.clip(cluster + 1, 1, capacity - 2)
    n = weights.shape[0]
    pos = jnp.arange(n)
    n_pop = (weights > 0).sum()
    cluster = jnp.where(pos == 0, 0, cluster)
    cluster = jnp.where((pos == n_pop - 1) & (pos > 0), capacity - 1, cluster)
    # zero-weight slots: park them in the last cluster with zero weight
    cluster = jnp.where(weights > 0, cluster, capacity - 1)
    new_w = jax.ops.segment_sum(weights, cluster, num_segments=capacity)
    new_mw = jax.ops.segment_sum(
        weights * means, cluster, num_segments=capacity
    )
    new_m = jnp.where(new_w > 0, new_mw / jnp.maximum(new_w, 1e-30), 0.0)
    return new_m, new_w


@functools.partial(jax.jit, static_argnames=("capacity", "delta"))
def _insert(means, weights, values, sample_weights, capacity, delta):
    all_m = jnp.concatenate([means, values])
    all_w = jnp.concatenate([weights, sample_weights])
    # sort by mean, zero-weight entries pushed to the end
    key = jnp.where(all_w > 0, all_m, jnp.inf)
    order = jnp.argsort(key)
    sm, sw = all_m[order], all_w[order]
    # Small-N exactness: while every populated centroid fits in the slot
    # array, keep them as singletons — the digest is EXACT below
    # ~capacity samples (quantiles interpolate the raw data), and k-scale
    # smearing only begins once clustering is actually necessary.
    # Populated entries sort to the front, so truncation is lossless in
    # that branch.
    n_pop = (sw > 0).sum()
    return jax.lax.cond(
        n_pop <= capacity,
        lambda: (sm[:capacity], sw[:capacity]),
        lambda: _compress(sm, sw, capacity, delta),
    )


def _pad_pow2(arr: "np_or_jnp", fill: float):
    """Pad a 1-D array to the next power of two so jit compiles O(log N)
    executables instead of one per distinct batch length."""
    import numpy as np

    n = arr.shape[0]
    padded = 1 << max(0, (int(n) - 1).bit_length())
    if padded == n:
        return arr
    return jnp.concatenate(
        [jnp.asarray(arr), jnp.full(padded - n, fill, dtype=jnp.float32)]
    )


def insert(
    means, weights, values, sample_weights=None,
    config: TDigestConfig = TDigestConfig(),
):
    """Insert a batch of samples (optionally weighted) into the digest.
    Batches are padded to the next power of two with weight-0 entries, so
    arbitrary batch sizes reuse O(log N) compiled executables."""
    values = jnp.asarray(values, dtype=jnp.float32)
    # Library-wide NaN/inf policy (matches the codec: NaN pins to the zero
    # bucket, magnitudes saturate): NaN -> 0.0, +/-inf -> float32 extremes.
    # Unsanitized, a NaN/inf mean would sort past the zero-weight +inf
    # sentinel keys in _insert and be silently dropped from the count.
    values = jnp.nan_to_num(
        values,
        nan=0.0,
        posinf=jnp.finfo(jnp.float32).max,
        neginf=jnp.finfo(jnp.float32).min,
    )
    if sample_weights is None:
        sample_weights = jnp.ones_like(values)
    else:
        sample_weights = jnp.asarray(sample_weights, dtype=jnp.float32)
    values = _pad_pow2(values, 0.0)
    sample_weights = _pad_pow2(sample_weights, 0.0)  # weight-0: ignored
    return _insert(
        means, weights, values, sample_weights,
        capacity=config.capacity, delta=config.delta,
    )


def merge(a, b, config: TDigestConfig = TDigestConfig()):
    """Merge two digests — associative, so it rides psum-style tree merges."""
    return insert(a[0], a[1], b[0], b[1], config=config)


@jax.jit
def quantile(means, weights, qs):
    """Interpolated quantile estimates from a digest.

    TAIL quantiles (q >= 0.9) between positive increasing centroids use a
    POWER-LAW fit: linear in (log survival, log value) space rather than
    (q, value) space (VERDICT r2 item 8).  Latency-like heavy tails
    (pareto, lognormal) are convex in linear space, so the straight chord
    between two smeared cluster means UNDERSHOOTS the quantile badly
    exactly where t-digests are sold (41% at pareto p9999 measured in
    r2); a power law is exact for pareto tails, and measured error drops
    to ~6% (ACCURACY.md).  Uniform/normal tail segments are barely
    curved in that space, so the fit is within noise of linear there.
    BODY quantiles (q < 0.9) and segments touching zero/negative means
    keep plain linear interpolation — geometric interpolation across a
    sparse body segment would bias toward the low endpoint (a two-sample
    {1, 1000} digest must report q50 ~ 500, not ~13), preserving the
    small-N exactness contract.

    APPLICABILITY (the bimodal twin of the heavy-tail note above): a
    body quantile that falls inside a DENSITY GAP — e.g. p50 of a
    bimodal mix whose modes straddle the median — has no unique "right"
    answer; this digest returns a value interpolated between the
    gap-adjacent centroids (an observed-data-range answer), which can
    sit ~46% from np.quantile's own interpolation (ACCURACY.md) while
    both are inside the same empty gap.  If body quantiles of
    multi-modal data must match rank-interpolation semantics, use the
    log-bucket histogram (`loghisto_tpu.ops.stats`): it keeps exact
    counts per bucket, so its answer lands in the correct mode every
    time — the same division of labor as heavy tails, where t-digest
    needs the power-law fit but loghist is exact by construction."""
    w_sorted_idx = jnp.argsort(jnp.where(weights > 0, means, jnp.inf))
    m = means[w_sorted_idx]
    w = weights[w_sorted_idx]
    total = jnp.maximum(w.sum(), 1e-30)
    cum = jnp.cumsum(w) - w / 2.0
    qpos = cum / total
    qs = jnp.asarray(qs, dtype=jnp.float32)
    # last populated slot; empty tail slots carry qpos == 1.0
    last = jnp.maximum((w > 0).sum() - 1, 0)

    def one(qq):
        idx = jnp.searchsorted(qpos, qq)
        lo = jnp.clip(idx - 1, 0, last)
        hi = jnp.clip(idx, 0, last)
        span = jnp.maximum(qpos[hi] - qpos[lo], 1e-30)
        frac = jnp.clip((qq - qpos[lo]) / span, 0.0, 1.0)
        linear = m[lo] + frac * (m[hi] - m[lo])
        # power-law branch (guarded logs; `where` picks per-element)
        s_lo = jnp.maximum(1.0 - qpos[lo], 1e-12)
        s_hi = jnp.maximum(1.0 - qpos[hi], 1e-12)
        s_q = jnp.maximum(1.0 - qq, 1e-12)
        denom = jnp.minimum(jnp.log(s_hi) - jnp.log(s_lo), -1e-12)
        pfrac = jnp.clip((jnp.log(s_q) - jnp.log(s_lo)) / denom, 0.0, 1.0)
        log_lo = jnp.log(jnp.maximum(m[lo], 1e-30))
        log_hi = jnp.log(jnp.maximum(m[hi], 1e-30))
        powerlaw = jnp.exp(log_lo + pfrac * (log_hi - log_lo))
        in_tail = qq >= 0.9
        return jnp.where(
            in_tail & (m[lo] > 0) & (m[hi] > m[lo]), powerlaw, linear
        )

    return jax.vmap(one)(qs)


def count(weights) -> jnp.ndarray:
    return weights.sum()
