"""LogHistogram: the dense log-bucket histogram as a standalone, mergeable
sketch object — one metric's row of the [num_metrics, num_buckets] tensor.

This is the 'model' at the center of the framework: lossless counting into
log-spaced buckets (the reference's core idea, metrics.go:316-332) carried
by a dense vector so that insert is a scatter-add, statistics are one CDF
scan, and merge is elementwise addition (psum across a mesh).
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp
import numpy as np

from loghisto_tpu.config import MetricConfig
from loghisto_tpu.ops.ingest import bucket_indices
from loghisto_tpu.ops.stats import dense_stats


@dataclasses.dataclass
class LogHistogram:
    """A single-metric dense log-bucket histogram."""

    counts: jnp.ndarray  # int32 [num_buckets]
    config: MetricConfig = MetricConfig()

    @classmethod
    def empty(cls, config: MetricConfig = MetricConfig()) -> "LogHistogram":
        return cls(
            counts=jnp.zeros(config.num_buckets, dtype=jnp.int32),
            config=config,
        )

    def insert(self, values) -> "LogHistogram":
        values = jnp.asarray(values, dtype=jnp.float32)
        idx = bucket_indices(values, self.config.bucket_limit,
                             self.config.precision)
        return LogHistogram(
            counts=self.counts.at[idx].add(1), config=self.config
        )

    def merge(self, other: "LogHistogram") -> "LogHistogram":
        return LogHistogram(
            counts=self.counts + other.counts, config=self.config
        )

    def statistics(self, ps) -> dict:
        stats = dense_stats(
            self.counts[None, :], np.asarray(ps, dtype=np.float32),
            self.config.bucket_limit, self.config.precision,
        )
        return {
            "count": int(stats["counts"][0]),
            "sum": float(stats["sums"][0]),
            "percentiles": np.asarray(stats["percentiles"][0]),
        }

    @property
    def count(self) -> int:
        return int(self.counts.sum())
