"""Moments quantile sketch (cf. "Moment-Based Quantile Sketches for
Efficient High Cardinality Aggregation Queries", PAPERS.md) as JAX ops.

The cheapest mergeable sketch of all: count, mean, and *central* power
sums M2..M4 plus min/max.  Insert is a handful of fused multiply-adds per
sample (ideal VPU work), merge is Pebay's parallel combination (exact and
associative, so it rides psum-style tree merges like everything else in
this framework), and the state is O(1).

Numerical design, for float32 on TPU:
  * central moments (not raw power sums) — raw sums cancel
    catastrophically when mean >> std; centered accumulation keeps
    variance accurate at any location;
  * values are normalized by a running scale (max |x| seen), and the
    stored mean/M2..M4 are rescaled when the scale grows — no overflow at
    any magnitude;
  * counts are int32 (exact to 2^31; float32 would silently stop counting
    at 2^24);
  * NaN samples are pinned to 0.0, matching ops.ingest.bucket_indices so
    every tier treats NaN identically.

Quantile estimates use a Cornish-Fisher expansion from the standardized
moments, clamped to [min, max], with exact observed endpoints at q=0/1.
Accuracy is distribution-dependent (near-exact for Gaussians, rough for
wild multimodal data) — this sketch trades accuracy for extreme
compactness; the log-bucket histogram remains the <=1% tool.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp


@dataclasses.dataclass
class MomentsState:
    count: jnp.ndarray  # int32 scalar
    mean: jnp.ndarray  # f32 scalar, of scaled values
    m2: jnp.ndarray  # f32 central sums of scaled values
    m3: jnp.ndarray
    m4: jnp.ndarray
    scale: jnp.ndarray  # f32 scalar >= max |x| seen
    min: jnp.ndarray  # f32 scalar, original units
    max: jnp.ndarray  # f32 scalar, original units


def empty() -> MomentsState:
    z = jnp.float32(0.0)
    return MomentsState(
        count=jnp.int32(0), mean=z, m2=z, m3=z, m4=z,
        scale=jnp.float32(1.0),
        min=jnp.float32(jnp.inf), max=jnp.float32(-jnp.inf),
    )


def _rescaled(state: MomentsState, new_scale: jnp.ndarray) -> MomentsState:
    r = state.scale / new_scale
    return MomentsState(
        count=state.count,
        mean=state.mean * r,
        m2=state.m2 * r ** 2,
        m3=state.m3 * r ** 3,
        m4=state.m4 * r ** 4,
        scale=new_scale,
        min=state.min,
        max=state.max,
    )


def _combine(a: MomentsState, b: MomentsState) -> MomentsState:
    """Pebay's parallel central-moment combination; a and b must share a
    scale."""
    na = a.count.astype(jnp.float32)
    nb = b.count.astype(jnp.float32)
    n = jnp.maximum(na + nb, 1.0)
    delta = b.mean - a.mean
    mean = a.mean + delta * nb / n
    m2 = a.m2 + b.m2 + delta ** 2 * na * nb / n
    m3 = (
        a.m3 + b.m3
        + delta ** 3 * na * nb * (na - nb) / n ** 2
        + 3.0 * delta * (na * b.m2 - nb * a.m2) / n
    )
    m4 = (
        a.m4 + b.m4
        + delta ** 4 * na * nb * (na ** 2 - na * nb + nb ** 2) / n ** 3
        + 6.0 * delta ** 2 * (na ** 2 * b.m2 + nb ** 2 * a.m2) / n ** 2
        + 4.0 * delta * (na * b.m3 - nb * a.m3) / n
    )
    return MomentsState(
        count=a.count + b.count,
        mean=jnp.where(a.count + b.count > 0, mean, 0.0),
        m2=m2, m3=m3, m4=m4,
        scale=a.scale,
        min=jnp.minimum(a.min, b.min),
        max=jnp.maximum(a.max, b.max),
    )


@jax.jit
def _insert(state: MomentsState, x, n_valid) -> MomentsState:
    valid = jnp.arange(x.shape[0]) < n_valid
    x = jnp.where(jnp.isnan(x), 0.0, x)  # NaN pinned like bucket_indices
    x = jnp.where(valid, x, 0.0)
    nf = jnp.maximum(n_valid.astype(jnp.float32), 1.0)
    new_scale = jnp.maximum(state.scale, jnp.abs(x).max())
    xs = x / new_scale
    bmean = xs.sum() / nf
    d = jnp.where(valid, xs - bmean, 0.0)
    batch = MomentsState(
        count=n_valid.astype(jnp.int32),
        mean=bmean,
        m2=(d ** 2).sum(),
        m3=(d ** 3).sum(),
        m4=(d ** 4).sum(),
        scale=new_scale,
        min=jnp.where(valid, x, jnp.inf).min(),
        max=jnp.where(valid, x, -jnp.inf).max(),
    )
    return _combine(_rescaled(state, new_scale), batch)


def insert(state: MomentsState, values) -> MomentsState:
    """Insert a batch.  Batches pad to the next power of two (padding
    masked out), so arbitrary batch sizes reuse O(log N) executables."""
    x = jnp.asarray(values, dtype=jnp.float32).reshape(-1)
    n = x.shape[0]
    padded = 1 << max(0, (int(n) - 1).bit_length())
    if padded != n:
        x = jnp.concatenate([x, jnp.zeros(padded - n, dtype=jnp.float32)])
    return _insert(state, x, jnp.int32(n))


@jax.jit
def merge(a: MomentsState, b: MomentsState) -> MomentsState:
    scale = jnp.maximum(a.scale, b.scale)
    return _combine(_rescaled(a, scale), _rescaled(b, scale))


def standardized_moments(state: MomentsState):
    """(mean, std, skewness, kurtosis) in original units."""
    n = jnp.maximum(state.count.astype(jnp.float32), 1.0)
    var = state.m2 / n
    # Degenerate distributions (0/1 samples, all-equal values): shape
    # moments are undefined; report Gaussian shape so downstream
    # expansions stay finite instead of 0/0 -> NaN.
    degenerate = var <= 1e-14
    var_s = jnp.maximum(var, 1e-14)
    std = jnp.sqrt(var_s)
    skew = jnp.where(degenerate, 0.0, (state.m3 / n) / std ** 3)
    kurt = jnp.where(degenerate, 3.0, (state.m4 / n) / var_s ** 2)
    std = jnp.where(degenerate, 0.0, std)
    return (
        state.mean * state.scale, std * state.scale, skew, kurt,
    )


@jax.jit
def quantile(state: MomentsState, qs) -> jnp.ndarray:
    """Cornish-Fisher quantile estimates, clamped to the observed range."""
    from jax.scipy.stats import norm

    mean, std, skew, kurt = standardized_moments(state)
    qs_raw = jnp.asarray(qs, dtype=jnp.float32)
    qs_c = jnp.clip(qs_raw, 1e-6, 1 - 1e-6)
    z = norm.ppf(qs_c)
    g1, g2 = skew, kurt - 3.0
    w = (
        z
        + (z ** 2 - 1) * g1 / 6.0
        + (z ** 3 - 3 * z) * g2 / 24.0
        - (2 * z ** 3 - 5 * z) * g1 ** 2 / 36.0
    )
    est = jnp.clip(mean + std * w, state.min, state.max)
    # exact endpoints (CF is unreliable at extreme z with strong skew)
    est = jnp.where(qs_raw <= 0.0, state.min, est)
    est = jnp.where(qs_raw >= 1.0, state.max, est)
    # empty sketch: no observed range; report 0 like the other sketches
    return jnp.where(state.count > 0, est, 0.0)


def count(state: MomentsState) -> jnp.ndarray:
    return state.count


jax.tree_util.register_dataclass(
    MomentsState,
    data_fields=["count", "mean", "m2", "m3", "m4", "scale", "min", "max"],
    meta_fields=[],
)
