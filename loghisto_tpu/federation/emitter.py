"""FederationEmitter: the frontend half of the federation tier.

Runs inside ANY process — a web frontend, a worker, a sidecar — and
deliberately imports no jax (tests pin this): the whole dependency path
is numpy + the host-tier fold.  Per interval it folds everything
recorded since the last flush into packed ``[n, 3]`` int32 triples in
EMITTER-LOCAL id space, prepends the delta of names not yet shipped,
frames the payload (ops/codec.py: versioned header + CRC32), and hands
the frame to a ``submitter.BacklogSender`` — the same evicting-backlog /
capped-exponential-backoff / fresh-dial machinery the TSDB submitter
uses, pointed at the aggregator pod's ``FederationReceiver``.

Delivery contract: at-least-once from the backlog (a frame is popped
only after a successful send; the receiver deduplicates by sequence
number), degrading to shed-don't-block when the receiver stays down
long enough to wrap the backlog ring (the receiver's gap counter shows
exactly how many frames died that way).

Two recording surfaces:

  * direct — ``record(name, value)`` / ``record_batch(local_ids,
    values)`` with ids from ``local_id(name)``; the firehose path.
  * wrapped — ``attach(metric_system)`` subscribes to a host
    ``MetricSystem``'s raw broadcast and re-ships every interval's
    histograms (already codec buckets) as cells, so an existing app's
    recorder path federates without touching call sites.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Optional

import numpy as np

from loghisto_tpu._native import fold_packed, pack_cells
from loghisto_tpu.config import MetricConfig
from loghisto_tpu.federation import wire
from loghisto_tpu.labels.model import canonical_name
from loghisto_tpu.obs.spans import LatencyHistogram, SpanRecorder
from loghisto_tpu.ops.codec import encode_frame
from loghisto_tpu.submitter import BACKLOG_SLOTS, BacklogSender


class FederationEmitter:
    def __init__(
        self,
        address: tuple[str, int],
        network: str = "tcp",
        interval: float = 1.0,
        config: MetricConfig = MetricConfig(),
        emitter_id: Optional[int] = None,
        backlog_slots: int = 4 * BACKLOG_SLOTS,
        dial_timeout: float = 5.0,
        backoff=None,
        fault_injector=None,
        wire_version: int = 2,
        obs_capacity: int = 1024,
        restarts: int = 0,
    ):
        """``address`` is the receiver's (host, port).  ``interval`` is
        the flush/ship cadence.  ``config`` must agree with the
        aggregator's on precision (the fold runs the shared f64 codec, so
        matching precision makes the federated aggregate bit-identical
        to recording the same samples locally); bucket indices are
        clipped to ``bucket_limit`` at fold time like every other
        transport.  ``backlog_slots`` defaults wider than the TSDB
        submitter's 60 — a federation frame is an interval of unique
        cells, cheap to hold, expensive to lose.

        ``wire_version`` picks the frame kind: 2 (default) stamps every
        frame with capture timestamps and piggybacks a health summary
        at most once per ``health_interval_s`` (frames in between carry
        an empty health blob and the receiver keeps the last one — the
        summary changes at ~1 Hz, while the JSON encode/decode per
        frame is the dominant wire-v2 cost at high frame rates); 1
        emits the PR-11 format for old receivers.  ``restarts`` seeds
        the restart counter shipped in the health summary (a supervisor
        that respawns this process passes its attempt count)."""
        if wire_version not in (1, 2):
            raise ValueError(f"wire_version must be 1 or 2, got {wire_version}")
        self.config = config
        self.wire_version = int(wire_version)
        self.interval = float(interval)
        self.emitter_id = (
            int(emitter_id) if emitter_id is not None
            else int.from_bytes(os.urandom(8), "little") or 1
        )
        self._sender = BacklogSender(
            network, address,
            backlog_slots=backlog_slots, dial_timeout=dial_timeout,
            interval=self.interval, backoff=backoff, fault_site="fed.send",
        )
        self._sender.fault_injector = fault_injector
        self.fault_injector = fault_injector
        self._lock = threading.Lock()
        self._flush_lock = threading.Lock()
        self._names: dict[str, int] = {}     # name -> emitter-local id
        self._names_unsent: list[tuple[int, str]] = []
        self._staged_ids: list[np.ndarray] = []
        self._staged_values: list[np.ndarray] = []
        self._staged_cells: list[np.ndarray] = []  # pre-bucketed [n,3]
        self._seq = 0
        self.samples_recorded = 0
        self.frames_shipped = 0
        self.samples_shipped = 0
        self._ticker: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._attached = None  # (ResilientSubscription, thread)
        # fleet-observability plane: capture stamps for the interval in
        # flight (first staged sample since the last flush; None when
        # nothing landed yet), own span ring (jax-free, like everything
        # else on this path), and per-stage latency histograms whose
        # p99s ride in the health summary
        self._capture_mono_ns: Optional[int] = None
        self._capture_wall_ns: Optional[int] = None
        self.obs = SpanRecorder(obs_capacity)
        self.stage_latency = {
            "fold": LatencyHistogram(config.precision),
            "encode": LatencyHistogram(config.precision),
        }
        self.restarts = int(restarts)
        self._started_mono = time.monotonic()
        # health piggyback cadence: the summary rides at most this often
        # (0 ships it on every frame, as chaos drills want)
        self.health_interval_s = 1.0
        self._health_shipped_mono = float("-inf")

    # -- recording ------------------------------------------------------ #

    def local_id(self, name: str) -> int:
        """Emitter-local dense id for ``name`` (registers on first use
        and queues the name for the next frame's dictionary delta)."""
        with self._lock:
            lid = self._names.get(name)
            if lid is None:
                lid = len(self._names)
                self._names[name] = lid
                self._names_unsent.append((lid, name))
            return lid

    def record(self, name: str, value: float, labels=None) -> None:
        """``labels`` (optional mapping) canonicalizes AT RECORD TIME
        (ISSUE 16): every permutation of the same label set becomes one
        canonical ``name;k=v`` string and therefore ONE emitter-local
        id, one dictionary-delta row, one aggregator registry row.  The
        wire dictionary ships the canonical name as an opaque string —
        no federation format change."""
        if labels:
            name = canonical_name(name, labels)
        self.record_batch(
            np.array([self.local_id(name)], dtype=np.int32),
            np.array([value], dtype=np.float32),
        )

    def record_batch(self, ids: np.ndarray, values: np.ndarray) -> None:
        """Stage a batch of (emitter-local id, value) samples for the
        next flush.  O(1) list append — the fold runs at flush time."""
        ids = np.asarray(ids, dtype=np.int32)
        values = np.asarray(values, dtype=np.float32)
        if ids.shape != values.shape:
            raise ValueError("ids and values must have the same shape")
        with self._lock:
            if self._capture_mono_ns is None:
                self._stamp_capture_locked()
            self._staged_ids.append(ids)
            self._staged_values.append(values)
            self.samples_recorded += len(ids)

    # -- wrapping a host MetricSystem ----------------------------------- #

    def attach(self, metric_system) -> None:
        """Subscribe to ``metric_system``'s raw broadcast and re-ship
        every interval's histograms.  The host tier already folded each
        histogram to sparse codec buckets, so this path stages cells
        directly (clipped to this emitter's bucket_limit) instead of
        re-folding samples."""
        if self._attached is not None:
            return
        from loghisto_tpu.channel import (
            ChannelClosed, ResilientSubscription,
        )

        ch = ResilientSubscription(
            metric_system.subscribe_to_raw_metrics,
            metric_system.unsubscribe_from_raw_metrics,
            16,
        )

        def _drain() -> None:
            while True:
                try:
                    raw = ch.get()
                except ChannelClosed:
                    return
                self.stage_raw(raw)

        t = threading.Thread(
            target=_drain, daemon=True, name="loghisto-fed-wrap"
        )
        t.start()
        self._attached = (ch, t)

    def stage_raw(self, raw) -> None:
        """Stage one RawMetricSet's histograms as pre-bucketed cells."""
        bl = self.config.bucket_limit
        for name, buckets in raw.histograms.items():
            if not buckets:
                continue
            lid = self.local_id(name)
            b = np.clip(
                np.fromiter(buckets.keys(), dtype=np.int64,
                            count=len(buckets)),
                -bl, bl,
            )
            c = np.fromiter(buckets.values(), dtype=np.int64,
                            count=len(buckets))
            cells = pack_cells(np.full(len(b), lid, dtype=np.int64), b, c)
            with self._lock:
                if self._capture_mono_ns is None:
                    self._stamp_capture_locked()
                self._staged_cells.append(cells)
                self.samples_recorded += int(c.sum())

    # -- clocks / health -------------------------------------------------- #

    def _wall_ns(self) -> int:
        """Wall clock for wire stamps; honors an injected ``clock_step``
        offset so chaos drills can step this emitter's wall clock
        without touching the host."""
        ns = time.time_ns()
        inj = self.fault_injector
        if inj is not None:
            off = getattr(inj, "clock_offset", None)
            if off is not None:
                ns += int(off() * 1e9)
        return ns

    def _stamp_capture_locked(self) -> None:
        self._capture_mono_ns = time.monotonic_ns()
        self._capture_wall_ns = self._wall_ns()

    def health_summary(self) -> dict:
        """Compact health summary piggybacked on every v2 frame: stage
        p99s (via the jax-free percentile mirror — this process never
        loads jax), backlog depth, send failures, restart count, and
        uptime.  A few hundred bytes of JSON per frame."""
        return {
            "p99_us": {
                stage: round(hist.percentile_host(99.0), 1)
                for stage, hist in self.stage_latency.items()
            },
            "backlog": self._sender.backlog_depth(),
            "fail": self._sender.send_failures,
            "restarts": self.restarts,
            "up_s": round(time.monotonic() - self._started_mono, 1),
            "frames": self.frames_shipped,
            "samples": self.samples_shipped,
        }

    # -- flush / ship --------------------------------------------------- #

    def flush(self, heartbeat: bool = True) -> int:
        """Fold everything staged into one DELTA frame and enqueue it
        for sending.  Returns the number of samples in the frame.  With
        ``heartbeat`` (default) an empty interval still ships a zero-row
        frame — the receiver's per-emitter lag gauge and the
        ``emitter_starvation`` invariant feed on frame arrival times, so
        an idle emitter must stay audible."""
        # one flush at a time: concurrent flushes could enqueue their
        # frames out of seq order, and the receiver would shed the
        # late-arriving lower seq as a duplicate
        with self._flush_lock:
            return self._flush_locked(heartbeat)

    def _flush_locked(self, heartbeat: bool) -> int:
        inj = self.fault_injector
        if inj is not None:
            inj.check("fed.flush")
        flush_t0 = time.perf_counter_ns()
        with self._lock:
            ids = self._staged_ids
            values = self._staged_values
            cells = self._staged_cells
            names = self._names_unsent
            mono_ns = self._capture_mono_ns
            wall_ns = self._capture_wall_ns
            self._staged_ids, self._staged_values = [], []
            self._staged_cells = []
            self._names_unsent = []
            self._capture_mono_ns = None
            self._capture_wall_ns = None
        # this seq is ours: _seq only advances under _flush_lock, which
        # the caller holds — so the flow id can label the fold/encode
        # spans before the frame exists
        seq = self._seq + 1
        flow = wire.fed_flow_id(self.emitter_id, seq)
        fold_t0 = time.perf_counter_ns()
        parts = list(cells)
        if ids:
            parts.append(fold_packed(
                np.concatenate(ids), np.concatenate(values),
                self.config.bucket_limit, self.config.precision,
            ))
        parts = [p for p in parts if len(p)]
        if parts:
            packed = parts[0] if len(parts) == 1 else np.concatenate(parts)
        else:
            if not heartbeat and not names:
                return 0
            packed = np.empty((0, 3), dtype=np.int32)
        fold_t1 = time.perf_counter_ns()
        self.obs.record("fed.fold", fold_t0, fold_t1, seq, flow)
        self.stage_latency["fold"].add((fold_t1 - fold_t0) / 1e3)
        self._seq = seq
        # empty heartbeats stamp at flush time: there was no first
        # sample, so "capture" degenerates to "now" and the freshness
        # sample measures pure pipeline latency
        if mono_ns is None:
            mono_ns = time.monotonic_ns()
            wall_ns = self._wall_ns()
        enc_t0 = time.perf_counter_ns()
        if self.wire_version >= 2:
            health = None
            now_mono = time.monotonic()
            if now_mono - self._health_shipped_mono >= self.health_interval_s:
                health = self.health_summary()
                self._health_shipped_mono = now_mono
            payload = wire.encode_delta2(
                self.emitter_id, seq, names, packed,
                mono_ns, wall_ns, health,
            )
            kind = wire.KIND_DELTA2
        else:
            payload = wire.encode_delta(self.emitter_id, seq, names, packed)
            kind = wire.KIND_DELTA
        frame = encode_frame(kind, payload)
        enc_t1 = time.perf_counter_ns()
        self.obs.record("fed.encode", enc_t0, enc_t1, seq, flow)
        self.stage_latency["encode"].add((enc_t1 - enc_t0) / 1e3)
        self._sender.enqueue(frame)
        samples = int(packed[:, 2].sum(dtype=np.int64))
        self.frames_shipped += 1
        self.samples_shipped += samples
        self.obs.record(
            "fed.flush", flush_t0, time.perf_counter_ns(), seq, flow
        )
        return samples

    def drain(self, timeout: float = 10.0) -> bool:
        """Retry until the backlog is empty or ``timeout`` passes.
        Returns True when every enqueued frame was handed to the socket
        — the emitter-side half of exact conservation."""
        deadline = time.monotonic() + timeout
        while True:
            self._sender.retry_backlog()
            if self._sender.backlog_depth() == 0:
                return True
            if time.monotonic() >= deadline:
                return False
            time.sleep(min(0.05, self.interval / 4.0))

    # -- lifecycle ------------------------------------------------------ #

    def _ticker_loop(self) -> None:
        while not self._stop.is_set():
            self._stop.wait(
                timeout=self.interval - (time.time() % self.interval)
            )
            if self._stop.is_set():
                return
            self.flush()

    def start(self) -> None:
        """Spawn the sender thread and the per-interval flush ticker."""
        self._sender.start_sender("loghisto-fed-send")
        if self._ticker is None or not self._ticker.is_alive():
            self._stop.clear()
            self._ticker = threading.Thread(
                target=self._ticker_loop, daemon=True,
                name="loghisto-fed-tick",
            )
            self._ticker.start()

    def close(self, drain_timeout: float = 10.0) -> bool:
        """Final flush, best-effort drain, stop threads.  Returns the
        drain verdict (False: frames remained undeliverable and were
        abandoned with the process — shed-don't-block, like every other
        exit path in the pipeline)."""
        self._stop.set()
        if self._ticker is not None:
            self._ticker.join(timeout=5.0)
            self._ticker = None
        if self._attached is not None:
            ch, t = self._attached
            ch.close()
            t.join(timeout=5.0)
            self._attached = None
        self.flush(heartbeat=False)
        ok = self.drain(timeout=drain_timeout)
        self._sender.stop_sender()
        return ok

    # -- introspection --------------------------------------------------- #

    @property
    def backlog_depth(self) -> int:
        return self._sender.backlog_depth()

    @property
    def bytes_sent(self) -> int:
        return self._sender.bytes_sent

    @property
    def send_failures(self) -> int:
        return self._sender.send_failures
