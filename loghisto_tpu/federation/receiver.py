"""FederationReceiver: the aggregator-pod half of the federation tier.

A TCP listener whose accept thread (and each connection's decode thread)
runs under the resilience supervisor when one is attached — a crashed
loop restarts with capped-exponential backoff and shows on the
``thread_restarted`` health invariant.  Per connection: buffered recv,
greedy frame parse (ops/codec.py), DELTA payload decode
(federation/wire.py), then apply:

  * sequence tracking per emitter_id — a seq applied before (or fallen
    behind the reorder window) is counted and dropped (idempotent
    re-delivery: the at-least-once sender can repeat frames freely).
    Each frame rides its own TCP connection, so connection threads can
    legally apply frames out of order; a never-seen seq inside the
    window still applies and un-counts its provisional gap.  Seqs still
    missing count ``seq_gaps`` (frames that died in an emitter's
    wrapped backlog or crash).
  * name interning — dictionary deltas map emitter-local ids to
    aggregator registry rows through ``TPUAggregator._id_for`` (the
    free-list reuse / grow-then-shed policy every other ingest path
    gets); the triple id column is rewritten vectorized.  Rows whose
    local id has no mapping yet PARK (bounded) while the emitter has
    open seq gaps — the dictionary frame may merely be late — and merge
    when it lands; they shed only when every gap is filled and the name
    still never arrived, on age-out/overflow, or at stop().
  * merge — rewritten triples drain into the aggregator's packed ingest
    (``merge_packed``), i.e. the PR-6 staging/transfer pipeline and the
    same fused commit as local samples.  int32 scatter-adds are
    order-independent: the aggregate is bit-identical to a
    single-process oracle fed the same samples in any order.

Corruption never merges: a frame that fails CRC or schema validation
counts ``decode_errors`` and drops the CONNECTION (the stream offers no
resync point), exactly like an emitter crash mid-frame — whose torn
partial frame is likewise counted and discarded at EOF.

With ``journal_path`` every applied frame is write-ahead appended to a
binary ``FrameJournal`` (same frame codec as the wire); after a
receiver restart with a fresh aggregator, ``replay_journal()`` rebuilds
bit-identical state — duplicates in the journal deduplicate through the
same seq tracking as live frames.

Chaos hook sites: ``fed.accept`` (accept loop, per connection) and
``fed.decode`` (per frame, before apply); the emitter side holds
``fed.send``.
"""

from __future__ import annotations

import socket
import threading
import time
from typing import Optional

import numpy as np

from loghisto_tpu.federation import wire
from loghisto_tpu.obs.spans import LatencyHistogram
from loghisto_tpu.ops.codec import (
    FrameError, FrameTruncated, decode_frame,
)

_ACCEPT_POLL_S = 0.25
# Reorder window: a never-before-seen seq no further than this behind
# the high-water mark still applies (one connection per frame means
# frames from one emitter can race each other through conn threads);
# anything older is indistinguishable from a stale re-delivery and is
# dropped as a duplicate.
SEQ_WINDOW = 4096
# row_map sentinels: a local id whose dictionary entry never arrived
# (may be in a late frame) vs. one whose name the registry shed
ROW_UNKNOWN = -2
ROW_SHED = -1
# parked-row bounds per emitter: rows waiting on a late dictionary
# frame shed once this many rows queue up or once the emitter's seq
# high-water mark has advanced this far past their arrival
MAX_PARKED_ROWS = 1 << 16
PARK_SEQ_AGE = 64
# host-side freshness ledger bound (the bit-identity oracle's input);
# past this the histograms keep counting but the ledger stops
FRESHNESS_LEDGER_CAP = 1 << 16


class _NullSpan:
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


class _NullRecorder:
    def span(self, *_a, **_k):
        return _NullSpan()

    def record(self, *_a, **_k):
        pass


_NULL_RECORDER = _NullRecorder()


class _EmitterState:
    """Per-emitter sequencing + id-mapping state, keyed by emitter_id."""

    __slots__ = (
        "last_seq", "seen", "row_map", "parked", "parked_rows",
        "last_frame_t", "frames", "samples", "duplicates", "gaps",
        # fleet-observability plane (v2 frames only)
        "e_mono0", "r_mono0", "e_wall0", "last_e_mono", "skew_ns",
        "health", "health_t", "freshness", "wire_v",
    )

    def __init__(self):
        self.last_seq = 0          # high-water mark
        self.seen: set[int] = set()  # applied seqs within SEQ_WINDOW
        # emitter-local id -> aggregator row (ROW_UNKNOWN: dictionary
        # entry not seen yet; ROW_SHED: the registry shed the name)
        self.row_map = np.full(64, ROW_UNKNOWN, dtype=np.int32)
        # rows waiting on a late dictionary frame: (hwm_at_park, packed)
        self.parked: list = []
        self.parked_rows = 0
        self.last_frame_t = time.monotonic()
        self.frames = 0
        self.samples = 0
        self.duplicates = 0
        self.gaps = 0
        # clock anchors: emitter monotonic/wall at first v2 frame of
        # this emitter incarnation, paired with the receiver monotonic
        # at arrival.  All lag/freshness math runs on monotonic deltas
        # against these; the wall stamp only feeds the skew detector.
        self.e_mono0: Optional[int] = None
        self.r_mono0 = 0
        self.e_wall0 = 0
        self.last_e_mono = 0
        self.skew_ns = 0  # (wall delta) - (mono delta) since anchor
        self.health: Optional[dict] = None
        self.health_t = 0.0
        self.freshness = LatencyHistogram()
        self.wire_v = 1


class FederationReceiver:
    def __init__(
        self,
        aggregator,
        host: str = "127.0.0.1",
        port: int = 0,
        *,
        journal_path: Optional[str] = None,
        replay_on_start: bool = False,
        expected_emitters: int = 0,
        supervisor=None,
        fault_injector=None,
        obs_recorder=None,
        recv_bytes: int = 1 << 16,
    ):
        self.aggregator = aggregator
        self.host = host
        self.port = int(port)  # rewritten to the bound port on start()
        self.journal_path = journal_path
        self.replay_on_start = replay_on_start
        self.expected_emitters = int(expected_emitters)
        self.supervisor = supervisor
        self.fault_injector = fault_injector
        self.obs_recorder = obs_recorder or _NULL_RECORDER
        self.recv_bytes = recv_bytes

        self._sock: Optional[socket.socket] = None
        self._accept_thread = None
        self._conn_threads: list = []
        self._stop = threading.Event()
        self._lock = threading.Lock()       # guards apply + counters
        self._journal = None
        self._started_t: Optional[float] = None

        self.emitters: dict[int, _EmitterState] = {}
        self.frames_received = 0
        self.bytes_received = 0
        self.decode_errors = 0
        self.duplicate_frames = 0
        self.seq_gaps = 0
        self.samples_merged = 0
        self.samples_shed = 0    # rows whose name never resolved
        self.samples_parked = 0  # rows currently waiting on a late dict
        self.frames_replayed = 0
        self.connections_total = 0
        self.connections_active = 0
        # frames/s gauge state: (monotonic t, frames_received) at last read
        self._rate_mark = (time.monotonic(), 0)
        # -- fleet-observability plane -------------------------------- #
        self.frames_v1 = 0          # legacy frames applied (no stamps)
        self.fleet_freshness = LatencyHistogram()
        # applied-but-not-yet-queryable frames: (emitter_id,
        # apply_mono_ns, capture->apply latency ns).  A wired committer
        # (``has_publisher``) completes these at snapshot publish via
        # note_publish(); standalone receivers complete at apply time.
        self._pending: list = []
        self.has_publisher = False
        # host-side oracle ledger of completed freshness samples (µs)
        self.freshness_values: list = []
        self.freshness_dropped = 0
        # thresholds read by fleet_report()/watchdog; system wiring
        # overwrites from FederationConfig
        self.starvation_s = 3.0
        self.skew_tolerance_s = 1.0

    # -- lifecycle ------------------------------------------------------ #

    def start(self) -> None:
        """Replay the journal if configured, bind, and start accepting.
        ``self.port`` holds the real bound port afterwards (port=0 asks
        the OS for an ephemeral one)."""
        if self._sock is not None:
            return
        if self.replay_on_start and self.journal_path is not None:
            import os

            if os.path.exists(self.journal_path):
                self.replay_journal()
        if self.journal_path is not None:
            from loghisto_tpu.utils.journal import FrameJournal

            self._journal = FrameJournal(self.journal_path)
        sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        sock.bind((self.host, self.port))
        sock.listen(128)
        sock.settimeout(_ACCEPT_POLL_S)  # poll so stop() can interrupt
        self.port = sock.getsockname()[1]
        self._sock = sock
        self._stop.clear()
        self._started_t = time.monotonic()
        self._accept_thread = self._spawn(
            self._accept_loop, "loghisto-fed-accept"
        )

    def _spawn(self, target, name: str):
        if self.supervisor is not None:
            return self.supervisor.spawn(target, name)
        t = threading.Thread(target=target, daemon=True, name=name)
        t.start()
        return t

    def stop(self) -> None:
        """Stop accepting, close every connection's thread, close the
        journal.  In-flight decoded frames finish applying; the
        aggregator's transfer queue keeps whatever was already merged."""
        self._stop.set()
        t = self._accept_thread
        if t is not None:
            if hasattr(t, "stop"):
                t.stop()  # SupervisedThread: no restart after this
            self._accept_thread = None
        sock, self._sock = self._sock, None
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass
        if t is not None:
            t.join(timeout=5.0)
        for ct in self._conn_threads:
            if hasattr(ct, "stop"):
                ct.stop()
            ct.join(timeout=5.0)
        self._conn_threads = []
        with self._lock:
            # finalize the ledger: rows still waiting on a dictionary
            # frame at shutdown will never resolve — count them shed
            for state in self.emitters.values():
                for _hwm, upack in state.parked:
                    samples = int(upack[:, 2].sum(dtype=np.int64))
                    self.samples_shed += samples
                    self.samples_parked -= samples
                state.parked = []
                state.parked_rows = 0
        if self._journal is not None:
            self._journal.close()
            self._journal = None

    # -- accept / decode ------------------------------------------------ #

    def _accept_loop(self) -> None:
        sock = self._sock
        while not self._stop.is_set() and sock is not None:
            try:
                conn, _addr = sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return  # listener closed by stop()
            inj = self.fault_injector
            if inj is not None:
                # a scripted raise here crashes the (supervised) accept
                # thread AFTER the 3-way handshake — the client sees the
                # connection reset, the supervisor restarts the loop
                try:
                    inj.check("fed.accept")
                except Exception:
                    conn.close()
                    raise
            self.connections_total += 1
            self._conn_threads = [
                ct for ct in self._conn_threads if ct.is_alive()
            ]
            self._conn_threads.append(self._spawn(
                lambda c=conn: self._conn_loop(c),
                f"loghisto-fed-conn-{self.connections_total}",
            ))

    def _conn_loop(self, conn: socket.socket) -> None:
        self.connections_active += 1
        buf = bytearray()
        try:
            conn.settimeout(_ACCEPT_POLL_S)
            while not self._stop.is_set():
                try:
                    chunk = conn.recv(self.recv_bytes)
                except socket.timeout:
                    continue
                except OSError:
                    break
                if not chunk:
                    break  # peer closed
                self.bytes_received += len(chunk)
                buf += chunk
                if not self._drain_buffer(buf):
                    return  # corrupt frame: drop the connection
            # EOF with a partial frame = emitter crashed (or was killed)
            # mid-frame: count it, merge nothing from it
            if len(buf):
                with self._lock:
                    self.decode_errors += 1
        finally:
            self.connections_active -= 1
            try:
                conn.close()
            except OSError:
                pass

    def _drain_buffer(self, buf: bytearray) -> bool:
        """Greedily decode+apply every complete frame in ``buf``,
        consuming the decoded prefix.  False means the stream is corrupt
        and the caller must drop the connection."""
        offset = 0
        try:
            while True:
                try:
                    kind, payload, offset = decode_frame(buf, offset)
                except FrameTruncated:
                    break  # need more bytes
                self._handle_frame(kind, payload)
        except (FrameError, wire.WireError):
            with self._lock:
                self.decode_errors += 1
            return False
        finally:
            if offset:
                del buf[:offset]
        return True

    def _handle_frame(self, kind: int, payload: bytes) -> None:
        inj = self.fault_injector
        if inj is not None:
            # scripted decode failure: follows the organic-corruption
            # path (counted, connection dropped), not a thread crash
            try:
                inj.check("fed.decode")
            except Exception as e:
                raise wire.WireError(f"injected decode fault: {e}") from e
        if kind not in (wire.KIND_DELTA, wire.KIND_DELTA2):
            raise wire.WireError(f"unknown frame kind {kind}")
        t0 = time.perf_counter_ns()
        delta = wire.decode_payload(kind, payload)
        flow = wire.fed_flow_id(delta.emitter_id, delta.seq)
        self.obs_recorder.record(
            "fed.decode", t0, time.perf_counter_ns(), None, flow
        )
        with self.obs_recorder.span("fed.apply", flow=flow):
            if self._journal is not None:
                # write-ahead, before apply: replay after a crash
                # re-applies through the same seq dedup, so the journal
                # being ahead of the aggregator is safe; behind is not
                self._journal.append(kind, payload)
            self._apply_delta(delta)

    # -- apply ---------------------------------------------------------- #

    def _apply_delta(self, delta: wire.DeltaFrame, live: bool = True) -> None:
        agg = self.aggregator
        flow = wire.fed_flow_id(delta.emitter_id, delta.seq)
        now_mono_ns = time.monotonic_ns()
        fresh_ns = None  # completed-at-apply freshness (no publisher)
        newly_parked = False
        with self._lock:
            state = self.emitters.get(delta.emitter_id)
            if state is None:
                state = self.emitters[delta.emitter_id] = _EmitterState()
                self._register_emitter_gauge(delta.emitter_id)
            # dictionary deltas apply even on duplicate frames —
            # interning is idempotent and a re-delivered frame may be
            # the only carrier of a name whose first copy half-applied
            for local_id, name in delta.names:
                if local_id >= len(state.row_map):
                    grown = np.full(
                        max(2 * len(state.row_map), local_id + 1),
                        ROW_UNKNOWN, dtype=np.int32,
                    )
                    grown[:len(state.row_map)] = state.row_map
                    state.row_map = grown
                state.row_map[local_id] = agg._id_for(name)
            state.last_frame_t = time.monotonic()
            # clock anchors update on EVERY live v2 frame, duplicates
            # included — any arrival proves liveness and carries the
            # freshest clock/health readings.  Replayed frames are
            # excluded: their stamps describe a past incarnation and
            # would anchor emitter clocks against the wrong receiver
            # clock.
            if delta.mono_ns is not None and live:
                state.wire_v = 2
                if state.e_mono0 is None or delta.mono_ns < state.e_mono0:
                    # first v2 frame from this emitter incarnation, or
                    # its monotonic clock reset (process restart):
                    # (re-)anchor both clock pairs here
                    state.e_mono0 = delta.mono_ns
                    state.r_mono0 = now_mono_ns
                    state.e_wall0 = delta.wall_ns
                    state.last_e_mono = delta.mono_ns
                state.last_e_mono = max(state.last_e_mono, delta.mono_ns)
                # a wall-clock step (NTP slew, fault injection) shows as
                # wall advancing at a different rate than monotonic;
                # lag/freshness never read the wall clock so a backward
                # step can only trip the skew flag, never go negative
                state.skew_ns = (
                    (delta.wall_ns - state.e_wall0)
                    - (delta.mono_ns - state.e_mono0)
                )
                if delta.health is not None:
                    state.health = delta.health
                    state.health_t = time.monotonic()
            seq = delta.seq
            merges: list = []
            if seq in state.seen or seq <= state.last_seq - SEQ_WINDOW:
                state.duplicates += 1
                self.duplicate_frames += 1
            else:
                if seq > state.last_seq:
                    missed = seq - state.last_seq - 1
                    if missed:
                        # provisional: a frame applying late un-counts
                        # itself below
                        state.gaps += missed
                        self.seq_gaps += missed
                    state.last_seq = seq
                else:
                    # in-window reorder: this seq was counted as a gap
                    # when a higher seq overtook it — it arrived after
                    # all
                    state.gaps -= 1
                    self.seq_gaps -= 1
                state.seen.add(seq)
                if len(state.seen) > 2 * SEQ_WINDOW:
                    floor = state.last_seq - SEQ_WINDOW
                    state.seen = {s for s in state.seen if s > floor}
                self.frames_received += 1
                state.frames += 1
                if delta.mono_ns is None:
                    self.frames_v1 += 1
                elif live:
                    # capture -> apply latency via the monotonic anchor
                    # pair; clamped, because transit jitter can make the
                    # anchor-predicted capture time land marginally
                    # after "now" for the fastest frames
                    base_ns = max(
                        0,
                        (now_mono_ns - state.r_mono0)
                        - (delta.mono_ns - state.e_mono0),
                    )
                    if self.has_publisher:
                        self._pending.append(
                            (delta.emitter_id, now_mono_ns, base_ns)
                        )
                    else:
                        fresh_ns = base_ns
                parked_before = state.parked_rows
                if len(delta.packed):
                    self._map_rows_locked(state, delta.packed, merges)
                newly_parked = state.parked_rows > parked_before
            # a frame (even a duplicate) may have carried the dictionary
            # entries parked rows were waiting on
            if state.parked:
                self._resolve_parked_locked(state, merges)
        if merges:
            with self.obs_recorder.span("fed.merge", flow=flow):
                for packed in merges:
                    agg.merge_packed(packed)
        if newly_parked:
            # instantaneous marker: this frame parked rows on a missing
            # dictionary entry
            t = time.perf_counter_ns()
            self.obs_recorder.record("fed.park", t, t, None, flow)
        if fresh_ns is not None:
            self._complete_freshness(delta.emitter_id, fresh_ns)

    def _map_rows_locked(self, state: _EmitterState, packed, merges) -> None:
        """Rewrite the local-id column through ``row_map``; merge the
        mapped rows, shed registry-shed rows, park unknown ones while a
        seq gap leaves room for their dictionary frame to still arrive.
        Caller holds ``self._lock``."""
        local = packed[:, 0]
        n = len(state.row_map)
        mapped = np.where(
            (local >= 0) & (local < n),
            state.row_map[np.clip(local, 0, n - 1)], ROW_UNKNOWN,
        )
        shed = mapped == ROW_SHED
        if shed.any():
            self.samples_shed += int(packed[shed, 2].sum(dtype=np.int64))
        unknown = mapped == ROW_UNKNOWN
        if unknown.any():
            upack = packed[unknown]
            usamples = int(upack[:, 2].sum(dtype=np.int64))
            if (state.gaps > 0
                    and state.parked_rows + len(upack) <= MAX_PARKED_ROWS):
                state.parked.append((state.last_seq, upack))
                state.parked_rows += len(upack)
                self.samples_parked += usamples
            else:
                # no open gap can explain the missing dictionary entry
                # (or the park bound is hit): the name never arrived
                self.samples_shed += usamples
        keep = mapped >= 0
        if keep.any():
            out = packed[keep]
            out[:, 0] = mapped[keep]
            samples = int(out[:, 2].sum(dtype=np.int64))
            state.samples += samples
            self.samples_merged += samples
            merges.append(out)

    def _resolve_parked_locked(self, state: _EmitterState, merges) -> None:
        """Retry parked rows against the (possibly just-extended)
        row_map: resolved rows merge, registry-shed rows shed, rows
        still unknown stay parked while a gap remains open and they have
        not aged out.  Caller holds ``self._lock``."""
        still: list = []
        for hwm, upack in state.parked:
            local = upack[:, 0]
            n = len(state.row_map)
            mapped = np.where(
                (local >= 0) & (local < n),
                state.row_map[np.clip(local, 0, n - 1)], ROW_UNKNOWN,
            )
            resolved = mapped >= 0
            if resolved.any():
                out = upack[resolved]
                out[:, 0] = mapped[resolved]
                samples = int(out[:, 2].sum(dtype=np.int64))
                state.samples += samples
                self.samples_merged += samples
                self.samples_parked -= samples
                merges.append(out)
            regshed = mapped == ROW_SHED
            if regshed.any():
                samples = int(upack[regshed, 2].sum(dtype=np.int64))
                self.samples_shed += samples
                self.samples_parked -= samples
            unknown = mapped == ROW_UNKNOWN
            if unknown.any():
                rest = upack[unknown]
                samples = int(rest[:, 2].sum(dtype=np.int64))
                if (state.gaps > 0
                        and state.last_seq - hwm <= PARK_SEQ_AGE):
                    still.append((hwm, rest))
                else:
                    self.samples_shed += samples
                    self.samples_parked -= samples
        state.parked = still
        state.parked_rows = sum(len(p) for _, p in still)

    # -- freshness (record -> queryable) ---------------------------------- #

    def _complete_freshness(self, emitter_id: int, fresh_ns: int) -> None:
        """One frame became queryable ``fresh_ns`` after its first
        sample was recorded: feed the fleet and per-emitter log-bucket
        histograms, the host-side oracle ledger, and (when wired into a
        system) the ordinary ``fed.FreshnessUs`` histogram path."""
        us = fresh_ns / 1e3
        self.fleet_freshness.add(us)
        with self._lock:
            state = self.emitters.get(emitter_id)
            if len(self.freshness_values) < FRESHNESS_LEDGER_CAP:
                self.freshness_values.append(us)
            else:
                self.freshness_dropped += 1
        if state is not None:
            state.freshness.add(us)
        ms = getattr(self, "_ms", None)
        if ms is not None:
            ms.histogram("fed.FreshnessUs", us)
            ms.histogram(f"fed.emitter.{emitter_id:016x}.FreshnessUs", us)

    def note_publish(self, seq=None) -> int:
        """Snapshot-publish hook: the committer calls this right after
        an interval's aggregate became queryable.  Every frame applied
        since the previous publish completes its freshness sample here
        (capture->apply latency from the wire stamps, plus apply->
        publish measured receiver-side).  Returns the number of frames
        completed."""
        now_ns = time.monotonic_ns()
        with self._lock:
            pending, self._pending = self._pending, []
        for emitter_id, apply_ns, base_ns in pending:
            self._complete_freshness(
                emitter_id, base_ns + (now_ns - apply_ns)
            )
        return len(pending)

    def oldest_pending_age_s(self) -> float:
        """Age of the oldest applied-but-unpublished frame — the
        ``fleet_freshness_stall`` invariant's input.  0 when nothing is
        pending (an idle fleet is not a stalled fleet)."""
        now_ns = time.monotonic_ns()
        with self._lock:
            if not self._pending:
                return 0.0
            return (now_ns - min(p[1] for p in self._pending)) / 1e9

    def freshness_totals(self, budget_us: float, emitter_id=None):
        """(total, over-budget) sample counts from the freshness
        histograms — the ``freshness`` SLO-burn rule's observation."""
        if emitter_id is None:
            hist = self.fleet_freshness
        else:
            with self._lock:
                state = self.emitters.get(emitter_id)
            if state is None:
                return 0, 0
            hist = state.freshness
        return hist.count, hist.count_above(budget_us)

    # -- journal replay -------------------------------------------------- #

    def replay_journal(self, path: Optional[str] = None) -> int:
        """Re-apply every journaled frame through the normal apply path
        (duplicates deduplicate by seq exactly like live re-delivery).
        Returns the number of frames applied.  Only meaningful against
        an aggregator that does NOT already contain these samples — the
        receiver-restart-with-fresh-state recovery drill."""
        from loghisto_tpu.utils.journal import FrameJournal

        path = path if path is not None else self.journal_path
        if path is None:
            raise ValueError("no journal_path configured or given")
        n = 0
        for kind, payload in FrameJournal.replay(path):
            if kind not in (wire.KIND_DELTA, wire.KIND_DELTA2):
                continue
            try:
                # live=False: a replayed frame's stamps describe a past
                # incarnation — rebuilding state must not fabricate
                # freshness samples
                self._apply_delta(wire.decode_payload(kind, payload),
                                  live=False)
            except wire.WireError:
                with self._lock:
                    self.decode_errors += 1
                continue
            n += 1
        self.frames_replayed += n
        return n

    # -- health / gauges ------------------------------------------------- #

    def _lag_locked(self, state: _EmitterState, now_mono_ns: int) -> float:
        """Per-emitter lag in seconds, computed from MONOTONIC deltas
        against the anchor pair so a wall-clock step on either side can
        never drive it negative; clamped anyway because transit jitter
        on the anchor frame can predict a capture marginally in the
        future.  v1 emitters (no stamps) fall back to arrival age."""
        if state.e_mono0 is not None:
            lag_ns = (
                (now_mono_ns - state.r_mono0)
                - (state.last_e_mono - state.e_mono0)
            )
            return max(0.0, lag_ns / 1e9)
        return max(0.0, time.monotonic() - state.last_frame_t)

    def max_emitter_lag_s(self) -> float:
        """Lag of the STALEST emitter (0 with no emitters): the
        fleet-wide freshness bound the lag gauge and the starvation
        invariant read."""
        now_ns = time.monotonic_ns()
        with self._lock:
            if not self.emitters:
                return 0.0
            return max(
                self._lag_locked(s, now_ns) for s in self.emitters.values()
            )

    def max_emitter_skew_s(self) -> float:
        """Largest absolute wall-vs-monotonic divergence any emitter
        has shown since its clock anchor — the ``emitter_clock_skew``
        invariant's input."""
        with self._lock:
            if not self.emitters:
                return 0.0
            return max(
                abs(s.skew_ns) / 1e9 for s in self.emitters.values()
            )

    def last_frame_age_s(self) -> float:
        """Seconds since ANY frame arrived (since start() before the
        first frame; 0 when never started)."""
        now = time.monotonic()
        with self._lock:
            if self.emitters:
                return min(
                    now - s.last_frame_t for s in self.emitters.values()
                )
        if self._started_t is None:
            return 0.0
        return now - self._started_t

    def frames_per_s(self) -> float:
        """Frame arrival rate since the last call (gauge-scrape shaped)."""
        now = time.monotonic()
        t0, f0 = self._rate_mark
        frames = self.frames_received
        self._rate_mark = (now, frames)
        dt = now - t0
        if dt <= 0.0:
            return 0.0
        return (frames - f0) / dt

    def stats(self) -> dict:
        now_ns = time.monotonic_ns()
        with self._lock:
            per_emitter = {
                f"{eid:016x}": {
                    "last_seq": s.last_seq,
                    "frames": s.frames,
                    "samples": s.samples,
                    "duplicates": s.duplicates,
                    "gaps": s.gaps,
                    "parked_rows": s.parked_rows,
                    "wire_v": s.wire_v,
                    "lag_s": round(self._lag_locked(s, now_ns), 3),
                    "skew_s": round(s.skew_ns / 1e9, 6),
                }
                for eid, s in self.emitters.items()
            }
            pending = len(self._pending)
        return {
            "port": self.port,
            "connections_active": self.connections_active,
            "connections_total": self.connections_total,
            "frames_received": self.frames_received,
            "frames_replayed": self.frames_replayed,
            "frames_v1": self.frames_v1,
            "bytes_received": self.bytes_received,
            "decode_errors": self.decode_errors,
            "duplicate_frames": self.duplicate_frames,
            "seq_gaps": self.seq_gaps,
            "samples_merged": self.samples_merged,
            "samples_shed": self.samples_shed,
            "samples_parked": self.samples_parked,
            "freshness_samples": self.fleet_freshness.count,
            "freshness_pending": pending,
            "freshness_dropped": self.freshness_dropped,
            "emitters": per_emitter,
        }

    def fleet_report(self, top_k: int = 3) -> dict:
        """The ``/fleetz`` payload: every emitter's rollup (sequencing,
        lag, freshness p99, clock skew, piggybacked health), top-K
        slowest / laggiest / flappiest lists, and starvation / skew flag
        lists.  Percentiles run through the jax-free mirror so a bare
        receiver can serve this without device code."""
        now_ns = time.monotonic_ns()
        now = time.monotonic()
        with self._lock:
            snap = list(self.emitters.items())
            rows = {}
            for eid, s in snap:
                health = s.health or {}
                p99s = health.get("p99_us", {})
                lag = self._lag_locked(s, now_ns)
                rows[f"{eid:016x}"] = {
                    "last_seq": s.last_seq,
                    "frames": s.frames,
                    "samples": s.samples,
                    "gaps": s.gaps,
                    "duplicates": s.duplicates,
                    "parked_rows": s.parked_rows,
                    "wire_v": s.wire_v,
                    "lag_s": round(lag, 3),
                    "skew_s": round(s.skew_ns / 1e9, 6),
                    "stalled": lag > self.starvation_s,
                    "freshness_p99_us": round(
                        s.freshness.percentile_host(99.0), 1
                    ),
                    "stage_p99_us": p99s,
                    "backlog": health.get("backlog", 0),
                    "send_failures": health.get("fail", 0),
                    "restarts": health.get("restarts", 0),
                    "uptime_s": health.get("up_s", 0.0),
                    "health_age_s": (
                        round(now - s.health_t, 1) if s.health else None
                    ),
                }
            pending = len(self._pending)
        def _top(key) -> list:
            ranked = sorted(
                rows.items(), key=lambda kv: key(kv[1]), reverse=True
            )
            return [eid for eid, r in ranked[:top_k] if key(r) > 0]
        return {
            "emitters": rows,
            "fleet": {
                "emitters": len(rows),
                "expected_emitters": self.expected_emitters,
                "freshness_p99_us": round(
                    self.fleet_freshness.percentile_host(99.0), 1
                ),
                "freshness_samples": self.fleet_freshness.count,
                "freshness_pending": pending,
                "oldest_pending_age_s": round(
                    self.oldest_pending_age_s(), 3
                ),
                "frames_received": self.frames_received,
                "seq_gaps": self.seq_gaps,
                "samples_merged": self.samples_merged,
                "samples_shed": self.samples_shed,
            },
            "top": {
                "slowest": _top(
                    lambda r: max(r["stage_p99_us"].values(), default=0.0)
                ),
                "laggiest": _top(lambda r: r["lag_s"]),
                "flappiest": _top(
                    lambda r: r["restarts"] * 1000 + r["send_failures"]
                ),
            },
            "flags": {
                "starved": [
                    eid for eid, r in rows.items() if r["stalled"]
                ],
                "clock_skew": [
                    eid for eid, r in rows.items()
                    if abs(r["skew_s"]) > self.skew_tolerance_s
                ],
            },
        }

    def register_gauges(self, ms) -> None:
        """The ``federation.*`` gauge family on the ordinary exporter
        pipeline; per-emitter lag gauges register lazily as emitters
        first appear."""
        self._ms = ms
        ms.register_gauge_func(
            "federation.ConnectedEmitters",
            lambda: float(len(self.emitters)),
        )
        ms.register_gauge_func(
            "federation.ActiveConnections",
            lambda: float(self.connections_active),
        )
        ms.register_gauge_func(
            "federation.FramesReceived",
            lambda: float(self.frames_received),
        )
        ms.register_gauge_func(
            "federation.FramesPerSec", self.frames_per_s,
        )
        ms.register_gauge_func(
            "federation.BytesReceived",
            lambda: float(self.bytes_received),
        )
        ms.register_gauge_func(
            "federation.DecodeErrors",
            lambda: float(self.decode_errors),
        )
        ms.register_gauge_func(
            "federation.DuplicateFrames",
            lambda: float(self.duplicate_frames),
        )
        ms.register_gauge_func(
            "federation.SeqGaps", lambda: float(self.seq_gaps),
        )
        ms.register_gauge_func(
            "federation.SamplesMerged",
            lambda: float(self.samples_merged),
        )
        ms.register_gauge_func(
            "federation.SamplesShed",
            lambda: float(self.samples_shed),
        )
        ms.register_gauge_func(
            "federation.SamplesParked",
            lambda: float(self.samples_parked),
        )
        ms.register_gauge_func(
            "federation.MaxEmitterLagS", self.max_emitter_lag_s,
        )
        ms.register_gauge_func(
            "federation.MaxEmitterSkewS", self.max_emitter_skew_s,
        )
        ms.register_gauge_func(
            "fed.freshness_p99_us",
            lambda: self.fleet_freshness.percentile_host(99.0),
        )
        ms.register_gauge_func(
            "fed.freshness_pending",
            lambda: float(len(self._pending)),
        )

    def _register_emitter_gauge(self, emitter_id: int) -> None:
        ms = getattr(self, "_ms", None)
        if ms is None:
            return
        def _lag(eid=emitter_id) -> float:
            now_ns = time.monotonic_ns()
            with self._lock:
                s = self.emitters.get(eid)
                if s is None:
                    return 0.0
                return self._lag_locked(s, now_ns)
        ms.register_gauge_func(
            f"federation.emitter.{emitter_id:016x}.LagS", _lag
        )
        def _fresh_p99(eid=emitter_id) -> float:
            with self._lock:
                s = self.emitters.get(eid)
            if s is None:
                return 0.0
            return s.freshness.percentile_host(99.0)
        ms.register_gauge_func(
            f"fed.emitter.{emitter_id:016x}.freshness_p99_us", _fresh_p99
        )
