"""Federation tier: many emitter processes, one aggregator pod (ISSUE 11).

``FederationEmitter`` runs inside any frontend process — it is jax-free
by construction (this package imports it lazily, and everything on its
dependency path stays off jax) — folds locally recorded samples into
packed ``[n, 3]`` int32 (id, codec_bucket, count) triples once per
interval, frames them (versioned header + name-dictionary delta + CRC32,
ops/codec.py), and ships them over TCP through the shared
``submitter.BacklogSender`` retry machinery.

``FederationReceiver`` runs next to the ``TPUAggregator``: supervised
accept/decode threads, per-emitter sequence tracking with gap detection
and idempotent re-delivery, name→row interning through the registry
free-list, and the decoded triples drain into the aggregator's packed
ingest path so federated deltas merge through the same fused commit.
int32 scatter-adds are order-independent, so the aggregate is
bit-identical to a single-process oracle regardless of arrival order.

Wired into the system as ``TPUMetricSystem(federation=
FederationConfig(...))``; chaos hook sites ``fed.accept`` /
``fed.decode`` / ``fed.send``; ``federation.*`` gauges; the
``emitter_starvation`` and ``fed_decode_errors`` health invariants.
"""

from __future__ import annotations

import dataclasses
from typing import Optional


@dataclasses.dataclass
class FederationConfig:
    """Receiver-side federation knobs for TPUMetricSystem.

    Attributes:
      host/port: TCP listen address; port 0 binds an ephemeral port
        (read it back from ``ms.federation.port`` after ``start()``).
      expected_emitters: how many distinct emitters SHOULD be feeding
        this pod.  Zero means "whatever shows up"; nonzero arms the
        ``emitter_starvation`` health invariant before the first frame
        ever arrives, so a pod that never hears from its fleet pages.
      journal_path: append every applied frame to a binary frame journal
        (utils/journal.FrameJournal) for receiver-restart replay.
      replay_on_start: re-apply the journal into the (fresh) aggregator
        when the receiver starts — bit-identical restart recovery.
        Leave False when the aggregator state is restored by checkpoint
        recovery instead (replaying on top would double count).
      starvation_intervals: how many system intervals of frame silence
        before ``emitter_starvation`` trips.
      skew_tolerance_s: how far an emitter's wall clock may diverge
        from its monotonic clock (since its anchor frame) before the
        ``emitter_clock_skew`` invariant trips and the emitter lands in
        ``/fleetz``'s ``clock_skew`` flag list (ISSUE 12).
    """

    host: str = "127.0.0.1"
    port: int = 0
    expected_emitters: int = 0
    journal_path: Optional[str] = None
    replay_on_start: bool = False
    starvation_intervals: float = 3.0
    skew_tolerance_s: float = 1.0


def __getattr__(name):
    # Lazy (PEP 562): the emitter must import without jax; the receiver
    # pulls numpy-heavy machinery the config-only import path can skip.
    if name == "FederationEmitter":
        from loghisto_tpu.federation.emitter import FederationEmitter

        return FederationEmitter
    if name == "FederationReceiver":
        from loghisto_tpu.federation.receiver import FederationReceiver

        return FederationReceiver
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


__all__ = ["FederationConfig", "FederationEmitter", "FederationReceiver"]
