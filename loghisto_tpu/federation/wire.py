"""Federation payload schema: the DELTA frame.

One frame carries one emitter interval, self-describing given the
frames before it from the same emitter:

    <u64 emitter_id> <u64 seq> <u32 n_names> <u32 n_rows>
    n_names x ( <u32 local_id> <u16 len> <len B utf-8 name> )
    n_rows  x ( <i32 local_id> <i32 codec_bucket> <i32 count> )

* ``emitter_id`` is a random u64 minted per emitter process; the
  receiver keys sequence tracking and the local-id→row map on it.
* ``seq`` is monotonic from 1 per emitter.  The receiver applies each
  seq at most once (idempotent re-delivery) and counts gaps.
* The name dictionary is DELTA encoded: only names first shipped in
  this frame appear, so steady state pays ~0 dictionary bytes.  Row
  triples reference emitter-local ids; the receiver interns names into
  aggregator registry rows and rewrites the id column.
* Triples are the PR-6 packed ``[n, 3]`` int32 layout verbatim —
  ``numpy.tobytes()`` little-endian on the way out, ``frombuffer`` on
  the way in.  Counts are positive and < 2^30 (the packed-row cap), so
  the receiver-side scatter-add can never overflow mid-merge.

Framing (magic/version/length/CRC) is ops/codec.py's; this module only
owns the DELTA payload bytes.  Decode is strict: every declared length
must land exactly on the payload end, and any violation raises
``WireError`` — which the receiver counts as a decode error and refuses
to apply, because a mis-split triple array would merge garbage counts.
"""

from __future__ import annotations

import dataclasses
import struct

import numpy as np

# frame ``kind`` byte (ops.codec.encode_frame) for DELTA payloads
KIND_DELTA = 1

_DELTA_HEAD = struct.Struct("<QQII")
_NAME_HEAD = struct.Struct("<IH")
_MAX_NAME_BYTES = 4096


class WireError(ValueError):
    """A structurally invalid DELTA payload (the frame CRC passed, so
    this is a schema bug or version skew, not line noise)."""


@dataclasses.dataclass
class DeltaFrame:
    emitter_id: int
    seq: int
    names: list  # [(local_id, name), ...] first shipped in this frame
    packed: np.ndarray  # int32 [n, 3] (local_id, codec_bucket, count)

    @property
    def samples(self) -> int:
        return int(self.packed[:, 2].sum(dtype=np.int64))


def encode_delta(
    emitter_id: int, seq: int, names, packed: np.ndarray
) -> bytes:
    """Assemble one DELTA payload (see module docstring for the layout)."""
    packed = np.ascontiguousarray(packed, dtype=np.int32)
    if packed.ndim != 2 or packed.shape[1] != 3:
        raise ValueError(
            f"packed must be [n, 3] (id, bucket, count); got {packed.shape}"
        )
    parts = [_DELTA_HEAD.pack(emitter_id, seq, len(names), len(packed))]
    for local_id, name in names:
        raw = name.encode("utf-8")
        if len(raw) > _MAX_NAME_BYTES:
            raise ValueError(
                f"metric name {name[:40]!r}... is {len(raw)} B "
                f"(cap {_MAX_NAME_BYTES})"
            )
        parts.append(_NAME_HEAD.pack(local_id, len(raw)))
        parts.append(raw)
    if not packed.dtype.isnative:
        packed = packed.astype("<i4")
    parts.append(packed.tobytes())
    return b"".join(parts)


def decode_delta(payload: bytes) -> DeltaFrame:
    """Parse one DELTA payload; raises WireError on any structural
    violation instead of returning a best guess."""
    if len(payload) < _DELTA_HEAD.size:
        raise WireError(
            f"DELTA payload {len(payload)} B is shorter than its "
            f"{_DELTA_HEAD.size} B header"
        )
    emitter_id, seq, n_names, n_rows = _DELTA_HEAD.unpack_from(payload, 0)
    off = _DELTA_HEAD.size
    names = []
    for _ in range(n_names):
        if off + _NAME_HEAD.size > len(payload):
            raise WireError("DELTA name dictionary overruns the payload")
        local_id, name_len = _NAME_HEAD.unpack_from(payload, off)
        off += _NAME_HEAD.size
        if name_len > _MAX_NAME_BYTES or off + name_len > len(payload):
            raise WireError("DELTA name entry overruns the payload")
        try:
            name = payload[off:off + name_len].decode("utf-8")
        except UnicodeDecodeError as e:
            raise WireError(f"DELTA name is not utf-8: {e}") from e
        off += name_len
        names.append((local_id, name))
    rows_bytes = n_rows * 12
    if off + rows_bytes != len(payload):
        raise WireError(
            f"DELTA declares {n_rows} rows ({rows_bytes} B) but "
            f"{len(payload) - off} B remain past the dictionary"
        )
    packed = (
        np.frombuffer(payload, dtype="<i4", count=n_rows * 3, offset=off)
        .reshape(n_rows, 3)
        .astype(np.int32)  # native, writable copy: the receiver rewrites
    )                      # the id column in place
    return DeltaFrame(
        emitter_id=emitter_id, seq=seq, names=names, packed=packed
    )
