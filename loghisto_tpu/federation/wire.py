"""Federation payload schema: the DELTA frame.

One frame carries one emitter interval, self-describing given the
frames before it from the same emitter:

    <u64 emitter_id> <u64 seq> <u32 n_names> <u32 n_rows>
    n_names x ( <u32 local_id> <u16 len> <len B utf-8 name> )
    n_rows  x ( <i32 local_id> <i32 codec_bucket> <i32 count> )

* ``emitter_id`` is a random u64 minted per emitter process; the
  receiver keys sequence tracking and the local-id→row map on it.
* ``seq`` is monotonic from 1 per emitter.  The receiver applies each
  seq at most once (idempotent re-delivery) and counts gaps.
* The name dictionary is DELTA encoded: only names first shipped in
  this frame appear, so steady state pays ~0 dictionary bytes.  Row
  triples reference emitter-local ids; the receiver interns names into
  aggregator registry rows and rewrites the id column.
* Triples are the PR-6 packed ``[n, 3]`` int32 layout verbatim —
  ``numpy.tobytes()`` little-endian on the way out, ``frombuffer`` on
  the way in.  Counts are positive and < 2^30 (the packed-row cap), so
  the receiver-side scatter-add can never overflow mid-merge.

Framing (magic/version/length/CRC) is ops/codec.py's; this module only
owns the DELTA payload bytes.  Decode is strict: every declared length
must land exactly on the payload end, and any violation raises
``WireError`` — which the receiver counts as a decode error and refuses
to apply, because a mis-split triple array would merge garbage counts.

Wire v2 (``KIND_DELTA2``) prepends observability fields to the same
body so the fleet plane can trace and time frames across the process
boundary:

    <u64 emitter_id> <u64 seq>
    <u64 mono_ns> <u64 wall_ns>          capture stamps (emitter clocks)
    <u32 health_len> health_len B json   compact emitter health summary
    <u32 n_names> <u32 n_rows> ...       v1 body, unchanged

``mono_ns``/``wall_ns`` are the emitter's CLOCK_MONOTONIC and wall
clock at the moment the interval's first sample was staged (flush time
for empty heartbeats).  Monotonic stamps are only comparable to other
stamps from the same process; the receiver anchors them per emitter and
works in deltas, using the wall stamp purely as a merge-alignment
anchor and clock-skew detector.  The payload version rides on the frame
*kind* — never on ops.codec's FRAME_VERSION, which old decoders reject
outright — so a v1 receiver skips v2 frames as unknown kinds and a v2
receiver still applies v1 frames (minus freshness/health).
"""

from __future__ import annotations

import dataclasses
import json
import struct
from typing import Optional

import numpy as np

# frame ``kind`` bytes (ops.codec.encode_frame) for DELTA payloads
KIND_DELTA = 1   # v1: id/seq + dictionary + rows
KIND_DELTA2 = 2  # v2: v1 + capture stamps + health summary

_DELTA_HEAD = struct.Struct("<QQII")
_DELTA2_HEAD = struct.Struct("<QQQQI")  # emitter_id, seq, mono_ns, wall_ns, health_len
_NAME_HEAD = struct.Struct("<IH")
_MAX_NAME_BYTES = 4096
_MAX_HEALTH_BYTES = 65536


class WireError(ValueError):
    """A structurally invalid DELTA payload (the frame CRC passed, so
    this is a schema bug or version skew, not line noise)."""


@dataclasses.dataclass
class DeltaFrame:
    emitter_id: int
    seq: int
    names: list  # [(local_id, name), ...] first shipped in this frame
    packed: np.ndarray  # int32 [n, 3] (local_id, codec_bucket, count)
    # v2-only observability fields; None when decoded from a v1 frame.
    mono_ns: Optional[int] = None  # emitter CLOCK_MONOTONIC at capture
    wall_ns: Optional[int] = None  # emitter wall clock at capture
    health: Optional[dict] = None  # compact emitter health summary

    @property
    def samples(self) -> int:
        return int(self.packed[:, 2].sum(dtype=np.int64))


def fed_flow_id(emitter_id: int, seq: int) -> int:
    """Deterministic Perfetto flow id for one (emitter, interval) frame.

    Both sides of the process boundary derive the same id from fields
    already on the wire, so no extra bytes are spent on trace context.
    Kept under 2^53 so the id survives a JSON round trip exactly.
    """
    return ((emitter_id & 0x1FFFFF) << 32) | (seq & 0xFFFFFFFF)


def _encode_body(names, packed: np.ndarray) -> list:
    """Shared v1/v2 tail: <u32 n_names> <u32 n_rows> dictionary rows."""
    packed = np.ascontiguousarray(packed, dtype=np.int32)
    if packed.ndim != 2 or packed.shape[1] != 3:
        raise ValueError(
            f"packed must be [n, 3] (id, bucket, count); got {packed.shape}"
        )
    parts = [struct.pack("<II", len(names), len(packed))]
    for local_id, name in names:
        raw = name.encode("utf-8")
        if len(raw) > _MAX_NAME_BYTES:
            raise ValueError(
                f"metric name {name[:40]!r}... is {len(raw)} B "
                f"(cap {_MAX_NAME_BYTES})"
            )
        parts.append(_NAME_HEAD.pack(local_id, len(raw)))
        parts.append(raw)
    if not packed.dtype.isnative:
        packed = packed.astype("<i4")
    parts.append(packed.tobytes())
    return parts


def encode_delta(
    emitter_id: int, seq: int, names, packed: np.ndarray
) -> bytes:
    """Assemble one v1 DELTA payload (see module docstring)."""
    body = _encode_body(names, packed)
    return b"".join(
        [struct.pack("<QQ", emitter_id, seq)] + body
    )


def encode_delta2(
    emitter_id: int,
    seq: int,
    names,
    packed: np.ndarray,
    mono_ns: int,
    wall_ns: int,
    health: Optional[dict] = None,
) -> bytes:
    """Assemble one v2 DELTA payload: capture stamps + health + v1 body."""
    raw_health = b""
    if health:
        raw_health = json.dumps(
            health, separators=(",", ":"), sort_keys=True
        ).encode("utf-8")
        if len(raw_health) > _MAX_HEALTH_BYTES:
            raise ValueError(
                f"health summary is {len(raw_health)} B "
                f"(cap {_MAX_HEALTH_BYTES})"
            )
    head = _DELTA2_HEAD.pack(
        emitter_id, seq, int(mono_ns), int(wall_ns), len(raw_health)
    )
    body = _encode_body(names, packed)
    return b"".join([head, raw_health] + body)


def _decode_body(payload: bytes, off: int):
    """Parse <u32 n_names> <u32 n_rows> dictionary rows from ``off`` to
    exactly the payload end; returns (names, packed)."""
    if off + 8 > len(payload):
        raise WireError(
            f"DELTA payload {len(payload)} B is shorter than its header"
        )
    n_names, n_rows = struct.unpack_from("<II", payload, off)
    off += 8
    names = []
    for _ in range(n_names):
        if off + _NAME_HEAD.size > len(payload):
            raise WireError("DELTA name dictionary overruns the payload")
        local_id, name_len = _NAME_HEAD.unpack_from(payload, off)
        off += _NAME_HEAD.size
        if name_len > _MAX_NAME_BYTES or off + name_len > len(payload):
            raise WireError("DELTA name entry overruns the payload")
        try:
            name = payload[off:off + name_len].decode("utf-8")
        except UnicodeDecodeError as e:
            raise WireError(f"DELTA name is not utf-8: {e}") from e
        off += name_len
        names.append((local_id, name))
    rows_bytes = n_rows * 12
    if off + rows_bytes != len(payload):
        raise WireError(
            f"DELTA declares {n_rows} rows ({rows_bytes} B) but "
            f"{len(payload) - off} B remain past the dictionary"
        )
    packed = (
        np.frombuffer(payload, dtype="<i4", count=n_rows * 3, offset=off)
        .reshape(n_rows, 3)
        .astype(np.int32)  # native, writable copy: the receiver rewrites
    )                      # the id column in place
    return names, packed


def decode_delta(payload: bytes) -> DeltaFrame:
    """Parse one v1 DELTA payload; raises WireError on any structural
    violation instead of returning a best guess."""
    if len(payload) < _DELTA_HEAD.size:
        raise WireError(
            f"DELTA payload {len(payload)} B is shorter than its "
            f"{_DELTA_HEAD.size} B header"
        )
    emitter_id, seq = struct.unpack_from("<QQ", payload, 0)
    names, packed = _decode_body(payload, 16)
    return DeltaFrame(
        emitter_id=emitter_id, seq=seq, names=names, packed=packed
    )


def decode_delta2(payload: bytes) -> DeltaFrame:
    """Parse one v2 DELTA payload (stamps + health + v1 body)."""
    if len(payload) < _DELTA2_HEAD.size:
        raise WireError(
            f"DELTA2 payload {len(payload)} B is shorter than its "
            f"{_DELTA2_HEAD.size} B header"
        )
    emitter_id, seq, mono_ns, wall_ns, health_len = _DELTA2_HEAD.unpack_from(
        payload, 0
    )
    off = _DELTA2_HEAD.size
    if health_len > _MAX_HEALTH_BYTES or off + health_len > len(payload):
        raise WireError(
            f"DELTA2 health blob of {health_len} B overruns the payload"
        )
    health = None
    if health_len:
        try:
            health = json.loads(payload[off:off + health_len])
        except (UnicodeDecodeError, json.JSONDecodeError) as e:
            raise WireError(f"DELTA2 health blob is not json: {e}") from e
        if not isinstance(health, dict):
            raise WireError("DELTA2 health blob must be a json object")
    off += health_len
    names, packed = _decode_body(payload, off)
    return DeltaFrame(
        emitter_id=emitter_id,
        seq=seq,
        names=names,
        packed=packed,
        mono_ns=mono_ns,
        wall_ns=wall_ns,
        health=health,
    )


def decode_payload(kind: int, payload: bytes) -> DeltaFrame:
    """Dispatch on the frame kind byte; raises WireError for kinds this
    receiver does not speak (forward-compat: count and drop, don't crash)."""
    if kind == KIND_DELTA:
        return decode_delta(payload)
    if kind == KIND_DELTA2:
        return decode_delta2(payload)
    raise WireError(f"unknown DELTA frame kind {kind}")
