"""loghisto_tpu — TPU-native metrics framework with the capabilities of
spacejam/loghisto: counters and sampling-free log-bucketed histograms whose
percentiles stay within 1% of the true value, aggregated by XLA/Pallas
kernels over a dense bucket tensor and merged across device meshes with
psum collectives.  See SURVEY.md for the structural map to the reference."""

from loghisto_tpu.channel import Channel, ChannelClosed
from loghisto_tpu.config import DEFAULT_PERCENTILES, MetricConfig
from loghisto_tpu.metrics import (
    FastCounter,
    FastRecorder,
    FastTimer,
    FastTimerToken,
    MetricSystem,
    ProcessedMetricSet,
    RawMetricSet,
    TimerToken,
    merge_raw_metric_sets,
)
__version__ = "0.1.0"


def __getattr__(name):
    # Lazy (PEP 562): TPUMetricSystem pulls jax; federation emitter
    # processes import this package jax-free on the host-tier names
    # above.  Everything else about the public surface is unchanged.
    if name == "TPUMetricSystem":
        from loghisto_tpu.system import TPUMetricSystem

        return TPUMetricSystem
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")

# Package-level default system, mirroring the reference's
# `var Metrics = NewMetricSystem(60*time.Second, true)` (metrics.go:137-139).
# Not auto-started; call Metrics.start() to begin collection.
Metrics = MetricSystem(interval=60.0, sys_stats=True)

__all__ = [
    "Channel",
    "ChannelClosed",
    "DEFAULT_PERCENTILES",
    "FastCounter",
    "FastRecorder",
    "FastTimer",
    "FastTimerToken",
    "MetricConfig",
    "MetricSystem",
    "Metrics",
    "ProcessedMetricSet",
    "RawMetricSet",
    "TPUMetricSystem",
    "TimerToken",
    "merge_raw_metric_sets",
]
