"""Host-facing MetricSystem: ingest, collection, processing, broadcast.

This is the rebuild of the reference's layers L2+L3 (metrics.go), redesigned
for a batch-oriented TPU backend instead of Go's per-sample
mutex-and-atomics design:

  * Ingest (`counter`/`histogram`/`start_timer`) appends to *lock-striped
    shard buffers* — histogram samples are stored raw as (value) appends per
    name, NOT bucketed per call.  Bucketing happens once per interval as a
    vectorized batch (NumPy on the host tier, XLA/Pallas on the device
    tier), which is what makes the hot path cheap and the math TPU-shaped.
  * The reaper is an interval-aligned daemon thread: swap-and-reset the
    shard buffers, fold counters into the lifetime store, poll gauges,
    broadcast a RawMetricSet, then hand statistic derivation to a bounded
    worker pool which broadcasts the ProcessedMetricSet (reference
    metrics.go:508-653 semantics: non-blocking broadcast, strike eviction,
    whole-interval shedding when the pool is saturated).

Behavioral parity notes (SURVEY.md §2):
  * naming scheme: counters -> bare name (lifetime) and `<name>_rate`
    (interval delta); histograms -> `<name>_{count,sum,avg}`, percentile
    labels `label % name`, lifetime `<name>_agg_{avg,count,sum}`; gauges
    verbatim (metrics.go:481-506, 585-608).
  * subscribers are evicted after `config.eviction_strikes` consecutive
    failed deliveries (the reference's code evicts on the 2nd;
    metrics.go:574,620) by closing their channel.
  * interval timestamps are floored to interval boundaries
    (metrics.go:421-423).
  * out-of-range percentile specs are logged and skipped
    (metrics.go:378-385).
  * `go_compat=True` reproduces the uint64-truncated lifetime sums and
    integer `_agg_avg` division (metrics.go:374, 601-602).

Deliberate improvements over the reference (documented deviations):
  * lifetime `_agg_*` folding happens once at *collection*, not during
    processing — `process_metrics` is pure, double-processing a
    RawMetricSet cannot double-count, and shed intervals still reach the
    lifetime aggregates.
  * a raising gauge function is logged and skipped instead of taking down
    the reaper.
"""

from __future__ import annotations

import dataclasses
import datetime as _dt
import functools
import itertools
import logging
import os
import queue
import threading
import time
from array import array
from typing import Callable, Dict, Mapping, Optional

import numpy as np

from loghisto_tpu.channel import Channel
from loghisto_tpu.config import DEFAULT_PERCENTILES, MetricConfig
from loghisto_tpu.labels.model import canonical_name
from loghisto_tpu.obs.spans import NULL_RECORDER
from loghisto_tpu.ops.codec import compress_np
# ops.stats is imported lazily inside the functions that need it: this
# module is on the base-package import path and federation emitter
# processes must import it without pulling jax
from loghisto_tpu.utils.sysstats import default_gauges

logger = logging.getLogger("loghisto_tpu")

_UINT64_MASK = 0xFFFFFFFFFFFFFFFF


@dataclasses.dataclass
class RawMetricSet:
    """Per-interval raw collection output (reference metrics.go:54-60).

    histograms maps name -> {bucket_index: count} — sparse, full int16
    span, exactly mergeable across systems/hosts by elementwise addition.

    ``duration`` is the collection interval in seconds (None for sets
    built before this field existed, e.g. old journal lines).  Rates are
    per-interval deltas, so any consumer doing per-second math (burn
    rates, replayed-history rates in the timewheel) needs the real
    duration, not an assumed live interval.

    ``seq`` is the interval sequence number minted by the reaper at
    collection (ISSUE 9): every observability span recorded while this
    set moves through the pipeline attributes to it, and journal lines
    carry it so replayed intervals correlate with archived traces.
    None for pre-obs sets (old journal lines, hand-built sets).
    """

    time: _dt.datetime
    counters: Dict[str, int]
    rates: Dict[str, int]
    histograms: Dict[str, Dict[int, int]]
    gauges: Dict[str, float]
    duration: Optional[float] = None
    seq: Optional[int] = None


@dataclasses.dataclass
class ProcessedMetricSet:
    """Flat human-readable metrics (reference metrics.go:47-50)."""

    time: _dt.datetime
    metrics: Dict[str, float]


def merge_raw_metric_sets(a: RawMetricSet, b: RawMetricSet) -> RawMetricSet:
    """Merge two RawMetricSets — e.g. the same interval collected by two
    processes/hosts.  Counters/rates add, histograms merge bucket-wise
    (the exact mergeability the device tier rides via psum), gauges keep
    the second argument's value on collision (gauges are point samples
    and don't add).  The earlier timestamp wins (both are
    interval-floored, so same-interval merges keep their boundary)."""
    counters = dict(a.counters)
    for name, v in b.counters.items():
        counters[name] = counters.get(name, 0) + v
    rates = dict(a.rates)
    for name, v in b.rates.items():
        rates[name] = rates.get(name, 0) + v
    histograms: Dict[str, Dict[int, int]] = {
        name: dict(buckets) for name, buckets in a.histograms.items()
    }
    for name, buckets in b.histograms.items():
        _merge_counts(
            histograms.setdefault(name, {}), buckets.keys(), buckets.values()
        )
    gauges = dict(a.gauges)
    gauges.update(b.gauges)
    # same-interval merges (the intended use) keep the shared duration;
    # mismatched durations can't be reconciled, so drop to unknown
    duration = a.duration if a.duration == b.duration else None
    return RawMetricSet(
        time=min(a.time, b.time),
        counters=counters,
        rates=rates,
        histograms=histograms,
        gauges=gauges,
        duration=duration,
        # two different intervals merged: neither seq attributes the
        # result, so trace correlation honestly says "unknown"
        seq=a.seq if a.seq == b.seq else None,
    )


def _record_duration(system: "MetricSystem", name: str, duration_ns: int) -> int:
    """Shared Python-clock sample routing for TimerToken and _PyTimer —
    the one place a unit or routing change applies to both (the Fast*
    twins stage in C instead and never pass through here)."""
    system.histogram(name, float(duration_ns))
    return duration_ns


class TimerToken:
    """Concurrent named duration timing (reference metrics.go:62-67).

    stop() records the duration as a histogram sample in nanoseconds and
    returns it."""

    __slots__ = ("name", "start_ns", "_system")

    def __init__(self, name: str, system: "MetricSystem"):
        self.name = name
        self._system = system
        self.start_ns = time.perf_counter_ns()

    def stop(self) -> int:
        duration_ns = time.perf_counter_ns() - self.start_ns
        return _record_duration(self._system, self.name, duration_ns)

    # Context-manager sugar (not in the reference, natural in Python).
    def __enter__(self) -> "TimerToken":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    Stop = stop


class _PyTimer:
    """Python-clock twin of FastTimer for systems without fast_ingest:
    same start()/stop(stamp) handle API, perf_counter_ns clocks, samples
    routed through histogram()."""

    __slots__ = ("name", "_system")

    def __init__(self, name: str, system: "MetricSystem"):
        self.name = name
        self._system = system

    def start(self) -> int:
        return time.perf_counter_ns()

    def stop(self, start_ns: int) -> int:
        duration_ns = time.perf_counter_ns() - start_ns
        return _record_duration(self._system, self.name, duration_ns)


class FastTimerToken:
    """C-extension timer token (VERDICT r3 item 6): the clock is read by
    the extension itself — last operation before ``timer_start`` returns,
    first operation when ``timer_stop`` enters — so the measured gap
    carries only the Python call plumbing between the two C calls, not
    name resolution (done here, before the clock starts), not histogram
    staging (done in C, after the clock stops), and not the fold check
    (one int compare on the staged size the C call returns).  Same API
    surface as TimerToken (reference metrics.go:62-67)."""

    __slots__ = ("name", "start_ns", "_stop_p", "_threshold", "_system")

    def __init__(self, name: str, system: "MetricSystem", stop_p):
        self.name = name
        self._system = system
        # per-name functools.partial(timer_stop, buf, fid) shared across
        # tokens: two slot loads inside the measured gap instead of four
        self._stop_p = stop_p
        self._threshold = system._fast_fold_threshold
        self.start_ns = system._fastpath.timer_start()

    def stop(self) -> int:
        duration_ns, size = self._stop_p(self.start_ns)
        if size >= self._threshold:
            self._system._fast_fold()
        return duration_ns

    def __enter__(self) -> "FastTimerToken":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    Stop = stop


class FastTimer:
    """Reusable per-name timer handle for hot loops: resolves the metric
    id once, then ``start()``/``stop(stamp)`` are one C call each with
    locals-only plumbing — the lowest-overhead timing path this runtime
    offers (no token allocation per measurement).

        timer = system.timer("op_latency")
        t = timer.start()
        ...
        dur_ns = timer.stop(t)
    """

    __slots__ = ("name", "_start_fn", "_stop_p", "_threshold", "_system")

    def __init__(self, name: str, system: "MetricSystem", stop_p):
        self.name = name
        self._system = system
        self._start_fn = system._fastpath.timer_start
        self._stop_p = stop_p
        self._threshold = system._fast_fold_threshold

    def start(self) -> int:
        return self._start_fn()

    def stop(self, start_ns: int) -> int:
        duration_ns, size = self._stop_p(start_ns)
        if size >= self._threshold:
            self._system._fast_fold()
        return duration_ns


class FastRecorder:
    """Reusable per-name histogram recorder for hot loops: resolves the
    metric name once, then ``record(value)`` is ONE C staging call
    (``record_sized``, which returns the post-stage buffer size) plus an
    int compare against the fold threshold — the per-call twin of
    FastTimer, without even the thread-local stride poll the generic
    ``histogram(name, value)`` path pays (the exact size comes back for
    free from the C call, so the fold check is precise, not strided).

        rec = system.recorder("payload_bytes")
        rec.record(len(payload))
    """

    __slots__ = ("name", "_rec_p", "_threshold", "_system")

    def __init__(self, name: str, system: "MetricSystem", rec_p):
        self.name = name
        self._system = system
        self._rec_p = rec_p
        self._threshold = system._fast_fold_threshold

    def record(self, value: float) -> None:
        if self._rec_p(value) >= self._threshold:
            self._system._fast_fold()


class _PyRecorder:
    """Python fallback for systems without fast_ingest: same
    record(value) surface, routed through histogram()."""

    __slots__ = ("name", "_system")

    def __init__(self, name: str, system: "MetricSystem"):
        self.name = name
        self._system = system

    def record(self, value: float) -> None:
        self._system.histogram(self.name, value)


class FastCounter:
    """Reusable per-name counter handle — the counter twin of
    FastRecorder (counters are the reference's other per-call hot path,
    metrics.go:251-269).  ``add(amount)`` is one C staging call + an int
    compare; amounts outside the integer-exact window (non-int, or
    |amount| > 2^31) take the full counter() path, preserving its
    exactness contract.

        reqs = system.counter_handle("requests")
        reqs.add(1)
    """

    __slots__ = ("name", "_add_p", "_threshold", "_system")

    def __init__(self, name: str, system: "MetricSystem", add_p):
        self.name = name
        self._system = system
        self._add_p = add_p
        self._threshold = system._fast_fold_threshold

    def add(self, amount: int = 1) -> None:
        if type(amount) is int and _I32_LO <= amount <= _I32_HI:
            if self._add_p(amount) >= self._threshold:
                self._system._fast_fold()
        else:
            self._system.counter(self.name, amount)


# The integer-exactness window both counter paths share (one spelling:
# the 2^53 float64 fold bound in counter()'s docstring is derived from
# it, so the two APIs must never drift apart).
_I32_LO = -(1 << 31)
_I32_HI = 1 << 31


class _PyCounter:
    """Python fallback counter handle: same add(amount) surface."""

    __slots__ = ("name", "_system")

    def __init__(self, name: str, system: "MetricSystem"):
        self.name = name
        self._system = system

    def add(self, amount: int = 1) -> None:
        self._system.counter(self.name, amount)


class _Shard:
    """One lock stripe of the ingest path: counter dict + histogram
    append-buffers + folded sparse bucket counts.  Threads are assigned a
    shard round-robin; contention is 1/num_shards.  When a metric's raw
    buffer reaches the configured cap it is compressed and folded into
    `bucket_counts`, bounding memory at O(buckets) regardless of sample
    rate or whether the reaper is running."""

    __slots__ = ("lock", "counters", "histograms", "bucket_counts")

    def __init__(self):
        self.lock = threading.Lock()
        self.counters: Dict[str, int] = {}
        self.histograms: Dict[str, array] = {}
        self.bucket_counts: Dict[str, Dict[int, int]] = {}


def _num_default_shards() -> int:
    return max(4, min(64, (os.cpu_count() or 4)))


def _merge_counts(dst: Dict[int, int], buckets, counts) -> None:
    """Fold (bucket, count) pairs into a sparse bucket->count dict."""
    for b, c in zip(buckets, counts):
        b = int(b)
        dst[b] = dst.get(b, 0) + int(c)


class MetricSystem:
    """Collects and distributes metrics (rebuild of reference
    metrics.go:79-195)."""

    def __init__(
        self,
        interval: float = 60.0,
        sys_stats: bool = True,
        config: MetricConfig = MetricConfig(),
        num_shards: Optional[int] = None,
        fast_ingest: bool = False,
    ):
        """`fast_ingest=True` routes per-call histogram samples AND
        integer counter increments through C-extension staging buffers
        (several times the pure-Python hot path); falls back silently
        when the extension can't build.  The lifetime counter *store*
        stays integer-exact: amounts beyond 2^31 (and non-integer
        amounts) take the Python path, and fold sums stay under float64's
        2^53.  (Exported ProcessedMetricSet values are float64 either
        way, like the reference's uint64->float64 conversion.)"""
        if interval <= 0:
            raise ValueError("interval must be positive seconds")
        self.interval = float(interval)
        self.config = config
        self._percentiles: Dict[str, float] = dict(DEFAULT_PERCENTILES)

        self._fast_record = None
        if fast_ingest:
            from loghisto_tpu import _native

            if _native.fastpath_available():
                mod = _native.fastpath_module()
                self._fastpath = mod
                # fold triggers poll size(buf) per-buffer (see _fast_put);
                # the counter buffer is created lazily so histogram-only
                # workloads don't pay for it
                self._fast_buf = mod.create(1 << 22)
                self._fast_counter_buf = None
                self._fast_record = mod.record
                self._fast_lock = threading.Lock()
                self._fast_name_ids: Dict[str, int] = {}
                self._fast_names: list[str] = []
                # folded sparse counts, so memory stays O(buckets) like
                # the Python path regardless of interval length
                self._fast_folded: Dict[str, Dict[int, int]] = {}
                self._fast_counter_folded: Dict[str, int] = {}
                self._fast_fold_threshold = 1 << 21  # half the buffer
                self._fast_dropped_total = 0  # lifetime-cumulative
                self._fast_counter_dropped_total = 0
                self._fast_stop_partials: Dict[str, object] = {}
                self._fast_rec_partials: Dict[str, object] = {}
                self._fast_add_partials: Dict[str, object] = {}
            else:
                logger.warning(
                    "fast_ingest requested but the extension is "
                    "unavailable; using the Python path"
                )

        self._shards = [_Shard() for _ in range(num_shards or _num_default_shards())]
        # Threads are assigned shards round-robin via a thread-local (a
        # modulo of thread ids degenerates badly: glibc pthread ids share
        # their low bits across threads).
        self._thread_local = threading.local()
        self._shard_counter = itertools.count()

        # labeled-handle cache (ISSUE 16): recorder()/timer()/
        # counter_handle() calls with labels= resolve the canonical name
        # and reuse ONE handle per (kind, label set), so hot loops pay
        # the sort+validate exactly once per label set, not per call.
        # Benign-race dict (worst case a duplicate handle build); capped.
        self._labeled_handles: Dict[tuple, object] = {}

        # lifetime stores
        self._store_lock = threading.Lock()
        self._counter_store: Dict[str, int] = {}
        # name -> [lifetime_sum, lifetime_count]; sums are floats unless
        # go_compat truncates them per interval like the reference's uint64.
        self._histogram_agg_store: Dict[str, list] = {}

        self._gauge_lock = threading.Lock()
        self._gauge_funcs: Dict[str, Callable[[], float]] = {}
        if sys_stats:
            self._gauge_funcs.update(default_gauges())

        # subscription management: requests queue up and apply at the tick
        self._sub_requests: "queue.Queue[tuple[str, Channel]]" = queue.Queue()
        self._subscribers_lock = threading.Lock()
        self._raw_subscribers: Dict[Channel, int] = {}
        self._processed_subscribers: Dict[Channel, int] = {}

        self._lifecycle_lock = threading.Lock()
        self._shutdown = threading.Event()
        self._reaper_thread: Optional[threading.Thread] = None

        # observability (ISSUE 9): the reaper mints one sequence number
        # per collected interval; every pipeline span downstream of this
        # RawMetricSet attributes to it.  The recorder defaults to the
        # no-op twin; TPUMetricSystem(observability=...) swaps in a real
        # ring.
        self._interval_seq = itertools.count(1)
        self.obs_recorder = NULL_RECORDER

    # ------------------------------------------------------------------ #
    # ingest hot path (reference layer L2)
    # ------------------------------------------------------------------ #

    def _shard(self) -> _Shard:
        idx = getattr(self._thread_local, "shard_idx", None)
        if idx is None:
            idx = next(self._shard_counter) % len(self._shards)
            self._thread_local.shard_idx = idx
        return self._shards[idx]

    def _fast_put(self, buf, name: str, value: float) -> None:
        """Shared fast-path staging: record + fold-threshold heuristic.
        Folding at half the (equal-sized) buffers' capacity keeps
        steady-state loss at zero regardless of the counter/histogram
        traffic mix.  Worst-case poll lag is 4096 * n_threads records,
        far inside the half-capacity headroom (2^21 records)."""
        fid = self._fast_name_ids.get(name)
        if fid is None:
            fid = self._fast_id(name)
        self._fast_record(buf, fid, value)
        self._fast_tick(buf)

    def _fast_tick(self, buf) -> None:
        """Fold-threshold poll after a fast-path record (the
        histogram()/counter() path; the timer and recorder handles get
        the exact staged size back from their C call and compare it
        directly instead).  The trigger uses a THREAD-LOCAL stride
        counter plus the extension's authoritative ``size(buf)`` — a
        shared Python counter would lose increments under concurrent
        writers and let the staging buffer overflow before a fold
        fires."""
        tl = self._thread_local
        n = getattr(tl, "fast_n", 0) + 1
        # stride scales down with the threshold so shrunken test buffers
        # still poll often enough; capped so the steady-state C-call
        # overhead stays ~1/4096 records
        stride = min(4096, self._fast_fold_threshold >> 3) or 1
        if n >= stride:
            n = 0
            if self._fastpath.size(buf) >= self._fast_fold_threshold:
                self._fast_fold()
        tl.fast_n = n

    def counter(
        self, name: str, amount: int = 1,
        labels: Optional[Mapping[str, str]] = None,
    ) -> None:
        """Record `amount` occurrences of an event (metrics.go:251-269).
        ``labels`` dimension the counter: the increment lands on the
        canonical row ``name;k1=v1;...`` (sorted keys — every insertion
        order is ONE series; see loghisto_tpu/labels/model.py)."""
        if labels:
            name = canonical_name(name, labels)
        # fast path is exact for INTEGER |amount| <= 2^31 (2^21
        # records/fold x 2^31 < 2^53 float64-exact); bigger or
        # non-integer amounts take the Python path unchanged
        if (
            self._fast_record is not None
            and type(amount) is int
            and _I32_LO <= amount <= _I32_HI
        ):
            self._fast_put(self._fast_ensure_counter_buf(), name, amount)
            return
        shard = self._shard()
        with shard.lock:
            shard.counters[name] = shard.counters.get(name, 0) + amount

    def _fast_ensure_counter_buf(self):
        """Lazily create the counter staging buffer (double-checked; the
        one creation policy counter() and counter_handle() share)."""
        buf = self._fast_counter_buf
        if buf is None:
            with self._fast_lock:
                if self._fast_counter_buf is None:
                    self._fast_counter_buf = self._fastpath.create(1 << 22)
                buf = self._fast_counter_buf
        return buf

    def _fast_id(self, name: str) -> int:
        with self._fast_lock:
            fid = self._fast_name_ids.get(name)
            if fid is None:
                fid = len(self._fast_names)
                self._fast_names.append(name)
                self._fast_name_ids[name] = fid
            return fid

    def _fast_fold(self) -> None:
        """Drain the C staging buffer and fold into sparse bucket counts —
        the fast-path analog of _fold_shard_buffer, keeping memory at
        O(buckets) and the buffer from ever filling in steady state."""
        with self._fast_lock:
            # drain + drop accounting under the lock: concurrent folds
            # would otherwise move the lifetime watermark backward and
            # double-report sheds
            ids_b, vals_b, dropped = self._fastpath.drain(self._fast_buf)
            new_dropped = int(dropped) - self._fast_dropped_total
            self._fast_dropped_total = int(dropped)
            if self._fast_counter_buf is not None:
                cids_b, camounts_b, cdropped = self._fastpath.drain(
                    self._fast_counter_buf
                )
                new_cdropped = (
                    int(cdropped) - self._fast_counter_dropped_total
                )
                self._fast_counter_dropped_total = int(cdropped)
            else:
                cids_b, camounts_b, new_cdropped = b"", b"", 0
            names = list(self._fast_names)
        if new_dropped > 0:
            logger.error(
                "fast-ingest buffer overflowed; %d histogram samples shed",
                new_dropped,
            )
        if new_cdropped > 0:
            logger.error(
                "fast-ingest COUNTER buffer overflowed; %d increments shed "
                "— lifetime totals now under-report", new_cdropped,
            )
        if cids_b:
            cids = np.frombuffer(cids_b, dtype=np.int32)
            camounts = np.frombuffer(camounts_b, dtype=np.float64)
            sums = np.bincount(cids, weights=camounts)
            with self._fast_lock:
                # iterate ids actually recorded (not nonzero sums): a
                # counter(name, 0) still creates its rate entry, like the
                # reference
                for fid in np.unique(cids):
                    name = names[fid]
                    self._fast_counter_folded[name] = (
                        self._fast_counter_folded.get(name, 0)
                        + int(sums[fid])
                    )
        if not ids_b:
            return
        fids = np.frombuffer(ids_b, dtype=np.int32)
        fvals = np.frombuffer(vals_b, dtype=np.float64)
        order = np.argsort(fids, kind="stable")
        fids_s, fvals_s = fids[order], fvals[order]
        uniq, starts = np.unique(fids_s, return_index=True)
        bounds = np.append(starts, len(fids_s))
        for k, fid in enumerate(uniq):
            buckets = compress_np(
                fvals_s[bounds[k]:bounds[k + 1]], self.config.precision
            )
            ub, cnt = np.unique(buckets, return_counts=True)
            with self._fast_lock:
                _merge_counts(
                    self._fast_folded.setdefault(names[fid], {}), ub, cnt
                )

    def histogram(
        self, name: str, value: float,
        labels: Optional[Mapping[str, str]] = None,
    ) -> None:
        """Record one continuous value (metrics.go:273-295).  Values are
        appended raw; log-bucketing happens vectorized (at the buffer cap
        or at collection, whichever comes first).  ``labels`` dimension
        the series (canonical-row encoding; prefer ``recorder(name,
        labels=...)`` in hot loops — it prepays the canonicalization)."""
        if labels:
            name = canonical_name(name, labels)
        if self._fast_record is not None:
            self._fast_put(self._fast_buf, name, value)
            return
        shard = self._shard()
        with shard.lock:
            buf = shard.histograms.get(name)
            if buf is None:
                buf = shard.histograms[name] = array("d")
            buf.append(value)
            if len(buf) >= self.config.ingest_buffer_cap:
                self._fold_shard_buffer(shard, name, buf)

    def histogram_batch(
        self, name: str, values,
        labels: Optional[Mapping[str, str]] = None,
    ) -> None:
        """Record many values of one metric in a single call — the natural
        API for batch-oriented callers (no reference equivalent; the Go hot
        loop is per-sample)."""
        if labels:
            name = canonical_name(name, labels)
        shard = self._shard()
        with shard.lock:
            buf = shard.histograms.get(name)
            if buf is None:
                buf = shard.histograms[name] = array("d")
            buf.extend(values)
            if len(buf) >= self.config.ingest_buffer_cap:
                self._fold_shard_buffer(shard, name, buf)

    def _fold_shard_buffer(self, shard: _Shard, name: str, buf: array) -> None:
        """Compress a full raw buffer into the shard's sparse bucket counts.
        Caller holds shard.lock."""
        values = np.frombuffer(buf, dtype=np.float64)
        buckets = compress_np(values, self.config.precision)
        uniq, cnt = np.unique(buckets, return_counts=True)
        _merge_counts(shard.bucket_counts.setdefault(name, {}), uniq, cnt)
        shard.histograms[name] = array("d")

    def _labeled_handle(self, kind: str, name: str, labels, build):
        """One cached handle per (kind, canonical labeled name): hot
        loops calling ``recorder(name, labels={...})`` per request reuse
        the same handle object — canonicalization (sort + validate) and
        fast-path name resolution are paid once per label set."""
        cname = canonical_name(name, labels)
        key = (kind, cname)
        handle = self._labeled_handles.get(key)
        if handle is None:
            handle = build(cname)
            if len(self._labeled_handles) >= 4096:
                self._labeled_handles.clear()
            self._labeled_handles[key] = handle
        return handle

    def start_timer(
        self, name: str, labels: Optional[Mapping[str, str]] = None,
    ) -> "TimerToken | FastTimerToken":
        """Begin a named timing; stop() the returned token (metrics.go:232).
        With fast_ingest, the token's clock reads happen inside the C
        extension (FastTimerToken, same surface) — measured overhead
        drops ~2x."""
        if labels:
            name = canonical_name(name, labels)
        if self._fast_record is not None:
            return FastTimerToken(name, self, self._fast_stop_partial(name))
        return TimerToken(name, self)

    def timer(
        self, name: str, labels: Optional[Mapping[str, str]] = None,
    ) -> "FastTimer | _PyTimer":
        """Reusable per-name timer handle for hot loops (no per-
        measurement token allocation); see FastTimer.  Falls back to a
        Python-clock handle without fast_ingest.  With ``labels`` the
        handle is cached per label set (one object per canonical row)."""
        if labels:
            return self._labeled_handle("timer", name, labels, self.timer)
        if self._fast_record is not None:
            return FastTimer(name, self, self._fast_stop_partial(name))
        return _PyTimer(name, self)

    def recorder(
        self, name: str, labels: Optional[Mapping[str, str]] = None,
    ) -> "FastRecorder | _PyRecorder":
        """Reusable per-name histogram recorder for hot loops (name
        resolved once; record(value) is one C call + fold poll); see
        FastRecorder.  Python fallback without fast_ingest.  With
        ``labels`` the handle is cached per label set, so per-request
        ``recorder("http.latency", labels={"route": r})`` costs one dict
        probe after the first call for each route."""
        if labels:
            return self._labeled_handle(
                "recorder", name, labels, self.recorder
            )
        if self._fast_record is not None:
            return FastRecorder(name, self, self._fast_record_partial(name))
        return _PyRecorder(name, self)

    def counter_handle(
        self, name: str, labels: Optional[Mapping[str, str]] = None,
    ) -> "FastCounter | _PyCounter":
        """Reusable per-name counter handle for hot loops; see
        FastCounter.  Python fallback without fast_ingest.  With
        ``labels`` the handle is cached per label set."""
        if labels:
            return self._labeled_handle(
                "counter", name, labels, self.counter_handle
            )
        if self._fast_record is not None:
            return FastCounter(name, self, self._fast_add_partial(name))
        return _PyCounter(name, self)

    def _fast_record_partial(self, name: str):
        """Per-name functools.partial(record_sized, buf, fid) for
        recorder(), cached with the same (buffer, partial) identity
        check as _fast_stop_partial — repeated recorder() calls for one
        name reuse the binding, and a test-swapped staging buffer gets a
        rebuilt one at the next handle creation."""
        entry = self._fast_rec_partials.get(name)
        if entry is not None and entry[0] is self._fast_buf:
            return entry[1]
        p = functools.partial(
            self._fastpath.record_sized, self._fast_buf, self._fast_id(name)
        )
        self._fast_rec_partials[name] = (self._fast_buf, p)
        return p

    def _fast_add_partial(self, name: str):
        """counter_handle()'s cached binding, keyed against the COUNTER
        staging buffer (created lazily here, like counter())."""
        buf = self._fast_ensure_counter_buf()
        entry = self._fast_add_partials.get(name)
        if entry is not None and entry[0] is buf:
            return entry[1]
        p = functools.partial(
            self._fastpath.record_sized, buf, self._fast_id(name)
        )
        self._fast_add_partials[name] = (buf, p)
        return p

    def _fast_stop_partial(self, name: str):
        """Per-name functools.partial(timer_stop, buf, fid), cached —
        built once per metric so every token shares it (the binding work
        happens before any clock starts).  The partial freezes the
        CURRENT staging buffer: ``_fast_buf`` is write-once in product
        code, but tests that swap it for a smaller buffer get a rebuilt
        binding at the next token/handle creation (cache entries carry
        the buffer they bound; handles created BEFORE a swap keep
        staging into the old buffer — create handles after)."""
        entry = self._fast_stop_partials.get(name)
        if entry is not None and entry[0] is self._fast_buf:
            return entry[1]
        fid = self._fast_id(name)
        p = functools.partial(
            self._fastpath.timer_stop, self._fast_buf, fid
        )
        self._fast_stop_partials[name] = (self._fast_buf, p)
        return p

    def register_gauge_func(self, name: str, f: Callable[[], float]) -> None:
        with self._gauge_lock:
            self._gauge_funcs[name] = f

    def deregister_gauge_func(self, name: str) -> None:
        with self._gauge_lock:
            self._gauge_funcs.pop(name, None)

    def specify_percentiles(self, percentiles: Mapping[str, float]) -> None:
        """Override the default percentile set (metrics.go:197-201).
        Labels are %-format templates applied to the metric name; a
        malformed template is rejected HERE rather than poisoning every
        interval's processing later."""
        for label in percentiles:
            try:
                rendered = label % "name"
            except (TypeError, ValueError) as e:
                raise ValueError(
                    f"percentile label {label!r} is not a valid %-format "
                    f"template for a metric name: {e}"
                ) from None
            if not isinstance(rendered, str):
                raise ValueError(
                    f"percentile label {label!r} must render to a string"
                )
        self._percentiles = dict(percentiles)

    # ------------------------------------------------------------------ #
    # subscription boundary (reference layer L3)
    # ------------------------------------------------------------------ #

    def subscribe_to_raw_metrics(self, ch: Channel) -> None:
        self._sub_requests.put(("sub_raw", ch))

    def unsubscribe_from_raw_metrics(self, ch: Channel) -> None:
        self._sub_requests.put(("unsub_raw", ch))

    def subscribe_to_processed_metrics(self, ch: Channel) -> None:
        self._sub_requests.put(("sub_processed", ch))

    def unsubscribe_from_processed_metrics(self, ch: Channel) -> None:
        self._sub_requests.put(("unsub_processed", ch))

    def _update_subscribers(self) -> None:
        """Apply queued (un)subscribe requests — once per tick, like the
        reference's channel-of-channels drain (metrics.go:508-525)."""
        with self._subscribers_lock:
            while True:
                try:
                    op, ch = self._sub_requests.get_nowait()
                except queue.Empty:
                    return
                if op == "sub_raw":
                    self._raw_subscribers.setdefault(ch, 0)
                elif op == "unsub_raw":
                    self._raw_subscribers.pop(ch, None)
                elif op == "sub_processed":
                    self._processed_subscribers.setdefault(ch, 0)
                elif op == "unsub_processed":
                    self._processed_subscribers.pop(ch, None)

    def _broadcast(self, subscribers: Dict[Channel, int], item) -> None:
        """Non-blocking delivery with strike eviction (metrics.go:565-581):
        a full channel earns a strike; `eviction_strikes` consecutive
        strikes closes and forgets the channel.  Must be called with
        _subscribers_lock held."""
        evict = []
        for ch in subscribers:
            if ch.closed:
                # deliberately closed by its owner (e.g. an orderly detach
                # before the queued unsubscribe applies): forget it quietly,
                # no strike logging
                evict.append(ch)
                continue
            if ch.offer(item):
                subscribers[ch] = 0
            else:
                subscribers[ch] += 1
                logger.error(
                    "a subscriber has allowed their channel to fill up; "
                    "dropping their metrics rather than blocking"
                )
                if subscribers[ch] >= self.config.eviction_strikes:
                    logger.error(
                        "subscriber dropped metrics %d times in a row; "
                        "closing the channel",
                        subscribers[ch],
                    )
                    evict.append(ch)
        for ch in evict:
            del subscribers[ch]
            ch.close()

    # ------------------------------------------------------------------ #
    # collection (reference layer L3: collectRawMetrics, metrics.go:420-479)
    # ------------------------------------------------------------------ #

    def _interval_floor(self, now: Optional[float] = None) -> _dt.datetime:
        """Timestamps are floored to interval boundaries (metrics.go:421)."""
        now = time.time() if now is None else now
        ns = int(now * 1e9)
        interval_ns = max(1, int(self.interval * 1e9))
        floored = ns // interval_ns * interval_ns
        return _dt.datetime.fromtimestamp(floored / 1e9, tz=_dt.timezone.utc)

    def collect_raw_metrics(self) -> RawMetricSet:
        ts = self._interval_floor()

        fresh_counters: Dict[str, int] = {}
        hist_buffers: Dict[str, list] = {}
        folded_counts: Dict[str, Dict[int, int]] = {}

        if self._fast_record is not None:
            self._fast_fold()
            with self._fast_lock:
                fast_folded, self._fast_folded = self._fast_folded, {}
                fast_counters, self._fast_counter_folded = (
                    self._fast_counter_folded, {}
                )
            for name, counts in fast_folded.items():
                _merge_counts(
                    folded_counts.setdefault(name, {}),
                    counts.keys(), counts.values(),
                )
            for name, amount in fast_counters.items():
                fresh_counters[name] = fresh_counters.get(name, 0) + amount

        for shard in self._shards:
            with shard.lock:
                counters, shard.counters = shard.counters, {}
                hists, shard.histograms = shard.histograms, {}
                folded, shard.bucket_counts = shard.bucket_counts, {}
            for name, amount in counters.items():
                fresh_counters[name] = fresh_counters.get(name, 0) + amount
            for name, buf in hists.items():
                if len(buf):
                    hist_buffers.setdefault(name, []).append(buf)
            for name, counts in folded.items():
                _merge_counts(
                    folded_counts.setdefault(name, {}),
                    counts.keys(), counts.values(),
                )

        rates = dict(fresh_counters)
        with self._store_lock:
            for name, amount in fresh_counters.items():
                self._counter_store[name] = (
                    self._counter_store.get(name, 0) + amount
                )
            counters = dict(self._counter_store)

        histograms: Dict[str, Dict[int, int]] = folded_counts
        for name, bufs in hist_buffers.items():
            values = np.concatenate(
                [np.frombuffer(b, dtype=np.float64) for b in bufs]
            ) if len(bufs) > 1 else np.frombuffer(bufs[0], dtype=np.float64)
            buckets = compress_np(values, self.config.precision)
            uniq, cnt = np.unique(buckets, return_counts=True)
            _merge_counts(histograms.setdefault(name, {}), uniq, cnt)

        # Fold this interval into the lifetime aggregate store HERE, at
        # collection — exactly once per interval.  (The reference folds
        # during processing, metrics.go:359-376, which double-counts if a
        # RawMetricSet is processed twice and *under*-counts shed intervals;
        # folding at collection fixes both.)  The folded sum is the
        # decompressed-representative sum, like the reference's.
        agg_increments = []
        if histograms:
            from loghisto_tpu.ops.stats import summarize_sparse
        for name, bucket_counts in histograms.items():
            buckets = np.fromiter(bucket_counts.keys(), dtype=np.int64)
            cnt = np.fromiter(bucket_counts.values(), dtype=np.uint64)
            total_sum, total_count = summarize_sparse(
                buckets, cnt, self.config.precision
            )
            # go_compat (metrics.go:374): the float sum converts through
            # uint64 — truncating fractions, and wrapping negatives mod
            # 2^64 the way Go's amd64 conversion behaves for the in-range
            # magnitudes this library sees (Go leaves out-of-range
            # float->uint conversion implementation-defined, so extreme
            # >=2^63 sums are not bit-matched across architectures).
            sum_inc = (
                int(total_sum) if self.config.go_compat else total_sum
            )
            agg_increments.append((name, sum_inc, total_count))
        with self._store_lock:
            for name, sum_inc, total_count in agg_increments:
                entry = self._histogram_agg_store.setdefault(name, [0, 0])
                entry[0] += sum_inc
                if self.config.go_compat:
                    entry[0] &= _UINT64_MASK
                entry[1] += total_count

        with self._gauge_lock:
            gauge_funcs = dict(self._gauge_funcs)
        gauges = {}
        for name, f in gauge_funcs.items():
            try:
                gauges[name] = float(f())
            except Exception:
                logger.exception("gauge func %r raised; skipping", name)

        return RawMetricSet(
            time=ts,
            counters=counters,
            rates=rates,
            histograms=histograms,
            gauges=gauges,
            duration=self.interval,
            seq=next(self._interval_seq),
        )

    # ------------------------------------------------------------------ #
    # processing (reference processMetrics/processHistograms,
    # metrics.go:334-418, 481-506)
    # ------------------------------------------------------------------ #

    def _process_histogram(
        self, name: str, bucket_counts: Mapping[int, int]
    ) -> Dict[str, float]:
        from loghisto_tpu.ops.stats import (
            percentiles_sparse, summarize_sparse,
        )

        out: Dict[str, float] = {}
        buckets = np.fromiter(bucket_counts.keys(), dtype=np.int64)
        counts = np.fromiter(bucket_counts.values(), dtype=np.uint64)
        total_sum, total_count = summarize_sparse(
            buckets, counts, self.config.precision
        )

        out[f"{name}_count"] = float(total_count)
        out[f"{name}_sum"] = total_sum
        out[f"{name}_avg"] = total_sum / total_count if total_count else 0.0

        labels, ps = [], []
        for label, p in self._percentiles.items():
            if not 0.0 <= p <= 1.0:
                logger.error(
                    "unable to calculate percentile %r=%s: must be in [0,1]",
                    label, p,
                )
                continue
            labels.append(label)
            ps.append(p)
        if labels:
            pct = percentiles_sparse(
                buckets, counts, np.asarray(ps), self.config.precision
            )
            for label, value in zip(labels, pct):
                out[label % name] = float(value)
        return out

    def process_metrics(self, raw: RawMetricSet) -> ProcessedMetricSet:
        metrics: Dict[str, float] = {}
        for name, count in raw.counters.items():
            metrics[name] = float(count)
        for name, count in raw.rates.items():
            metrics[f"{name}_rate"] = float(count)
        for name, bucket_counts in raw.histograms.items():
            metrics.update(self._process_histogram(name, bucket_counts))
        metrics.update(raw.gauges)
        return ProcessedMetricSet(time=raw.time, metrics=metrics)

    def _attach_aggregates(
        self, processed: ProcessedMetricSet, raw: RawMetricSet
    ) -> None:
        """Add lifetime `_agg_{avg,count,sum}` (reference metrics.go:589-608)."""
        with self._store_lock:
            snapshot = {
                name: (entry[0], entry[1])
                for name, entry in self._histogram_agg_store.items()
                if name in raw.histograms
            }
        for name, (agg_sum, agg_count) in snapshot.items():
            if agg_count <= 0:
                continue
            if self.config.go_compat:
                avg = float(int(agg_sum) // int(agg_count))
            else:
                avg = agg_sum / agg_count
            processed.metrics[f"{name}_agg_avg"] = avg
            processed.metrics[f"{name}_agg_count"] = float(agg_count)
            processed.metrics[f"{name}_agg_sum"] = float(agg_sum)

    # ------------------------------------------------------------------ #
    # reaper loop (reference metrics.go:527-653)
    # ------------------------------------------------------------------ #

    def _reaper(self, shutdown: threading.Event) -> None:
        # Bounded worker pool for statistic derivation; queue and shutdown
        # event are per reaper generation, so a restarted system can never
        # inherit stale tasks or shutdown sentinels.
        process_queue: "queue.Queue[Callable[[], None]]" = queue.Queue(16)
        n_workers = max((os.cpu_count() or 4) // 4, 4)
        workers = [
            threading.Thread(
                target=self._worker, args=(process_queue, shutdown),
                daemon=True, name="loghisto-worker",
            )
            for _ in range(n_workers)
        ]
        for w in workers:
            w.start()

        try:
            while True:
                now = time.time()
                tts = self.interval - (now % self.interval)
                if shutdown.wait(timeout=tts):
                    return
                try:
                    self._tick(process_queue)
                except Exception:
                    # A failing collection/broadcast must not kill metric
                    # collection for the process lifetime.
                    logger.exception("reaper tick failed; continuing")
        finally:
            # Per-generation queue, so these sentinels can only ever reach
            # this generation's workers.
            for _ in workers:
                try:
                    process_queue.put(None, timeout=1.0)
                except queue.Full:
                    break  # workers are wedged; they are daemons anyway

    def _tick(self, process_queue: "queue.Queue") -> None:
        raw = self.collect_raw_metrics()
        self._update_subscribers()

        with self.obs_recorder.span("obs.broadcast", raw.seq):
            with self._subscribers_lock:
                self._broadcast(self._raw_subscribers, raw)

        def send_processed(raw=raw):
            processed = self.process_metrics(raw)
            self._attach_aggregates(processed, raw)
            with self._subscribers_lock:
                self._broadcast(self._processed_subscribers, processed)

        try:
            process_queue.put_nowait(send_processed)
        except queue.Full:
            # Shed the whole interval rather than stall the reaper
            # (reference metrics.go:630-637).
            logger.error(
                "metric processing is saturated; dropping the %s "
                "interval rather than blocking the reaper",
                raw.time,
            )

    def _worker(
        self, process_queue: "queue.Queue", shutdown: threading.Event
    ) -> None:
        # Exit on a None sentinel (prompt path) OR on shutdown+idle (the
        # guaranteed path: sentinel delivery is best-effort when the queue
        # is saturated at stop time, and workers must not leak).
        while True:
            try:
                task = process_queue.get(timeout=0.5)
            except queue.Empty:
                if shutdown.is_set():
                    return
                continue
            if task is None:
                return
            try:
                task()
            except Exception:
                logger.exception("metric processing task failed")

    def start(self) -> None:
        """Start the reaper; idempotent while running (metrics.go:644-648)."""
        with self._lifecycle_lock:
            if self._reaper_thread is not None and self._reaper_thread.is_alive():
                return
            self._shutdown = threading.Event()
            shutdown = self._shutdown
            supervisor = getattr(self, "supervisor", None)
            if supervisor is not None:
                # resilience (ISSUE 10): a crashed reaper restarts with
                # capped backoff on the same shutdown event — metric
                # collection survives a generation's crash instead of
                # going quiet for the process lifetime
                self._reaper_thread = supervisor.spawn(
                    lambda: self._reaper(shutdown), "loghisto-reaper"
                )
            else:
                self._reaper_thread = threading.Thread(
                    target=self._reaper, args=(shutdown,),
                    daemon=True, name="loghisto-reaper",
                )
                self._reaper_thread.start()

    def stop(self) -> None:
        """Shut the reaper and worker pool down (metrics.go:651-653).
        Joins the reaper so an immediate start() spawns a fresh one."""
        with self._lifecycle_lock:
            self._shutdown.set()
            t = self._reaper_thread
        if t is not None and t is not threading.current_thread():
            # a supervised handle's restart loop must stop too, or a
            # backoff nap could outlive the join below
            stop_fn = getattr(t, "stop", None)
            if stop_fn is not None:
                stop_fn()
            t.join(timeout=5.0)

    # Go-style aliases for drop-in familiarity with the reference API.
    Counter = counter
    Histogram = histogram
    StartTimer = start_timer
    RegisterGaugeFunc = register_gauge_func
    DeregisterGaugeFunc = deregister_gauge_func
    SpecifyPercentiles = specify_percentiles
    SubscribeToRawMetrics = subscribe_to_raw_metrics
    UnsubscribeFromRawMetrics = unsubscribe_from_raw_metrics
    SubscribeToProcessedMetrics = subscribe_to_processed_metrics
    UnsubscribeFromProcessedMetrics = unsubscribe_from_processed_metrics
    Start = start
    Stop = stop
