"""Configuration for the TPU-native loghisto framework.

The Go reference has no config system: its only knobs are the constructor
arguments ``(interval, sysStats)`` (reference metrics.go:143), the
``SpecifyPercentiles`` override (metrics.go:199-201) and the compile-time
``precision = 100`` constant (metrics.go:40-43).  We keep zero-config defaults
that match the reference exactly, and expose the remaining TPU-specific knobs
(dense bucket range, mesh shape) in one frozen dataclass.
"""

from __future__ import annotations

import dataclasses
from typing import Mapping

# Default percentile label -> quantile mapping, identical to the reference
# (metrics.go:145-155).  Labels are %-format templates applied to the metric
# name, e.g. "%s_99.9" % "latency" -> "latency_99.9".
DEFAULT_PERCENTILES: Mapping[str, float] = {
    "%s_min": 0.0,
    "%s_50": 0.5,
    "%s_75": 0.75,
    "%s_90": 0.9,
    "%s_95": 0.95,
    "%s_99": 0.99,
    "%s_99.9": 0.999,
    "%s_99.99": 0.9999,
    "%s_max": 1.0,
}

# Bucketing precision: bucket = round(precision * ln(1 + |v|)), giving bucket
# boundary ratio e^(1/precision) ~= 1.01, i.e. <=1% relative error
# (reference metrics.go:40-43, 316-332).
PRECISION = 100

# Full int16 bucket span of the reference codec.
INT16_BUCKET_LIMIT = 32767


@dataclasses.dataclass(frozen=True)
class MetricConfig:
    """Numeric / behavioral configuration.

    Attributes:
      precision: log-bucketing precision (reference: fixed at 100).
      bucket_limit: maximum absolute bucket index for the *dense* device-side
        accumulator.  The default +/-4096 covers |v| up to e^40.96 ~= 6.2e17
        (every nanosecond latency up to ~19 years) at a dense tensor cost of
        (2*4096+1) * 4 bytes = 32 KB per metric.  The host-side sparse tier
        always uses the full int16 span like the reference.
      eviction_strikes: consecutive failed deliveries before a subscriber is
        evicted.  The reference's *docs* say 3 (metrics.go:18-23) but its code
        evicts on the 2nd (metrics.go:574,620); we default to the observed
        behavior.
      go_compat: reproduce the reference's integer quirks bit-for-bit:
        lifetime histogram sums accumulated via uint64 truncation
        (metrics.go:374) and `_agg_avg` computed with integer division
        (metrics.go:601-602).  Default False: clean float semantics (the
        difference is below the 1% accuracy contract either way).
    """

    precision: int = PRECISION
    bucket_limit: int = 4096
    eviction_strikes: int = 2
    go_compat: bool = False
    # Raw histogram samples per metric buffered in a shard before being
    # folded into sparse bucket counts at ingest time.  Bounds ingest-path
    # memory to O(buckets) like the reference's per-call bucketing while
    # keeping the batch-vectorized compression.
    ingest_buffer_cap: int = 65536

    def __post_init__(self):
        if not 0 < self.bucket_limit <= 8192:
            # exp(bucket/precision) overflows float32 at bucket ~8873; cap
            # below that so dense representatives stay finite on device.
            raise ValueError(
                "bucket_limit must be in (0, 8192] — float32 representatives "
                f"overflow beyond that; got {self.bucket_limit}"
            )
        if self.precision <= 0:
            raise ValueError(f"precision must be positive, got {self.precision}")
        if self.ingest_buffer_cap < 64:
            # below this the per-sample fold overhead dominates the hot path
            raise ValueError(
                "ingest_buffer_cap must be >= 64, got "
                f"{self.ingest_buffer_cap}"
            )
        if self.eviction_strikes < 1:
            raise ValueError(
                f"eviction_strikes must be >= 1, got {self.eviction_strikes}"
            )

    @property
    def num_buckets(self) -> int:
        """Dense bucket-axis size: indices -bucket_limit..+bucket_limit."""
        return 2 * self.bucket_limit + 1


DEFAULT_CONFIG = MetricConfig()
