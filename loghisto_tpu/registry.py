"""Metric name <-> dense row id registry.

The reference keys everything by string name in sparse maps
(metrics.go:112-126).  The device tier instead stores bucket counts in a
dense ``[num_metrics, num_buckets]`` tensor, so names map to stable integer
rows.  The registry is append-only (ids are never reused) and thread-safe;
capacity is fixed so the device accumulator shape is static under jit.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional


class RegistryFullError(RuntimeError):
    pass


class MetricRegistry:
    def __init__(self, capacity: int = 16384):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self.capacity = capacity
        self._lock = threading.Lock()
        self._name_to_id: Dict[str, int] = {}
        self._names: List[str] = []

    def id_for(self, name: str) -> int:
        """Return the row id for `name`, registering it on first use."""
        existing = self._name_to_id.get(name)
        if existing is not None:
            return existing
        with self._lock:
            existing = self._name_to_id.get(name)
            if existing is not None:
                return existing
            if len(self._names) >= self.capacity:
                raise RegistryFullError(
                    f"metric registry is full ({self.capacity} names)"
                )
            new_id = len(self._names)
            self._names.append(name)
            self._name_to_id[name] = new_id
            return new_id

    def grow(self, new_capacity: int) -> None:
        """Raise capacity (never shrinks; ids are stable).  Used by the
        aggregator's on_registry_full="grow" policy — the reference admits
        new names forever (metrics.go:281-294), so the device tier grows
        its row space geometrically instead of hard-failing."""
        with self._lock:
            if new_capacity > self.capacity:
                self.capacity = new_capacity

    def lookup(self, name: str) -> Optional[int]:
        return self._name_to_id.get(name)

    def name_for(self, metric_id: int) -> str:
        return self._names[metric_id]

    def names(self) -> List[str]:
        with self._lock:
            return list(self._names)

    def __len__(self) -> int:
        return len(self._names)
