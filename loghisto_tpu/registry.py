"""Metric name <-> dense row id registry.

The reference keys everything by string name in sparse maps
(metrics.go:112-126).  The device tier instead stores bucket counts in a
dense ``[num_metrics, num_buckets]`` tensor, so names map to stable integer
rows.  The registry is thread-safe; capacity is bounded so the device
accumulator shape is static under jit.

Lifecycle (ISSUE 4): the registry is no longer strictly append-only.
``evict()`` releases ids back to a free-list (reused by ``id_for``
before the row space grows) and ``apply_permutation()`` remaps every
live id after a device compaction.  Both bump ``generation`` — the
invalidation signal every id-keyed cache (glob resolution, query plan
/ result caches, snapshot handles) must key on: an id is only
meaningful for a fixed generation.  Pure appends do NOT bump the
generation (previously resolved ids stay valid; caches may extend
incrementally by scanning the new tail), which preserves the
append-only fast path the query engine was built on.
"""

from __future__ import annotations

import threading
from typing import Dict, Iterable, List, Optional, Sequence


class RegistryFullError(RuntimeError):
    pass


class MetricRegistry:
    def __init__(self, capacity: int = 16384):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self.capacity = capacity
        self._lock = threading.Lock()
        self._name_to_id: Dict[str, int] = {}
        # dense id -> name table; None marks a freed (evictable-reuse) slot
        self._names: List[Optional[str]] = []
        # freed slot ids, reused LIFO before the table grows a new row
        self._free: List[int] = []
        self._generation = 0

    @property
    def generation(self) -> int:
        """Structural generation: bumped whenever an existing id's
        meaning changes (eviction, free-slot reuse, permutation) — NOT
        on pure appends.  Caches must treat any id resolved under a
        different generation as dead."""
        return self._generation

    def id_for(self, name: str) -> int:
        """Return the row id for `name`, registering it on first use.
        Freed slots are reused (LIFO) before the table grows."""
        existing = self._name_to_id.get(name)
        if existing is not None:
            return existing
        with self._lock:
            existing = self._name_to_id.get(name)
            if existing is not None:
                return existing
            if self._free:
                new_id = self._free.pop()
                self._names[new_id] = name
                # an old generation's caches may still map this id to the
                # evicted tenant; reuse is a structural change
                self._generation += 1
            else:
                if len(self._names) >= self.capacity:
                    raise RegistryFullError(
                        f"metric registry is full ({self.capacity} names)"
                    )
                new_id = len(self._names)
                self._names.append(name)
            self._name_to_id[name] = new_id
            return new_id

    def grow(self, new_capacity: int) -> None:
        """Raise capacity (never shrinks here; ``apply_permutation`` owns
        shrinks).  Used by the aggregator's on_registry_full="grow"
        policy — the reference admits new names forever
        (metrics.go:281-294), so the device tier grows its row space
        geometrically instead of hard-failing.  The free-list and
        generation counter are deliberately untouched: growth neither
        invalidates an id nor forfeits reclaimed slots."""
        with self._lock:
            if new_capacity > self.capacity:
                self.capacity = new_capacity

    def evict(self, ids: Iterable[int]) -> List[str]:
        """Release the given ids: their names unregister, the slots join
        the free-list, and the generation bumps once.  Unknown / already
        free ids are ignored.  Returns the evicted names."""
        evicted: List[str] = []
        with self._lock:
            for mid in ids:
                mid = int(mid)
                if not 0 <= mid < len(self._names):
                    continue
                name = self._names[mid]
                if name is None:
                    continue
                del self._name_to_id[name]
                self._names[mid] = None
                self._free.append(mid)
                evicted.append(name)
            if evicted:
                self._generation += 1
        return evicted

    def apply_permutation(
        self, perm: Sequence[int], new_capacity: Optional[int] = None
    ) -> None:
        """Remap every live id after a device compaction: ``perm[new]``
        is the OLD id now living at row ``new`` (negative = empty row).
        Every old live id must appear exactly once or the mapping would
        silently drop or duplicate series — validated.  Rebuilds the
        free-list from the holes and bumps the generation."""
        with self._lock:
            old_live = {
                mid for mid, name in enumerate(self._names)
                if name is not None
            }
            # out-of-range entries (negative, or the DROP sentinel) mark
            # empty rows; only in-range sources must be unique
            sources = [
                int(p) for p in perm
                if 0 <= int(p) < len(self._names)
            ]
            if len(sources) != len(set(sources)):
                raise ValueError("compaction permutation duplicates a row")
            live_sources = {s for s in sources if s in old_live}
            if live_sources != old_live:
                missing = sorted(old_live - live_sources)[:8]
                raise ValueError(
                    f"compaction permutation drops live ids {missing}"
                )
            cap = int(new_capacity) if new_capacity is not None \
                else self.capacity
            if cap < len(perm):
                raise ValueError(
                    f"new capacity {cap} below permutation length "
                    f"{len(perm)}"
                )
            names: List[Optional[str]] = [None] * len(perm)
            for new_id, old_id in enumerate(perm):
                old_id = int(old_id)
                if old_id < 0 or old_id >= len(self._names):
                    continue
                names[new_id] = self._names[old_id]
            # trim trailing holes so append-path ids stay dense
            while names and names[-1] is None:
                names.pop()
            self._names = names
            self._name_to_id = {
                name: mid for mid, name in enumerate(names)
                if name is not None
            }
            self._free = [
                mid for mid, name in enumerate(names) if name is None
            ]
            self.capacity = cap
            self._generation += 1

    def lookup(self, name: str) -> Optional[int]:
        return self._name_to_id.get(name)

    def name_for(self, metric_id: int) -> Optional[str]:
        """Name at a row id, or None for a freed / never-used slot."""
        if 0 <= metric_id < len(self._names):
            return self._names[metric_id]
        return None

    def names(self) -> List[Optional[str]]:
        """Dense id -> name table; freed slots hold None.  Callers that
        report by name must skip the holes."""
        with self._lock:
            return list(self._names)

    def free_count(self) -> int:
        with self._lock:
            return len(self._free)

    def live_count(self) -> int:
        with self._lock:
            return len(self._name_to_id)

    def __len__(self) -> int:
        """High-water row count (table length INCLUDING freed holes) —
        the append-only growth proxy caches pair with ``generation``."""
        return len(self._names)
