"""Static contract analyzer (ISSUE 20): prove the repo's headline
invariants once, centrally, at trace time — no TPU required.

Three passes, one gate:

  * ``jaxpr_audit``  — a declarative registry mapping every compiled
    program factory to its contract (dispatch count, exact pallas_call
    count, donation/aliasing, forbidden dense intermediates, int32
    accumulation discipline, exactly-one stream psum in sharded
    programs), checked by recursively walking closed jaxprs on CPU with
    abstract shapes.
  * ``import_lint`` — AST module graph enforcing the declared layering:
    the jax-free frontier (federation emitter, label model, span ring,
    host metrics) must not transitively reach jax at import time, and
    the PEP 562 lazy surfaces must resolve every advertised name.
  * ``lock_lint``   — AST concurrency discipline: no blocking device
    call or socket op while holding a lock, and supervised worker entry
    points must take their declared lock before writing shared
    attributes.  Intentional exceptions are pinned (with reasons) in
    ``analysis/baseline.py``.

``python -m loghisto_tpu.analysis`` runs all passes and exits nonzero
with per-finding ``file:line reason`` output; tests/test_contracts.py
runs the same passes inside tier-1, so every PR inherits the gate.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Iterable, Sequence

# Repo root (the directory holding loghisto_tpu/): every finding path is
# reported relative to it so baseline keys survive checkouts.
REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


@dataclasses.dataclass(frozen=True)
class Finding:
    """One contract violation.

    ``key()`` (pass, path, scope, detail) deliberately excludes the line
    number so baseline suppressions survive unrelated edits to the same
    file — the scope (qualified function / program name) and detail (the
    violating construct) pin the finding, the line is presentation.
    """

    pass_name: str   # "jaxpr" | "imports" | "locks" | "baseline"
    path: str        # repo-relative file
    line: int
    scope: str       # program name / qualified function / module
    detail: str      # machine-ish identifier of the violated rule
    reason: str      # human sentence naming the violated contract

    def key(self) -> tuple:
        return (self.pass_name, self.path, self.scope, self.detail)

    def render(self) -> str:
        return (
            f"{self.path}:{self.line} [{self.pass_name}] {self.scope}: "
            f"{self.reason}"
        )


def relpath(path: str) -> str:
    """Normalize an absolute path to the repo-relative finding path."""
    ap = os.path.abspath(path)
    if ap.startswith(REPO_ROOT + os.sep):
        return os.path.relpath(ap, REPO_ROOT)
    return path


def apply_baseline(
    findings: Iterable[Finding],
    baseline: Sequence[tuple] | None = None,
    passes: Sequence[str] | None = None,
) -> list[Finding]:
    """Suppress findings pinned in the baseline; surface stale baseline
    entries (suppressions that no longer match anything) as findings of
    their own so the table cannot rot.  ``passes`` limits staleness
    detection to the passes that actually ran (a locks suppression is
    not stale just because only the jaxpr pass was selected)."""
    from loghisto_tpu.analysis import baseline as baseline_mod

    entries = baseline_mod.BASELINE if baseline is None else baseline
    if passes is not None:
        entries = [e for e in entries if e[0] in passes]
    by_key = {tuple(e[:4]): e for e in entries}
    used: set[tuple] = set()
    kept: list[Finding] = []
    for f in findings:
        if f.key() in by_key:
            used.add(f.key())
        else:
            kept.append(f)
    for key, entry in by_key.items():
        if key not in used:
            kept.append(Finding(
                pass_name="baseline",
                path="loghisto_tpu/analysis/baseline.py",
                line=1,
                scope=":".join(key[:2]),
                detail="stale-suppression",
                reason=(
                    f"baseline entry {key!r} no longer matches any "
                    f"finding — remove it (was: {entry[4]!r})"
                ),
            ))
    return kept


__all__ = ["Finding", "REPO_ROOT", "apply_baseline", "relpath"]
