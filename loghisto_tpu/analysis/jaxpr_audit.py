"""jaxpr contract auditor: one declarative registry mapping every
compiled program factory to its contract, checked on CPU abstract
traces (``jax.make_jaxpr`` — nothing executes, no TPU required).

A ``Contract`` pins, per program:

  * ``dispatches``      — exact top-level program-launch count (pjit /
                          pallas_call eqns in the traced jaxpr; nested
                          pjits inline at compile time and don't count)
  * ``pallas_calls``    — exact pallas_call count anywhere in the tree
  * ``donated``         — exact donated-invar count on the program eqn,
                          each of which must alias an output with the
                          same shape+dtype (a donated carry whose update
                          silently stopped being returned — "dropped
                          donation" — fails here)
  * ``stream_psums``    — exact count of stream-axis psums (sharded
                          programs pin exactly one; single-device pin 0)
  * ``int32_scatter_shapes`` — carry shapes whose scatter-add updates
                          must stay int32 (cross-tile accumulation is
                          bit-exact only because integer adds commute)
  * ``forbidden_shapes``— intermediate shapes that must NOT appear as
                          any eqn output (paged routes pin the dense
                          [M, B] and the shard-local [M/s, B] shapes)

plus two global rules: no host-callback primitive may appear inside
any audited program, and stream psums on int carries must be int32.

``assert_contract(name)`` is the public entry point the per-test
guards delegate to; ``audit_all()`` feeds the CLI gate.
"""

from __future__ import annotations

import dataclasses
import functools
import inspect
from typing import Callable, Sequence

from loghisto_tpu.analysis import Finding, relpath

STREAM_AXIS_NAME = "stream"

# f32 in-tile partial sums are exact only while a tile's total count
# stays under 2^24 (the float32 integer-exactness bound); the Pallas
# sample tile is the largest per-tile population one kernel invocation
# can accumulate before the int32 cross-tile fold takes over.
F32_EXACT_BOUND = 1 << 24


@dataclasses.dataclass(frozen=True)
class Contract:
    """Static contract for one compiled program.  ``None`` disables a
    check (used by ad-hoc ``audit_callable`` traces of un-jitted
    functions, where there is no program eqn to count)."""

    dispatches: int | None = 1
    pallas_calls: int | None = 0
    donated: int | None = 0
    stream_psums: int | None = 0
    int32_scatter_shapes: tuple = ()
    forbidden_shapes: tuple = ()
    description: str = ""


@dataclasses.dataclass(frozen=True)
class ProgramSpec:
    name: str
    factory: str                 # dotted factory path, for the docs table
    build: Callable              # () -> (traceable_fn, args tuple)
    contract: Contract


# ---------------------------------------------------------------------- #
# jaxpr walking
# ---------------------------------------------------------------------- #


def _sub_jaxprs(params):
    """Yield every sub-jaxpr hiding in an eqn's params.  pjit/scan/cond
    carry ClosedJaxpr values (``.jaxpr`` attribute); shard_map and
    pallas_call carry raw Jaxprs (``.eqns`` directly); cond carries a
    tuple of branches."""
    for value in params.values():
        items = value if isinstance(value, (list, tuple)) else (value,)
        for item in items:
            inner = getattr(item, "jaxpr", None)
            if inner is not None and hasattr(inner, "eqns"):
                yield inner
            elif hasattr(item, "eqns"):
                yield item


def iter_eqns(jaxpr):
    """Depth-first over every eqn in a (Closed)Jaxpr and all sub-jaxprs."""
    if hasattr(jaxpr, "jaxpr"):      # ClosedJaxpr
        jaxpr = jaxpr.jaxpr
    for eqn in jaxpr.eqns:
        yield eqn
        for sub in _sub_jaxprs(eqn.params):
            yield from iter_eqns(sub)


def jaxpr_primitives(closed) -> list:
    """(primitive name, output shapes) for every eqn, recursively —
    the shape the scattered per-test guards used to compute locally."""
    return [
        (eqn.primitive.name, [tuple(v.aval.shape) for v in eqn.outvars])
        for eqn in iter_eqns(closed)
    ]


def _aval_sig(var):
    aval = var.aval
    return (tuple(aval.shape), getattr(aval, "dtype", None))


# ---------------------------------------------------------------------- #
# the audit
# ---------------------------------------------------------------------- #

_PROGRAM_EQNS = ("pjit", "jit", "xla_call", "pallas_call")


def audit_jaxpr(closed, contract: Contract, name: str,
                path: str = "", line: int = 0) -> list[Finding]:
    """Check one traced program against its contract.  Returns findings
    (empty = contract holds)."""

    def finding(detail, reason):
        return Finding("jaxpr", path, line, name, detail, reason)

    out: list[Finding] = []
    top = closed.jaxpr if hasattr(closed, "jaxpr") else closed

    # -- dispatch budget: every top-level eqn is a device launch --
    if contract.dispatches is not None:
        launches = [e for e in top.eqns
                    if e.primitive.name in _PROGRAM_EQNS]
        stragglers = [e for e in top.eqns
                      if e.primitive.name not in _PROGRAM_EQNS]
        if len(launches) != contract.dispatches:
            out.append(finding(
                "dispatch-count",
                f"contract pins {contract.dispatches} dispatch(es), "
                f"trace has {len(launches)} top-level program eqns "
                f"({[e.primitive.name for e in launches]})",
            ))
        if stragglers:
            out.append(finding(
                "eager-top-level-eqn",
                "ops outside the jitted program would run eagerly "
                f"op-by-op at runtime: "
                f"{sorted({e.primitive.name for e in stragglers})}",
            ))

    all_eqns = list(iter_eqns(closed))

    # -- exact pallas_call census --
    if contract.pallas_calls is not None:
        n_pallas = sum(
            1 for e in all_eqns if e.primitive.name == "pallas_call"
        )
        if n_pallas != contract.pallas_calls:
            out.append(finding(
                "pallas-count",
                f"contract pins exactly {contract.pallas_calls} "
                f"pallas_call(s), trace has {n_pallas}",
            ))

    # -- donation: declared count, and every donated invar must alias
    #    an output (shape+dtype) or XLA silently drops the donation --
    if contract.donated is not None:
        donated_total = 0
        for eqn in top.eqns:
            flags = eqn.params.get("donated_invars")
            if not flags:
                continue
            sigs = [_aval_sig(var)
                    for var, is_donated in zip(eqn.invars, flags)
                    if is_donated]
            donated_total += len(sigs)
            outs = [_aval_sig(v) for v in eqn.outvars]
            for sig in sigs:
                if sig in outs:
                    outs.remove(sig)   # each output absorbs one donation
                else:
                    out.append(finding(
                        "donation-alias",
                        f"donated operand {sig[0]}:{sig[1]} has no "
                        "matching output aval — XLA drops the donation "
                        "silently and the carry double-buffers",
                    ))
        if donated_total != contract.donated:
            out.append(finding(
                "donation-count",
                f"contract pins {contract.donated} donated carr"
                f"{'y' if contract.donated == 1 else 'ies'}, program "
                f"donates {donated_total}",
            ))

    # -- exactly-one stream psum in sharded programs (0 elsewhere) --
    psums = [e for e in all_eqns if e.primitive.name.startswith("psum")
             and STREAM_AXIS_NAME in tuple(e.params.get("axes", ()))]
    if contract.stream_psums is not None:
        if len(psums) != contract.stream_psums:
            out.append(finding(
                "psum-count",
                f"contract pins exactly {contract.stream_psums} "
                f"stream-axis psum(s), trace has {len(psums)}",
            ))
        for eqn in psums:
            for var in eqn.outvars:
                shape, dtype = _aval_sig(var)
                if dtype is not None and dtype.kind == "i" \
                        and str(dtype) != "int32":
                    out.append(finding(
                        "psum-dtype",
                        f"stream psum output {shape} is {dtype}; "
                        "cross-device accumulation must be int32 for "
                        "bit-identity with the single-device path",
                    ))

    # -- int32 cross-tile accumulation on the declared carry shapes --
    for eqn in all_eqns:
        if not eqn.primitive.name.startswith("scatter"):
            continue
        for var in eqn.outvars:
            shape, dtype = _aval_sig(var)
            if shape in contract.int32_scatter_shapes \
                    and str(dtype) != "int32":
                out.append(finding(
                    "scatter-dtype",
                    f"scatter-add into carry shape {shape} is {dtype}; "
                    "the accumulation contract requires int32 (integer "
                    "adds commute, float adds do not)",
                ))

    # -- forbidden intermediates (dense [M, B] in paged routes) --
    if contract.forbidden_shapes:
        hit: set = set()
        for eqn in all_eqns:
            for var in eqn.outvars:
                shape = tuple(var.aval.shape)
                if shape in contract.forbidden_shapes and shape not in hit:
                    hit.add(shape)
                    out.append(finding(
                        "forbidden-shape",
                        f"forbidden dense intermediate {shape} "
                        f"materialized by `{eqn.primitive.name}` — the "
                        "paged route must never build an [M, B] tensor",
                    ))

    # -- no host round-trips inside an audited program --
    callbacks = sorted({
        e.primitive.name for e in all_eqns
        if "callback" in e.primitive.name
    })
    if callbacks:
        out.append(finding(
            "host-callback",
            f"host callback primitive(s) {callbacks} inside the "
            "program — every audited program must be a pure device "
            "launch",
        ))
    return out


def audit_callable(fn, args, contract: Contract, name: str = "<adhoc>",
                   **kwargs) -> list[Finding]:
    """Trace ``fn(*args, **kwargs)`` and audit the jaxpr — for ad-hoc
    guards over shapes the registry doesn't carry."""
    import jax

    closed = jax.make_jaxpr(functools.partial(fn, **kwargs))(*args)
    path, line = _callable_origin(fn)
    return audit_jaxpr(closed, contract, name, path, line)


def _callable_origin(fn) -> tuple[str, int]:
    try:
        target = inspect.unwrap(fn)
        code = getattr(target, "__code__", None)
        if code is None and hasattr(target, "__wrapped__"):
            code = target.__wrapped__.__code__
        if code is not None:
            return relpath(code.co_filename), code.co_firstlineno
    except Exception:
        pass
    return "loghisto_tpu/analysis/jaxpr_audit.py", 0


# ---------------------------------------------------------------------- #
# trace geometry
# ---------------------------------------------------------------------- #
#
# Shapes are chosen so every contracted quantity is unambiguous:
#   dense rows M=32 (ROWS_TILE-aligned), buckets B=129 (bucket_limit 64),
#   tier rings (slots 3, rows 32/16), batch N=256 (divides the stream
#   axis), mesh 4x2 (needs the 8 forced host devices).
#   Paged rows PM=40 and the shard-local PM/2=20 collide with NO other
#   dimension in the trace, so forbidding (40, 129) / (20, 129) pins
#   "no dense [M, B] on the paged route" without false positives.

BL = 64
B = 2 * BL + 1            # 129
M = 32
N = 256
TIERS = 2
RING_ROWS = (32, 16)
SLOTS = 3
VIEWS = 1
PM = 40                   # paged metric rows
PPR = 2                   # page-table pages per row
POOL_PAGES = 48
PAGE = 256                # ops.paged_store.PAGE_SIZE
BANKS = 2
MESH_SHAPE = (4, 2)       # (stream, metric)

_DENSE_CARRIES = ((M, B), (SLOTS, RING_ROWS[0], B), (SLOTS, RING_ROWS[1], B))
_POOL_CARRY = ((POOL_PAGES, PAGE),)
_NO_DENSE_MB = ((PM, B), (PM // MESH_SHAPE[1], B))


def _required_devices() -> int:
    return MESH_SHAPE[0] * MESH_SHAPE[1]


class AuditEnvironmentError(RuntimeError):
    pass


@functools.lru_cache(maxsize=1)
def _mesh():
    import jax

    need = _required_devices()
    if len(jax.devices()) < need:
        raise AuditEnvironmentError(
            f"jaxpr audit needs {need} devices for the mesh contracts; "
            f"have {len(jax.devices())}.  Run on CPU with XLA_FLAGS="
            f"--xla_force_host_platform_device_count={need} (the "
            "analysis CLI and tests/conftest.py both set this)."
        )
    from loghisto_tpu.parallel.mesh import make_mesh

    return make_mesh(*MESH_SHAPE)


def _z(shape, dtype="int32"):
    import jax.numpy as jnp

    return jnp.zeros(shape, dtype=dtype)


def _scalar(value=0, dtype="int32"):
    import jax.numpy as jnp

    return jnp.asarray(value, dtype=dtype)


def _dense_carries():
    return (
        _z((M, B)),
        tuple(_z((SLOTS, rows, B)) for rows in RING_ROWS),
    )


def _cells():
    return _z((N,)), _z((N,)), _z((N,))       # ids, idx, weights


def _tier_scalars():
    return _z((TIERS,)), _z((TIERS,))          # slots, keeps


def _masks():
    return tuple(_z((VIEWS, SLOTS), dtype="bool") for _ in range(TIERS))


def _paged_carries():
    return (
        _z((POOL_PAGES, PAGE)),
        tuple(_z((SLOTS, rows, B)) for rows in (24, 16)),
    )


def _paged_ring_shapes():
    return ((SLOTS, 24, B), (SLOTS, 16, B))


def _triples():
    return _z((N, 3))


def _paged_luts():
    return _z((PM,)), _z((3, B)), _z((PM, PPR))  # row_codec, enc_luts, table


# ---------------------------------------------------------------------- #
# the registry
# ---------------------------------------------------------------------- #


def _spec(name, factory, build, **contract_kwargs):
    return ProgramSpec(name, factory, build, Contract(**contract_kwargs))


def _build_fused_commit():
    from loghisto_tpu.ops.commit import make_fused_commit_fn

    fn = make_fused_commit_fn(TIERS)
    acc, rings = _dense_carries()
    slots, keeps = _tier_scalars()
    return fn, (acc, rings, slots, keeps, *_cells())


def _build_fused_commit_full():
    from loghisto_tpu.ops.commit import make_fused_commit_fn

    fn = make_fused_commit_fn(TIERS, track_activity=True,
                              track_baseline=True)
    acc, rings = _dense_carries()
    slots, keeps = _tier_scalars()
    return fn, (acc, rings, _z((M,)), _z((M, B)), slots, keeps,
                *_cells(), _scalar(1), _scalar(1))


def _build_fused_commit_snapshot():
    from loghisto_tpu.ops.commit import make_fused_commit_snapshot_fn

    fn = make_fused_commit_snapshot_fn(TIERS, BL)
    acc, rings = _dense_carries()
    slots, keeps = _tier_scalars()
    return fn, (acc, rings, slots, keeps, *_cells(), _masks())


def _build_fused_commit_snapshot_full():
    from loghisto_tpu.ops.commit import make_fused_commit_snapshot_fn

    fn = make_fused_commit_snapshot_fn(
        TIERS, BL, track_activity=True, track_baseline=True
    )
    acc, rings = _dense_carries()
    slots, keeps = _tier_scalars()
    banks = (_z((BANKS, M, B), "float32"), _z((BANKS, M), "float32"))
    return fn, (acc, rings, _z((M,)), _z((M, B)), banks, slots, keeps,
                *_cells(), _scalar(1), _masks(), _scalar(1), _scalar(0),
                _scalar(0.5, "float32"), _scalar(10))


def _build_sharded_fused_commit():
    from loghisto_tpu.ops.commit import make_sharded_fused_commit_fn

    fn = make_sharded_fused_commit_fn(_mesh(), TIERS)
    acc, rings = _dense_carries()
    slots, keeps = _tier_scalars()
    return fn, (acc, rings, slots, keeps, *_cells())


def _build_sharded_fused_commit_snapshot():
    from loghisto_tpu.ops.commit import (
        make_sharded_fused_commit_snapshot_fn,
    )

    fn = make_sharded_fused_commit_snapshot_fn(_mesh(), TIERS, BL)
    acc, rings = _dense_carries()
    slots, keeps = _tier_scalars()
    return fn, (acc, rings, slots, keeps, *_cells(), _masks())


def _build_paged_fused_commit():
    from loghisto_tpu.ops.commit import make_paged_fused_commit_fn

    fn = make_paged_fused_commit_fn(TIERS)
    pool, rings = _paged_carries()
    slots, keeps = _tier_scalars()
    return fn, (pool, rings, slots, keeps, *_cells(), _triples())


def _build_paged_fused_commit_snapshot():
    from loghisto_tpu.ops.commit import make_paged_fused_commit_snapshot_fn

    fn = make_paged_fused_commit_snapshot_fn(TIERS, BL)
    pool, rings = _paged_carries()
    slots, keeps = _tier_scalars()
    return fn, (pool, rings, slots, keeps, *_cells(), _triples(),
                _masks())


def _build_sharded_paged_fused_commit():
    from loghisto_tpu.ops.commit import make_sharded_paged_fused_commit_fn

    fn = make_sharded_paged_fused_commit_fn(
        _mesh(), POOL_PAGES // MESH_SHAPE[1], TIERS
    )
    pool, rings = _paged_carries()
    slots, keeps = _tier_scalars()
    return fn, (pool, rings, slots, keeps, *_cells(), _triples())


def _build_sharded_paged_fused_commit_snapshot():
    from loghisto_tpu.ops.commit import (
        make_sharded_paged_fused_commit_snapshot_fn,
    )

    fn = make_sharded_paged_fused_commit_snapshot_fn(
        _mesh(), POOL_PAGES // MESH_SHAPE[1], TIERS, BL
    )
    pool, rings = _paged_carries()
    slots, keeps = _tier_scalars()
    return fn, (pool, rings, slots, keeps, *_cells(), _triples(),
                _masks())


def _build_fused_ingest():
    from loghisto_tpu.ops.fused_ingest import make_fused_ingest_fn

    fn = make_fused_ingest_fn(BL)
    return fn, (_z((M, B)), _z((N,)), _z((N,), "float32"))


def _build_fused_paged_ingest():
    from loghisto_tpu.ops.fused_ingest import make_fused_paged_ingest_fn

    fn = make_fused_paged_ingest_fn(BL)
    return fn, (_z((POOL_PAGES, PAGE)), _z((N,)), _z((N,), "float32"),
                *_paged_luts())


def _build_sharded_fused_paged_ingest():
    from loghisto_tpu.ops.fused_ingest import (
        make_sharded_fused_paged_ingest_fn,
    )

    fn = make_sharded_fused_paged_ingest_fn(
        _mesh(), PM // MESH_SHAPE[1], POOL_PAGES // MESH_SHAPE[1], BL
    )
    return fn, (_z((POOL_PAGES, PAGE)), _z((N,)), _z((N,), "float32"),
                *_paged_luts())


def _build_sparse_ingest(kernel):
    from loghisto_tpu.ops.sparse_ingest import make_sparse_ingest_fn

    fn = make_sparse_ingest_fn(BL, kernel=kernel)
    return fn, (_z((M, B)), _z((N, 3)))


def _build_paged_commit(kernel):
    from loghisto_tpu.ops.paged_store import make_paged_commit_fn

    fn = make_paged_commit_fn(kernel)
    return fn, (_z((POOL_PAGES, PAGE)), _z((N, 3)))


def _build_sharded_paged_commit():
    from loghisto_tpu.ops.paged_store import make_sharded_paged_commit_fn

    fn = make_sharded_paged_commit_fn(_mesh(), POOL_PAGES // MESH_SHAPE[1])
    return fn, (_z((POOL_PAGES, PAGE)), _z((N, 3)))


def _build_paged_query():
    from loghisto_tpu.config import PRECISION
    from loghisto_tpu.ops.paged_store import make_paged_query_fn

    fn = make_paged_query_fn(BL, PRECISION)
    # 5 requested rows, identity codec: dec_lut [B] storage buckets
    return fn, (_z((POOL_PAGES, PAGE)), _z((5, PPR)), _z((B,)),
                _z((3,), "float32"))


def _build_snapshot_query():
    from loghisto_tpu.ops.stats import make_snapshot_query_fn

    fn = make_snapshot_query_fn(BL)
    return fn, (_z((M, B)), _z((M,)), _z((M,), "float32"), _z((8,)),
                _z((3,), "float32"))


def _build_group_query():
    from loghisto_tpu.ops.stats import make_group_query_fn

    fn = make_group_query_fn(BL)
    args = (_z((M, B)), _z((M,)), _z((M,), "float32"), _z((8,)),
            _z((8,)), _z((3,), "float32"))
    return (lambda *a: fn(*a, num_groups=4)), args


def _build_fold_evict():
    from loghisto_tpu.ops.lifecycle import make_fold_evict_fn

    fn = make_fold_evict_fn(TIERS)
    acc, rings = _dense_carries()
    return fn, (acc, rings, _z((M,)), _z((4,)), _z((4,)), _scalar(1))


def _build_fold_evict_paged():
    from loghisto_tpu.ops.lifecycle import make_fold_evict_fn

    fn = make_fold_evict_fn(TIERS, with_acc=False)
    _, rings = _paged_carries()
    return fn, (rings, _z((PM,)), _z((4,)), _z((4,)), _scalar(1))


def _build_compact():
    from loghisto_tpu.ops.lifecycle import make_compact_fn

    fn = make_compact_fn(TIERS)
    acc, rings = _dense_carries()
    return fn, (acc, rings, _z((M,)), _z((M,)), _scalar(1))


def _build_divergence():
    from loghisto_tpu.ops.anomaly import make_divergence_fn

    fn = make_divergence_fn("jnp")
    return fn, (_z((M, B)), _z((M,)), _z((BANKS, M, B), "float32"),
                _z((BANKS, M), "float32"), _scalar(0), _scalar(10))


def _build_bank_evict():
    from loghisto_tpu.ops.anomaly import make_bank_evict_fn

    fn = make_bank_evict_fn()
    return fn, (_z((BANKS, M, B), "float32"), _z((BANKS, M), "float32"),
                _z((M, B)), _z((4,)))


def _build_bank_compact():
    from loghisto_tpu.ops.anomaly import make_bank_compact_fn

    fn = make_bank_compact_fn()
    return fn, (_z((BANKS, M, B), "float32"), _z((BANKS, M), "float32"),
                _z((M, B)), _z((M,)))


PROGRAMS: tuple[ProgramSpec, ...] = (
    # -- fused commit, dense carries ---------------------------------- #
    _spec("fused_commit", "ops.commit.make_fused_commit_fn",
          _build_fused_commit,
          donated=3, int32_scatter_shapes=_DENSE_CARRIES,
          description="chunk commit: acc fold + every tier's open-slot "
                      "scatter, one dispatch"),
    _spec("fused_commit_full", "ops.commit.make_fused_commit_fn[act,base]",
          _build_fused_commit_full,
          donated=5, int32_scatter_shapes=_DENSE_CARRIES,
          description="commit + activity stamp + interval histogram, "
                      "same dispatch"),
    _spec("fused_commit_snapshot",
          "ops.commit.make_fused_commit_snapshot_fn",
          _build_fused_commit_snapshot,
          donated=3, int32_scatter_shapes=_DENSE_CARRIES,
          description="final-chunk commit + snapshot payload emission"),
    _spec("fused_commit_snapshot_full",
          "ops.commit.make_fused_commit_snapshot_fn[act,base]",
          _build_fused_commit_snapshot_full,
          donated=7, int32_scatter_shapes=_DENSE_CARRIES,
          description="final chunk + activity + EWMA bank decay, one "
                      "dispatch"),
    _spec("sharded_fused_commit",
          "ops.commit.make_sharded_fused_commit_fn",
          _build_sharded_fused_commit,
          donated=3, stream_psums=1,
          description="mesh commit: shard-local scatters, ONE stream "
                      "psum"),
    _spec("sharded_fused_commit_snapshot",
          "ops.commit.make_sharded_fused_commit_snapshot_fn",
          _build_sharded_fused_commit_snapshot,
          donated=3, stream_psums=1,
          description="mesh final-chunk commit + shard-local snapshot"),
    # -- fused commit, paged pool carries ----------------------------- #
    _spec("paged_fused_commit", "ops.commit.make_paged_fused_commit_fn",
          _build_paged_fused_commit,
          donated=3, forbidden_shapes=_NO_DENSE_MB,
          int32_scatter_shapes=_POOL_CARRY,
          description="pool scatter + dense tier rings, one dispatch"),
    _spec("paged_fused_commit_snapshot",
          "ops.commit.make_paged_fused_commit_snapshot_fn",
          _build_paged_fused_commit_snapshot,
          donated=3, forbidden_shapes=_NO_DENSE_MB,
          int32_scatter_shapes=_POOL_CARRY,
          description="paged final-chunk commit + tier snapshots"),
    _spec("sharded_paged_fused_commit",
          "ops.commit.make_sharded_paged_fused_commit_fn",
          _build_sharded_paged_fused_commit,
          donated=3, stream_psums=1, forbidden_shapes=_NO_DENSE_MB,
          description="per-shard page arenas, ONE stream psum"),
    _spec("sharded_paged_fused_commit_snapshot",
          "ops.commit.make_sharded_paged_fused_commit_snapshot_fn",
          _build_sharded_paged_fused_commit_snapshot,
          donated=3, stream_psums=1, forbidden_shapes=_NO_DENSE_MB,
          description="sharded paged final chunk + snapshots"),
    # -- ingest ------------------------------------------------------- #
    _spec("fused_ingest", "ops.fused_ingest.make_fused_ingest_fn",
          _build_fused_ingest,
          donated=1, pallas_calls=1, int32_scatter_shapes=(),
          description="compress->bucket->scatter in ONE pallas_call; "
                      "no per-sample [M, B] scatter"),
    _spec("fused_paged_ingest",
          "ops.fused_ingest.make_fused_paged_ingest_fn",
          _build_fused_paged_ingest,
          donated=1, pallas_calls=1, forbidden_shapes=_NO_DENSE_MB,
          description="compress->encode->translate->scatter straight "
                      "into the donated pool"),
    _spec("sharded_fused_paged_ingest",
          "ops.fused_ingest.make_sharded_fused_paged_ingest_fn",
          _build_sharded_fused_paged_ingest,
          donated=1, stream_psums=1, forbidden_shapes=_NO_DENSE_MB,
          description="mesh direct-to-paged ingest (jnp scatter tier), "
                      "ONE stream psum"),
    _spec("sparse_ingest_jnp", "ops.sparse_ingest.make_sparse_ingest_fn",
          functools.partial(_build_sparse_ingest, "jnp"),
          donated=1, int32_scatter_shapes=((M, B),),
          description="packed [n,3] sparse merge, XLA scatter tier"),
    _spec("sparse_ingest_pallas",
          "ops.sparse_ingest.make_sparse_ingest_fn[pallas]",
          functools.partial(_build_sparse_ingest, "pallas"),
          donated=1, pallas_calls=1,
          description="packed [n,3] sparse merge, per-cell DMA kernel"),
    # -- paged storage ------------------------------------------------ #
    _spec("paged_commit_jnp", "ops.paged_store.make_paged_commit_fn",
          functools.partial(_build_paged_commit, "jnp"),
          donated=1, forbidden_shapes=_NO_DENSE_MB,
          int32_scatter_shapes=_POOL_CARRY,
          description="translated-triple pool commit, XLA scatter"),
    _spec("paged_commit_pallas",
          "ops.paged_store.make_paged_commit_fn[pallas]",
          functools.partial(_build_paged_commit, "pallas"),
          donated=1, pallas_calls=1, forbidden_shapes=_NO_DENSE_MB,
          description="translated-triple pool commit, per-cell DMA"),
    _spec("sharded_paged_commit",
          "ops.paged_store.make_sharded_paged_commit_fn",
          _build_sharded_paged_commit,
          donated=1, stream_psums=1, forbidden_shapes=_NO_DENSE_MB,
          description="arena-local triple scatter, ONE stream psum"),
    _spec("paged_query", "ops.paged_store.make_paged_query_fn",
          _build_paged_query,
          donated=0, forbidden_shapes=_NO_DENSE_MB,
          description="page gather + codec decode + row stats; dense "
                      "only in the requested [n, B] rows, never [M, B]"),
    # -- query engine ------------------------------------------------- #
    _spec("snapshot_query", "ops.stats.make_snapshot_query_fn",
          _build_snapshot_query,
          donated=0,
          description="sparse row gather + percentile selection, never "
                      "donated (lock-free snapshot handles)"),
    _spec("group_query", "ops.stats.make_group_query_fn",
          _build_group_query,
          donated=0,
          description="segment-sum rollup + row stats, one dispatch"),
    # -- lifecycle ---------------------------------------------------- #
    _spec("fold_evict", "ops.lifecycle.make_fold_evict_fn",
          _build_fold_evict,
          donated=4, int32_scatter_shapes=_DENSE_CARRIES,
          description="victim fold into overflow rows + zero + stamp"),
    _spec("fold_evict_paged", "ops.lifecycle.make_fold_evict_fn[paged]",
          _build_fold_evict_paged,
          donated=3,
          description="ring-only fold (pool fold is a host translate)"),
    _spec("compact", "ops.lifecycle.make_compact_fn",
          _build_compact,
          donated=4,
          description="survivor-permutation repack of every carry"),
    # -- drift engine ------------------------------------------------- #
    _spec("divergence", "ops.anomaly.make_divergence_fn",
          _build_divergence,
          donated=0,
          description="KS/JSD/EMD vs the EWMA bank; operands are "
                      "snapshot handles, never donated"),
    _spec("bank_evict", "ops.anomaly.make_bank_evict_fn",
          _build_bank_evict,
          donated=3,
          description="zero victims' baselines + interval rows"),
    _spec("bank_compact", "ops.anomaly.make_bank_compact_fn",
          _build_bank_compact,
          donated=3,
          description="survivor permutation over the bank carries"),
)

_BY_NAME = {spec.name: spec for spec in PROGRAMS}


def program_names() -> tuple:
    return tuple(spec.name for spec in PROGRAMS)


def get_spec(name: str) -> ProgramSpec:
    try:
        return _BY_NAME[name]
    except KeyError:
        raise KeyError(
            f"unknown audited program {name!r}; registered: "
            f"{', '.join(sorted(_BY_NAME))}"
        ) from None


@functools.lru_cache(maxsize=None)
def _trace(name: str):
    """Trace the registered program on CPU abstract shapes.  Cached —
    the per-test delegations and the CLI share one trace per program."""
    import jax

    spec = get_spec(name)
    fn, args = spec.build()
    closed = jax.make_jaxpr(fn)(*args)
    path, line = _callable_origin(fn)
    return closed, path, line


def audit_program(name: str) -> list[Finding]:
    spec = get_spec(name)
    closed, path, line = _trace(name)
    return audit_jaxpr(closed, spec.contract, name, path, line)


def audit_spec(spec: ProgramSpec) -> list[Finding]:
    """Audit an out-of-registry ProgramSpec (fixture programs, ad-hoc
    guards over store-specific shapes)."""
    import jax

    fn, args = spec.build()
    closed = jax.make_jaxpr(fn)(*args)
    path, line = _callable_origin(fn)
    return audit_jaxpr(closed, spec.contract, spec.name, path, line)


def assert_contract(name: str) -> None:
    """The per-test entry point: raise AssertionError listing every
    violated contract clause for ``name``."""
    findings = audit_program(name)
    if findings:
        raise AssertionError(
            f"static contract violated for program {name!r}:\n"
            + "\n".join("  " + f.render() for f in findings)
        )


def constant_findings() -> list[Finding]:
    """Static dtype-rule constants: the Pallas in-tile f32 partial sums
    are exact only while a tile's population stays under 2^24."""
    from loghisto_tpu.ops import pallas_kernels

    out: list[Finding] = []
    if pallas_kernels.SAMPLE_TILE >= F32_EXACT_BOUND:
        out.append(Finding(
            "jaxpr", "loghisto_tpu/ops/pallas_kernels.py", 40,
            "SAMPLE_TILE", "f32-tile-bound",
            f"SAMPLE_TILE={pallas_kernels.SAMPLE_TILE} >= 2^24 breaks "
            "the f32 in-tile exactness bound",
        ))
    return out


def audit_all(names: Sequence[str] | None = None) -> list[Finding]:
    """Audit every registered program (the CLI gate's jaxpr pass)."""
    out: list[Finding] = []
    for name in (names or program_names()):
        out.extend(audit_program(name))
    if names is None:
        out.extend(constant_findings())
    return out
