"""Reviewed baseline suppressions for the static contract analyzer.

Each entry pins ONE intentional finding, capability-table style:

    (pass, repo-relative path, scope, detail, reason)

The first four fields are the finding's line-number-independent key
(``Finding.key()``); the fifth is the human justification a reviewer
signed off on.  A stale entry — one that no longer matches any finding
— is itself reported as a failure, so the table can only shrink when
the code actually improves.  Populated after a HEAD run review; see
ARCHITECTURE.md "Static contract analysis".
"""

BASELINE: tuple[tuple[str, str, str, str, str], ...] = (
    (
        "locks", "loghisto_tpu/lifecycle/manager.py",
        "LifecycleManager.compact",
        "blocking-under-lock:block_until_ready",
        "compaction is deliberately stop-the-world: the permuted "
        "carries must be live before the registry republishes row ids, "
        "so the manager synchronizes inside its lock; commit traffic "
        "is paused by design for the (rare) compaction window",
    ),
)
