"""Import-graph linter: AST-level module graph over ``loghisto_tpu/``
enforcing the declared layering.

Two rules:

  * **jax-free frontier** — the modules that run inside emitter /
    host-only processes (``federation.emitter``, ``labels.model``,
    ``obs.spans``, ``metrics``) must not *transitively* reach jax (or
    jaxlib/numpy-free accelerator deps) at import time.  The federation
    drill proves this with a subprocess oracle; this pass proves it
    statically on every run, with the offending import chain in the
    finding.
  * **lazy surfaces resolve** — the PEP 562 ``__getattr__`` surfaces in
    ``loghisto_tpu/__init__.py`` and ``ops/__init__.py`` must resolve
    every name they advertise in ``__all__`` (a renamed symbol behind a
    lazy indirection otherwise fails only at first customer access).

Only module-level imports count: an import inside a function body is a
deliberate lazy import (the repo's standard idiom for breaking the
frontier), and ``if TYPE_CHECKING:`` blocks never execute.
"""

from __future__ import annotations

import ast
import os
from typing import Iterable

from loghisto_tpu.analysis import Finding, REPO_ROOT

PACKAGE = "loghisto_tpu"
PACKAGE_ROOT = os.path.join(REPO_ROOT, PACKAGE)

# Modules that must stay importable in a process with no accelerator
# stack: the federation emitter tier, the label data model, the span
# ring, and the host metrics registry.
JAX_FREE_FRONTIER = (
    "loghisto_tpu.federation.emitter",
    "loghisto_tpu.labels.model",
    "loghisto_tpu.obs.spans",
    "loghisto_tpu.metrics",
)

# Top-level distributions the frontier must never reach at import time.
FORBIDDEN_ROOTS = ("jax", "jaxlib")

# Packages whose __getattr__-advertised names must resolve.
LAZY_SURFACES = ("loghisto_tpu", "loghisto_tpu.ops")


def _is_type_checking_test(test: ast.expr) -> bool:
    return (isinstance(test, ast.Name) and test.id == "TYPE_CHECKING") or (
        isinstance(test, ast.Attribute) and test.attr == "TYPE_CHECKING"
    )


def _module_level_imports(tree: ast.Module) -> Iterable[ast.stmt]:
    """Import statements that execute at import time: module body plus
    any try/if/with nesting — but not function bodies (lazy imports)
    or TYPE_CHECKING blocks (never execute)."""
    stack: list[ast.stmt] = list(tree.body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.Import, ast.ImportFrom)):
            yield node
        elif isinstance(node, ast.If):
            if not _is_type_checking_test(node.test):
                stack.extend(node.body)
            stack.extend(node.orelse)
        elif isinstance(node, ast.Try):
            stack.extend(node.body)
            for handler in node.handlers:
                stack.extend(handler.body)
            stack.extend(node.orelse)
            stack.extend(node.finalbody)
        elif isinstance(node, (ast.With, ast.ClassDef)):
            stack.extend(node.body)


def _module_name(path: str, root: str = REPO_ROOT,
                 package: str = PACKAGE) -> str | None:
    rel = os.path.relpath(path, root)
    if not rel.endswith(".py"):
        return None
    parts = rel[:-3].split(os.sep)
    if parts[-1] == "__init__":
        parts = parts[:-1]
    if not parts or parts[0] != package:
        return None
    return ".".join(parts)


def build_import_graph(
    package_root: str = PACKAGE_ROOT, package: str = PACKAGE,
    repo_root: str = REPO_ROOT,
) -> dict[str, list[tuple[str, str, int]]]:
    """module -> [(imported module, file, line)] for every module-level
    import in the package tree.  ``from pkg import name`` records both
    ``pkg`` and ``pkg.name`` when the latter is itself a module."""
    graph: dict[str, list[tuple[str, str, int]]] = {}
    modules: set[str] = set()
    files: dict[str, str] = {}
    for dirpath, _dirnames, filenames in os.walk(package_root):
        for fname in filenames:
            if not fname.endswith(".py"):
                continue
            path = os.path.join(dirpath, fname)
            mod = _module_name(path, repo_root, package)
            if mod is not None:
                modules.add(mod)
                files[mod] = path
    for mod, path in files.items():
        with open(path, "r", encoding="utf-8") as fh:
            tree = ast.parse(fh.read(), filename=path)
        edges: list[tuple[str, str, int]] = []
        is_pkg = os.path.basename(path) == "__init__.py"
        for node in _module_level_imports(tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    edges.append((alias.name, path, node.lineno))
            else:  # ImportFrom
                if node.level:
                    base_parts = mod.split(".")
                    # a package's own __init__ resolves level-1 against
                    # itself, a plain module against its parent package
                    up = node.level - (1 if is_pkg else 0)
                    if up:
                        base_parts = base_parts[:-up]
                    base = ".".join(base_parts)
                    target = f"{base}.{node.module}" if node.module else base
                else:
                    target = node.module or ""
                if target:
                    edges.append((target, path, node.lineno))
                for alias in node.names:
                    sub = f"{target}.{alias.name}" if target else alias.name
                    if sub in modules:
                        edges.append((sub, path, node.lineno))
        graph[mod] = edges
    return graph


def _closure_chain(
    graph: dict, start: str, forbidden_roots: tuple,
) -> tuple[list[str], str, int] | None:
    """BFS the import-time closure of ``start``; on reaching a forbidden
    root, return (module chain, offending file, line)."""
    parent: dict[str, tuple[str, str, int] | None] = {start: None}
    queue = [start]
    while queue:
        mod = queue.pop(0)
        for target, path, line in graph.get(mod, ()):
            root = target.split(".")[0]
            if root in forbidden_roots:
                chain = [target]
                cursor: str | None = mod
                while cursor is not None:
                    chain.append(cursor)
                    entry = parent[cursor]
                    cursor = entry[0] if entry else None
                return list(reversed(chain)), path, line
            # importing pkg.sub executes pkg's __init__ too
            parts = target.split(".")
            for depth in range(1, len(parts) + 1):
                prefix = ".".join(parts[:depth])
                if prefix in graph and prefix not in parent:
                    parent[prefix] = (mod, path, line)
                    queue.append(prefix)
    return None


def frontier_findings(
    frontier: tuple = JAX_FREE_FRONTIER,
    forbidden_roots: tuple = FORBIDDEN_ROOTS,
    graph: dict | None = None,
) -> list[Finding]:
    from loghisto_tpu.analysis import relpath

    if graph is None:
        graph = build_import_graph()
    out: list[Finding] = []
    for mod in frontier:
        if mod not in graph:
            out.append(Finding(
                "imports", "loghisto_tpu/analysis/import_lint.py", 1,
                mod, "frontier-missing",
                f"declared jax-free frontier module {mod} does not "
                "exist — update JAX_FREE_FRONTIER",
            ))
            continue
        hit = _closure_chain(graph, mod, forbidden_roots)
        if hit is not None:
            chain, path, line = hit
            out.append(Finding(
                "imports", relpath(path), line, mod, f"jax-import:{chain[-1]}",
                f"jax-free frontier module {mod} transitively imports "
                f"{chain[-1]} at import time: {' -> '.join(chain)}",
            ))
    return out


def lazy_surface_findings(
    surfaces: tuple = LAZY_SURFACES,
) -> list[Finding]:
    """Resolve every ``__all__`` name of the PEP 562 surfaces.  This is
    a *dynamic* check by design: the lazy indirection's whole failure
    mode is a name that parses fine and only breaks on getattr."""
    import importlib

    out: list[Finding] = []
    for modname in surfaces:
        mod = importlib.import_module(modname)
        path = getattr(mod, "__file__", modname) or modname
        from loghisto_tpu.analysis import relpath

        for name in getattr(mod, "__all__", ()):
            try:
                getattr(mod, name)
            except Exception as exc:  # AttributeError or deeper ImportError
                out.append(Finding(
                    "imports", relpath(path), 1, modname,
                    f"lazy-surface:{name}",
                    f"{modname}.__all__ advertises {name!r} but "
                    f"resolving it raises {type(exc).__name__}: {exc}",
                ))
    return out


def run(include_dynamic: bool = True) -> list[Finding]:
    out = frontier_findings()
    if include_dynamic:
        out.extend(lazy_surface_findings())
    return out
