"""CLI gate: ``python -m loghisto_tpu.analysis [--pass NAME ...]``.

Runs the three static passes (jaxpr contract audit, import-graph lint,
concurrency lint), applies the reviewed baseline, prints one
``file:line [pass] scope: reason`` line per surviving finding, and
exits nonzero if any survive.  The jaxpr pass traces every registered
program on CPU abstract shapes — safe to run anywhere, including as
bench.py's preflight on a TPU host (it forces the CPU platform in its
own process).
"""

from __future__ import annotations

import argparse
import os
import sys

PASSES = ("jaxpr", "imports", "locks")


def _force_cpu_devices() -> None:
    """Must run before jax is imported anywhere in this process: the
    jaxpr pass needs 8 virtual CPU devices for the mesh contracts (the
    same bootstrap tests/conftest.py performs)."""
    flag = "--xla_force_host_platform_device_count=8"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = f"{flags} {flag}".strip()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _run_jaxpr_pass(programs_file: str | None = None):
    import jax

    # The env var alone is not enough on hosts whose sitecustomize
    # force-registers an accelerator plugin; the config update is.
    jax.config.update("jax_platforms", "cpu")
    from loghisto_tpu.analysis import jaxpr_audit

    if programs_file is None:
        return jaxpr_audit.audit_all()
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "_loghisto_audit_programs", programs_file
    )
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    findings = []
    for program in module.PROGRAMS:
        findings.extend(jaxpr_audit.audit_spec(program))
    return findings


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m loghisto_tpu.analysis",
        description="static contract analyzer (jaxpr audit, import "
                    "lint, lock lint)",
    )
    parser.add_argument(
        "--pass", dest="passes", action="append", choices=PASSES,
        help="run only the named pass (repeatable; default: all)",
    )
    parser.add_argument(
        "--list", action="store_true",
        help="list the audited programs and their contracts, then exit",
    )
    # Fixture-tree overrides (tests/test_contracts.py drives the CLI
    # against tests/analysis_fixtures/ with these; baseline suppression
    # is skipped when any is set):
    parser.add_argument(
        "--programs", metavar="FILE",
        help="audit ProgramSpecs from FILE's PROGRAMS tuple instead of "
             "the built-in registry (jaxpr pass)",
    )
    parser.add_argument(
        "--root", metavar="DIR",
        help="lint DIR instead of loghisto_tpu/ (locks/imports passes)",
    )
    parser.add_argument(
        "--package", metavar="NAME",
        help="package name under --root (imports pass)",
    )
    parser.add_argument(
        "--frontier", action="append", metavar="MODULE",
        help="override the jax-free frontier module list (imports pass)",
    )
    args = parser.parse_args(argv)
    selected = tuple(args.passes) if args.passes else PASSES
    overridden = bool(args.programs or args.root or args.frontier)

    if "jaxpr" in selected:
        _force_cpu_devices()

    if args.list:
        _force_cpu_devices()
        from loghisto_tpu.analysis.jaxpr_audit import PROGRAMS

        for spec in PROGRAMS:
            c = spec.contract
            print(f"{spec.name:40s} dispatches={c.dispatches} "
                  f"pallas={c.pallas_calls} donated={c.donated} "
                  f"stream_psums={c.stream_psums} "
                  f"no_dense_MB={bool(c.forbidden_shapes)}  "
                  f"[{spec.factory}]")
        return 0

    from loghisto_tpu.analysis import apply_baseline

    findings = []
    for name in selected:
        if name == "jaxpr":
            findings.extend(_run_jaxpr_pass(args.programs))
        elif name == "imports":
            from loghisto_tpu.analysis import import_lint

            if args.root and args.package:
                graph = import_lint.build_import_graph(
                    package_root=os.path.join(args.root, args.package),
                    package=args.package,
                    repo_root=args.root,
                )
                findings.extend(import_lint.frontier_findings(
                    frontier=tuple(args.frontier or ()), graph=graph,
                ))
            else:
                findings.extend(import_lint.run())
        elif name == "locks":
            from loghisto_tpu.analysis import lock_lint

            findings.extend(
                lock_lint.run(args.root) if args.root else lock_lint.run()
            )

    survivors = (list(findings) if overridden
                 else apply_baseline(findings, passes=selected))
    for finding in sorted(survivors, key=lambda f: (f.path, f.line)):
        print(finding.render())
    suppressed = len(findings) - sum(
        1 for f in survivors if f.pass_name != "baseline"
    )
    print(
        f"analysis: {len(survivors)} finding(s), {suppressed} "
        f"baseline-suppressed, passes={','.join(selected)}",
        file=sys.stderr,
    )
    return 1 if survivors else 0


if __name__ == "__main__":
    raise SystemExit(main())
