"""Concurrency-discipline lint: AST pass over ``loghisto_tpu/``.

Two rules:

  * **no blocking call under a lock** — ``block_until_ready`` /
    ``device_get`` (device syncs that can stall for a full dispatch) and
    blocking socket ops must not execute inside a ``with <lock>:``
    block: every reader of that lock then stalls behind the device or
    the peer.  The handful of deliberate cases (e.g. an observe-only
    span sync) are pinned in ``analysis/baseline.py`` with reasons.
  * **locked worker writes** — a function handed to a thread as an
    entry point (``threading.Thread(target=...)``, ``ThreadSupervisor
    .spawn(...)``) shares ``self`` with the spawning thread; plain
    ``self.attr = ...`` writes from the worker body outside any ``with
    <lock>:`` scope are unsynchronized publication.  Baseline entries
    document today's benign cases (single-writer fields, monotonic
    flags) instead of letting new ones land silently.

Heuristics are name-based by design (a lock is anything whose terminal
name contains ``lock``); the point is a cheap tripwire with a reviewed
baseline, not an alias-analysis prover.
"""

from __future__ import annotations

import ast
import os

from loghisto_tpu.analysis import Finding, REPO_ROOT, relpath

PACKAGE_ROOT = os.path.join(REPO_ROOT, "loghisto_tpu")

# call-terminal-name -> what blocks
BLOCKING_CALLS = {
    "block_until_ready": "device sync",
    "device_get": "blocking D2H readback",
    "recv": "blocking socket read",
    "recv_into": "blocking socket read",
    "recvfrom": "blocking socket read",
    "sendall": "blocking socket write",
    "accept": "blocking socket accept",
    "connect": "blocking socket connect",
    "create_connection": "blocking socket connect",
}


def _terminal_name(node: ast.expr) -> str | None:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Call):
        return _terminal_name(node.func)
    return None


def _lock_name(node: ast.expr) -> str | None:
    """The lock a ``with`` item acquires, if its terminal name smells
    like one (``self._lock``, ``shard.lock``, ``self._flush_lock``);
    condition variables (``self._xfer_cv``) wrap a lock and count as
    lock scope for both rules."""
    name = _terminal_name(node)
    if name is None:
        return None
    low = name.lower()
    if "lock" in low or "cond" in low or low.endswith("_cv") or low == "cv":
        return name
    return None


class _FunctionScanner(ast.NodeVisitor):
    """Scan one function body tracking the with-lock nesting depth."""

    def __init__(self, path: str, qualname: str, findings: list):
        self.path = path
        self.qualname = qualname
        self.findings = findings
        self.lock_stack: list[str] = []

    # nested defs get their own scan via _iter_functions; don't descend
    def visit_FunctionDef(self, node):
        pass

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_Lambda = visit_FunctionDef

    def visit_With(self, node: ast.With):
        locks = [
            _lock_name(item.context_expr) for item in node.items
        ]
        locks = [name for name in locks if name]
        self.lock_stack.extend(locks)
        for stmt in node.body:
            self.visit(stmt)
        for _ in locks:
            self.lock_stack.pop()

    visit_AsyncWith = visit_With

    def visit_Call(self, node: ast.Call):
        name = _terminal_name(node.func)
        if name in BLOCKING_CALLS and self.lock_stack:
            self.findings.append(Finding(
                "locks", relpath(self.path), node.lineno,
                self.qualname, f"blocking-under-lock:{name}",
                f"{BLOCKING_CALLS[name]} `{name}` while holding "
                f"`{self.lock_stack[-1]}` — every contender on the lock "
                "stalls behind it",
            ))
        self.generic_visit(node)


class _EntryScanner(ast.NodeVisitor):
    """Find names handed to threads as entry points in one file."""

    def __init__(self):
        self.entry_names: set[str] = set()

    def visit_Call(self, node: ast.Call):
        callee = _terminal_name(node.func)
        candidates: list[ast.expr] = []
        if callee == "Thread":
            candidates += [kw.value for kw in node.keywords
                           if kw.arg == "target"]
        elif callee == "spawn":
            if node.args:
                candidates.append(node.args[0])
            candidates += [kw.value for kw in node.keywords
                           if kw.arg in ("target", "fn")]
        for cand in candidates:
            if isinstance(cand, ast.Call):   # functools.partial(self.f,...)
                cand = cand.args[0] if cand.args else cand.func
            name = _terminal_name(cand)
            if name:
                self.entry_names.add(name)
        self.generic_visit(node)


def _iter_functions(tree: ast.Module):
    """(qualname, node) for every def, including methods and nested."""
    stack = [("", node) for node in tree.body]
    while stack:
        prefix, node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            qual = f"{prefix}{node.name}"
            yield qual, node
            stack.extend((f"{qual}.", child) for child in node.body
                         if isinstance(child, (ast.FunctionDef,
                                               ast.AsyncFunctionDef,
                                               ast.ClassDef)))
        elif isinstance(node, ast.ClassDef):
            stack.extend((f"{node.name}.", child) for child in node.body)


class _EntryBodyScanner(ast.NodeVisitor):
    """Track with-lock scope inside a thread entry point and record
    ``self.attr`` writes that happen outside every lock."""

    def __init__(self):
        self.lock_depth = 0
        self.writes: dict[str, int] = {}

    def visit_FunctionDef(self, node):
        pass

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_Lambda = visit_FunctionDef

    def visit_With(self, node: ast.With):
        locked = any(_lock_name(i.context_expr) for i in node.items)
        self.lock_depth += bool(locked)
        for stmt in node.body:
            self.visit(stmt)
        self.lock_depth -= bool(locked)

    visit_AsyncWith = visit_With

    def _record(self, target: ast.expr, lineno: int):
        if (
            self.lock_depth == 0
            and isinstance(target, ast.Attribute)
            and isinstance(target.value, ast.Name)
            and target.value.id == "self"
        ):
            self.writes.setdefault(target.attr, lineno)

    def visit_Assign(self, node: ast.Assign):
        for target in node.targets:
            self._record(target, node.lineno)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign):
        self._record(node.target, node.lineno)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign):
        self._record(node.target, node.lineno)
        self.generic_visit(node)


def lint_file(path: str) -> list[Finding]:
    with open(path, "r", encoding="utf-8") as fh:
        tree = ast.parse(fh.read(), filename=path)

    findings: list[Finding] = []
    functions = list(_iter_functions(tree))

    # rule 1: blocking calls under a lock, everywhere
    for qualname, node in functions:
        scanner = _FunctionScanner(path, qualname, findings)
        for stmt in node.body:
            scanner.visit(stmt)

    # rule 2: unlocked self-writes in thread entry points
    entries = _EntryScanner()
    entries.visit(tree)
    if entries.entry_names:
        for qualname, node in functions:
            if node.name not in entries.entry_names:
                continue
            body = _EntryBodyScanner()
            for stmt in node.body:
                body.visit(stmt)
            for attr, lineno in sorted(
                body.writes.items(), key=lambda kv: kv[1]
            ):
                findings.append(Finding(
                    "locks", relpath(path), lineno, qualname,
                    f"unlocked-worker-write:{attr}",
                    f"thread entry point `{qualname}` writes shared "
                    f"`self.{attr}` outside any lock scope",
                ))
    return findings


def run(package_root: str = PACKAGE_ROOT) -> list[Finding]:
    out: list[Finding] = []
    for dirpath, _dirnames, filenames in os.walk(package_root):
        for fname in sorted(filenames):
            if fname.endswith(".py"):
                out.extend(lint_file(os.path.join(dirpath, fname)))
    return sorted(out, key=lambda f: (f.path, f.line))
