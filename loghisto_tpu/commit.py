"""IntervalCommitter: the single subscription that pays every device
consumer of an interval with one fused dispatch.

Before this module, a committed interval with retention enabled fanned
out across two independent bridges: the TPUAggregator's bridge thread
merged the interval's histograms via its weighted scatter launch, and
the TimeWheel's bridge re-resolved the same names, rebuilt the same
cell arrays, and dispatched one scatter per tier — >= 4 device launches
and >= 4 uploads of the same data per interval, each behind its own
lock.

The committer replaces both bridges with ONE subscription behind the
raw boundary:

  1. the interval's sparse histograms are resolved to ``(ids, codec
     bucket, weight)`` cells ONCE (the aggregator's registry/growth/shed
     policy applies — the wheel shares the registry by construction);
  2. the cells are staged through a depth-2 double-buffered H2D ring
     (``ops.commit.CellStagingRing``) so the next chunk/interval's
     transfer overlaps the in-flight commit dispatch;
  3. one jitted donated-carry program (``ops.commit.make_fused_commit_fn``)
     folds the cells into the aggregator accumulator AND every tier's
     open slot — slot indices and ring-wrap keep factors ride along as
     traced int32 operands, so tier rotation never recompiles.

A typical interval is therefore 1 dispatch + 1 upload, bounded at
ceil(cells / COMMIT_CHUNK) dispatches for pathological cardinality
(tests/test_commit.py pins the <= 2 dispatch guarantee and bit-identical
parity with the fan-out path).

Overflow contract: intervals that would break the aggregator's int32
guarantee (interval total past ``spill_threshold``, or any single cell
weight >= 2^30) take the aggregator's exact host-spill machinery and
the wheel's fan-out scatter for that interval — correctness first, the
fused program only ever runs inside the proven int32 envelope.

Lock ordering: the committer is the only code that holds the
aggregator's ``_dev_lock`` and the wheel's lock simultaneously, always
acquired in that order (device state, then wheel state); neither
subsystem ever takes them in reverse, so the pairing cannot deadlock.

Self-metrics: dispatches/interval, H2D bytes/interval, and a commit
latency histogram are exported as ``commit.*`` gauges through the
normal pipeline (``register_gauges``), plus a ``commit.LatencyUs``
histogram recorded into the attached MetricSystem each interval.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from loghisto_tpu.channel import ChannelClosed, ResilientSubscription
from loghisto_tpu.metrics import MetricSystem, RawMetricSet
from loghisto_tpu.obs.spans import NULL_RECORDER, LatencyHistogram
from loghisto_tpu.ops.commit import (
    COMMIT_CHUNK,
    CellStagingRing,
    PagedTripleRing,
    make_fused_commit_fn,
    make_fused_commit_snapshot_fn,
    make_paged_fused_commit_fn,
    make_paged_fused_commit_snapshot_fn,
    make_sharded_fused_commit_fn,
    make_sharded_fused_commit_snapshot_fn,
    make_sharded_paged_fused_commit_fn,
    make_sharded_paged_fused_commit_snapshot_fn,
)
from loghisto_tpu.parallel.mesh import (
    STREAM_AXIS,
    cell_sharding,
    triple_sharding,
)
from loghisto_tpu.window.snapshot import AccSnapshot
from loghisto_tpu.window.store import trailing_mask

logger = logging.getLogger("loghisto_tpu")


def commit_incompatibility(aggregator, wheel) -> Optional[str]:
    """Why this (aggregator, wheel) pair cannot share one fused commit
    program, or None when it can.  The fused program scatters ONE cell
    array into both carries, so the pair must agree on row ids (shared
    registry) and bucket geometry (bucket_limit/precision).

    r18: paged aggregators no longer refuse — the paged fused-commit
    family (``ops.commit.make_paged_fused_commit_fn``) carries the pool
    in the accumulator's place and scatters the interval's
    host-translated triples into it in the same dispatch as the tier
    rings; only the anomaly pairing (dense [M, B] interval-histogram
    carry) stays dense-only, checked in the constructor."""
    if aggregator.registry is not wheel.registry:
        return "aggregator and wheel use different registries"
    if aggregator.config.bucket_limit != wheel.config.bucket_limit:
        return (
            f"bucket_limit mismatch (aggregator "
            f"{aggregator.config.bucket_limit}, wheel "
            f"{wheel.config.bucket_limit})"
        )
    if aggregator.config.precision != wheel.config.precision:
        return (
            f"precision mismatch (aggregator {aggregator.config.precision},"
            f" wheel {wheel.config.precision})"
        )
    if getattr(aggregator, "mesh", None) is not getattr(wheel, "mesh", None):
        return (
            "aggregator and wheel are sharded over different meshes (the "
            "fused program's carries must share one row sharding)"
        )
    return None


class IntervalCommitter:
    """One-subscription interval commit for a (TPUAggregator, TimeWheel)
    pair — see the module docstring for the design.  ``chunk`` is the
    fixed commit launch width (tests shrink it to exercise multi-chunk
    intervals and pad sentinels); ``staging_depth`` sizes the H2D
    overlap ring."""

    def __init__(
        self,
        aggregator,
        wheel,
        chunk: int = COMMIT_CHUNK,
        staging_depth: int = 2,
        lifecycle=None,
        anomaly=None,
    ):
        reason = commit_incompatibility(aggregator, wheel)
        if reason is not None:
            raise ValueError(f"fused commit unavailable: {reason}")
        if anomaly is not None and not wheel.snapshots_enabled:
            raise ValueError(
                "drift engine requires commit-time snapshots: the EWMA "
                "bank update rides the final-chunk snapshot program and "
                "scoring consumes the published window CDFs"
            )
        self.aggregator = aggregator
        self.wheel = wheel
        self.chunk = int(chunk)
        # a LifecycleManager threads its donated last_active carry (and
        # a traced epoch) through the SAME fused programs — activity
        # tracking costs zero extra dispatches on the fused path
        self.lifecycle = lifecycle
        # an AnomalyManager likewise threads its donated interval
        # histogram + EWMA baseline banks; the bank decay step runs in
        # the final-chunk snapshot program — zero extra dispatches
        self.anomaly = anomaly
        track = lifecycle is not None
        track_b = anomaly is not None
        self.paged = getattr(aggregator, "paged", None)
        if anomaly is not None and self.paged is not None:
            raise ValueError(
                "drift engine requires the dense accumulator: the "
                "interval-histogram and EWMA baseline-bank carries are "
                "dense [M, B] tensors, which paged storage exists to "
                "avoid keeping"
            )
        self.mesh = getattr(aggregator, "mesh", None)
        staging_sharding = None
        trip_sharding = None
        tiers_n = len(wheel._tiers)
        if self.mesh is not None:
            # sharded fused path: identical operand protocol, but the
            # program runs under shard_map — staged cells arrive
            # stream-sharded and ONE psum per chunk merges the deltas
            # before the shard-local carry updates
            n_stream = self.mesh.shape[STREAM_AXIS]
            if self.chunk % n_stream:
                raise ValueError(
                    f"commit chunk {self.chunk} not divisible by the mesh "
                    f"stream axis ({n_stream}): staged cell chunks always "
                    "pad to the full width, which must split evenly"
                )
            if self.paged is not None:
                self._fused = make_sharded_paged_fused_commit_fn(
                    self.mesh, self.paged.shard_pages, tiers_n, track
                )
                self._fused_snap = make_sharded_paged_fused_commit_snapshot_fn(
                    self.mesh, self.paged.shard_pages, tiers_n,
                    wheel.config.bucket_limit, wheel.config.precision,
                    wheel.merge_path, track_activity=track,
                )
                trip_sharding = triple_sharding(self.mesh)
            else:
                self._fused = make_sharded_fused_commit_fn(
                    self.mesh, tiers_n, track, track_b
                )
                self._fused_snap = make_sharded_fused_commit_snapshot_fn(
                    self.mesh, tiers_n, wheel.config.bucket_limit,
                    wheel.config.precision, wheel.merge_path,
                    track_activity=track, track_baseline=track_b,
                )
            staging_sharding = cell_sharding(self.mesh)
        elif self.paged is not None:
            # paged pair (r18): the pool is the donated accumulator
            # carry; each chunk's cells also translate to (slot, offset,
            # count) triples on the host (under _dev_lock, so the page
            # table can allocate) and ride the same dispatch
            self._fused = make_paged_fused_commit_fn(tiers_n, track)
            self._fused_snap = make_paged_fused_commit_snapshot_fn(
                tiers_n, wheel.config.bucket_limit,
                wheel.config.precision, wheel.merge_path,
                track_activity=track,
            )
        else:
            self._fused = make_fused_commit_fn(tiers_n, track, track_b)
            # final-chunk variant: same fold + the query engine's snapshot
            # emission (per-tier window CDFs + the acc CDF) in ONE dispatch
            self._fused_snap = make_fused_commit_snapshot_fn(
                tiers_n, wheel.config.bucket_limit,
                wheel.config.precision, wheel.merge_path,
                track_activity=track, track_baseline=track_b,
            )
        self._staging = CellStagingRing(depth=staging_depth,
                                        width=self.chunk,
                                        sharding=staging_sharding)
        self._triples = (
            PagedTripleRing(depth=staging_depth, width=self.chunk,
                            sharding=trip_sharding)
            if self.paged is not None else None
        )
        # the one chunk whose translate ran but whose dispatch hasn't
        # succeeded yet — the failure handler's double-count guard
        self._trip_inflight = None

        # self-metrics (ISSUE 2): per-interval dispatch/H2D accounting.
        # The latency store IS one of the system's own log-bucketed
        # histograms (ISSUE 9 dogfooding): the LatencyP50Us/P99Us gauges
        # are served by the same codec + CDF walk as every user metric,
        # not an ad-hoc bounded host reservoir.
        self._metrics_lock = threading.Lock()
        self.intervals_committed = 0
        self.fused_intervals = 0
        self.fanout_intervals = 0  # spill or policy fan-outs
        self.last_dispatches = 0
        self.last_h2d_bytes = 0
        self.last_uploads = 0
        self._latency_hist = LatencyHistogram(wheel.config.precision)

        # observability (ISSUE 9): span ring + dogfooding + watchdog,
        # all installed by TPUMetricSystem(observability=...); the
        # defaults cost two no-op calls per site
        self.obs_recorder = NULL_RECORDER
        self.self_observer = None
        self.watchdog = None
        # fleet observability (ISSUE 12): the federation receiver's
        # note_publish — pending freshness samples complete the moment
        # the interval snapshot becomes queryable
        self.freshness_hook = None

        # resilience (ISSUE 10), installed by TPUMetricSystem
        # (resilience=...): the supervisor respawns a crashed bridge,
        # the breaker pins the fan-out/spill path after repeated device
        # failures, the injector scripts chaos faults (None = one
        # attribute test per site), and the recovery manager checkpoints
        # on the bridge cadence
        self.supervisor = None
        self.breaker = None
        self.fault_injector = None
        self.recovery = None

        self._ms: Optional[MetricSystem] = None
        self._sub: Optional[ResilientSubscription] = None
        self._thread: Optional[threading.Thread] = None

    # -- cell construction ---------------------------------------------- #

    def _cells_from_raw(self, raw: RawMetricSet):
        """Sparse interval histograms -> (ids int32, codec bucket int64,
        weight int64), resolved ONCE through the aggregator's registry
        policy (growth up to max_metrics, shed past it).  Shed samples
        are mirrored into the wheel's shed counter so both subsystems'
        gauges stay truthful with a single bridge."""
        agg = self.aggregator
        ids, bidx, weights = [], [], []
        shed = 0
        for name, bucket_counts in raw.histograms.items():
            mid = agg._id_for(name, samples=sum(bucket_counts.values()))
            if mid < 0:
                shed += sum(bucket_counts.values())
                continue
            for bucket, count in bucket_counts.items():
                ids.append(mid)
                bidx.append(bucket)
                weights.append(count)
        if shed:
            with self.wheel._lock:
                self.wheel.shed_samples += shed
        if not ids:
            return None
        return (
            np.asarray(ids, dtype=np.int32),
            np.asarray(bidx, dtype=np.int64),
            np.asarray(weights, dtype=np.int64),
        )

    def _dense_cells(self, cells):
        """(ids, codec bucket, int64 weight) -> the wheel's dense int32
        triplet, bit-for-bit the same conversion as
        TimeWheel._cells_from_raw (clip to the dense range; clip weights
        to the int32 wire contract)."""
        ids, bidx64, w64 = cells
        bl = self.wheel.config.bucket_limit
        idx = (np.clip(bidx64, -bl, bl) + bl).astype(np.int32)
        w32 = np.minimum(w64, np.int64(2**31 - 1)).astype(np.int32)
        return ids, idx, w32

    # -- the commit ----------------------------------------------------- #

    def commit(self, raw: RawMetricSet, duration: Optional[float] = None):
        """Land one interval on the aggregator AND every retention tier.
        Returns the path taken ("fused", "fanout", or "empty")."""
        rec = self.obs_recorder
        # adopt the reaper-minted interval sequence number: every span
        # recorded until the next commit attributes to this interval
        seq = rec.begin_interval(raw.seq)
        t0_ns = time.perf_counter_ns()
        t0 = time.perf_counter()
        wheel = self.wheel
        dur = (
            float(duration) if duration is not None
            else float(raw.duration) if raw.duration is not None
            else wheel.interval
        )
        up0 = self._staging.uploads
        b0 = self._staging.bytes_uploaded
        with rec.span("commit.cells", seq):
            cells = self._cells_from_raw(raw)
        if cells is None:
            # cell-less interval: slot rotation/durations still advance
            # (a reopened slot's clear is the only possible dispatch)
            wheel.push_cells(None, raw, dur)
            mode, dispatches = "empty", 0
        else:
            mode, dispatches = self._commit_cells(cells, raw, dur)
        if self.anomaly is not None:
            # score the freshly published snapshot BEFORE the hooks run,
            # so distribution_drift rules evaluate THIS interval's
            # scores, not last interval's — same bridge thread, so no
            # device state races with the commit that just landed
            self.anomaly.on_interval(raw)
        wheel.run_hooks(raw)
        if self.lifecycle is not None:
            # policy tick OUTSIDE every lock: eviction/compaction work
            # never extends the commit critical section, and sharing the
            # bridge thread means no interval's cells are in flight
            # while rows are folded or repacked
            self.lifecycle.on_interval()
        us = (time.perf_counter() - t0) * 1e6
        # the end-to-end span every stage span above nests inside
        rec.record("commit.e2e", t0_ns, time.perf_counter_ns(), seq)
        with self._metrics_lock:
            self.intervals_committed += 1
            if mode == "fused":
                self.fused_intervals += 1
            elif mode == "fanout":
                self.fanout_intervals += 1
            self.last_dispatches = dispatches
            self.last_uploads = self._staging.uploads - up0
            self.last_h2d_bytes = self._staging.bytes_uploaded - b0
        self._latency_hist.add(us)
        if self._ms is not None:
            # the commit latency histogram rides the normal pipeline,
            # so exporters/retention see it like any other metric
            try:
                self._ms.histogram("commit.LatencyUs", us)
            except Exception:  # pragma: no cover - defensive
                pass
        if self.watchdog is not None:
            self.watchdog.note_commit(seq)
        if self.freshness_hook is not None:
            # federated frames applied before this commit are now
            # queryable: close their record→queryable freshness samples
            try:
                self.freshness_hook(seq)
            except Exception:  # pragma: no cover - defensive
                pass
        if self.self_observer is not None:
            # dogfooding: this interval's closed spans re-enter through
            # the normal histogram() path as obs.<stage>.LatencyUs
            self.self_observer.on_interval(seq)
        if self.recovery is not None:
            # watermark + cadenced checkpoint ride the bridge thread,
            # never the ingest path (resilience/recovery.py)
            self.recovery.on_commit(raw)
        return mode

    def _commit_cells(self, cells, raw: RawMetricSet, dur: float):
        """Dispatch one interval's cells.  Returns (mode, dispatches)."""
        agg, wheel = self.aggregator, self.wheel
        ids, bidx64, w64 = cells
        total = int(w64.sum(dtype=np.int64))
        # an open breaker pins the fan-out/spill path: after repeated
        # device failures every fused attempt costs a donated-carry
        # rebuild, so stop attempting until the open window passes and a
        # half-open trial succeeds (resilience/recovery.py)
        pinned = self.breaker is not None and self.breaker.is_open()
        with agg._dev_lock:
            if (
                pinned
                or agg._interval_ingested + total >= agg.spill_threshold
                or int(w64.max()) >= 1 << 30
            ):
                # int32-overflow envelope exceeded: the aggregator side
                # takes its exact host-spill machinery; the tiers take
                # the fan-out scatter below (their own int32 clip
                # contract).  Rare by construction — the guarantee wins
                # over the dispatch count for this interval.
                agg._merge_cells_locked(ids, bidx64, w64)
                agg.stats_snapshot = None  # spill path; handle is stale
                if self.lifecycle is not None:
                    # spill intervals can't fuse the activity stamp;
                    # one tiny touch dispatch keeps TTLs truthful
                    self.lifecycle.touch_locked(ids)
                fused = False
            else:
                with wheel._lock:
                    dispatches = self._fused_dispatch_locked(
                        cells, raw, dur
                    )
                fused = True
        if fused:
            return "fused", dispatches
        dense = self._dense_cells(cells)
        wheel.push_cells(dense, raw, dur)
        # estimate: one weighted-scatter chunk ladder for the aggregator
        # plus one per tier (slot clears excluded)
        nchunks = -(-len(ids) // self.chunk)
        return "fanout", nchunks * (1 + len(wheel._tiers))

    def _post_close_masks(self, t, slot: int, dur: float, windows):
        """Snapshot view masks for one tier as they will read AFTER this
        interval's close-out, computed BEFORE the commit dispatches (the
        masks ride the fused program as operands).  Simulates
        ``_tier_close_locked``'s metadata fold on copies — written flag,
        duration accrual, slot rotation — and runs the same
        ``trailing_mask`` walk the live query path uses."""
        written = t.written.copy()
        durations = t.durations.copy()
        written[slot] = True
        durations[slot] += dur
        in_slot = t.in_slot + 1
        cur = slot
        if in_slot >= t.spec.res:
            cur = (slot + 1) % t.spec.slots
            in_slot = 0
        return np.stack([
            trailing_mask(written, durations, cur, in_slot,
                          t.spec.slots, w)
            for w in windows
        ])

    def _fused_dispatch_locked(self, cells, raw: RawMetricSet, dur: float):
        """The fused path.  Caller holds agg._dev_lock THEN wheel._lock
        (the committer's documented ordering).  Chunks the cells through
        the staging ring and the single fused program; first chunk
        carries the ring-wrap keep factors, later chunks keep
        everything; the FINAL chunk runs the snapshot-emitting variant,
        so the query engine's per-tier window CDFs and the aggregator's
        acc CDF cost zero extra dispatches.  Returns the dispatch
        count."""
        agg, wheel = self.aggregator, self.wheel
        ids, idx, w32 = self._dense_cells(cells)
        w64 = cells[2]
        tiers = wheel._tiers
        slots_host = [t.slot for t in tiers]
        keeps_host = [
            0 if wheel._tier_open_locked(t, s) else 1
            for t, s in zip(tiers, slots_host)
        ]
        slots = np.asarray(slots_host, dtype=np.int32)
        keeps = np.asarray(keeps_host, dtype=np.int32)
        ones = np.ones_like(keeps)
        wheel._note_interval_locked(raw.time, (ids, idx, w32))
        lc = self.lifecycle
        an = self.anomaly
        if lc is not None:
            la = lc.ensure_capacity_locked(agg.num_metrics)
            epoch = np.int32(wheel.intervals_pushed)
        if an is not None:
            ihist, banks = an.ensure_capacity_locked(agg.num_metrics)
            bank = an.bank_for(raw.time)
        emit = wheel.snapshots_enabled
        if emit:
            windows = wheel._view_windows_locked()
            masks = tuple(
                self._post_close_masks(t, s, dur, windows)
                for t, s in zip(tiers, slots_host)
            )
        n = len(ids)
        dispatches = 0
        applied = 0
        reset_tiers = ()
        payloads = acc_payload = None
        paged = self.paged
        bl = wheel.config.bucket_limit
        try:
            rec = self.obs_recorder
            inj = self.fault_injector
            for off in range(0, n, self.chunk):
                if inj is not None:
                    # chaos hook: a scripted device failure fires inside
                    # the try so _on_fused_failure_locked recovers it
                    # exactly like an organic dispatch failure
                    inj.check("commit.dispatch")
                take = min(self.chunk, n - off)
                with rec.span("commit.upload"):
                    dev_ids, dev_idx, dev_w = self._staging.stage(
                        ids[off:off + take],
                        idx[off:off + take],
                        w32[off:off + take],
                    )
                    if paged is not None:
                        # host translate against the page table (both
                        # locks held — allocation is safe), then stage
                        # the triples through their own overlap ring.
                        # Cells translate can't place (arena saturated,
                        # no overflow row) land in the exact host spill
                        # INSIDE translate; the in-flight record keeps
                        # the failure handler from re-spilling them.
                        pk = np.empty((take, 3), dtype=np.int32)
                        pk[:, 0] = ids[off:off + take]
                        pk[:, 1] = np.clip(
                            cells[1][off:off + take], -bl, bl
                        )
                        pk[:, 2] = w32[off:off + take]
                        trip, _, _ = paged.translate(pk)
                        self._trip_inflight = (trip, take)
                        dev_trip = self._triples.stage(trip)
                chunk_keeps = keeps if dispatches == 0 else ones
                final = emit and off + take >= n
                # operand ordering per make_fused_commit_fn /
                # make_fused_commit_snapshot_fn (and their paged twins):
                # carries first (acc-or-pool, rings, [la], [ihist],
                # [banks]), then cells, [then triples], then the traced
                # scalars ([epoch], [masks], [ifirst, bank, decay,
                # min_count])
                args = [
                    paged._pool if paged is not None else agg._acc,
                    tuple(t.ring for t in tiers),
                ]
                if lc is not None:
                    args.append(la)
                if an is not None:
                    args.append(ihist)
                    if final:
                        args.append(banks)
                args += [slots, chunk_keeps, dev_ids, dev_idx, dev_w]
                if paged is not None:
                    args.append(dev_trip)
                if lc is not None:
                    args.append(epoch)
                if final:
                    args.append(masks)
                if an is not None:
                    # 0 on the interval's FIRST chunk clears the
                    # previous interval's histogram; later chunks keep
                    # accumulating into it
                    args.append(np.int32(0 if dispatches == 0 else 1))
                    if final:
                        args += [bank, an.decay32, an.min_count32]
                with rec.span("commit.dispatch"):
                    out = iter(
                        (self._fused_snap if final else self._fused)(*args)
                    )
                if paged is not None:
                    paged._pool = next(out)
                else:
                    agg._acc = next(out)
                for t, r in zip(tiers, next(out)):
                    t.ring = r
                if lc is not None:
                    la = next(out)
                    lc.store_carry_locked(la)
                if an is not None:
                    ihist = next(out)
                    if final:
                        banks = next(out)
                    an.store_carry_locked(ihist, banks)
                if final:
                    payloads = next(out)
                    # the paged snapshot variant emits no acc payload —
                    # pool counts live behind per-row codecs, served by
                    # the paged query engine instead
                    acc_payload = next(out) if paged is None else None
                dispatches += 1
                applied = off + take
                self._trip_inflight = None
                agg._device_down_until = 0.0
                agg._interval_ingested += int(
                    w64[off:off + take].sum(dtype=np.int64)
                )
            if rec.enabled and dispatches:
                # only when observing: wait out the async dispatches so
                # the device-sync span carries the real device time
                # instead of it leaking into whoever touches the carries
                # next (a device failure here takes the normal recovery)
                with rec.span("commit.device_sync"):
                    jax.block_until_ready(
                        paged._pool if paged is not None else agg._acc
                    )
            if self.breaker is not None:
                # closes a half-open breaker after a successful trial;
                # failures are recorded in ONE place (the aggregator's
                # _on_device_failure_locked) so fan-out hooks can't
                # multi-count a single physical failure
                self.breaker.record_success()
        except Exception:
            payloads = acc_payload = None
            reset_tiers = self._on_fused_failure_locked(
                cells, applied
            )
        for t, s in zip(tiers, slots_host):
            if t in reset_tiers:
                continue  # recovery already re-zeroed its metadata
            wheel._tier_close_locked(t, s, raw.rates, dur)
        if payloads is not None and not reset_tiers:
            # the tier metadata now matches the simulated post-close
            # state the masks encoded; publish the lock-free handles
            with self.obs_recorder.span("commit.snapshot_publish"):
                wheel.publish_snapshot_locked(tuple(
                    wheel._tier_snapshot_locked(ti, windows, masks[ti],
                                                payloads[ti])
                    for ti in range(len(tiers))
                ))
                if acc_payload is not None:
                    agg.stats_snapshot = AccSnapshot(
                        epoch=wheel.intervals_pushed,
                        cdf=acc_payload["cdf"],
                        counts=acc_payload["counts"],
                        sums=acc_payload["sums"],
                    )
        return dispatches

    def _on_fused_failure_locked(self, cells, applied: int):
        """Device-failure recovery for the fused path (both locks held,
        called from inside the except handler).  The aggregator's
        handler recovers a consumed accumulator and arms the cooldown;
        consumed tier rings are rebuilt empty (retention history for
        that tier resets — logged); the UNAPPLIED cell remainder folds
        into the exact host spill, mirroring _merge_cells_locked's
        accounting so no sample is lost or double-counted on the
        aggregator side.  Returns the tiers whose state was reset."""
        agg, wheel = self.aggregator, self.wheel
        agg._on_device_failure_locked()  # also drops agg.stats_snapshot
        if self.lifecycle is not None:
            # the activity carry was donated into the failed dispatch;
            # rebuild it stamped "just active" (delays evictions only)
            self.lifecycle.on_device_failure_locked()
        if self.anomaly is not None:
            # likewise the interval histogram / baseline banks: rebuild
            # cold (drift detection restarts its EWMA warm-up — scores
            # stay floored until baselines re-establish, never wrong)
            self.anomaly.on_device_failure_locked()
        # the published wheel handle may describe rings this failure
        # consumed; queries fall back to locked recompute until the next
        # successful commit republishes
        wheel.invalidate_snapshot_locked()
        reset = []
        for t in wheel._tiers:
            if getattr(t.ring, "is_deleted", lambda: False)():
                z = jnp.zeros(
                    (t.spec.slots, wheel.num_metrics,
                     wheel.config.num_buckets),
                    dtype=jnp.int32,
                )
                t.ring = (
                    jax.device_put(z, wheel._sharding)
                    if wheel._sharding is not None else z
                )
                t.written[:] = False
                t.durations[:] = 0.0
                t.rates = [dict() for _ in range(t.spec.slots)]
                t.slot = 0
                t.in_slot = 0
                reset.append(t)
        if reset:
            logger.error(
                "fused commit failure consumed %d tier ring(s); their "
                "retention history was reset", len(reset),
            )
        ids, bidx64, w64 = cells
        start = applied
        trip_inflight, self._trip_inflight = self._trip_inflight, None
        if self.paged is not None and trip_inflight is not None:
            # the failed chunk's translate already ran: its host-spill
            # portion was applied there, so only its DEVICE portion (the
            # translated triples) re-lands, via the page-table inverse —
            # spilling the chunk's cells would double-count
            trip, take_failed = trip_inflight
            self.paged.spill_triples(trip)
            start = applied + take_failed
        if start < len(ids):
            agg._spill_add_cells_locked(
                ids[start:], bidx64[start:], w64[start:]
            )
        return tuple(reset)

    # -- warmup / lifecycle --------------------------------------------- #

    def warmup(self) -> None:
        """Pre-compile the fused executable at THE commit shape (all
        pads — numerically a no-op), same rationale as the aggregator's
        _bridge_warmup: the first real interval must not pay the cold
        XLA compile while the reaper fills the freshly subscribed
        channel."""
        agg, wheel = self.aggregator, self.wheel
        lc = self.lifecycle
        an = self.anomaly
        empty = np.empty(0, dtype=np.int32)

        def run(fn, final):
            dev_ids, dev_idx, dev_w = self._staging.stage(
                empty, empty, empty
            )
            args = [
                self.paged._pool if self.paged is not None else agg._acc,
                tuple(t.ring for t in tiers),
            ]
            if lc is not None:
                args.append(la)
            if an is not None:
                args.append(ihist)
                if final:
                    args.append(banks)
            args += [slots, keeps, dev_ids, dev_idx, dev_w]
            if self.paged is not None:
                # all-pad triple chunk (slot -1 drops): warms the paged
                # program at THE fixed staging width
                args.append(
                    self._triples.stage(np.empty((0, 3), dtype=np.int32))
                )
            if lc is not None:
                args.append(epoch)
            if final:
                args.append(masks)
            if an is not None:
                # ifirst=1 with zero cells: the (all-zero) interval
                # histogram carries through unchanged, and zero counts
                # never clear the min_count bar — numerically a no-op
                args.append(np.int32(1))
                if final:
                    args += [an.bank_for(None), an.decay32,
                             an.min_count32]
            out = iter(fn(*args))
            if self.paged is not None:
                self.paged._pool = next(out)
            else:
                agg._acc = next(out)
            for t, r in zip(tiers, next(out)):
                t.ring = r
            if lc is not None:
                lc.store_carry_locked(next(out))
            if an is not None:
                ih = next(out)
                bk = next(out) if final else banks
                an.store_carry_locked(ih, bk)
                return ih, bk
            return None, None

        with agg._dev_lock:
            with wheel._lock:
                tiers = wheel._tiers
                slots = np.asarray([t.slot for t in tiers], dtype=np.int32)
                keeps = np.ones(len(tiers), dtype=np.int32)
                if lc is not None:
                    la = lc.ensure_capacity_locked(agg.num_metrics)
                    epoch = np.int32(wheel.intervals_pushed)
                if an is not None:
                    ihist, banks = an.ensure_capacity_locked(
                        agg.num_metrics
                    )
                ihist, banks = run(self._fused, final=False)
                if lc is not None:
                    la = lc.ensure_capacity_locked(agg.num_metrics)
                if wheel.snapshots_enabled:
                    # warm the final-chunk (snapshot-emitting) variant at
                    # the same shapes; all-False masks make the payloads
                    # numerically empty, so nothing is published
                    windows = wheel._view_windows_locked()
                    masks = tuple(
                        np.zeros((len(windows), t.spec.slots), dtype=bool)
                        for t in tiers
                    )
                    run(self._fused_snap, final=True)

    def attach(self, ms: MetricSystem, channel_capacity: int = 64) -> None:
        """Subscribe ONCE behind the raw boundary for both consumers —
        strike-eviction resilient, same recovery contract as the
        journal/exporters.

        The bridge is the system's only path from raw interval to
        queryable snapshot: an interval shed here permanently loses its
        histogram samples.  The channel is therefore deep enough to ride
        out multi-second scheduler stalls (64 intervals) and let the
        bridge catch up afterwards; sustained overload still sheds
        rather than blocking the reaper."""
        if self._thread is not None:
            raise RuntimeError("already attached")
        self.warmup()
        self._ms = ms
        self._sub = ResilientSubscription(
            ms.subscribe_to_raw_metrics,
            ms.unsubscribe_from_raw_metrics,
            channel_capacity,
        )
        sub = self._sub

        def bridge():
            while True:
                try:
                    raw = sub.get()
                except ChannelClosed:
                    return
                inj = self.fault_injector
                if inj is not None:
                    # chaos hook OUTSIDE the per-commit net: a scripted
                    # bridge crash escapes to the supervisor's restart
                    # loop (the per-commit except would swallow it)
                    inj.check("commit.bridge")
                try:
                    self.commit(raw)
                except Exception:  # pragma: no cover - defensive
                    logger.exception(
                        "fused interval commit failed for %s", raw.time
                    )

        if self.supervisor is not None:
            # crashed bridges restart with capped backoff; a clean
            # ChannelClosed return (detach) ends the thread for good
            self._thread = self.supervisor.spawn(bridge, "loghisto-commit")
        else:
            self._thread = threading.Thread(
                target=bridge, daemon=True, name="loghisto-commit"
            )
            self._thread.start()

    def detach(self) -> None:
        if self._sub is not None:
            self._sub.close()
            self._sub = None
        if self._thread is not None:
            # a supervised handle also needs its restart loop stopped —
            # otherwise a backoff nap could outlive the join below
            stop = getattr(self._thread, "stop", None)
            if stop is not None:
                stop()
            self._thread.join(timeout=5.0)
            self._thread = None

    # -- gauges ---------------------------------------------------------- #

    @property
    def bridge_evictions(self) -> int:
        return self._sub.evictions if self._sub is not None else 0

    def _latency_pct(self, q: float) -> float:
        # served from the system's own log-bucketed state (ISSUE 9):
        # same codec + CDF walk as any user histogram, full lifetime
        # history instead of a bounded reservoir
        return self._latency_hist.percentile(q)

    def register_gauges(self, ms: MetricSystem) -> None:
        """Export the commit-path self-metrics through the normal gauge
        pipeline: dispatches and H2D bytes per interval (the quantities
        the fused design exists to collapse), the fused/fan-out interval
        split, and the commit latency distribution."""
        ms.register_gauge_func(
            "commit.DispatchesPerInterval",
            lambda: float(self.last_dispatches),
        )
        ms.register_gauge_func(
            "commit.H2DBytesPerInterval",
            lambda: float(self.last_h2d_bytes),
        )
        ms.register_gauge_func(
            "commit.CellUploadsPerInterval",
            lambda: float(self.last_uploads),
        )
        ms.register_gauge_func(
            "commit.FusedIntervals", lambda: float(self.fused_intervals)
        )
        ms.register_gauge_func(
            "commit.FanoutIntervals", lambda: float(self.fanout_intervals)
        )
        ms.register_gauge_func(
            "commit.LatencyP50Us", lambda: self._latency_pct(50.0)
        )
        ms.register_gauge_func(
            "commit.LatencyP99Us", lambda: self._latency_pct(99.0)
        )
        ms.register_gauge_func(
            "commit.BridgeEvictions", lambda: float(self.bridge_evictions)
        )
