"""Host-side paged bucket storage: page tables, on-demand allocation,
variable-resolution codecs, and the spill-to-overflow policy.

``PagedStore`` is the storage="paged" backend behind TPUAggregator: it
owns the device page pool (ops/paged_store.py), the host page table,
and the per-metric codec choices, and exposes the same exactness
contract as the dense accumulator — every count lands somewhere
accountable (a mapped page, the overflow row, or the exact host spill),
never silently dropped.

Variable-resolution codecs
--------------------------

Each metric row stores its buckets under one of three layouts on a
STORAGE bucket axis that the codec maps to/from the native log-bucket
axis (dense index d in [0, B), native codec bucket d - bucket_limit):

  * ``dense``     — identity: native resolution, exact.  Rows whose
    occupied span fits a few pages keep full precision for free.
  * ``loglinear`` — circllhist-style coarsening ("A Log-Linear
    Histogram Data Structure for IT Infrastructure Monitoring",
    PAPERS.md): ``factor`` adjacent native log buckets merge into one
    storage bucket, sign-mirrored so bucket 0 stays centered.  Native
    buckets are already log-spaced, so the merged grid is linear in
    log space and the representative error is bounded by the half-chunk
    ratio: |err| <= (e^(ceil(factor/2)/precision) - 1) * (|v| + 1).
  * ``polytail``  — polynomial tail compression ("Polynomial Histograms
    for Memory-Efficient Representation of Long-tailed System
    Distributions", PAPERS.md): exact inside |bucket| <=
    body_halfwidth, beyond it chunk widths grow quadratically
    (1, 4, 9, ... native buckets) up to the width cap derived from
    ``tail_rel_error``, so the long sparse tail collapses to a few
    storage buckets while the tail percentile error stays bounded by
    construction.

All three reduce to a pair of LUTs (encode: native dense index ->
storage index; decode: storage index -> representative native dense
index), so translation is one vectorized NumPy gather per commit and
the device decode is one scatter through the traced LUT.  The
``max_halfwidth`` of a codec gives its asserted error bound
(tests/test_paged_store.py's parity oracle): a dense-codec row is
BIT-IDENTICAL to the dense accumulator; a compressed row's percentiles
are within ``(e^((max_halfwidth + 0.5)/precision) - 1)`` relative.

Allocation & spill policy
-------------------------

translate() sees every cell of a commit (the sparse transport already
folds batches to packed triples on host), so allocation is a host
decision with no device round trip: unmapped (row, page) pairs take
slots from the free list; when the pool saturates, cells re-route to
the ``overflow_row`` (whose pages are reserved at construction — the
catch-all row can never itself fail to allocate) under its coarse
codec; with no overflow row configured they fold into the exact host
spill dict.  Lifecycle composition: ``release_rows`` returns a victim's
pages to the free list (after the caller folds its counts), and
``apply_permutation`` repacks survivors by permuting page-table ROWS —
an O(M) host copy with zero device data movement, because pool pages
are position-independent.
"""

from __future__ import annotations

import dataclasses
import math
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from loghisto_tpu.config import PRECISION

CODEC_DENSE = "dense"
CODEC_LOGLINEAR = "loglinear"
CODEC_POLYTAIL = "polytail"


@dataclasses.dataclass(frozen=True)
class BucketCodec:
    """One storage layout: a pair of LUTs plus its error bound.

    enc_lut: int32 [B] — native dense index -> storage index.
    dec_lut: int32 [S] — storage index -> representative native dense
      index (injective: distinct storage buckets decode to distinct
      native buckets, so device expansion is an exact scatter).
    max_halfwidth: worst-case distance (native buckets) between a
      bucket and its chunk representative — 0 for the identity codec.
    """

    name: str
    enc_lut: np.ndarray
    dec_lut: np.ndarray
    max_halfwidth: int

    @property
    def storage_buckets(self) -> int:
        return len(self.dec_lut)

    def max_rel_error(self, precision: int = PRECISION) -> float:
        """Bounded representative error: |decode(encode(v)) - v| <=
        max_rel_error * (|v| + 1).  The +0.5 absorbs the native codec's
        own rounding so the bound is safe end to end."""
        if self.max_halfwidth == 0:
            return 0.0
        return math.exp((self.max_halfwidth + 0.5) / precision) - 1.0


def _codec_from_chunks(name: str, chunk_of: np.ndarray) -> BucketCodec:
    """Build a codec from a per-native-bucket chunk id array [B]: each
    chunk becomes one storage bucket whose representative is the
    chunk's center native bucket."""
    chunks, enc = np.unique(chunk_of, return_inverse=True)
    enc = enc.astype(np.int32)
    dec = np.zeros(len(chunks), dtype=np.int32)
    width = 0
    for s in range(len(chunks)):
        members = np.nonzero(enc == s)[0]
        dec[s] = members[(len(members) - 1) // 2]
        width = max(width, int(members[-1] - dec[s]), int(dec[s] - members[0]))
    return BucketCodec(
        name=name, enc_lut=enc, dec_lut=dec, max_halfwidth=width
    )


def dense_codec(num_buckets: int) -> BucketCodec:
    idx = np.arange(num_buckets, dtype=np.int32)
    return BucketCodec(
        name=CODEC_DENSE, enc_lut=idx, dec_lut=idx.copy(), max_halfwidth=0
    )


def loglinear_codec(bucket_limit: int, factor: int) -> BucketCodec:
    """Sign-mirrored coarsening: native codec bucket c chunks to
    sign(c) * (|c| // factor) — bucket 0's chunk stays centered on
    zero, so tiny values keep their sign and near-zero magnitude."""
    if factor < 2:
        raise ValueError(f"loglinear factor must be >= 2, got {factor}")
    c = np.arange(-bucket_limit, bucket_limit + 1, dtype=np.int64)
    chunk = np.sign(c) * (np.abs(c) // factor)
    return _codec_from_chunks(CODEC_LOGLINEAR, chunk)


def polytail_codec(
    bucket_limit: int,
    body_halfwidth: int,
    tail_rel_error: float,
    precision: int = PRECISION,
) -> BucketCodec:
    """Exact body, quadratically growing tail chunks capped so the
    tail representative error stays <= tail_rel_error."""
    if not 0 < body_halfwidth < bucket_limit:
        raise ValueError(
            f"body_halfwidth must be in (0, {bucket_limit}); "
            f"got {body_halfwidth}"
        )
    if tail_rel_error <= 0:
        raise ValueError(f"tail_rel_error must be > 0, got {tail_rel_error}")
    # widest admissible chunk: halfwidth w/2 must satisfy
    # e^((w/2 + 0.5)/precision) - 1 <= tail_rel_error
    cap = max(2, int(2 * (precision * math.log1p(tail_rel_error) - 0.5)))
    c = np.arange(-bucket_limit, bucket_limit + 1, dtype=np.int64)
    mag = np.abs(c)
    # tail chunk boundaries: widths 1, 4, 9, ... capped at `cap`
    bounds = [body_halfwidth]
    k = 1
    while bounds[-1] < bucket_limit:
        bounds.append(bounds[-1] + min(cap, k * k))
        k += 1
    bounds = np.asarray(bounds, dtype=np.int64)
    # body buckets chunk to themselves; tail buckets to their band
    tail_band = np.searchsorted(bounds, mag, side="left")
    chunk = np.where(
        mag <= body_halfwidth, c, np.sign(c) * (bucket_limit + tail_band)
    )
    return _codec_from_chunks(CODEC_POLYTAIL, chunk)


@dataclasses.dataclass(frozen=True)
class PagedStoreConfig:
    """Knobs for the paged backend.

    pool_pages: page-pool capacity (slot 0 is the reserved zero page).
      The default 4096 x 256 buckets = 4 MiB of pool — at ~2 pages per
      live sparse row that is ~2k rows; size it to the deployment
      (benchmarks/paged_store.py demonstrates the 1M-row config).
    codec: "auto" picks per row by occupancy (choose_codec below);
      naming one of dense/loglinear/polytail pins every row.
    dense_page_budget: auto keeps a row on the exact dense codec while
      its occupied span fits this many pages.
    tail_occupancy: auto prefers polytail when at least this fraction
      of a row's occupied buckets sit beyond body_halfwidth.
    """

    page_size: int = 256
    pool_pages: int = 4096
    codec: str = "auto"
    loglinear_factor: int = 4
    body_halfwidth: int = 1024
    tail_rel_error: float = 0.10
    dense_page_budget: int = 4
    tail_occupancy: float = 0.5
    overflow_row: Optional[int] = None

    def __post_init__(self):
        if self.codec not in (
            "auto", CODEC_DENSE, CODEC_LOGLINEAR, CODEC_POLYTAIL
        ):
            raise ValueError(f"unknown paged codec {self.codec!r}")
        if self.dense_page_budget < 1:
            raise ValueError(
                f"dense_page_budget must be >= 1, got {self.dense_page_budget}"
            )


class PagedStore:
    """Paged accumulator backend: device pool + host page table + codecs.

    Thread safety follows the aggregator's locking: every mutating call
    happens under the owner's _dev_lock; the internal lock only guards
    the host table for concurrent read-side queries.
    """

    def __init__(
        self,
        num_metrics: int,
        bucket_limit: int,
        precision: int = PRECISION,
        config: PagedStoreConfig = PagedStoreConfig(),
        kernel: str = "jnp",
        mesh=None,
    ):
        from loghisto_tpu.ops.paged_store import validate_pool_shape

        validate_pool_shape(config.pool_pages, config.page_size)
        self.mesh = mesh
        self._n_shards = 1
        self._n_stream = 1
        if mesh is not None:
            # dispatch.py's capability table pre-screens these shapes
            # ("mesh shape:" reasons); the raises here are backstops for
            # direct construction.
            from loghisto_tpu.ops.paged_store import COMMIT_CHUNK
            from loghisto_tpu.parallel.mesh import METRIC_AXIS, STREAM_AXIS

            self._n_shards = int(mesh.shape[METRIC_AXIS])
            self._n_stream = int(mesh.shape[STREAM_AXIS])
            if num_metrics % self._n_shards:
                raise ValueError(
                    f"num_metrics={num_metrics} not divisible by the "
                    f"{self._n_shards}-way metric axis"
                )
            if COMMIT_CHUNK % self._n_stream:
                raise ValueError(
                    f"COMMIT_CHUNK={COMMIT_CHUNK} not divisible by the "
                    f"{self._n_stream}-way stream axis"
                )
        self.config = config
        self.bucket_limit = int(bucket_limit)
        self.precision = int(precision)
        self.num_buckets = 2 * self.bucket_limit + 1
        self.num_metrics = int(num_metrics)
        self._lock = threading.Lock()

        # codec table: ids are indices into _codecs; rows start
        # unassigned (-1) and get a codec on first touch
        self._codecs: List[BucketCodec] = [
            dense_codec(self.num_buckets),
            loglinear_codec(self.bucket_limit, config.loglinear_factor),
            polytail_codec(
                self.bucket_limit,
                # the config default is tuned for the 4096-limit codec;
                # clamp for narrow histograms so construction never fails
                min(config.body_halfwidth, max(1, self.bucket_limit // 2)),
                config.tail_rel_error,
                self.precision,
            ),
        ]
        self._codec_ids = {c.name: i for i, c in enumerate(self._codecs)}
        # stacked LUTs for one-gather translation across mixed codecs
        self._enc = np.stack([c.enc_lut for c in self._codecs])
        self.row_codec = np.full(self.num_metrics, -1, dtype=np.int8)

        # page table: pages_per_row sized for the WIDEST codec (dense)
        page = config.page_size
        self.pages_per_row = -(-self.num_buckets // page)
        self.page_table = np.full(
            (self.num_metrics, self.pages_per_row), -1, dtype=np.int32
        )
        # Page arenas: metric shard k owns the contiguous GLOBAL slot
        # range [k*shard_pages, (k+1)*shard_pages), with the range base
        # slot reserved as that shard's local zero page (so shard_map's
        # re-based local slots keep the slot-0-is-zero-page contract).
        # The page table always stores global slots; rows only ever map
        # pages from their own shard's arena (_alloc, and the
        # permutation/grow migration below, maintain the invariant the
        # sharded fused ingest relies on).  Single-device is the
        # degenerate 1-shard case of the same layout.
        self.rows_per_shard = self.num_metrics // self._n_shards
        self.shard_pages = config.pool_pages
        self.total_pages = self._n_shards * config.pool_pages
        validate_pool_shape(self.total_pages, page)
        self._free_lists: List[List[int]] = [
            list(
                range((k + 1) * self.shard_pages - 1, k * self.shard_pages, -1)
            )
            for k in range(self._n_shards)
        ]

        import jax.numpy as jnp

        from loghisto_tpu.ops.paged_store import (
            make_paged_commit_fn,
            make_sharded_paged_commit_fn,
        )

        pool = jnp.zeros((self.total_pages, page), dtype=jnp.int32)
        if mesh is not None:
            import jax

            from loghisto_tpu.parallel.mesh import (
                pool_sharding,
                triple_sharding,
            )

            from loghisto_tpu.parallel.multihost import global_put

            self._pool_sharding = pool_sharding(mesh)
            self._triple_sharding = triple_sharding(mesh)
            pool = global_put(np.zeros(pool.shape, np.int32), self._pool_sharding)
            self._commit = make_sharded_paged_commit_fn(
                mesh, self.shard_pages
            )
        else:
            self._pool_sharding = None
            self._triple_sharding = None
            self._commit = make_paged_commit_fn(kernel)
        self._pool = pool

        # exact host spill for cells no page can hold (pool saturated
        # and the overflow row unavailable): {(row, native dense idx):
        # int count} — int64-exact at any magnitude
        self._host_spill: Dict[Tuple[int, int], int] = {}

        # accounting
        self.commits = 0
        self.h2d_bytes = 0
        self.last_h2d_bytes = 0
        self.allocated_pages = 0
        self.released_pages = 0
        self.overflowed_cells = 0
        self.spilled_cells = 0
        self.fused_dispatches = 0

        # fused direct-to-paged ingest state: device mirrors of
        # (row_codec, enc LUTs, page table), re-uploaded lazily only
        # after a host mutation (codec assignment, page alloc/release,
        # permutation, growth) — in the steady state where every page a
        # workload touches is mapped, no mirror H2D happens at all
        self._mirror = None
        self._fused_fn = None
        self._storage_buckets = np.array(
            [c.storage_buckets for c in self._codecs], dtype=np.int64
        )

        if config.overflow_row is not None:
            self._reserve_overflow_pages(config.overflow_row)

    # -- codec selection ------------------------------------------------ #

    def _choose_codec(self, dense_idx: np.ndarray) -> int:
        """Pick a codec for a row from its first-touch occupied native
        buckets: exact dense while the span fits the page budget, then
        polytail for tail-heavy rows, loglinear otherwise."""
        cfg = self.config
        if cfg.codec != "auto":
            return self._codec_ids[cfg.codec]
        page = cfg.page_size
        span_pages = len(np.unique(dense_idx // page))
        if span_pages <= cfg.dense_page_budget:
            return self._codec_ids[CODEC_DENSE]
        tail = np.abs(dense_idx - self.bucket_limit) > cfg.body_halfwidth
        if tail.mean() >= cfg.tail_occupancy:
            return self._codec_ids[CODEC_POLYTAIL]
        return self._codec_ids[CODEC_LOGLINEAR]

    def _assign_codecs(self, rows: np.ndarray, dense_idx: np.ndarray) -> None:
        new_rows = np.unique(rows[self.row_codec[rows] < 0])
        for r in new_rows:
            mask = rows == r
            self.row_codec[r] = self._choose_codec(dense_idx[mask])
        if len(new_rows):
            self._mirror = None

    def set_row_codec(self, row: int, name: str) -> None:
        """Pin a row's codec explicitly (checkpoint restore, tests).
        Only legal before the row holds data under a different codec."""
        want = self._codec_ids[name]
        if self.row_codec[row] >= 0 and self.row_codec[row] != want:
            if np.any(self.page_table[row] >= 0):
                raise ValueError(
                    f"row {row} already holds data under codec "
                    f"{self._codecs[self.row_codec[row]].name!r}"
                )
        self.row_codec[row] = want
        self._mirror = None

    # -- allocation ----------------------------------------------------- #

    def _shard_of_row(self, row: int) -> int:
        return int(row) // self.rows_per_shard

    def _free_for(self, row: int) -> List[int]:
        """The free list of the shard arena ``row`` allocates from."""
        return self._free_lists[self._shard_of_row(row)]

    def _reserve_overflow_pages(self, row: int) -> None:
        """The overflow row must never itself fail to allocate: map its
        (coarse-codec) pages eagerly at construction."""
        self.row_codec[row] = self._codec_ids[CODEC_LOGLINEAR]
        codec = self._codecs[self.row_codec[row]]
        page = self.config.page_size
        n_pages = -(-codec.storage_buckets // page)
        free = self._free_for(row)
        for p in range(n_pages):
            if self.page_table[row, p] < 0:
                if not free:
                    raise ValueError(
                        "pool too small to reserve the overflow row's "
                        f"{n_pages} pages; raise pool_pages"
                    )
                self.page_table[row, p] = free.pop()
                self.allocated_pages += 1
        self._mirror = None

    def _alloc(self, row: int, page_idx: int) -> int:
        """One page allocation from the row's own shard arena; returns
        the global slot or -1 when that arena is saturated."""
        free = self._free_for(row)
        if not free:
            return -1
        slot = free.pop()
        self.page_table[row, page_idx] = slot
        self.allocated_pages += 1
        self._mirror = None
        return slot

    @property
    def free_pages(self) -> int:
        return sum(len(fl) for fl in self._free_lists)

    @property
    def occupied_pages(self) -> int:
        return self._n_shards * (self.shard_pages - 1) - self.free_pages

    def shard_free_pages(self) -> List[int]:
        """Free pages remaining in each metric shard's arena."""
        return [len(fl) for fl in self._free_lists]

    def shard_occupancy(self) -> List[float]:
        """Occupied fraction of each shard arena (zero page excluded).
        The per-shard view matters because saturation is per-arena: one
        hot shard starts overflowing/spilling while the pool-wide
        average still looks healthy."""
        cap = max(1, self.shard_pages - 1)
        return [1.0 - len(fl) / cap for fl in self._free_lists]

    def pool_saturation(self) -> float:
        """Worst shard-arena occupancy in [0, 1] — the /healthz
        watchdog's pool_saturation invariant reads this."""
        return max(self.shard_occupancy())

    def hbm_bytes(self) -> int:
        """Device-resident footprint: the pool plus the (host) table's
        device-mirrorable size — what the 1M-row budget is measured
        against (benchmarks/paged_store.py)."""
        pool = self.total_pages * self.config.page_size * 4
        table = self.page_table.size * 4
        return pool + table

    # -- commit --------------------------------------------------------- #

    def translate(
        self, packed: np.ndarray
    ) -> Tuple[np.ndarray, int, int]:
        """Rewrite packed (row, codec_bucket, count) triples into
        translated (slot, offset, count) triples against the page
        table, allocating pages on demand and applying the spill
        policy.  Returns (device_triples, applied_count_total,
        spill_count_total); counts routed to the host spill are applied
        exactly there before this returns."""
        rows = packed[:, 0].astype(np.int64)
        keep = (rows >= 0) & (rows < self.num_metrics)
        rows = rows[keep]
        if not len(rows):
            return np.empty((0, 3), dtype=np.int32), 0, 0
        L = self.bucket_limit
        dense_idx = (
            np.clip(packed[keep, 1].astype(np.int64), -L, L) + L
        )
        weights = packed[keep, 2].astype(np.int64)

        self._assign_codecs(rows, dense_idx)
        storage = self._enc[self.row_codec[rows], dense_idx]
        page = self.config.page_size
        page_idx = storage // page
        offs = (storage % page).astype(np.int32)

        slots = self.page_table[rows, page_idx]
        missing = slots < 0
        if missing.any():
            # allocate each unique unmapped (row, page) once
            pairs = np.unique(
                np.stack([rows[missing], page_idx[missing]], axis=1), axis=0
            )
            for r, p in pairs:
                self._alloc(int(r), int(p))
            slots = self.page_table[rows, page_idx]

        mapped = slots >= 0
        out_rows, out_offs, out_w = slots, offs, weights
        spilled_total = 0
        if not mapped.all():
            # pool saturated: overflow-row redirect, else exact host spill
            um_rows = rows[~mapped]
            um_idx = dense_idx[~mapped]
            um_w = weights[~mapped]
            ov = self.config.overflow_row
            if ov is not None:
                self.overflowed_cells += len(um_rows)
                ov_codec = self.row_codec[ov]
                ov_storage = self._enc[ov_codec, um_idx]
                ov_slots = self.page_table[ov, ov_storage // page]
                out_rows = np.concatenate([slots[mapped], ov_slots])
                out_offs = np.concatenate(
                    [offs[mapped], (ov_storage % page).astype(np.int32)]
                )
                out_w = np.concatenate([weights[mapped], um_w])
            else:
                self.spilled_cells += len(um_rows)
                spilled_total = int(um_w.sum())
                with self._lock:
                    for r, d, w in zip(um_rows, um_idx, um_w):
                        key = (int(r), int(d))
                        self._host_spill[key] = (
                            self._host_spill.get(key, 0) + int(w)
                        )
                out_rows = slots[mapped]
                out_offs = offs[mapped]
                out_w = weights[mapped]

        dev = np.empty((len(out_rows), 3), dtype=np.int32)
        dev[:, 0] = out_rows
        dev[:, 1] = out_offs
        dev[:, 2] = out_w  # caller guarantees < 2^30 per cell
        return dev, int(out_w.sum()), spilled_total

    def commit(self, packed: np.ndarray) -> int:
        """Translate + device-commit one packed triple batch.  Returns
        the total count applied (device + host spill).  Launches pad to
        COMMIT_CHUNK multiples so one executable serves every interval;
        H2D accounting covers the padded wire bytes actually shipped."""
        from loghisto_tpu.ops.paged_store import COMMIT_CHUNK

        dev, applied, spilled = self.translate(
            np.ascontiguousarray(packed, dtype=np.int32)
        )
        n = len(dev)
        if n:
            padded = -(-n // COMMIT_CHUNK) * COMMIT_CHUNK
            if padded != n:
                pad = np.zeros((padded - n, 3), dtype=np.int32)
                pad[:, 0] = -1
                dev = np.concatenate([dev, pad])
            self._pool = self._commit(self._pool, self._put_triples(dev))
            self.commits += 1
            self.last_h2d_bytes = dev.nbytes
            self.h2d_bytes += dev.nbytes
        else:
            self.last_h2d_bytes = 0
        return applied + spilled

    def _put_triples(self, dev: np.ndarray):
        """Upload translated triples — split over the stream axis under
        a mesh (COMMIT_CHUNK padding keeps the length divisible)."""
        import jax.numpy as jnp

        if self._triple_sharding is None:
            return jnp.asarray(dev)
        from loghisto_tpu.parallel.multihost import global_put

        return global_put(dev, self._triple_sharding)

    def _place_pool(self, pool):
        """Re-pin the pool's metric-shard placement after an op (host
        scatter, reset) that may have produced an unsharded result."""
        if self._pool_sharding is None:
            return pool
        import jax

        if isinstance(pool, jax.Array):
            if (
                pool.sharding == self._pool_sharding
                or not pool.is_fully_addressable
            ):
                # already placed, or a multi-process global array the
                # next jitted dispatch re-shards itself (an eager
                # device_put would need a collective the CPU drill
                # lacks)
                return pool
            return jax.device_put(pool, self._pool_sharding)
        from loghisto_tpu.parallel.multihost import global_put

        return global_put(pool, self._pool_sharding)

    def warmup(self) -> None:
        """Pre-compile THE commit executable (one all-pad COMMIT_CHUNK
        launch — numerically a no-op: slot -1 triples drop).  Every
        later commit pads to COMMIT_CHUNK multiples, so this single
        compile covers all of them; without it the first real interval
        pays the cold XLA compile (the dense bridge's _bridge_warmup
        rationale, applied to the paged wire)."""
        from loghisto_tpu.ops.paged_store import COMMIT_CHUNK

        pad = np.zeros((COMMIT_CHUNK, 3), dtype=np.int32)
        pad[:, 0] = -1
        self._pool = self._commit(self._pool, self._put_triples(pad))

    # -- fused direct-to-paged ingest (r17) ------------------------------ #

    def device_luts(self):
        """Device mirrors (row_codec int32 [M], enc_luts int32 [C, B],
        page_table int32 [M, ppr]) for the fused ingest kernel, cached
        until a host mutation dirties them (_mirror = None sites)."""
        if self._mirror is None:
            import jax.numpy as jnp

            rc = jnp.asarray(self.row_codec, dtype=jnp.int32)
            enc = jnp.asarray(self._enc)
            tbl = jnp.asarray(self.page_table)
            if self.mesh is not None:
                # pre-place so the jitted shard_map never re-shards the
                # cached mirrors per dispatch: row_codec and the table
                # split over the metric axis, the enc LUTs replicate.
                # global_put keeps this collective-free across real
                # jax.distributed processes (host tables are identical
                # on every process by construction)
                from jax.sharding import NamedSharding, PartitionSpec

                from loghisto_tpu.parallel.mesh import METRIC_AXIS
                from loghisto_tpu.parallel.multihost import global_put

                rc = global_put(
                    self.row_codec.astype(np.int32),
                    NamedSharding(self.mesh, PartitionSpec(METRIC_AXIS)),
                )
                enc = global_put(
                    np.asarray(self._enc),
                    NamedSharding(self.mesh, PartitionSpec()),
                )
                tbl = global_put(
                    np.asarray(self.page_table),
                    NamedSharding(
                        self.mesh, PartitionSpec(METRIC_AXIS, None)
                    ),
                )
            self._mirror = (rc, enc, tbl)
        return self._mirror

    def prepare_batch(
        self, ids: np.ndarray, values: np.ndarray
    ) -> Tuple[np.ndarray, int]:
        """Bridge-thread half of the fused path: one vectorized pass
        that assigns codecs and allocates every page the batch's rows
        need BEFORE the upload, so the dispatch never consults the host
        table.  Returns (ids_rewritten, spilled_sample_count):

          * rows whose page cannot be mapped (pool saturated) rewrite to
            the overflow row — the device then encodes them under the
            overflow codec against its eagerly-reserved pages, exactly
            like translate()'s redirect;
          * with no overflow row, those samples fold into the exact host
            spill here and their ids rewrite to -1 (the kernel's dropped
            filler), so the count still lands somewhere accountable.

        The host codec runs in f64 (compress_np_host) while the kernel
        compresses in f32; a boundary value can therefore land one
        dense bucket off on device.  Since every encode LUT is
        monotonic, one dense bucket is at most one STORAGE bucket, so
        mapping the +/-1 storage neighbors' pages too keeps the device
        write covered whichever side of the boundary it rounds to.
        """
        from loghisto_tpu._native import compress_np_host

        out = np.array(ids, dtype=np.int32, copy=True)
        valid = (out >= 0) & (out < self.num_metrics)
        if not valid.any():
            return out, 0
        rows = out[valid].astype(np.int64)
        L = self.bucket_limit
        dense_idx = (
            np.clip(
                compress_np_host(
                    np.asarray(values, dtype=np.float64)[valid],
                    self.precision,
                ),
                -L,
                L,
            ).astype(np.int64)
            + L
        )
        self._assign_codecs(rows, dense_idx)
        codec = self.row_codec[rows]
        storage = self._enc[codec, dense_idx].astype(np.int64)
        page = self.config.page_size
        cap = self._storage_buckets[codec] - 1
        cand_pages = np.concatenate([
            storage // page,
            np.maximum(storage - 1, 0) // page,
            np.minimum(storage + 1, cap) // page,
        ])
        cand_rows = np.concatenate([rows, rows, rows])
        missing = self.page_table[cand_rows, cand_pages] < 0
        if missing.any():
            pairs = np.unique(
                np.stack(
                    [cand_rows[missing], cand_pages[missing]], axis=1
                ),
                axis=0,
            )
            for r, p in pairs:
                self._alloc(int(r), int(p))

        spilled = 0
        slots = self.page_table[rows, storage // page]
        unmapped = slots < 0
        if unmapped.any():
            where = np.nonzero(valid)[0][unmapped]
            ov = self.config.overflow_row
            if ov is not None:
                self.overflowed_cells += len(where)
                out[where] = ov
            else:
                pairs, counts = np.unique(
                    np.stack(
                        [rows[unmapped], dense_idx[unmapped]], axis=1
                    ),
                    axis=0,
                    return_counts=True,
                )
                self.spilled_cells += len(pairs)
                self.spill_cells(pairs[:, 0], pairs[:, 1], counts)
                out[where] = -1
                spilled = len(where)
        return out, spilled

    def _fused_ingest_fn(self):
        if self._fused_fn is None:
            if self.mesh is not None:
                from loghisto_tpu.ops.fused_ingest import (
                    make_sharded_fused_paged_ingest_fn,
                )

                self._fused_fn = make_sharded_fused_paged_ingest_fn(
                    self.mesh,
                    self.rows_per_shard,
                    self.shard_pages,
                    self.bucket_limit,
                    self.precision,
                )
            else:
                from loghisto_tpu.ops.fused_ingest import (
                    make_fused_paged_ingest_fn,
                )

                self._fused_fn = make_fused_paged_ingest_fn(
                    self.bucket_limit, self.precision
                )
        return self._fused_fn

    def ingest_raw(self, ids_dev, values_dev) -> None:
        """ONE-dispatch raw ingest into the donated pool.  The batch
        must have gone through prepare_batch before upload; ids the
        host rewrote to -1 drop on device."""
        self._pool = self._fused_ingest_fn()(
            self._pool, ids_dev, values_dev, *self.device_luts()
        )
        self.fused_dispatches += 1

    def warmup_fused(self, batch_size: int) -> None:
        """Pre-compile THE fused ingest executable at the staging chunk
        shape (all-(-1) ids: numerically a no-op — every sample takes
        the dropped filler cell)."""
        import jax.numpy as jnp

        ids = jnp.full(batch_size, -1, dtype=jnp.int32)
        vals = jnp.zeros(batch_size, dtype=jnp.float32)
        self.ingest_raw(ids, vals)
        self.fused_dispatches -= 1  # warmup is not a real dispatch

    # -- failure / spill ------------------------------------------------- #

    def pool_deleted(self) -> bool:
        return getattr(self._pool, "is_deleted", lambda: False)()

    def reset_pool(self) -> None:
        """Fresh zero pool (device-failure recovery).  Page-table
        mappings survive — the pages are zero again, counts already
        accounted by the caller's shed path."""
        import jax.numpy as jnp

        if self._pool_sharding is None:
            self._pool = jnp.zeros(
                (self.total_pages, self.config.page_size), dtype=jnp.int32
            )
        else:
            # host zeros through the collective-free global placement
            # (an eager device_put of a local jnp array cannot commit
            # onto a multi-process sharding on the CPU drill backend)
            self._pool = self._place_pool(
                np.zeros(
                    (self.total_pages, self.config.page_size),
                    dtype=np.int32,
                )
            )

    def spill_pool(self) -> None:
        """Fold every device count into the exact host spill and zero
        the pool (the paged twin of the dense _spill_fold: called when
        an interval's totals could overflow int32 cells)."""
        rows_d, idx_d, counts = self._decode_pool_cells()
        with self._lock:
            for r, d, w in zip(rows_d, idx_d, counts):
                key = (int(r), int(d))
                self._host_spill[key] = self._host_spill.get(key, 0) + int(w)
        self.reset_pool()

    def spill_cells(
        self, rows: np.ndarray, dense_idx: np.ndarray, weights: np.ndarray
    ) -> None:
        """Exact host-spill add for pre-bucketed cells (dense-axis
        indices), any magnitude."""
        with self._lock:
            for r, d, w in zip(rows, dense_idx, weights):
                key = (int(r), int(d))
                self._host_spill[key] = self._host_spill.get(key, 0) + int(w)

    def spill_triples(self, triples: np.ndarray) -> int:
        """Failure-path exactness: fold already-TRANSLATED ``(slot,
        offset, count)`` triples back into the host spill by inverting
        the page table (slot -> owning row/page -> codec decode).  The
        fused committer uses this for the one chunk whose translate ran
        but whose dispatch failed — its host-spill portion was applied
        inside translate, so only the device portion (these triples)
        must re-land, and spilling the chunk's CELLS would double-count.
        Returns the total count folded."""
        triples = np.asarray(triples)
        triples = triples[triples[:, 0] > 0]
        if not len(triples):
            return 0
        owner_row = np.full(self.total_pages, -1, dtype=np.int64)
        owner_page = np.zeros(self.total_pages, dtype=np.int64)
        mapped = self.page_table >= 0
        rows_of, pages_of = np.nonzero(mapped)
        slots_of = self.page_table[rows_of, pages_of]
        owner_row[slots_of] = rows_of
        owner_page[slots_of] = pages_of
        rows = owner_row[triples[:, 0]]
        keep = rows >= 0  # a since-released page's counts were folded
        rows = rows[keep]
        if not len(rows):
            return 0
        page = self.config.page_size
        storage = owner_page[triples[:, 0]][keep] * page + triples[keep, 1]
        counts = triples[keep, 2].astype(np.int64)
        codec = self.row_codec[rows]
        dense = np.zeros(len(rows), dtype=np.int64)
        for cid in np.unique(codec):
            sel = codec == cid
            dense[sel] = self._codecs[cid].dec_lut[storage[sel]]
        self.spill_cells(rows, dense, counts)
        return int(counts.sum())

    # -- decode / stats -------------------------------------------------- #

    def _decode_pool_cells(
        self,
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """All nonzero pool cells decoded to (row, native dense index,
        count int64) — one D2H of the pool, O(occupied) host work.
        Counts of distinct storage buckets never merge here (decode
        LUTs are injective per codec), but two storage buckets of
        DIFFERENT rows may share a pool page only if mapped there, so
        ownership comes from the page table, not the pool."""
        from loghisto_tpu.parallel.multihost import host_gather

        # multi-process safe: a pool sharded across real jax.distributed
        # processes is only partially addressable here, so the D2H copy
        # allgathers (single-process it is a plain np.asarray)
        pool_np = host_gather(self._pool)
        # slot -> (row, page_idx) ownership from the table
        mapped = self.page_table >= 0
        rows_of, pages_of = np.nonzero(mapped)
        slots_of = self.page_table[rows_of, pages_of]
        out_rows, out_idx, out_counts = [], [], []
        page = self.config.page_size
        for r, p, s in zip(rows_of, pages_of, slots_of):
            counts = pool_np[s]
            nz = np.nonzero(counts)[0]
            if not len(nz):
                continue
            codec = self._codecs[self.row_codec[r]]
            storage = p * page + nz
            # dense pages can overhang the storage axis; the translate
            # step never writes there
            in_range = storage < codec.storage_buckets
            storage = storage[in_range]
            nz = nz[in_range]
            out_rows.append(np.full(len(nz), r, dtype=np.int64))
            out_idx.append(codec.dec_lut[storage].astype(np.int64))
            out_counts.append(counts[nz].astype(np.int64))
        if not out_rows:
            empty = np.empty(0, dtype=np.int64)
            return empty, empty.copy(), empty.copy()
        return (
            np.concatenate(out_rows),
            np.concatenate(out_idx),
            np.concatenate(out_counts),
        )

    def decode_cells(
        self, include_spill: bool = True
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """(rows, native dense indices, int64 counts) across pool +
        host spill — the canonical sparse view of the whole store."""
        rows, idx, counts = self._decode_pool_cells()
        if include_spill and self._host_spill:
            with self._lock:
                items = list(self._host_spill.items())
            s_rows = np.array([k[0] for k, _ in items], dtype=np.int64)
            s_idx = np.array([k[1] for k, _ in items], dtype=np.int64)
            s_cnt = np.array([v for _, v in items], dtype=np.int64)
            rows = np.concatenate([rows, s_rows])
            idx = np.concatenate([idx, s_idx])
            counts = np.concatenate([counts, s_cnt])
        return rows, idx, counts

    def decode_dense(self, include_spill: bool = True) -> np.ndarray:
        """Canonical dense [M, B] int64 reconstruction (checkpoint
        portability: a paged save restores into a dense target and vice
        versa).  O(M x B) host memory — checkpoint-path only."""
        acc = np.zeros((self.num_metrics, self.num_buckets), dtype=np.int64)
        rows, idx, counts = self.decode_cells(include_spill)
        np.add.at(acc, (rows, idx), counts)
        return acc

    def stats(self, ps: np.ndarray, reset: bool = True):
        """Per-metric counts/sums/percentiles across every stored cell
        (pool + spill), computed sparsely: O(occupied cells), never a
        dense [M, B] materialization.  Bit-identical to the dense host
        oracle (dense_stats_np) for identity-codec rows; compressed
        rows stay inside their codec's max_rel_error bound."""
        from loghisto_tpu.ops.stats import sparse_cells_stats

        rows, idx, counts = self.decode_cells(include_spill=True)
        out = sparse_cells_stats(
            rows, idx, counts, self.num_metrics, np.asarray(ps),
            self.bucket_limit, self.precision,
        )
        if reset:
            self.reset_pool()
            with self._lock:
                self._host_spill.clear()
        return out

    def query(self, ids: np.ndarray, ps: np.ndarray):
        """Device-served snapshot query over the paged pool: rows group
        by codec (one executable per codec), each group gathers only
        its mapped pages and runs the dense engine's
        snapshot_row_stats.  Returns counts/sums/percentiles stacked in
        the request's id order.  Host-spill counts are NOT visible here
        (same contract as the dense snapshot engine, which serves the
        device tensor; spilled intervals read via stats())."""
        import jax.numpy as jnp

        from loghisto_tpu.ops.paged_store import make_paged_query_fn

        ids = np.asarray(ids, dtype=np.int64)
        ps_f = np.asarray(ps, dtype=np.float32)
        n, p_n = len(ids), len(ps_f)
        counts = np.zeros(n, dtype=np.int64)
        sums = np.zeros(n, dtype=np.float64)
        pcts = np.zeros((n, p_n), dtype=np.float64)
        qfn = make_paged_query_fn(self.bucket_limit, self.precision)
        codecs = self.row_codec[ids]
        for cid in np.unique(codecs):
            sel = np.nonzero(codecs == cid)[0]
            if cid < 0:
                continue  # untouched rows: zeros
            codec = self._codecs[cid]
            table_rows = self.page_table[ids[sel]]
            out = qfn(
                self._pool,
                jnp.asarray(table_rows),
                jnp.asarray(codec.dec_lut),
                jnp.asarray(ps_f),
            )
            counts[sel] = np.asarray(out["counts"])
            sums[sel] = np.asarray(out["sums"])
            pcts[sel] = np.asarray(out["percentiles"])
        return {"counts": counts, "sums": sums, "percentiles": pcts}

    # -- lifecycle composition ------------------------------------------- #

    def fold_rows_into(self, victims: List[int], target: int) -> int:
        """Count-exact eviction fold: decode each victim row's cells,
        re-encode them under the TARGET row's codec pages (the
        overflow row), release the victim's pages, and clear its codec.
        Returns the total count moved."""
        victims = [int(v) for v in victims if v != target]
        if not victims:
            return 0
        rows, idx, counts = self.decode_cells(include_spill=False)
        moved = 0
        sel = np.isin(rows, victims)
        if sel.any():
            packed = np.empty((int(sel.sum()), 3), dtype=np.int32)
            packed[:, 0] = target
            packed[:, 1] = idx[sel] - self.bucket_limit
            packed[:, 2] = counts[sel]
            moved = int(counts[sel].sum())
            # zero the victim pages BEFORE recommitting so the fold
            # cannot double-count (commit touches only target pages)
            self._zero_rows(victims)
            self.commit(packed)
        else:
            self._zero_rows(victims)
        # host-spill cells of victims move too
        with self._lock:
            spill_items = [
                (k, v) for k, v in self._host_spill.items()
                if k[0] in set(victims)
            ]
            for k, v in spill_items:
                del self._host_spill[k]
                tkey = (target, k[1])
                self._host_spill[tkey] = self._host_spill.get(tkey, 0) + v
                moved += v
        self.release_rows(victims)
        return moved

    def _zero_rows(self, rows: List[int]) -> None:
        import jax.numpy as jnp

        slots = self.page_table[rows].reshape(-1)
        slots = slots[slots >= 0]
        if len(slots):
            self._pool = self._place_pool(
                self._pool.at[jnp.asarray(slots)].set(0)
            )

    def release_rows(self, rows: List[int]) -> int:
        """Return every page mapped by ``rows`` to the free pool (pages
        must already be folded/zeroed by the caller); unassign their
        codecs.  Returns the number of pages freed."""
        freed = 0
        for r in rows:
            for p in range(self.pages_per_row):
                slot = int(self.page_table[r, p])
                if slot > 0:
                    # slots return to the arena they came from (always
                    # the row's shard, by the allocation invariant)
                    self._free_lists[slot // self.shard_pages].append(slot)
                    self.page_table[r, p] = -1
                    freed += 1
            self.row_codec[r] = -1
        self.released_pages += freed
        self._mirror = None
        return freed

    def drop_rows(self, rows: List[int]) -> None:
        """Discard victims entirely (eviction with a shed target): zero
        their pages, return them to the free lists, clear their codecs,
        and purge their host-spill cells.  The caller accounts the shed
        counts (lifecycle's overflowed-samples path)."""
        rows = [int(r) for r in rows]
        if not rows:
            return
        self._zero_rows(rows)
        self.release_rows(rows)
        with self._lock:
            dead = set(rows)
            self._host_spill = {
                k: v for k, v in self._host_spill.items() if k[0] not in dead
            }

    def _extract_rows(self, rows: List[int]) -> np.ndarray:
        """Pull the given rows' pool cells out as packed (row, centered
        codec bucket, count) triples, zero and free their pages, and
        clear their table entries — KEEPING row_codec, so a later
        commit() re-lands them under the same codec (the cross-shard
        migration step of apply_permutation/grow)."""
        rows = [int(r) for r in rows]
        if not rows:
            return np.empty((0, 3), dtype=np.int32)
        all_rows, all_idx, all_counts = self._decode_pool_cells()
        sel = np.isin(all_rows, rows)
        packed = np.empty((int(sel.sum()), 3), dtype=np.int32)
        packed[:, 0] = all_rows[sel]
        packed[:, 1] = all_idx[sel] - self.bucket_limit
        packed[:, 2] = all_counts[sel]
        self._zero_rows(rows)
        for r in rows:
            for p in range(self.pages_per_row):
                slot = int(self.page_table[r, p])
                if slot > 0:
                    self._free_lists[slot // self.shard_pages].append(slot)
                    self.page_table[r, p] = -1
                    self.released_pages += 1
        self._mirror = None
        return packed

    def apply_permutation(self, perm: List[int], m_rows: int) -> None:
        """Survivor repack: row r of the new layout takes old row
        perm[r] (-1 = hole -> unmapped).  Pure host table permutation —
        pool pages never move, so compaction is O(M) with zero device
        traffic (vs the dense path's full gather/scatter repack).

        Under a multi-shard mesh, survivors whose new id lands in a
        DIFFERENT metric shard can't keep their old-arena pages (the
        row-pages-in-own-shard invariant): their cells are extracted
        first (pages freed back to the old arena, codec kept) and
        recommitted under their new ids after the permutation, which
        re-allocates pages from the new shard's arena."""
        movers: List[int] = []
        remap_new: Dict[int, int] = {}
        if self._n_shards > 1:
            for new_id, old_id in enumerate(perm[:m_rows]):
                if old_id is None or old_id < 0:
                    continue
                if self._shard_of_row(old_id) != self._shard_of_row(new_id):
                    movers.append(int(old_id))
                    remap_new[int(old_id)] = int(new_id)
        packed = self._extract_rows(movers) if movers else None

        new_table = np.full_like(self.page_table, -1)
        new_codec = np.full_like(self.row_codec, -1)
        for new_id, old_id in enumerate(perm[:m_rows]):
            if old_id is None or old_id < 0:
                continue
            new_table[new_id] = self.page_table[old_id]
            new_codec[new_id] = self.row_codec[old_id]
        self.page_table = new_table
        self.row_codec = new_codec
        self._mirror = None
        with self._lock:
            remap = {
                old_id: new_id
                for new_id, old_id in enumerate(perm[:m_rows])
                if old_id is not None and old_id >= 0
            }
            spill = {}
            for (r, d), v in self._host_spill.items():
                nr = remap.get(r)
                if nr is not None:
                    spill[(nr, d)] = spill.get((nr, d), 0) + v
            self._host_spill = spill
        if packed is not None and len(packed):
            packed[:, 0] = np.array(
                [remap_new[int(r)] for r in packed[:, 0]], dtype=np.int32
            )
            self.commit(packed)

    def grow(self, new_m: int) -> None:
        if new_m <= self.num_metrics:
            return
        packed = None
        if self._n_shards > 1:
            if new_m % self._n_shards:
                raise ValueError(
                    f"grown num_metrics={new_m} not divisible by the "
                    f"{self._n_shards}-way metric axis"
                )
            # growth re-draws the shard boundaries (rows_per_shard
            # changes): rows whose owning shard changes migrate — cells
            # out, pages freed to the old arena, codec kept, recommit
            # below re-allocates from the new arena
            new_rps = new_m // self._n_shards
            movers = [
                r
                for r in range(self.num_metrics)
                if r // self.rows_per_shard != r // new_rps
                and np.any(self.page_table[r] >= 0)
            ]
            packed = self._extract_rows(movers) if movers else None
        extra = new_m - self.num_metrics
        self.page_table = np.concatenate(
            [
                self.page_table,
                np.full((extra, self.pages_per_row), -1, dtype=np.int32),
            ]
        )
        self.row_codec = np.concatenate(
            [self.row_codec, np.full(extra, -1, dtype=np.int8)]
        )
        self.num_metrics = new_m
        self.rows_per_shard = self.num_metrics // self._n_shards
        self._mirror = None
        # the sharded fused-ingest executable bakes rows_per_shard
        self._fused_fn = None if self._n_shards > 1 else self._fused_fn
        if packed is not None and len(packed):
            self.commit(packed)
        if self._n_shards > 1 and self.config.overflow_row is not None:
            # a migrated overflow row gets its reserved pages back
            # eagerly (idempotent for unmoved rows)
            self._reserve_overflow_pages(self.config.overflow_row)

    def max_cell(self) -> int:
        """Largest single pool count (spill-threshold headroom checks)."""
        import jax.numpy as jnp

        return int(jnp.max(self._pool))

    # -- checkpoint ------------------------------------------------------ #

    def codec_names(self) -> List[Optional[str]]:
        return [
            self._codecs[c].name if c >= 0 else None for c in self.row_codec
        ]

    def restore_codecs(self, names: List[Optional[str]]) -> None:
        for row, name in enumerate(names[: self.num_metrics]):
            if name is not None and self.row_codec[row] < 0:
                self.row_codec[row] = self._codec_ids[name]
        self._mirror = None
