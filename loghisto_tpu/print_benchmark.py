"""PrintBenchmark: live benchmark harness printing per-interval statistics
(reference print_benchmark.go:49-106).

Spawns `concurrency` worker threads looping start_timer -> op -> stop on a
1-second MetricSystem, subscribes to processed metrics, and prints the
fixed metric list each interval in aligned columns.  Differences from the
reference: an optional `duration` bound (the reference blocks forever),
an optional TPUAggregator so the same harness drives the device tier, and
the column alignment is computed directly instead of Go's tabwriter.

CLI:  python -m loghisto_tpu.print_benchmark --concurrency 100 --seconds 10
"""

from __future__ import annotations

import sys
import threading
import time
from typing import Callable, Optional, TextIO

from loghisto_tpu.channel import Channel, ChannelClosed
from loghisto_tpu.metrics import MetricSystem


def _interesting_metrics(name: str) -> list[str]:
    return [
        f"{name}_count",
        f"{name}_max",
        f"{name}_99.99",
        f"{name}_99.9",
        f"{name}_99",
        f"{name}_95",
        f"{name}_90",
        f"{name}_75",
        f"{name}_50",
        f"{name}_min",
        f"{name}_sum",
        f"{name}_avg",
        f"{name}_agg_avg",
        f"{name}_agg_count",
        f"{name}_agg_sum",
        "sys.Alloc",
        "sys.NumGC",
        "sys.PauseTotalNs",
        "sys.NumGoroutine",
    ]


def print_benchmark(
    name: str,
    concurrency: int,
    op: Callable[[], None],
    duration: Optional[float] = None,
    interval: float = 1.0,
    out: TextIO = sys.stdout,
    fast_ingest: bool = True,
    device: bool = False,
    handles: bool = False,
) -> None:
    """Run `op` at `concurrency` and print statistics each interval.

    Blocks for `duration` seconds (forever when None, like the reference).
    Uses the C-extension ingest fast path when available (pass
    fast_ingest=False to benchmark the pure-Python hot path).
    `device=True` runs the same harness on a TPUMetricSystem, printing
    statistics computed by the device aggregation path.
    `handles=True` times each op with the reusable per-name timer handle
    (`system.timer(name)`) instead of per-measurement tokens — the
    product hot-loop path; tokens remain the default because the
    reference's harness is token-shaped (print_benchmark.go:61-66).
    """
    if device:
        from loghisto_tpu.system import TPUMetricSystem

        ms = TPUMetricSystem(
            interval=interval, sys_stats=True, fast_ingest=fast_ingest
        )
        ms.device_metrics()  # warm the stats compile before ticking starts
    else:
        ms = MetricSystem(
            interval=interval, sys_stats=True, fast_ingest=fast_ingest
        )
    # device mode drains slower (a device stats round-trip per interval);
    # a little slack keeps the reaper from striking the subscriber out
    mc = Channel(4 if device else 1)
    ms.subscribe_to_processed_metrics(mc)
    ms.start()
    stop = threading.Event()

    def receiver():
        interesting = _interesting_metrics(name)
        width = max(len(m) for m in interesting) + 1
        while True:
            try:
                pms = mc.get(timeout=0.5)
            except ChannelClosed:
                return
            except Exception:
                if stop.is_set():
                    return
                continue
            metrics = pms.metrics
            if device:
                # statistics extracted by the device aggregation path
                # (reset=True: per-interval semantics matching host mode),
                # falling back to host values for counters/gauges
                metrics = dict(metrics)
                metrics.update(ms.device_metrics(reset=True).metrics)
            lines = [str(pms.time)]
            for metric in interesting:
                lines.append(
                    f"{metric + ':':<{width}}\t{metrics.get(metric, 0)}"
                )
            out.write("\n".join(lines) + "\n\n")
            out.flush()

    recv_thread = threading.Thread(target=receiver, daemon=True)
    recv_thread.start()

    def worker():
        if handles:
            t = ms.timer(name)
            tstart, tstop = t.start, t.stop
            while not stop.is_set():
                s = tstart()
                op()
                tstop(s)
        else:
            while not stop.is_set():
                token = ms.start_timer(name)
                op()
                token.stop()

    workers = [
        threading.Thread(target=worker, daemon=True)
        for _ in range(concurrency)
    ]
    for w in workers:
        w.start()

    try:
        if duration is None:
            while True:  # reference blocks forever (print_benchmark.go:69)
                time.sleep(3600)
        else:
            time.sleep(duration)
    finally:
        stop.set()
        for w in workers:
            w.join(timeout=2.0)
        ms.stop()
        mc.close()
        recv_thread.join(timeout=2.0)


def main(argv: Optional[list[str]] = None) -> None:
    import argparse

    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--name", default="benchmark_op")
    parser.add_argument("--concurrency", type=int, default=10)
    parser.add_argument(
        "--seconds", type=float, default=None,
        help="run time (default: forever, like the reference)",
    )
    parser.add_argument("--interval", type=float, default=1.0)
    parser.add_argument(
        "--no-fast", action="store_true",
        help="benchmark the pure-Python hot path",
    )
    parser.add_argument(
        "--device", action="store_true",
        help="aggregate on the device (TPUMetricSystem)",
    )
    parser.add_argument(
        "--handles", action="store_true",
        help="time with the reusable per-name handle (product hot loop) "
             "instead of per-measurement tokens",
    )
    args = parser.parse_args(argv)

    def op() -> None:
        pass  # time the measurement overhead itself, like the readme example

    print_benchmark(
        args.name, args.concurrency, op,
        duration=args.seconds, interval=args.interval,
        fast_ingest=not args.no_fast, device=args.device,
        handles=args.handles,
    )


if __name__ == "__main__":
    main()
