"""Closeable bounded channel — the subscription primitive.

The reference's subscription boundary is a Go buffered channel: broadcast is
non-blocking (`select` with `default`), and a subscriber that repeatedly
fails to drain is evicted by *closing its channel* (metrics.go:565-581).
Python's ``queue.Queue`` has no close semantics, so this wraps one with a
closed flag + sentinel wake-up, giving subscribers the same contract:

    ch = Channel(capacity=60)
    for metric_set in ch:   # terminates when the producer closes the channel
        ...

Designed for the single-reader case (every reference usage is one reader per
channel); multiple blocked readers may not all wake on close.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Iterator


class ChannelClosed(Exception):
    """Raised by get() on a closed, drained channel."""


class ResilientSubscription:
    """A subscription that survives strike-eviction.

    The reference's contract closes a slow subscriber's channel and
    forgets it (metrics.go:565-581) — correct shedding for arbitrary
    user channels, but a long-lived infrastructure consumer (exporter,
    journal) that dies permanently because of one transient stall is an
    operational hazard.  This wrapper's ``get`` transparently
    re-subscribes on a fresh channel after an eviction (the stalled
    intervals stay dropped — shed-don't-block is preserved) unless
    ``close`` was called, in which case ChannelClosed propagates.
    ``evictions`` counts occurrences for observability."""

    def __init__(self, subscribe, unsubscribe, capacity: int):
        self._subscribe = subscribe
        self._unsubscribe = unsubscribe
        self.capacity = capacity
        self._lock = threading.Lock()
        self._stopped = False
        self.evictions = 0
        ch = Channel(capacity)
        subscribe(ch)
        self._ch = ch

    def get(self, block: bool = True, timeout: float | None = None) -> Any:
        """Like Channel.get, but an eviction re-subscribes and retries.
        Raises ChannelClosed only after close(); queue.Empty on timeout."""
        while True:
            with self._lock:
                ch = self._ch
            try:
                return ch.get(block=block, timeout=timeout)
            except ChannelClosed:
                with self._lock:
                    if self._stopped:
                        raise
                    if self._ch is ch:  # first getter to notice re-subs
                        self.evictions += 1
                        fresh = Channel(self.capacity)
                        self._subscribe(fresh)
                        self._ch = fresh

    def close(self) -> None:
        """Unsubscribe and close; get() raises ChannelClosed afterwards.
        Idempotent."""
        with self._lock:
            if self._stopped:
                return
            self._stopped = True
            ch = self._ch
        self._unsubscribe(ch)
        ch.close()


class Channel:
    _SENTINEL = object()

    def __init__(self, capacity: int = 1):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self.capacity = capacity
        self._q: queue.Queue = queue.Queue(capacity)
        self._closed = threading.Event()

    @property
    def closed(self) -> bool:
        return self._closed.is_set()

    def offer(self, item: Any) -> bool:
        """Non-blocking put. Returns False when full or closed — the caller
        (the reaper) never blocks on a slow subscriber."""
        if self.closed:
            return False
        try:
            self._q.put_nowait(item)
        except queue.Full:
            return False
        if self.closed:
            # close() raced us: the item may sit behind the close sentinel
            # and never be delivered, so don't claim acceptance.
            return False
        return True

    def get(self, block: bool = True, timeout: float | None = None) -> Any:
        """Blocking get; raises ChannelClosed once closed and drained,
        queue.Empty on timeout."""
        while True:
            try:
                item = self._q.get(block=False)
            except queue.Empty:
                if self.closed:
                    raise ChannelClosed
                if not block:
                    raise
                try:
                    item = self._q.get(block=True, timeout=timeout)
                except queue.Empty:
                    if self.closed:
                        raise ChannelClosed
                    raise
            if item is self._SENTINEL:
                # propagate the wake-up to any other blocked reader
                try:
                    self._q.put_nowait(self._SENTINEL)
                except queue.Full:
                    pass
                raise ChannelClosed
            return item

    def close(self) -> None:
        """Close the channel; wakes a blocked reader. Idempotent."""
        if not self._closed.is_set():
            self._closed.set()
            try:
                self._q.put_nowait(self._SENTINEL)
            except queue.Full:
                pass

    def __iter__(self) -> Iterator[Any]:
        while True:
            try:
                yield self.get()
            except ChannelClosed:
                return

    def __len__(self) -> int:
        return self._q.qsize()
