// Native ingest runtime: lock-striped sample staging + vectorized codec.
//
// This is the C++ analog of the reference's hot path machinery (the Go
// library's RWMutex + atomic lock-promotion ingest, metrics.go:251-295),
// rebuilt for the batch/device design: writers append (metric_id, value)
// pairs into per-shard ring buffers under a per-shard mutex with the GIL
// released, and the reaper drains whole shards for vectorized compression
// and device upload.  Also provides the log-bucket codec and a dense
// accumulate as portable C for host-side verification and CPU fallback.
//
// Plain C ABI on purpose: loaded via ctypes (no pybind11 in the image).

#include <algorithm>
#include <atomic>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <memory>
#include <mutex>
#include <new>
#include <thread>
#include <vector>

namespace {

constexpr int16_t kBucketLimit = 32767;

struct Shard {
  std::mutex mu;
  std::vector<int32_t> ids;
  std::vector<double> values;
  // lifetime counters of dropped samples (buffer full)
  std::atomic<uint64_t> dropped{0};
};

struct Buffer {
  std::vector<Shard> shards;
  int64_t capacity_per_shard;
  explicit Buffer(int num_shards, int64_t cap)
      : shards(num_shards), capacity_per_shard(cap) {
    for (auto& s : shards) {
      s.ids.reserve(static_cast<size_t>(std::min<int64_t>(cap, 1 << 20)));
      s.values.reserve(static_cast<size_t>(std::min<int64_t>(cap, 1 << 20)));
    }
  }
};

inline int16_t compress_one(double value, int precision) {
  double mag = std::floor(precision * std::log1p(std::fabs(value)) + 0.5);
  if (std::isnan(mag)) mag = 0.0;  // NaN -> bucket 0 (matches device tier)
  if (mag > kBucketLimit) mag = kBucketLimit;
  int16_t i = static_cast<int16_t>(mag);
  return value < 0 ? static_cast<int16_t>(-i) : i;
}

}  // namespace

extern "C" {

void* lh_create(int num_shards, int64_t capacity_per_shard) {
  if (num_shards < 1 || capacity_per_shard < 1) return nullptr;
  return new (std::nothrow) Buffer(num_shards, capacity_per_shard);
}

void lh_destroy(void* handle) { delete static_cast<Buffer*>(handle); }

int lh_num_shards(void* handle) {
  return static_cast<int>(static_cast<Buffer*>(handle)->shards.size());
}

// Append a batch into one shard. Returns the number of samples accepted
// (the rest were dropped: shed-don't-block, like the reference's
// slow-subscriber policy).
int64_t lh_record_batch(void* handle, int shard_idx, const int32_t* ids,
                        const double* values, int64_t n) {
  Buffer* buf = static_cast<Buffer*>(handle);
  Shard& shard = buf->shards[shard_idx % buf->shards.size()];
  std::lock_guard<std::mutex> lock(shard.mu);
  int64_t room = buf->capacity_per_shard -
                 static_cast<int64_t>(shard.ids.size());
  int64_t take = std::max<int64_t>(0, std::min(room, n));
  if (take > 0) {
    shard.ids.insert(shard.ids.end(), ids, ids + take);
    shard.values.insert(shard.values.end(), values, values + take);
  }
  if (take < n) shard.dropped.fetch_add(static_cast<uint64_t>(n - take));
  return take;
}

int64_t lh_record(void* handle, int shard_idx, int32_t id, double value) {
  return lh_record_batch(handle, shard_idx, &id, &value, 1);
}

// Swap one shard's buffers and copy them out. Returns the sample count
// (<= max_n; anything beyond max_n is discarded and counted as dropped).
int64_t lh_drain(void* handle, int shard_idx, int32_t* ids_out,
                 double* values_out, int64_t max_n) {
  Buffer* buf = static_cast<Buffer*>(handle);
  Shard& shard = buf->shards[shard_idx % buf->shards.size()];
  std::vector<int32_t> ids;
  std::vector<double> values;
  {
    std::lock_guard<std::mutex> lock(shard.mu);
    ids.swap(shard.ids);
    values.swap(shard.values);
    // keep the warm reserve: without this, every post-drain interval
    // re-grows through the realloc ladder while holding the shard mutex
    size_t warm = std::min<size_t>(
        ids.capacity(), static_cast<size_t>(buf->capacity_per_shard));
    shard.ids.reserve(warm);
    shard.values.reserve(warm);
  }
  int64_t n = static_cast<int64_t>(ids.size());
  int64_t take = std::min(n, max_n);
  if (take > 0) {
    std::memcpy(ids_out, ids.data(), take * sizeof(int32_t));
    std::memcpy(values_out, values.data(), take * sizeof(double));
  }
  if (take < n) shard.dropped.fetch_add(static_cast<uint64_t>(n - take));
  return take;
}

uint64_t lh_dropped(void* handle) {
  Buffer* buf = static_cast<Buffer*>(handle);
  uint64_t total = 0;
  for (auto& s : buf->shards) total += s.dropped.load();
  return total;
}

// Vectorized codec: values -> int16 buckets (reference metrics.go:316-322
// semantics, saturating instead of wrapping).
void lh_compress(const double* values, int64_t n, int precision,
                 int16_t* out) {
  for (int64_t i = 0; i < n; ++i) out[i] = compress_one(values[i], precision);
}

void lh_decompress(const int16_t* buckets, int64_t n, int precision,
                   double* out) {
  for (int64_t i = 0; i < n; ++i) {
    double f = std::exp(std::fabs(static_cast<double>(buckets[i])) /
                        precision) - 1.0;
    out[i] = buckets[i] < 0 ? -f : f;
  }
}

}  // extern "C"

// Persistent host cell store: an open-addressing (id, codec_bucket) ->
// int64 count table that ACCUMULATES across flushes, so one device ship
// per interval carries the dedup of the whole interval, not one batch.
// This is the host-tier half of interval-granularity transport: sample
// rate is decoupled from wire bandwidth (wire cost = unique cells per
// interval), which is what lets a thin host->device link keep up with
// a firehose of samples.

namespace {

struct CellSlot {
  uint64_t key;  // (id << 16) | (bucket + 32768); 0 = empty
  int64_t count;
};

struct CellStore {
  std::vector<CellSlot> table;
  uint64_t mask;
  int64_t used = 0;

  explicit CellStore(uint64_t cap) : table(cap, CellSlot{0, 0}), mask(cap - 1) {}

  bool grow() {
    uint64_t new_cap = table.size() * 2;
    std::vector<CellSlot> fresh;
    try {
      fresh.assign(new_cap, CellSlot{0, 0});
    } catch (...) {
      return false;
    }
    uint64_t new_mask = new_cap - 1;
    for (const CellSlot& s : table) {
      if (s.key == 0) continue;
      uint64_t h = s.key * 0x9E3779B97F4A7C15ull;
      uint64_t j = (h ^ (h >> 32)) & new_mask;
      while (fresh[j].key != 0) j = (j + 1) & new_mask;
      fresh[j] = s;
    }
    table.swap(fresh);
    mask = new_mask;
    return true;
  }

  bool add_one(uint64_t key, int64_t weight) {
    uint64_t h = key * 0x9E3779B97F4A7C15ull;
    uint64_t j = (h ^ (h >> 32)) & mask;
    while (true) {
      if (table[j].key == key) {
        table[j].count += weight;
        return true;
      }
      if (table[j].key == 0) {
        // keep load factor under ~0.7 so probe chains stay short
        if ((used + 1) * 10 >= static_cast<int64_t>(table.size()) * 7) {
          if (!grow()) return false;
          return add_one(key, weight);
        }
        table[j].key = key;
        table[j].count = weight;
        ++used;
        return true;
      }
      j = (j + 1) & mask;
    }
  }
};

}  // namespace

extern "C" {

void* lh_cells_create(int64_t initial_capacity) {
  uint64_t cap = 1024;
  while (cap < static_cast<uint64_t>(initial_capacity)) cap <<= 1;
  try {
    // nothrow covers only the object shell; the constructor's vector
    // fill can itself throw, and an exception must never cross the C ABI
    return new (std::nothrow) CellStore(cap);
  } catch (...) {
    return nullptr;
  }
}

void lh_cells_destroy(void* store) { delete static_cast<CellStore*>(store); }

int64_t lh_cells_size(void* store) {
  return static_cast<CellStore*>(store)->used;
}

// Fold one batch into the store. Returns the number of samples CONSUMED
// from the input (including skipped negative ids): n on full success,
// or i < n if a table growth allocation failed before sample i — the
// prefix [0, i) is already folded, so the caller retries only ids[i:]
// (typically after draining).  This exactness contract is what lets the
// Python layer recover from allocation failure without double counting.
int64_t lh_cells_add(void* store, const int32_t* ids, const float* values,
                     int64_t n, int precision, int bucket_limit) {
  CellStore* cs = static_cast<CellStore*>(store);
  for (int64_t i = 0; i < n; ++i) {
    int32_t id = ids[i];
    if (id < 0) continue;
    int32_t b = compress_one(static_cast<double>(values[i]), precision);
    if (b < -bucket_limit) b = -bucket_limit;
    if (b > bucket_limit) b = bucket_limit;
    uint64_t key =
        (static_cast<uint64_t>(static_cast<uint32_t>(id)) << 16) |
        static_cast<uint16_t>(b + 32768);
    if (!cs->add_one(key, 1)) return i;
  }
  return n;
}

// Copy out every cell and clear the table (capacity retained). Output
// arrays must hold lh_cells_size entries. Returns the cell count.
int64_t lh_cells_drain(void* store, int32_t* ids_out, int32_t* buckets_out,
                       int64_t* counts_out) {
  CellStore* cs = static_cast<CellStore*>(store);
  int64_t m = 0;
  for (CellSlot& s : cs->table) {
    if (s.key == 0) continue;
    ids_out[m] = static_cast<int32_t>(s.key >> 16);
    buckets_out[m] = static_cast<int32_t>(s.key & 0xFFFF) - 32768;
    counts_out[m] = s.count;
    s.key = 0;
    s.count = 0;
    ++m;
  }
  cs->used = 0;
  return m;
}

// Copy out every cell as interleaved [id, codec_bucket, count] int32
// triples and clear the table (capacity retained).  int32 END TO END on
// purpose: the device merge never enables jax_enable_x64, so an int64
// wire array would be silently canonicalized to int32 — with the earlier
// (id << 16) key format that truncation corrupted every id >= 2^15.
// One packed array still means ONE host->device transfer per merge
// chunk instead of three — per-transfer latency is the dominant wire
// cost on a thin tunnel link.  out must hold 3 * lh_cells_size(store)
// entries.  A cell whose int64 count exceeds LH_PACKED_COUNT_CAP is
// emitted capped and LEFT IN THE TABLE with the remainder — the caller
// loops until lh_cells_size reaches 0 (one pass in any realistic run;
// the cap keeps every emitted row < 2^30, below the aggregator's int32
// accumulator spill threshold).
static const int64_t LH_PACKED_COUNT_CAP = (1 << 30) - 1;

int64_t lh_cells_drain_packed(void* store, int32_t* out) {
  CellStore* cs = static_cast<CellStore*>(store);
  int64_t m = 0;
  int64_t remaining = 0;
  for (CellSlot& s : cs->table) {
    if (s.key == 0) continue;
    int64_t c = s.count;
    int64_t emit = c > LH_PACKED_COUNT_CAP ? LH_PACKED_COUNT_CAP : c;
    out[3 * m] = static_cast<int32_t>(s.key >> 16);
    out[3 * m + 1] = static_cast<int32_t>(s.key & 0xFFFF) - 32768;
    out[3 * m + 2] = static_cast<int32_t>(emit);
    ++m;
    if (c > emit) {
      s.count = c - emit;
      ++remaining;
    } else {
      s.key = 0;
      s.count = 0;
    }
  }
  cs->used = remaining;
  return m;
}

}  // extern "C"

// -- pipelined sparse-delta transport (PR 6) -------------------------------
//
// The fold below is the host half of transport="sparse": one GIL-released
// call turns a raw (ids, values) batch into packed int32 [n, 3]
// (id, codec_bucket, count) triples — the r5 wire format — using T
// thread-local CellStores over disjoint batch slices.  Thread-local
// tables need no locks; duplicate (id, bucket) cells across slices cost
// only wire rows (the device merge is additive), the same bounded-
// duplication trade the sharded record-time store already makes.

namespace {

// Rows needed to emit one table under the 2^30-1 per-row count cap
// (split rule shared with lh_cells_drain_packed).
int64_t packed_rows_needed(const CellStore& cs, int64_t cap) {
  int64_t rows = 0;
  for (const CellSlot& s : cs.table) {
    if (s.key == 0) continue;
    rows += (s.count + cap - 1) / cap;
  }
  return rows;
}

// Emit every cell as split [id, bucket, count<=cap] triples at out;
// clears the table (capacity retained).  Returns rows written.
int64_t emit_packed_split(CellStore& cs, int64_t cap, int32_t* out) {
  int64_t m = 0;
  for (CellSlot& s : cs.table) {
    if (s.key == 0) continue;
    int64_t c = s.count;
    while (c > 0) {
      int64_t emit = c > cap ? cap : c;
      out[3 * m] = static_cast<int32_t>(s.key >> 16);
      out[3 * m + 1] = static_cast<int32_t>(s.key & 0xFFFF) - 32768;
      out[3 * m + 2] = static_cast<int32_t>(emit);
      c -= emit;
      ++m;
    }
    s.key = 0;
    s.count = 0;
  }
  cs.used = 0;
  return m;
}

}  // namespace

extern "C" {

void lh_packed_free(int32_t* p) { delete[] p; }

// Fold a raw batch into packed triples with `num_threads` parallel
// thread-local tables.  *out receives a buffer allocated here (release
// with lh_packed_free).  Returns the row count, or -1 when an
// allocation failed (nothing is leaked; the caller falls back to the
// NumPy tier or raw transport).
int64_t lh_fold_packed(const int32_t* ids, const float* values, int64_t n,
                       int precision, int bucket_limit, int num_threads,
                       int32_t** out) {
  const int64_t cap = LH_PACKED_COUNT_CAP;
  if (num_threads < 1) num_threads = 1;
  // below ~64k samples/thread the spawn+merge overhead beats the win
  int64_t max_t = n / 65536 + 1;
  if (num_threads > max_t) num_threads = static_cast<int>(max_t);
  std::vector<std::unique_ptr<CellStore>> stores;
  std::atomic<bool> failed{false};
  try {
    for (int t = 0; t < num_threads; ++t)
      stores.emplace_back(new CellStore(1 << 14));
  } catch (...) {
    return -1;
  }
  auto fold_slice = [&](int t) {
    int64_t lo = n * t / num_threads;
    int64_t hi = n * (t + 1) / num_threads;
    CellStore& cs = *stores[t];
    for (int64_t i = lo; i < hi; ++i) {
      int32_t id = ids[i];
      if (id < 0) continue;
      int32_t b = compress_one(static_cast<double>(values[i]), precision);
      if (b < -bucket_limit) b = -bucket_limit;
      if (b > bucket_limit) b = bucket_limit;
      uint64_t key =
          (static_cast<uint64_t>(static_cast<uint32_t>(id)) << 16) |
          static_cast<uint16_t>(b + 32768);
      if (!cs.add_one(key, 1)) {
        failed.store(true);
        return;
      }
    }
  };
  if (num_threads == 1) {
    fold_slice(0);
  } else {
    std::vector<std::thread> threads;
    threads.reserve(num_threads);
    for (int t = 0; t < num_threads; ++t)
      threads.emplace_back(fold_slice, t);
    for (auto& th : threads) th.join();
  }
  if (failed.load()) return -1;
  int64_t total = 0;
  std::vector<int64_t> offsets(num_threads);
  for (int t = 0; t < num_threads; ++t) {
    offsets[t] = total;
    total += packed_rows_needed(*stores[t], cap);
  }
  int32_t* buf = new (std::nothrow) int32_t[3 * std::max<int64_t>(total, 1)];
  if (!buf) return -1;
  if (num_threads == 1) {
    emit_packed_split(*stores[0], cap, buf);
  } else {
    std::vector<std::thread> threads;
    threads.reserve(num_threads);
    for (int t = 0; t < num_threads; ++t)
      threads.emplace_back([&, t] {
        emit_packed_split(*stores[t], cap, buf + 3 * offsets[t]);
      });
    for (auto& th : threads) th.join();
  }
  *out = buf;
  return total;
}

// Parallel drain of `num_stores` detached CellStore handles into one
// packed buffer (allocated here; release with lh_packed_free) — the
// ShardedCellStore's whole-store drain in one GIL-released call, shards
// scanned concurrently.  Returns total rows or -1 on allocation failure
// (the stores are left untouched in that case: sizing happens before
// any table is cleared).
int64_t lh_cells_drain_packed_multi(void** stores, int num_stores,
                                    int num_threads, int32_t** out) {
  const int64_t cap = LH_PACKED_COUNT_CAP;
  if (num_stores < 1) return 0;
  if (num_threads < 1) num_threads = 1;
  if (num_threads > num_stores) num_threads = num_stores;
  std::vector<int64_t> offsets(num_stores);
  int64_t total = 0;
  for (int i = 0; i < num_stores; ++i) {
    offsets[i] = total;
    total += packed_rows_needed(*static_cast<CellStore*>(stores[i]), cap);
  }
  int32_t* buf = new (std::nothrow) int32_t[3 * std::max<int64_t>(total, 1)];
  if (!buf) return -1;
  auto drain_range = [&](int t) {
    for (int i = t; i < num_stores; i += num_threads)
      emit_packed_split(*static_cast<CellStore*>(stores[i]), cap,
                        buf + 3 * offsets[i]);
  };
  if (num_threads == 1) {
    drain_range(0);
  } else {
    std::vector<std::thread> threads;
    threads.reserve(num_threads);
    for (int t = 0; t < num_threads; ++t)
      threads.emplace_back(drain_range, t);
    for (auto& th : threads) th.join();
  }
  *out = buf;
  return total;
}

// Dense accumulate on host: the CPU fallback / verification twin of the
// device scatter-add kernel. acc is uint32[num_metrics][2*bucket_limit+1].
void lh_accumulate_dense(const int32_t* ids, const double* values, int64_t n,
                         int precision, int bucket_limit, uint32_t* acc,
                         int32_t num_metrics) {
  const int64_t row = 2 * static_cast<int64_t>(bucket_limit) + 1;
  for (int64_t i = 0; i < n; ++i) {
    int32_t id = ids[i];
    if (id < 0 || id >= num_metrics) continue;
    int32_t b = compress_one(values[i], precision);
    if (b < -bucket_limit) b = -bucket_limit;
    if (b > bucket_limit) b = bucket_limit;
    ++acc[id * row + b + bucket_limit];
  }
}

}  // extern "C"
