"""ctypes loader and wrapper for the native ingest runtime.

Builds `ingest.cpp` with g++ on first use (cached next to the source);
every entry point degrades gracefully: `available()` is False when no
compiler exists, and callers fall back to the pure-NumPy host tier.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "ingest.cpp")
_LIB_PATH = os.path.join(_HERE, "libloghisto_ingest.so")
_FASTPATH_SRC = os.path.join(_HERE, "fastpath.cpp")
# ABI-tagged filename: a CPython extension built under one interpreter
# must never be dlopened by another (unlike the ctypes lib above)
import sysconfig as _sysconfig

_FASTPATH_PATH = os.path.join(
    _HERE, "loghisto_fastpath" + (_sysconfig.get_config_var("EXT_SUFFIX")
                                  or ".so")
)

_lib = None
_lib_lock = threading.Lock()
_build_error: str | None = None
_fastpath = None
_fastpath_error: str | None = None


def _compile(src: str, out_path: str, extra_flags: list[str]) -> str | None:
    """Compile `src` to `out_path` via a private temp file + atomic
    rename, so concurrent builders (e.g. pytest-xdist workers) can never
    dlopen a half-written .so.  Returns an error string or None."""
    import tempfile

    fd, tmp = tempfile.mkstemp(dir=_HERE, suffix=".so.tmp")
    os.close(fd)
    cmd = [
        "g++", "-O3", "-shared", "-fPIC", "-std=c++17",
        *extra_flags, "-o", tmp, src,
    ]
    try:
        proc = subprocess.run(
            cmd, capture_output=True, text=True, timeout=120
        )
        if proc.returncode != 0:
            return f"g++ failed: {proc.stderr[-2000:]}"
        os.replace(tmp, out_path)
        return None
    except (OSError, subprocess.TimeoutExpired) as e:
        return f"g++ invocation failed: {e}"
    finally:
        try:
            os.unlink(tmp)
        except OSError:
            pass


def _is_stale(lib_path: str, src: str) -> bool:
    try:
        return not os.path.exists(lib_path) or (
            os.path.getmtime(lib_path) < os.path.getmtime(src)
        )
    except OSError:
        # e.g. prebuilt .so shipped without the source: use it as-is
        return not os.path.exists(lib_path)


def _build() -> str | None:
    # -pthread: the parallel fold/drain entry points spawn std::threads
    return _compile(_SRC, _LIB_PATH, ["-march=native", "-pthread"])


def _load():
    global _lib, _build_error
    with _lib_lock:
        if _lib is not None or _build_error is not None:
            return _lib
        if _is_stale(_LIB_PATH, _SRC):
            _build_error = _build()
            if _build_error is not None:
                return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
        except OSError as e:
            _build_error = f"dlopen failed: {e}"
            return None

        lib.lh_create.restype = ctypes.c_void_p
        lib.lh_create.argtypes = [ctypes.c_int, ctypes.c_int64]
        lib.lh_destroy.argtypes = [ctypes.c_void_p]
        lib.lh_num_shards.restype = ctypes.c_int
        lib.lh_num_shards.argtypes = [ctypes.c_void_p]
        lib.lh_record.restype = ctypes.c_int64
        lib.lh_record.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.c_int32, ctypes.c_double,
        ]
        lib.lh_record_batch.restype = ctypes.c_int64
        lib.lh_record_batch.argtypes = [
            ctypes.c_void_p, ctypes.c_int,
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_double),
            ctypes.c_int64,
        ]
        lib.lh_drain.restype = ctypes.c_int64
        lib.lh_drain.argtypes = [
            ctypes.c_void_p, ctypes.c_int,
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_double),
            ctypes.c_int64,
        ]
        lib.lh_dropped.restype = ctypes.c_uint64
        lib.lh_dropped.argtypes = [ctypes.c_void_p]
        lib.lh_compress.argtypes = [
            ctypes.POINTER(ctypes.c_double), ctypes.c_int64, ctypes.c_int,
            ctypes.POINTER(ctypes.c_int16),
        ]
        lib.lh_decompress.argtypes = [
            ctypes.POINTER(ctypes.c_int16), ctypes.c_int64, ctypes.c_int,
            ctypes.POINTER(ctypes.c_double),
        ]
        lib.lh_accumulate_dense.argtypes = [
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_double),
            ctypes.c_int64, ctypes.c_int, ctypes.c_int,
            ctypes.POINTER(ctypes.c_uint32), ctypes.c_int32,
        ]
        lib.lh_cells_create.restype = ctypes.c_void_p
        lib.lh_cells_create.argtypes = [ctypes.c_int64]
        lib.lh_cells_destroy.argtypes = [ctypes.c_void_p]
        lib.lh_cells_size.restype = ctypes.c_int64
        lib.lh_cells_size.argtypes = [ctypes.c_void_p]
        lib.lh_cells_add.restype = ctypes.c_int64
        lib.lh_cells_add.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_float), ctypes.c_int64, ctypes.c_int,
            ctypes.c_int,
        ]
        lib.lh_cells_drain.restype = ctypes.c_int64
        lib.lh_cells_drain.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int64),
        ]
        lib.lh_cells_drain_packed.restype = ctypes.c_int64
        lib.lh_cells_drain_packed.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int32),
        ]
        lib.lh_packed_free.argtypes = [ctypes.POINTER(ctypes.c_int32)]
        lib.lh_fold_packed.restype = ctypes.c_int64
        lib.lh_fold_packed.argtypes = [
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_float),
            ctypes.c_int64, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.POINTER(ctypes.POINTER(ctypes.c_int32)),
        ]
        lib.lh_cells_drain_packed_multi.restype = ctypes.c_int64
        lib.lh_cells_drain_packed_multi.argtypes = [
            ctypes.POINTER(ctypes.c_void_p), ctypes.c_int, ctypes.c_int,
            ctypes.POINTER(ctypes.POINTER(ctypes.c_int32)),
        ]
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


def _load_fastpath():
    """Build+import the METH_FASTCALL per-call ingest extension."""
    global _fastpath, _fastpath_error
    with _lib_lock:
        if _fastpath is not None or _fastpath_error is not None:
            return _fastpath
        import sysconfig

        if _is_stale(_FASTPATH_PATH, _FASTPATH_SRC):
            include = sysconfig.get_paths()["include"]
            _fastpath_error = _compile(
                _FASTPATH_SRC, _FASTPATH_PATH, [f"-I{include}"]
            )
            if _fastpath_error is not None:
                return None
        try:
            import importlib.util

            spec = importlib.util.spec_from_file_location(
                "loghisto_fastpath", _FASTPATH_PATH
            )
            mod = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(mod)
        except Exception as e:  # ImportError, OSError
            _fastpath_error = f"import failed: {e}"
            return None
        _fastpath = mod
        return _fastpath


def fastpath_available() -> bool:
    return _load_fastpath() is not None


def fastpath_module():
    mod = _load_fastpath()
    if mod is None:
        raise RuntimeError(f"fastpath unavailable: {_fastpath_error}")
    return mod


def build_error() -> str | None:
    _load()
    return _build_error


def _i32(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))


def _f64(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_double))


def _i16(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int16))


def compress(values: np.ndarray, precision: int = 100) -> np.ndarray:
    """Native vectorized codec (matches ops.codec.compress_np exactly)."""
    lib = _load()
    if lib is None:
        raise RuntimeError(f"native library unavailable: {_build_error}")
    values = np.ascontiguousarray(values, dtype=np.float64)
    out = np.empty(len(values), dtype=np.int16)
    lib.lh_compress(_f64(values), len(values), precision, _i16(out))
    return out


def preaggregate(
    ids: np.ndarray, values: np.ndarray, bucket_limit: int,
    precision: int = 100,
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """One-shot compress + dedup of a batch into unique (id, codec_bucket,
    count) cells.  A thin convenience over CellStore (one implementation
    of the codec/dedup contract, not two).  Returns
    (ids int32[m], codec_buckets int32[m], counts int64[m])."""
    store = CellStore(bucket_limit, precision,
                      initial_capacity=max(1024, 2 * len(ids)))
    try:
        consumed = store.add(ids, values)
        if consumed < len(ids):
            raise MemoryError("cell table allocation failed")
        return store.drain()
    finally:
        store.close()


def accumulate_dense(
    ids: np.ndarray, values: np.ndarray, num_metrics: int,
    bucket_limit: int, precision: int = 100,
    acc: np.ndarray | None = None,
) -> np.ndarray:
    """Native dense accumulate — CPU verification twin of the device kernel."""
    lib = _load()
    if lib is None:
        raise RuntimeError(f"native library unavailable: {_build_error}")
    ids = np.ascontiguousarray(ids, dtype=np.int32)
    values = np.ascontiguousarray(values, dtype=np.float64)
    if acc is None:
        acc = np.zeros((num_metrics, 2 * bucket_limit + 1), dtype=np.uint32)
    lib.lh_accumulate_dense(
        _i32(ids), _f64(values), len(ids), precision, bucket_limit,
        acc.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)), num_metrics,
    )
    return acc


class CellStore:
    """Persistent (id, codec_bucket) -> count host accumulator.

    Batches fold in across flushes (`add`); `drain` empties it into
    unique-cell arrays for one weighted device merge.  This decouples
    sample rate from host->device wire bandwidth: the wire cost is the
    interval's unique cells, however many samples they absorbed."""

    def __init__(self, bucket_limit: int, precision: int = 100,
                 initial_capacity: int = 1 << 16):
        lib = _load()
        if lib is None:
            raise RuntimeError(f"native library unavailable: {_build_error}")
        self._lib = lib
        self._handle = lib.lh_cells_create(initial_capacity)
        if not self._handle:
            raise MemoryError("lh_cells_create failed")
        self.bucket_limit = bucket_limit
        self.precision = precision

    def __len__(self) -> int:
        return int(self._lib.lh_cells_size(self._handle))

    def add(self, ids: np.ndarray, values: np.ndarray) -> int:
        """Fold a batch in.  Returns the number of samples CONSUMED from
        the front of the batch: len(ids) on success, fewer only when the
        table could not grow — the consumed prefix is folded exactly
        once, so the caller retries ids[consumed:] (typically after
        draining).  Negative ids are consumed but skipped."""
        ids = np.ascontiguousarray(ids, dtype=np.int32)
        values = np.ascontiguousarray(values, dtype=np.float32)
        if ids.shape != values.shape:
            raise ValueError("ids and values must have the same shape")
        consumed = self._lib.lh_cells_add(
            self._handle, _i32(ids),
            values.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            len(ids), self.precision, self.bucket_limit,
        )
        return int(consumed)

    def drain(self) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Empty the store; returns (ids, codec_buckets, counts)."""
        m = len(self)
        ids_out = np.empty(m, dtype=np.int32)
        buckets_out = np.empty(m, dtype=np.int32)
        counts_out = np.empty(m, dtype=np.int64)
        got = self._lib.lh_cells_drain(
            self._handle, _i32(ids_out), _i32(buckets_out),
            counts_out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        )
        return ids_out[:got], buckets_out[:got], counts_out[:got]

    def drain_packed(self) -> np.ndarray:
        """Empty the store into one int32 [m, 3] array of
        (id, codec_bucket, count) rows — a single wire transfer for the
        device merge (ops.ingest.make_packed_ingest_fn), int32 end to
        end so no-x64 JAX canonicalization cannot truncate it.  A cell
        whose count exceeds the C side's 2^30-1 cap is emitted as
        multiple rows across passes (the drain loop below); histogram
        merges are additive, so split rows stay exact."""
        parts = []
        while True:
            m = len(self)
            if m == 0:
                break
            out = np.empty((m, 3), dtype=np.int32)
            got = self._lib.lh_cells_drain_packed(
                self._handle,
                out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            )
            parts.append(out[:got])
        if not parts:
            return np.empty((0, 3), dtype=np.int32)
        return parts[0] if len(parts) == 1 else np.concatenate(parts)

    def close(self) -> None:
        if self._handle:
            self._lib.lh_cells_destroy(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def unpack_cells(packed: np.ndarray):
    """Split the int32 [m, 3] (id, codec_bucket, count) wire array into
    (ids int32, codec_buckets int32, counts int64) columns — the host
    twin of the column reads in ops.ingest.make_packed_ingest_fn."""
    return (
        packed[:, 0],
        packed[:, 1],
        packed[:, 2].astype(np.int64),
    )


# -- packed-triple host fold (transport="sparse") -------------------------- #

# Per-row count cap of the packed wire format, mirroring ingest.cpp's
# LH_PACKED_COUNT_CAP: every emitted row stays < 2^30, below the
# aggregator's int32 spill threshold, and a larger count splits across
# rows (additive merges keep splits exact).
PACKED_COUNT_CAP = (1 << 30) - 1


def compress_np_host(values: np.ndarray, precision: int = 100) -> np.ndarray:
    """Float64 host codec, bit-for-bit the C side's compress_one (and
    ops.codec.compress_np) — duplicated here in pure NumPy so this module
    stays importable, and the preagg/sparse transports usable, without a
    compiler OR jax."""
    v = np.asarray(values, dtype=np.float64)
    mag = np.floor(precision * np.log1p(np.abs(v)) + 0.5)
    mag = np.where(np.isnan(mag), 0.0, mag)
    mag = np.minimum(mag, 32767.0)
    out = mag.astype(np.int32)
    return np.where(v < 0, -out, out).astype(np.int32)


def pack_cells(
    ids: np.ndarray, buckets: np.ndarray, counts: np.ndarray,
    cap: int = PACKED_COUNT_CAP,
) -> np.ndarray:
    """Assemble unique-cell columns into the int32 [m, 3] wire array,
    splitting any count > cap across rows (the NumPy twin of the C
    drain's split rule).  counts must be positive."""
    counts = np.asarray(counts, dtype=np.int64)
    if not len(counts):
        return np.empty((0, 3), dtype=np.int32)
    reps = (counts + cap - 1) // cap
    total = int(reps.sum())
    out = np.empty((total, 3), dtype=np.int32)
    out[:, 0] = np.repeat(np.asarray(ids, dtype=np.int64), reps)
    out[:, 1] = np.repeat(np.asarray(buckets, dtype=np.int64), reps)
    weights = np.full(total, cap, dtype=np.int64)
    ends = np.cumsum(reps) - 1
    weights[ends] = counts - (reps - 1) * cap
    out[:, 2] = weights
    return out


def fold_packed_numpy(
    ids: np.ndarray, values: np.ndarray, bucket_limit: int,
    precision: int = 100,
) -> np.ndarray:
    """Pure-NumPy fold of a raw batch into packed [m, 3] triples:
    compress (f64, same bits as the C/device codec boundary contract),
    key, unique — the compiler-less tier of transport="sparse"."""
    ids = np.asarray(ids, dtype=np.int32)
    values = np.asarray(values, dtype=np.float32)
    keep = ids >= 0
    if not keep.all():
        ids, values = ids[keep], values[keep]
    if not len(ids):
        return np.empty((0, 3), dtype=np.int32)
    b = np.clip(compress_np_host(values, precision),
                -bucket_limit, bucket_limit)
    keys = (ids.astype(np.int64) << 16) | (b.astype(np.int64) + 32768)
    ukeys, counts = np.unique(keys, return_counts=True)
    return pack_cells(ukeys >> 16, (ukeys & 0xFFFF) - 32768, counts)


def fold_packed_native(
    ids: np.ndarray, values: np.ndarray, bucket_limit: int,
    precision: int = 100, num_threads: int | None = None,
) -> np.ndarray:
    """Parallel native fold (lh_fold_packed): T thread-local hash tables
    over disjoint batch slices, GIL released for the whole call."""
    lib = _load()
    if lib is None:
        raise RuntimeError(f"native library unavailable: {_build_error}")
    ids = np.ascontiguousarray(ids, dtype=np.int32)
    values = np.ascontiguousarray(values, dtype=np.float32)
    if ids.shape != values.shape:
        raise ValueError("ids and values must have the same shape")
    if num_threads is None:
        num_threads = min(8, os.cpu_count() or 1)
    out_ptr = ctypes.POINTER(ctypes.c_int32)()
    rows = lib.lh_fold_packed(
        _i32(ids),
        values.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        len(ids), precision, bucket_limit, num_threads,
        ctypes.byref(out_ptr),
    )
    if rows < 0:
        raise MemoryError("lh_fold_packed allocation failed")
    try:
        if rows == 0:
            return np.empty((0, 3), dtype=np.int32)
        packed = np.ctypeslib.as_array(out_ptr, shape=(rows, 3)).copy()
    finally:
        lib.lh_packed_free(out_ptr)
    return packed


def fold_packed(
    ids: np.ndarray, values: np.ndarray, bucket_limit: int,
    precision: int = 100, num_threads: int | None = None,
) -> np.ndarray:
    """Fold a raw batch into packed triples via the fastest available
    tier: parallel native when the library built, pure NumPy otherwise
    (so the sparse transport never requires a compiler).  Both tiers run
    the same f64 codec, so their output cells are bit-identical."""
    if available():
        try:
            return fold_packed_native(
                ids, values, bucket_limit, precision, num_threads
            )
        except MemoryError:
            pass  # table/buffer allocation failed; NumPy tier below
    return fold_packed_numpy(ids, values, bucket_limit, precision)


class NumpyCellStore:
    """Pure-NumPy twin of CellStore (same add/drain/consumed-prefix
    contract) so transport="preagg" works without a compiler.  Each add
    deduplicates the batch vectorized (np.unique) and folds the unique
    cells into a dict keyed like the C table; drains share pack_cells'
    split rule."""

    def __init__(self, bucket_limit: int, precision: int = 100,
                 initial_capacity: int = 1 << 16):
        self._counts: dict[int, int] = {}
        self.bucket_limit = bucket_limit
        self.precision = precision

    def __len__(self) -> int:
        return len(self._counts)

    def add(self, ids: np.ndarray, values: np.ndarray) -> int:
        ids = np.asarray(ids, dtype=np.int32)
        values = np.asarray(values, dtype=np.float32)
        if ids.shape != values.shape:
            raise ValueError("ids and values must have the same shape")
        keep = ids >= 0
        kept_ids, kept_values = ids[keep], values[keep]
        if len(kept_ids):
            b = np.clip(
                compress_np_host(kept_values, self.precision),
                -self.bucket_limit, self.bucket_limit,
            )
            keys = (
                (kept_ids.astype(np.int64) << 16)
                | (b.astype(np.int64) + 32768)
            )
            ukeys, counts = np.unique(keys, return_counts=True)
            store = self._counts
            for k, c in zip(ukeys.tolist(), counts.tolist()):
                store[k] = store.get(k, 0) + c
        return len(ids)  # dict growth cannot partially fail mid-batch

    def drain_packed(self) -> np.ndarray:
        if not self._counts:
            return np.empty((0, 3), dtype=np.int32)
        keys = np.fromiter(
            self._counts.keys(), dtype=np.int64, count=len(self._counts)
        )
        counts = np.fromiter(
            self._counts.values(), dtype=np.int64, count=len(self._counts)
        )
        self._counts = {}
        return pack_cells(keys >> 16, (keys & 0xFFFF) - 32768, counts)

    def drain(self) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        return unpack_cells(self.drain_packed())

    def close(self) -> None:
        self._counts = {}


class ShardedCellStore:
    """K independent CellStores, each behind its own lock, with
    double-buffered draining (VERDICT r2 item 2: pipeline the preagg
    transport).

    * `add(ids, values)` folds into the CALLING THREAD's shard (sticky
      round-robin assignment) — ctypes releases the GIL during the C
      fold, so producer threads aggregate genuinely in parallel instead
      of serializing on one table lock.
    * `drain_packed_all()` swaps each shard's active store with its empty
      spare under the shard lock (O(1) critical section) and scans the
      detached table OUTSIDE the lock — producers never stall behind the
      O(capacity) drain, and the caller can overlap the device merge of
      shard k with the drain of shard k+1.

    Cell counts stay exact: a (key -> count) entry may exist in several
    shards; the device merge is additive, so duplicates across shards
    cost only wire bytes (bounded by K, worth it for lock-free-ish
    ingest)."""

    def __init__(self, bucket_limit: int, precision: int = 100,
                 num_shards: int | None = None,
                 initial_capacity: int = 1 << 14,
                 backend: str = "auto"):
        """``backend`` picks the per-shard store: "native" (C hash table,
        raises without a compiler), "numpy" (NumpyCellStore — slower adds
        but zero build dependency), or "auto" (native when available,
        NumPy otherwise — preagg no longer requires a compiler)."""
        if backend not in ("auto", "native", "numpy"):
            raise ValueError(
                f"backend={backend!r}: expected 'auto', 'native', or 'numpy'"
            )
        if backend == "auto":
            backend = "native" if available() else "numpy"
        self.backend = backend
        store_cls = CellStore if backend == "native" else NumpyCellStore
        if num_shards is None:
            num_shards = min(8, (os.cpu_count() or 1))
        self.num_shards = max(1, int(num_shards))
        self._locks = [threading.Lock() for _ in range(self.num_shards)]
        self._active = [
            store_cls(bucket_limit, precision, initial_capacity)
            for _ in range(self.num_shards)
        ]
        self._spare = [
            store_cls(bucket_limit, precision, initial_capacity)
            for _ in range(self.num_shards)
        ]
        # only one drainer manipulates the spare set at a time
        self._drain_lock = threading.Lock()
        self._tl = threading.local()
        self._assign = 0

    def _shard_idx(self) -> int:
        idx = getattr(self._tl, "idx", None)
        if idx is None:
            idx = self._assign % self.num_shards
            self._assign += 1  # benign race: placement heuristic only
            self._tl.idx = idx
        return idx

    def __len__(self) -> int:
        # racy sum (watermark heuristic, not an invariant)
        return sum(len(s) for s in self._active)

    def add(self, ids: np.ndarray, values: np.ndarray) -> int:
        """Fold a batch into this thread's shard.  Same exactness contract
        as CellStore.add: returns the consumed prefix length."""
        i = self._shard_idx()
        with self._locks[i]:
            return self._active[i].add(ids, values)

    def drain_packed_all(self) -> np.ndarray:
        """Drain every shard; returns one int32 [m, 3] packed array.
        Per shard: O(1) swap under the shard lock; the detached tables
        are then scanned OUTSIDE the locks — in ONE GIL-released parallel
        native call (lh_cells_drain_packed_multi) when the backend is
        native, shard-serial NumPy otherwise."""
        with self._drain_lock:
            detached = []
            for i in range(self.num_shards):
                with self._locks[i]:
                    self._active[i], self._spare[i] = (
                        self._spare[i], self._active[i]
                    )
                detached.append(self._spare[i])  # old active; drain unlocked
            if self.backend == "native":
                packed = self._drain_native_multi(detached)
                if packed is not None:
                    return packed
            parts = [s.drain_packed() for s in detached]
            parts = [p for p in parts if len(p)]
        if not parts:
            return np.empty((0, 3), dtype=np.int32)
        return np.concatenate(parts) if len(parts) > 1 else parts[0]

    @staticmethod
    def _drain_native_multi(stores) -> np.ndarray | None:
        """Parallel whole-set drain of detached native stores; None means
        the native call could not run (allocation failure) and the caller
        falls back to the per-shard Python drain."""
        lib = _load()
        handles = (ctypes.c_void_p * len(stores))(
            *[s._handle for s in stores]
        )
        threads = min(len(stores), os.cpu_count() or 1)
        out_ptr = ctypes.POINTER(ctypes.c_int32)()
        rows = lib.lh_cells_drain_packed_multi(
            handles, len(stores), threads, ctypes.byref(out_ptr)
        )
        if rows < 0:
            return None
        try:
            if rows == 0:
                return np.empty((0, 3), dtype=np.int32)
            return np.ctypeslib.as_array(out_ptr, shape=(rows, 3)).copy()
        finally:
            lib.lh_packed_free(out_ptr)

    def drain(self) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Compatibility form of drain_packed_all (ids, buckets, counts)."""
        return unpack_cells(self.drain_packed_all())

    def close(self) -> None:
        for s in self._active + self._spare:
            s.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class NativeIngestBuffer:
    """Lock-striped native staging buffer for (metric_id, value) samples.

    Writers call record/record_batch (GIL released inside the C call);
    the reaper drains shards for vectorized compression + device upload.
    Full shards shed samples and count them (`dropped`), mirroring the
    reference's shed-don't-block policy."""

    def __init__(self, num_shards: int = 16, capacity_per_shard: int = 1 << 20):
        lib = _load()
        if lib is None:
            raise RuntimeError(f"native library unavailable: {_build_error}")
        self._lib = lib
        self._handle = lib.lh_create(num_shards, capacity_per_shard)
        if not self._handle:
            raise MemoryError("lh_create failed")
        self.num_shards = num_shards
        self.capacity_per_shard = capacity_per_shard
        self._shard_counter = 0
        self._tl = threading.local()

    def _shard(self) -> int:
        idx = getattr(self._tl, "idx", None)
        if idx is None:
            idx = self._shard_counter % self.num_shards
            self._shard_counter += 1
            self._tl.idx = idx
        return idx

    def record(self, metric_id: int, value: float) -> int:
        return self._lib.lh_record(
            self._handle, self._shard(), metric_id, value
        )

    def record_batch(self, ids: np.ndarray, values: np.ndarray) -> int:
        ids = np.ascontiguousarray(ids, dtype=np.int32)
        values = np.ascontiguousarray(values, dtype=np.float64)
        if ids.shape != values.shape:
            raise ValueError("ids and values must have the same shape")
        return int(self._lib.lh_record_batch(
            self._handle, self._shard(), _i32(ids), _f64(values), len(ids)
        ))

    def drain(self) -> tuple[np.ndarray, np.ndarray]:
        """Swap out and return all staged samples from every shard."""
        cap = self.capacity_per_shard
        all_ids, all_values = [], []
        ids = np.empty(cap, dtype=np.int32)
        values = np.empty(cap, dtype=np.float64)
        for shard in range(self.num_shards):
            n = self._lib.lh_drain(
                self._handle, shard, _i32(ids), _f64(values), cap
            )
            if n > 0:
                all_ids.append(ids[:n].copy())
                all_values.append(values[:n].copy())
        if not all_ids:
            return (np.empty(0, dtype=np.int32), np.empty(0, dtype=np.float64))
        return np.concatenate(all_ids), np.concatenate(all_values)

    @property
    def dropped(self) -> int:
        return int(self._lib.lh_dropped(self._handle))

    def close(self) -> None:
        if self._handle:
            self._lib.lh_destroy(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
