// CPython fast-path extension for per-call ingest.
//
// The ctypes path costs ~1-2us per call (fine for batches, terrible per
// sample); this METH_FASTCALL extension gets one (metric_id, value)
// append down to ~100ns — the per-call analog of the reference's hot
// loop, feeding the same drain -> vectorized-compress pipeline.
//
// API (module loghisto_fastpath):
//   buf = create(capacity)                  # capsule
//   record(buf, metric_id, value)           # shed-don't-block when full
//   ids_bytes, vals_bytes, dropped = drain(buf)   # dropped is LIFETIME-
//                                                 # cumulative, not per-drain
//   n = size(buf)

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <ctime>

#include <cstdint>
#include <cstring>
#include <mutex>
#include <vector>

namespace {

constexpr const char* kCapsuleName = "loghisto.FastBuf";

struct FastBuf {
  std::mutex mu;
  std::vector<int32_t> ids;
  std::vector<double> vals;
  int64_t cap = 0;
  uint64_t dropped = 0;
};

FastBuf* get_buf(PyObject* capsule) {
  return static_cast<FastBuf*>(
      PyCapsule_GetPointer(capsule, kCapsuleName));
}

// Single stage-or-shed policy shared by record() and timer_stop(): cap
// check, int32 id cast, drop accounting — one place to change.
inline int64_t stage_sample(FastBuf* fb, long id, double v) {
  std::lock_guard<std::mutex> lock(fb->mu);
  if (static_cast<int64_t>(fb->ids.size()) < fb->cap) {
    fb->ids.push_back(static_cast<int32_t>(id));
    fb->vals.push_back(v);
  } else {
    ++fb->dropped;
  }
  return static_cast<int64_t>(fb->ids.size());
}

void destroy_buf(PyObject* capsule) {
  delete static_cast<FastBuf*>(
      PyCapsule_GetPointer(capsule, kCapsuleName));
}

PyObject* fb_create(PyObject*, PyObject* const* args, Py_ssize_t nargs) {
  if (nargs != 1) {
    PyErr_SetString(PyExc_TypeError, "create(capacity)");
    return nullptr;
  }
  long long cap = PyLong_AsLongLong(args[0]);
  if (cap <= 0) {
    if (!PyErr_Occurred())
      PyErr_SetString(PyExc_ValueError, "capacity must be positive");
    return nullptr;
  }
  FastBuf* fb = new (std::nothrow) FastBuf();
  if (!fb) return PyErr_NoMemory();
  fb->cap = cap;
  int64_t warm = cap < (1 << 20) ? cap : (1 << 20);
  fb->ids.reserve(static_cast<size_t>(warm));
  fb->vals.reserve(static_cast<size_t>(warm));
  return PyCapsule_New(fb, kCapsuleName, destroy_buf);
}

PyObject* fb_record(PyObject*, PyObject* const* args, Py_ssize_t nargs) {
  if (nargs != 3) {
    PyErr_SetString(PyExc_TypeError, "record(buf, metric_id, value)");
    return nullptr;
  }
  FastBuf* fb = get_buf(args[0]);
  if (!fb) return nullptr;
  long id = PyLong_AsLong(args[1]);
  if (id == -1 && PyErr_Occurred()) return nullptr;
  double v = PyFloat_AsDouble(args[2]);
  if (v == -1.0 && PyErr_Occurred()) return nullptr;
  stage_sample(fb, id, v);
  Py_RETURN_NONE;
}

// record_sized: like record(), but returns the post-stage buffer size so
// a per-name bound recorder can do its fold check with one int compare
// instead of the Python-side thread-local stride machinery.
PyObject* fb_record_sized(PyObject*, PyObject* const* args,
                          Py_ssize_t nargs) {
  if (nargs != 3) {
    PyErr_SetString(PyExc_TypeError, "record_sized(buf, metric_id, value)");
    return nullptr;
  }
  FastBuf* fb = get_buf(args[0]);
  if (!fb) return nullptr;
  long id = PyLong_AsLong(args[1]);
  if (id == -1 && PyErr_Occurred()) return nullptr;
  double v = PyFloat_AsDouble(args[2]);
  if (v == -1.0 && PyErr_Occurred()) return nullptr;
  return PyLong_FromLongLong(stage_sample(fb, id, v));
}

PyObject* fb_drain(PyObject*, PyObject* const* args, Py_ssize_t nargs) {
  if (nargs != 1) {
    PyErr_SetString(PyExc_TypeError, "drain(buf)");
    return nullptr;
  }
  FastBuf* fb = get_buf(args[0]);
  if (!fb) return nullptr;
  std::vector<int32_t> ids;
  std::vector<double> vals;
  uint64_t dropped;
  {
    std::lock_guard<std::mutex> lock(fb->mu);
    ids.swap(fb->ids);
    vals.swap(fb->vals);
    dropped = fb->dropped;
    size_t warm = ids.capacity() < static_cast<size_t>(fb->cap)
                      ? ids.capacity()
                      : static_cast<size_t>(fb->cap);
    fb->ids.reserve(warm);
    fb->vals.reserve(warm);
  }
  PyObject* ids_bytes = PyBytes_FromStringAndSize(
      reinterpret_cast<const char*>(ids.data()),
      static_cast<Py_ssize_t>(ids.size() * sizeof(int32_t)));
  if (!ids_bytes) return nullptr;
  PyObject* vals_bytes = PyBytes_FromStringAndSize(
      reinterpret_cast<const char*>(vals.data()),
      static_cast<Py_ssize_t>(vals.size() * sizeof(double)));
  if (!vals_bytes) {
    Py_DECREF(ids_bytes);
    return nullptr;
  }
  PyObject* out = Py_BuildValue("(NNK)", ids_bytes, vals_bytes,
                                static_cast<unsigned long long>(dropped));
  return out;
}

inline int64_t monotonic_ns() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return static_cast<int64_t>(ts.tv_sec) * 1000000000LL + ts.tv_nsec;
}

// C timer pair (VERDICT r3 item 6): the reference's 58.74ns p50 timer
// loop measures the gap between StartTimer's and Stop's clock reads.
// Here the clock read is the LAST operation before timer_start returns
// and the FIRST operation when timer_stop enters — everything Python
// does between the two calls (boxing the stamp, storing it, the call
// plumbing) is what the measured distribution reports, and nothing
// else rides inside it.
PyObject* fb_timer_start(PyObject*, PyObject* const*, Py_ssize_t nargs) {
  if (nargs != 0) {
    PyErr_SetString(PyExc_TypeError, "timer_start()");
    return nullptr;
  }
  return PyLong_FromLongLong(monotonic_ns());
}

// timer_stop(buf, metric_id, start_ns) -> (duration_ns, staged_size);
// the clock is read FIRST (before arg parsing), staging happens after
// the gap closes, and the post-stage size rides back in the same call
// so the caller's fold check is one int compare — no separate size()
// call, no stride bookkeeping.
PyObject* fb_timer_stop(PyObject*, PyObject* const* args, Py_ssize_t nargs) {
  const int64_t now = monotonic_ns();
  if (nargs != 3) {
    PyErr_SetString(PyExc_TypeError, "timer_stop(buf, metric_id, start_ns)");
    return nullptr;
  }
  FastBuf* fb = get_buf(args[0]);
  if (!fb) return nullptr;
  long id = PyLong_AsLong(args[1]);
  if (id == -1 && PyErr_Occurred()) return nullptr;
  long long start = PyLong_AsLongLong(args[2]);
  if (start == -1 && PyErr_Occurred()) return nullptr;
  const int64_t dur = now - static_cast<int64_t>(start);
  const int64_t size = stage_sample(fb, id, static_cast<double>(dur));
  PyObject* out = PyTuple_New(2);
  if (!out) return nullptr;
  PyObject* d = PyLong_FromLongLong(dur);
  PyObject* s = PyLong_FromLongLong(size);
  if (!d || !s) {
    Py_XDECREF(d);
    Py_XDECREF(s);
    Py_DECREF(out);
    return nullptr;
  }
  PyTuple_SET_ITEM(out, 0, d);
  PyTuple_SET_ITEM(out, 1, s);
  return out;
}

PyObject* fb_size(PyObject*, PyObject* const* args, Py_ssize_t nargs) {
  if (nargs != 1) {
    PyErr_SetString(PyExc_TypeError, "size(buf)");
    return nullptr;
  }
  FastBuf* fb = get_buf(args[0]);
  if (!fb) return nullptr;
  std::lock_guard<std::mutex> lock(fb->mu);
  return PyLong_FromSsize_t(static_cast<Py_ssize_t>(fb->ids.size()));
}

PyMethodDef kMethods[] = {
    {"create", reinterpret_cast<PyCFunction>(fb_create), METH_FASTCALL,
     "create(capacity) -> buffer capsule"},
    {"record", reinterpret_cast<PyCFunction>(fb_record), METH_FASTCALL,
     "record(buf, metric_id, value)"},
    {"record_sized", reinterpret_cast<PyCFunction>(fb_record_sized),
     METH_FASTCALL,
     "record_sized(buf, metric_id, value) -> staged size after append"},
    {"drain", reinterpret_cast<PyCFunction>(fb_drain), METH_FASTCALL,
     "drain(buf) -> (ids_bytes, values_bytes, dropped)"},
    {"size", reinterpret_cast<PyCFunction>(fb_size), METH_FASTCALL,
     "size(buf) -> staged sample count"},
    {"timer_start", reinterpret_cast<PyCFunction>(fb_timer_start),
     METH_FASTCALL, "timer_start() -> monotonic ns stamp"},
    {"timer_stop", reinterpret_cast<PyCFunction>(fb_timer_stop),
     METH_FASTCALL,
     "timer_stop(buf, metric_id, start_ns) -> (duration ns, staged size)"},
    {nullptr, nullptr, 0, nullptr},
};

PyModuleDef kModule = {
    PyModuleDef_HEAD_INIT, "loghisto_fastpath",
    "Per-call ingest fast path (C extension).", -1, kMethods,
};

}  // namespace

PyMODINIT_FUNC PyInit_loghisto_fastpath(void) {
  return PyModule_Create(&kModule);
}
