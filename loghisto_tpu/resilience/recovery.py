"""Crash-safe recovery orchestrator (ISSUE 10 tentpole, part 2).

The guarantee: **at most one interval is lost across a process crash.**
Two durable artifacts combine to deliver it:

  * periodic checkpoints — taken on the committer bridge thread every
    ``checkpoint_every_intervals`` committed intervals, atomic
    (temp + fsync + rename, utils/checkpoint.py) and stamped with the
    interval ``seq`` watermark of the last interval folded into the
    snapshotted state (FORMAT_VERSION 2);
  * the raw journal — every broadcast interval appends one JSONL line
    (utils/journal.py) carrying its ``seq``.

``recover()`` restores the newest checkpoint, reads its watermark, then
replays only journal intervals with ``seq > watermark`` through the
fused committer — so recovered percentiles are bit-identical to a
pre-crash oracle (tests/test_chaos.py pins this with exact equality).
The only interval that can be missing is the one in flight at the kill:
either its journal line is torn (skipped with a counted warning) or it
never reached the journal at all.

``CircuitBreaker`` guards the fused dispatch path: repeated device
failures inside ``breaker_window_s`` open the breaker and the committer
pins the fan-out/spill path (no further donated-carry dispatch attempts)
until ``breaker_open_s`` passes and a half-open trial succeeds.

Everything surfaces as ``resilience.*`` gauges and three new
HealthWatchdog invariants (``thread_restarted``, ``breaker_open``,
``recovery_in_progress``) in ``/healthz``.
"""

from __future__ import annotations

import itertools
import logging
import os
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Optional

from loghisto_tpu.resilience.faults import FaultInjector

logger = logging.getLogger("loghisto_tpu")


@dataclass
class ResilienceConfig:
    """Knobs for the resilience subsystem (TPUMetricSystem(resilience=...)).

    With ``checkpoint_path`` set, the committer bridge checkpoints every
    ``checkpoint_every_intervals`` committed intervals; with
    ``journal_path`` set, a RawJournal subscriber appends every interval
    and ``recover()`` replays past the checkpoint watermark.  Leave both
    None for supervision + breaker only (no durability)."""

    checkpoint_path: Optional[str] = None
    journal_path: Optional[str] = None
    checkpoint_every_intervals: int = 10
    recover_on_start: bool = True
    supervise: bool = True
    restart_backoff_s: float = 0.05
    restart_backoff_cap_s: float = 2.0
    breaker_threshold: int = 3
    breaker_window_s: float = 30.0
    breaker_open_s: float = 10.0
    fault_injector: Optional[FaultInjector] = None


class CircuitBreaker:
    """Count-over-window breaker for the device dispatch path.

    closed -> open when ``threshold`` failures land inside ``window_s``;
    open -> half-open after ``open_s`` (is_open() starts returning False
    so ONE trial dispatch goes through); half-open -> closed on success,
    half-open -> open on failure.  While open the committer routes every
    interval down the fan-out/spill path — the same path a single device
    failure already takes, just pinned, so a flapping device can't burn
    a donated-carry rebuild per interval."""

    def __init__(
        self,
        threshold: int = 3,
        window_s: float = 30.0,
        open_s: float = 10.0,
    ):
        self.threshold = threshold
        self.window_s = window_s
        self.open_s = open_s
        self._lock = threading.Lock()
        self._failures: deque = deque()
        self._state = "closed"
        self._opened_at = 0.0
        self.opened_total = 0
        self.failures_total = 0

    def record_failure(self, source: str = "") -> bool:
        """Note one device failure; returns True if this opened the
        breaker.  Called from exactly ONE place per physical failure
        (the aggregator's _on_device_failure_locked) so consumer hooks
        fanning out from a failure can't multi-count it."""
        now = time.monotonic()
        with self._lock:
            self.failures_total += 1
            self._failures.append(now)
            while self._failures and \
                    now - self._failures[0] > self.window_s:
                self._failures.popleft()
            if self._state == "half-open":
                self._state = "open"
                self._opened_at = now
                self.opened_total += 1
                logger.warning(
                    "circuit breaker re-opened (half-open trial failed%s)",
                    f"; source={source}" if source else "",
                )
                return True
            if self._state == "closed" \
                    and len(self._failures) >= self.threshold:
                self._state = "open"
                self._opened_at = now
                self.opened_total += 1
                logger.warning(
                    "circuit breaker OPEN: %d device failures in %.1fs%s — "
                    "pinning the fan-out/spill commit path for %.1fs",
                    len(self._failures), self.window_s,
                    f" ({source})" if source else "", self.open_s,
                )
                return True
        return False

    def record_success(self) -> None:
        with self._lock:
            if self._state == "half-open":
                self._state = "closed"
                self._failures.clear()
                logger.info("circuit breaker closed (trial succeeded)")

    def is_open(self) -> bool:
        with self._lock:
            if self._state == "open":
                if time.monotonic() - self._opened_at >= self.open_s:
                    self._state = "half-open"
                    return False
                return True
            return False

    @property
    def state(self) -> str:
        with self._lock:
            return self._state


@dataclass
class RecoveryReport:
    watermark: Optional[int]
    replayed_intervals: int
    skipped_intervals: int
    corrupt_lines: int
    wall_time_s: float
    checkpoint_found: bool
    journal_found: bool


class RecoveryManager:
    """Owns the durability pair (checkpoint cadence + journal) and the
    restart-time replay.  ``on_commit`` rides the committer bridge: one
    watermark store per interval plus a cadenced checkpoint — the async
    checkpoint never blocks ingest, only the bridge's commit loop, and
    the staging rings absorb that hiccup like any other slow interval."""

    def __init__(
        self,
        metric_system,
        aggregator=None,
        committer=None,
        lifecycle=None,
        anomaly=None,
        *,
        checkpoint_path: Optional[str] = None,
        journal_path: Optional[str] = None,
        checkpoint_every_intervals: int = 10,
        fault_injector: Optional[FaultInjector] = None,
    ):
        self._ms = metric_system
        self._agg = aggregator
        self._committer = committer
        self._lifecycle = lifecycle
        self._anomaly = anomaly
        self.checkpoint_path = checkpoint_path
        self.journal_path = journal_path
        self.checkpoint_every_intervals = max(
            int(checkpoint_every_intervals), 1
        )
        self.fault_injector = fault_injector
        self._lock = threading.Lock()
        self._journal = None
        self.in_progress = False
        self.last_seq: Optional[int] = None
        self.last_checkpoint_seq: Optional[int] = None
        self.checkpoints_taken = 0
        self.checkpoint_errors = 0
        self.checkpoint_last_ms = 0.0
        self.replayed_intervals = 0
        self.recoveries = 0
        self._since_checkpoint = 0

    # -- bridge-side cadence -------------------------------------------- #

    def on_commit(self, raw) -> None:
        """Committer tail hook (bridge thread).  Always advances the
        watermark; takes a checkpoint every N intervals unless a
        recovery replay is driving the commits."""
        if raw.seq is not None:
            self.last_seq = int(raw.seq)
        if self.in_progress or self.checkpoint_path is None:
            return
        self._since_checkpoint += 1
        inj = self.fault_injector
        if inj is not None:
            inj.check("recovery.tick")
        if self._since_checkpoint >= self.checkpoint_every_intervals:
            self.checkpoint_now()

    def checkpoint_now(self) -> bool:
        """Atomic snapshot stamped with the current watermark.  A failed
        write (disk full, injected crash) leaves the previous checkpoint
        intact — counted, logged, never fatal to the bridge."""
        if self.checkpoint_path is None:
            return False
        from loghisto_tpu.utils import checkpoint

        t0 = time.perf_counter()
        try:
            with self._lock:
                checkpoint.save(
                    self.checkpoint_path,
                    self._ms,
                    self._agg,
                    self._lifecycle,
                    self._anomaly,
                    seq_watermark=self.last_seq,
                    fault_injector=self.fault_injector,
                )
        except Exception as e:
            self.checkpoint_errors += 1
            logger.warning(
                "checkpoint to %s failed (%s); previous snapshot intact",
                self.checkpoint_path, e,
            )
            self._since_checkpoint = 0
            return False
        self.checkpoint_last_ms = (time.perf_counter() - t0) * 1000.0
        self.checkpoints_taken += 1
        self.last_checkpoint_seq = self.last_seq
        self._since_checkpoint = 0
        return True

    # -- restart-time replay -------------------------------------------- #

    def recover(self) -> RecoveryReport:
        """Restore checkpoint + replay journal past the watermark.  Safe
        on a cold start (neither file exists -> empty report).  Sets
        ``in_progress`` for the HealthWatchdog invariant and to suppress
        cadence checkpoints while replayed intervals flow through the
        committer."""
        from loghisto_tpu.utils import checkpoint, journal

        t0 = time.perf_counter()
        watermark: Optional[int] = None
        replayed = skipped = 0
        max_seq = 0
        ckpt_found = (
            self.checkpoint_path is not None
            and os.path.exists(self.checkpoint_path)
        )
        jrnl_found = (
            self.journal_path is not None
            and os.path.exists(self.journal_path)
        )
        corrupt_before = journal.corrupt_lines_total()
        self.in_progress = True
        try:
            if ckpt_found:
                watermark = checkpoint.restore(
                    self.checkpoint_path,
                    self._ms,
                    self._agg,
                    self._lifecycle,
                    self._anomaly,
                )
                if watermark is not None:
                    max_seq = watermark
                    self.last_seq = watermark
            if jrnl_found:
                for raw in journal.replay(self.journal_path):
                    if (
                        watermark is not None
                        and raw.seq is not None
                        and raw.seq <= watermark
                    ):
                        skipped += 1
                        continue
                    if self._committer is not None:
                        self._committer.commit(raw)
                    else:
                        # fan-out path: feed both consumers the bridges
                        # would have fed live
                        if self._agg is not None:
                            self._agg.merge_raw(raw)
                        wheel = getattr(self._ms, "retention", None)
                        if wheel is not None:
                            wheel.push(raw)
                    if raw.seq is not None:
                        max_seq = max(max_seq, int(raw.seq))
                        self.last_seq = max_seq
                    replayed += 1
            # the reaper must mint seqs PAST everything recovered, or
            # the next journal lines would collide with replayed ones
            if max_seq and hasattr(self._ms, "_interval_seq"):
                self._ms._interval_seq = itertools.count(max_seq + 1)
        finally:
            self.in_progress = False
        self.replayed_intervals += replayed
        self.recoveries += 1
        report = RecoveryReport(
            watermark=watermark,
            replayed_intervals=replayed,
            skipped_intervals=skipped,
            corrupt_lines=journal.corrupt_lines_total() - corrupt_before,
            wall_time_s=time.perf_counter() - t0,
            checkpoint_found=ckpt_found,
            journal_found=jrnl_found,
        )
        logger.info(
            "recovery: watermark=%s replayed=%d skipped=%d corrupt=%d "
            "in %.1fms",
            report.watermark, report.replayed_intervals,
            report.skipped_intervals, report.corrupt_lines,
            report.wall_time_s * 1000.0,
        )
        return report

    # -- lifecycle ------------------------------------------------------ #

    def start(self) -> None:
        """Start the journal subscriber (idempotent)."""
        if self.journal_path is None or self._journal is not None:
            return
        from loghisto_tpu.utils.journal import RawJournal

        self._journal = RawJournal(self._ms, self.journal_path)
        self._journal.fault_injector = self.fault_injector
        self._journal.start()

    def stop(self, final_checkpoint: bool = True) -> None:
        """Stop the journal; a clean shutdown checkpoint makes restart
        lossless (the journal covers the crash case)."""
        if self._journal is not None:
            self._journal.stop()
            self._journal = None
        if final_checkpoint and self.checkpoint_path is not None:
            self.checkpoint_now()


def register_resilience_gauges(
    ms,
    supervisor=None,
    breaker=None,
    recovery=None,
    injector=None,
) -> None:
    """Surface the resilience subsystem on the ordinary gauge pipeline
    (scrapes/exports see ``resilience.*`` next to everything else)."""
    from loghisto_tpu.utils import journal

    if supervisor is not None:
        ms.register_gauge_func(
            "resilience.ThreadRestarts",
            lambda: float(supervisor.total_restarts),
        )
        ms.register_gauge_func(
            "resilience.RestartBackoffMs",
            lambda: float(supervisor.current_backoff_ms()),
        )
    if breaker is not None:
        ms.register_gauge_func(
            "resilience.BreakerOpen",
            lambda: 1.0 if breaker.state != "closed" else 0.0,
        )
        ms.register_gauge_func(
            "resilience.BreakerOpenedTotal",
            lambda: float(breaker.opened_total),
        )
        ms.register_gauge_func(
            "resilience.BreakerFailures",
            lambda: float(breaker.failures_total),
        )
    if recovery is not None:
        ms.register_gauge_func(
            "resilience.CheckpointsTaken",
            lambda: float(recovery.checkpoints_taken),
        )
        ms.register_gauge_func(
            "resilience.CheckpointErrors",
            lambda: float(recovery.checkpoint_errors),
        )
        ms.register_gauge_func(
            "resilience.CheckpointLastMs",
            lambda: float(recovery.checkpoint_last_ms),
        )
        ms.register_gauge_func(
            "resilience.ReplayedIntervals",
            lambda: float(recovery.replayed_intervals),
        )
        ms.register_gauge_func(
            "resilience.RecoveryInProgress",
            lambda: 1.0 if recovery.in_progress else 0.0,
        )
    if injector is not None:
        ms.register_gauge_func(
            "resilience.FaultsInjected",
            lambda: float(injector.faults_injected),
        )
    ms.register_gauge_func(
        "journal.CorruptLines",
        lambda: float(journal.corrupt_lines_total()),
    )
