"""Capped exponential backoff with jitter — the shared retry cadence for
supervised thread restarts and exporter resends (ISSUE 10 satellite).

The submitter's retry_backlog used to re-poke the backlog on a fixed
interval cadence; graphite/opentsdb callers hand-rolled nothing at all.
One policy, one implementation: delay_k = min(cap, base * mult^k),
jittered +/- ``jitter`` fraction with a seeded RNG so tests are
reproducible.  ``current_ms`` feeds the ``export.RetryBackoffMs`` /
``resilience.RestartBackoffMs`` gauges.
"""

from __future__ import annotations

from typing import Optional

import numpy as np


class Backoff:
    def __init__(
        self,
        base_s: float = 0.05,
        cap_s: float = 5.0,
        multiplier: float = 2.0,
        jitter: float = 0.1,
        seed: Optional[int] = None,
    ):
        if base_s <= 0 or cap_s < base_s or multiplier < 1.0:
            raise ValueError("backoff wants 0 < base_s <= cap_s, mult >= 1")
        self.base_s = base_s
        self.cap_s = cap_s
        self.multiplier = multiplier
        self.jitter = jitter
        self._rng = np.random.default_rng(seed)
        self._attempt = 0
        self._current_s = 0.0

    def next_delay(self) -> float:
        """The delay (seconds) to sleep before the next retry; advances
        the attempt counter."""
        raw = min(self.cap_s, self.base_s * self.multiplier ** self._attempt)
        self._attempt += 1
        if self.jitter > 0.0:
            raw *= 1.0 + self.jitter * float(self._rng.uniform(-1.0, 1.0))
        self._current_s = min(raw, self.cap_s)
        return self._current_s

    def reset(self) -> None:
        """Back to the base delay after a success (or a healthy run)."""
        self._attempt = 0
        self._current_s = 0.0

    @property
    def attempt(self) -> int:
        return self._attempt

    @property
    def current_s(self) -> float:
        return self._current_s

    @property
    def current_ms(self) -> float:
        return self._current_s * 1000.0


def send_with_backoff(
    network: str,
    address,
    payload: bytes,
    attempts: int = 3,
    backoff: Optional[Backoff] = None,
    timeout: float = 5.0,
) -> Optional[Exception]:
    """Push ``payload`` with up to ``attempts`` tries under the shared
    backoff policy; returns the last error or None (the submitter's
    send_once error contract).  The retrying push path graphite.py /
    opentsdb.py callers previously had to hand-roll."""
    import time

    from loghisto_tpu.submitter import send_once

    bo = backoff if backoff is not None else Backoff()
    err: Optional[Exception] = None
    for attempt in range(max(attempts, 1)):
        err = send_once(network, address, payload, timeout=timeout)
        if err is None:
            bo.reset()
            return None
        if attempt + 1 < attempts:
            time.sleep(bo.next_delay())
    return err
