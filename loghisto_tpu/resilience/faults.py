"""Deterministic fault injection for the chaos harness (ISSUE 10).

Production failure paths — device failure mid-dispatch, a wedged
transfer worker, a crash between journal append and checkpoint rename —
are exactly the code that never runs in a clean test.  The
``FaultInjector`` scripts them: every hook site in the pipeline calls
``check(site)`` (or ``mangle(site, text)`` for data corruption) through
a single attribute read, and a seeded plan decides deterministically
which call at which site fires which fault.

The disabled form is the common case and must stay off the flame graph:
components hold ``self.fault_injector = None`` and every hook compiles
down to one attribute load + ``is None`` test (the <1% firehose budget
in benchmarks/recovery_bench.py pins this).

Hook sites wired in this round (see ARCHITECTURE.md for the table):

    commit.dispatch    inside the fused dispatch try (device failure)
    commit.bridge      committer bridge loop, outside the per-commit try
    agg.ingest         aggregator transfer worker's device ingest
    agg.xfer_worker    transfer worker loop top (wedge / crash)
    wheel.push         time-wheel tier push
    checkpoint.write   before the npz payload is written
    checkpoint.rename  after fsync, before the atomic rename
    journal.append     mangle() over the serialized line (torn/corrupt)
    export.send        submitter send path
    recovery.tick      recovery manager's cadenced checkpoint
    fed.send           federation emitter's frame send (BacklogSender)
    fed.accept         federation receiver accept loop, per connection
    fed.decode         federation receiver, per decoded frame pre-apply

Actions: ``raise`` (InjectedFault), ``delay`` (sleep ``delay_s`` —
slow-subscriber / slow-device), ``wedge`` (block until
``release_wedges()``, bounded by ``wedge_timeout_s``), ``clock_step``
(arm a backward clock offset readable via ``clock_offset()``).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np


class InjectedFault(RuntimeError):
    """A scripted fault fired at a hook site.  Deliberately a RuntimeError
    subclass so the pipeline's real except-nets treat it exactly like the
    organic failure it stands in for."""


@dataclass
class FaultRule:
    site: str
    action: str = "raise"          # raise | delay | wedge | clock_step
    on_call: Optional[int] = None  # fire on the Nth check() at this site
    every: Optional[int] = None    # or on every Nth call
    times: int = 1                 # stop after firing this many times
    delay_s: float = 0.05          # for action="delay"
    step_s: float = -60.0          # for action="clock_step"
    calls: int = 0
    fires: int = 0

    def should_fire(self) -> bool:
        self.calls += 1
        if self.fires >= self.times:
            return False
        if self.on_call is not None and self.calls != self.on_call:
            return False
        if self.every is not None and self.calls % self.every != 0:
            return False
        if self.on_call is None and self.every is None and self.calls != 1:
            return False
        self.fires += 1
        return True


class FaultInjector:
    """Seeded, scripted fault plans keyed by hook site.

    >>> inj = FaultInjector(seed=7)
    >>> inj.plan("commit.dispatch", on_call=3)          # doctest: +SKIP
    >>> inj.plan("journal.append", action="corrupt", on_call=2)

    Thread-safe: hook sites fire from the bridge / transfer-worker /
    reaper threads concurrently.  ``fired`` records every fault that
    fired as ``(site, action, call_number)`` for test assertions.
    """

    def __init__(self, seed: int = 0, wedge_timeout_s: float = 30.0):
        self.seed = seed
        self.wedge_timeout_s = wedge_timeout_s
        self._rng = np.random.default_rng(seed)
        self._lock = threading.Lock()
        self._rules: Dict[str, List[FaultRule]] = {}
        self._wedge_release = threading.Event()
        self._clock_offset = 0.0
        self.fired: List[Tuple[str, str, int]] = []
        self.faults_injected = 0
        self.wedged_now = 0

    # -- plan construction -------------------------------------------- #

    def plan(
        self,
        site: str,
        action: str = "raise",
        *,
        on_call: Optional[int] = None,
        every: Optional[int] = None,
        times: int = 1,
        delay_s: float = 0.05,
        step_s: float = -60.0,
    ) -> "FaultInjector":
        """Script a fault at ``site``; returns self for chaining.  With
        neither ``on_call`` nor ``every``, fires on the first call."""
        if action not in ("raise", "delay", "wedge", "clock_step",
                          "corrupt", "truncate"):
            raise ValueError(f"unknown fault action {action!r}")
        rule = FaultRule(
            site=site, action=action, on_call=on_call, every=every,
            times=times, delay_s=delay_s, step_s=step_s,
        )
        with self._lock:
            self._rules.setdefault(site, []).append(rule)
        return self

    def reset(self) -> None:
        with self._lock:
            self._rules.clear()
            self.fired.clear()
            self._clock_offset = 0.0
        self.release_wedges()
        self._wedge_release.clear()

    # -- hook-site API -------------------------------------------------- #

    def check(self, site: str) -> None:
        """Hot hook: fire any scripted fault due at ``site``.  Raises
        InjectedFault for action="raise"; blocks for delay/wedge; arms
        the clock offset for clock_step."""
        with self._lock:
            rules = self._rules.get(site)
            if not rules:
                return
            due = None
            for rule in rules:
                if rule.should_fire():
                    due = rule
                    break
            if due is None:
                return
            self.fired.append((site, due.action, due.calls))
            self.faults_injected += 1
            if due.action == "clock_step":
                self._clock_offset += due.step_s
                return
        # block/raise outside the lock: a wedged worker must not wedge
        # every other hook site with it
        if due.action == "raise":
            raise InjectedFault(f"injected fault at {site} "
                                f"(call {due.calls})")
        if due.action == "delay":
            time.sleep(due.delay_s)
            return
        if due.action == "wedge":
            self.wedged_now += 1
            try:
                self._wedge_release.wait(timeout=self.wedge_timeout_s)
            finally:
                self.wedged_now -= 1
            return

    def mangle(self, site: str, text: str) -> str:
        """Data-corruption hook (journal append): return ``text`` mangled
        per any due rule at ``site``.  action="truncate" tears the line
        at a seeded offset (crash mid-append); action="corrupt" flips it
        into non-JSON junk."""
        with self._lock:
            rules = self._rules.get(site)
            if not rules:
                return text
            due = None
            for rule in rules:
                if rule.action in ("corrupt", "truncate") \
                        and rule.should_fire():
                    due = rule
                    break
            if due is None:
                return text
            self.fired.append((site, due.action, due.calls))
            self.faults_injected += 1
            if due.action == "truncate":
                cut = int(self._rng.integers(1, max(len(text) - 1, 2)))
                return text[:cut]
            return "\x00corrupt " + text[: max(len(text) // 4, 1)]

    def clock_offset(self) -> float:
        """Armed backward/forward clock step (seconds), consumed by
        time-sensitive sites (recovery cadence, breaker windows)."""
        with self._lock:
            return self._clock_offset

    def release_wedges(self) -> None:
        """Un-wedge every blocked hook site (chaos-test recovery step)."""
        self._wedge_release.set()

    # -- introspection -------------------------------------------------- #

    def fires_at(self, site: str) -> int:
        with self._lock:
            return sum(r.fires for r in self._rules.get(site, ()))
