"""Thread supervision: pipeline bridges restart instead of silently
dying (ISSUE 10 tentpole, part 3).

The reaper, committer bridge, time-wheel bridge and transfer worker are
all daemon threads whose death previously meant the pipeline went quiet
with no signal beyond a log line.  ``ThreadSupervisor.spawn`` wraps the
target in a restart loop: a normal return (e.g. ChannelClosed after
detach) ends the thread; an exception logs, counts a restart, sleeps a
capped-exponential backoff, and re-enters the target.  A run that stays
healthy for ``healthy_after_s`` resets the backoff so a burst of crashes
hours apart never escalates to the cap.

The returned ``SupervisedThread`` handle is drop-in for the raw
``threading.Thread`` the call sites stored before: ``is_alive()``,
``join()``, ``name``, ``daemon`` all behave, plus ``stop()`` which wakes
a backoff sleep immediately (detach paths call it duck-typed so a
5-second join can't lose the race against a 2-second backoff nap).

Restart counts surface as ``resilience.ThreadRestarts`` and latch the
``thread_restarted`` HealthWatchdog invariant.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Dict, Optional

from loghisto_tpu.resilience.backoff import Backoff

logger = logging.getLogger("loghisto_tpu")


class SupervisedThread:
    """Restart-looping thread handle (see module docstring)."""

    def __init__(
        self,
        target: Callable[[], None],
        name: str,
        supervisor: "ThreadSupervisor",
        backoff: Backoff,
        healthy_after_s: float = 5.0,
    ):
        self._target = target
        self.name = name
        self._supervisor = supervisor
        self._backoff = backoff
        self._healthy_after_s = healthy_after_s
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name=name
        )

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        """Ask the restart loop to exit: wakes any backoff sleep and
        prevents further restarts.  The target itself is interrupted by
        its own shutdown contract (closed subscription etc.)."""
        self._stop.set()

    def join(self, timeout: Optional[float] = None) -> None:
        # call sites compare their stored handle against
        # threading.current_thread() before joining; with a handle that
        # check can't match the inner thread, so guard here instead
        if self._thread is threading.current_thread():
            return
        self._thread.join(timeout)

    def is_alive(self) -> bool:
        return self._thread.is_alive()

    @property
    def daemon(self) -> bool:
        return self._thread.daemon

    def _run(self) -> None:
        while not self._stop.is_set():
            started = time.monotonic()
            try:
                self._target()
                return  # clean exit (ChannelClosed path) — do not restart
            except BaseException:
                if self._stop.is_set():
                    return
                logger.exception(
                    "supervised thread %s crashed; restarting", self.name
                )
                if time.monotonic() - started >= self._healthy_after_s:
                    self._backoff.reset()
                self._supervisor._note_restart(self.name)
                if self._stop.wait(timeout=self._backoff.next_delay()):
                    return


class ThreadSupervisor:
    """Factory + restart ledger for the pipeline's bridge threads."""

    def __init__(
        self,
        base_backoff_s: float = 0.05,
        max_backoff_s: float = 2.0,
        seed: int = 0,
    ):
        self.base_backoff_s = base_backoff_s
        self.max_backoff_s = max_backoff_s
        self._seed = seed
        self._lock = threading.Lock()
        self.total_restarts = 0
        self.restarts_by_name: Dict[str, int] = {}
        self._last_backoff: Optional[Backoff] = None

    def spawn(
        self, target: Callable[[], None], name: str, start: bool = True
    ) -> SupervisedThread:
        backoff = Backoff(
            base_s=self.base_backoff_s, cap_s=self.max_backoff_s,
            seed=self._seed + len(self.restarts_by_name),
        )
        with self._lock:
            self._last_backoff = backoff
        t = SupervisedThread(target, name, self, backoff)
        if start:
            t.start()
        return t

    def _note_restart(self, name: str) -> None:
        with self._lock:
            self.total_restarts += 1
            self.restarts_by_name[name] = \
                self.restarts_by_name.get(name, 0) + 1

    def note_external_restart(self, name: str) -> None:
        """Ledger entry for a component that respawns its own thread
        (the aggregator's lazily-revived transfer worker) so every
        restart in the process shows on one gauge."""
        self._note_restart(name)

    def current_backoff_ms(self) -> float:
        with self._lock:
            bo = self._last_backoff
        return bo.current_ms if bo is not None else 0.0
