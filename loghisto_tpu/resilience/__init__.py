"""Resilience subsystem: fault injection, crash-safe recovery, thread
supervision, circuit breaking (ISSUE 10).

    from loghisto_tpu.resilience import ResilienceConfig, FaultInjector
    ms = TPUMetricSystem(..., resilience=ResilienceConfig(
        checkpoint_path="state.npz", journal_path="intervals.jsonl"))
    ms.recover()   # restore + replay: at most one interval lost
"""

from loghisto_tpu.resilience.backoff import Backoff, send_with_backoff
from loghisto_tpu.resilience.faults import FaultInjector, InjectedFault
from loghisto_tpu.resilience.recovery import (
    CircuitBreaker,
    RecoveryManager,
    RecoveryReport,
    ResilienceConfig,
    register_resilience_gauges,
)
from loghisto_tpu.resilience.supervise import SupervisedThread, ThreadSupervisor

__all__ = [
    "Backoff",
    "CircuitBreaker",
    "FaultInjector",
    "InjectedFault",
    "RecoveryManager",
    "RecoveryReport",
    "ResilienceConfig",
    "SupervisedThread",
    "ThreadSupervisor",
    "register_resilience_gauges",
    "send_with_backoff",
]
