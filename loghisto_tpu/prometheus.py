"""Prometheus exposition-format serializer + pull endpoint (layer L4).

The reference ships push-style Graphite/OpenTSDB serializers and notes
that output plugins are meant to be easy to add (readme.md:113).  This is
the modern third protocol: the text exposition format served over a pull
endpoint.

Metric names are sanitized per the Prometheus data model (invalid chars
become `_`; a leading digit gets a `_` prefix).  Percentile-labelled
names (`lat_99.9`) are emitted as one `summary`-style family with
`quantile` labels where recognizable; everything else is a gauge.

    from loghisto_tpu.prometheus import PrometheusEndpoint
    PrometheusEndpoint(ms, port=9464).start()   # GET /metrics

With a retention wheel the endpoint also serves sliding-window tails —
``<metric>_w5m{quantile="0.99"}`` — computed fresh per scrape from the
timewheel (one fused device reduction per configured window):

    ms = TPUMetricSystem(retention=True)
    PrometheusEndpoint(ms, wheel=ms.retention).start()
"""

from __future__ import annotations

import http.server
import logging
import re
import threading
from typing import Optional

from loghisto_tpu.channel import ChannelClosed, ResilientSubscription
from loghisto_tpu.labels.model import parse_canonical, split_processed
from loghisto_tpu.metrics import MetricSystem, ProcessedMetricSet

logger = logging.getLogger("loghisto_tpu")

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")
_QUANTILE_SUFFIX = re.compile(r"^(.*)_(50|75|90|95|99|99\.9|99\.99)$")
_SUFFIX_TO_Q = {
    "50": "0.5", "75": "0.75", "90": "0.9", "95": "0.95",
    "99": "0.99", "99.9": "0.999", "99.99": "0.9999",
}


def _sanitize(name: str) -> str:
    out = _NAME_RE.sub("_", name)
    if out and out[0].isdigit():
        out = "_" + out
    return out


def _escape_label_value(value: str) -> str:
    """Exposition-format label-value escaping: backslash, double quote,
    and newline (the canonical grammar forbids all three, but foreign
    names parsed tolerantly may still carry them — escape, never drop)."""
    return (
        value.replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _label_str(pairs) -> str:
    """``(("code","500"),("route","/api"))`` ->
    ``code="500",route="/api"`` — canonical pairs arrive key-sorted, so
    the rendering is deterministic.  Keys are sanitized (dots in the
    canonical key grammar become ``_`` per the Prometheus data model)."""
    return ",".join(
        f'{_sanitize(k)}="{_escape_label_value(v)}"' for k, v in pairs
    )


def prometheus_exposition(
    metric_set: ProcessedMetricSet,
    include_timestamps: bool = False,
) -> bytes:
    """Serialize a ProcessedMetricSet in the text exposition format.
    Usable directly as a Submitter serializer too (push-gateway style).

    Timestamps are omitted by default: explicitly-timestamped samples
    bypass Prometheus staleness handling and eventually get rejected as
    out-of-bounds when re-served from a cache; pass
    include_timestamps=True only for push-style delivery."""
    stamp = (
        f" {int(metric_set.time.timestamp() * 1000)}"
        if include_timestamps else ""
    )
    plain: list[str] = []
    # family -> label-string ("" for flat) -> quantile -> value; one
    # ``# TYPE`` line per family even when several label sets share it
    summaries: dict[str, dict[str, dict[str, float]]] = {}
    for name, value in sorted(metric_set.metrics.items()):
        sp = split_processed(name)
        if sp is not None:
            # labeled row (ISSUE 16): canonical ``base;k=v`` tail with
            # the processed suffix appended after it — re-emit as native
            # exposition labels, ``http_latency{route="/api"}``
            base, pairs, suffix = sp
            lstr = _label_str(pairs)
            qs = suffix[1:]  # "_99" -> "99"
            body = name[: -len(suffix)] if suffix else name
            if qs in _SUFFIX_TO_Q and f"{body}_count" in metric_set.metrics:
                summaries.setdefault(_sanitize(base), {}).setdefault(
                    lstr, {}
                ).setdefault(_SUFFIX_TO_Q[qs], value)
            else:
                plain.append(
                    f"{_sanitize(base + suffix)}{{{lstr}}} {value}{stamp}"
                )
            continue
        m = _QUANTILE_SUFFIX.match(name)
        # only treat a _NN suffix as a quantile when its histogram-family
        # sibling `<base>_count` exists — a counter named `disk_90` must
        # not masquerade as a latency quantile
        if m and f"{m.group(1)}_count" in metric_set.metrics:
            family = _sanitize(m.group(1))
            q = _SUFFIX_TO_Q[m.group(2)]
            # keep-first on sanitization collisions: duplicate
            # family+quantile samples fail the whole scrape
            summaries.setdefault(family, {}).setdefault(
                "", {}
            ).setdefault(q, value)
        else:
            plain.append(f"{_sanitize(name)} {value}{stamp}")
    lines = []
    for family, by_labels in sorted(summaries.items()):
        lines.append(f"# TYPE {family} summary")
        for lstr, quantiles in sorted(by_labels.items()):
            sep = "," if lstr else ""
            for q, value in sorted(
                quantiles.items(), key=lambda x: float(x[0])
            ):
                lines.append(
                    f'{family}{{{lstr}{sep}quantile="{q}"}} '
                    f"{value}{stamp}"
                )
    lines.extend(plain)
    return ("\n".join(lines) + "\n").encode()


def _window_label(seconds: float) -> str:
    """300 -> "5m", 3600 -> "1h", 90 -> "90s" — the window tag in
    ``<metric>_w5m`` family names."""
    s = int(seconds)
    if s >= 3600 and s % 3600 == 0:
        return f"{s // 3600}h"
    if s >= 60 and s % 60 == 0:
        return f"{s // 60}m"
    return f"{s}s"


def windowed_exposition(
    wheel,
    windows: tuple[float, ...] = (300.0,),
    quantiles: tuple[float, ...] = (0.5, 0.9, 0.99),
    pattern: str = "*",
) -> bytes:
    """Serialize sliding-window statistics from a TimeWheel: one summary
    family per (metric, window) — ``<metric>_w5m{quantile="0.99"}`` plus
    ``_count``/``_sum`` siblings — each window one fused device query.
    The window tag keeps families disjoint from the last-interval
    summaries prometheus_exposition emits for the same metric."""
    lines: list[str] = []
    for window in windows:
        label = _window_label(window)
        res = wheel.query(pattern, window, percentiles=quantiles)
        typed: set[str] = set()
        for name, entry in sorted(res.metrics.items()):
            base, pairs = parse_canonical(name)
            family = f"{_sanitize(base)}_w{label}"
            lstr = _label_str(pairs)
            sep = "," if lstr else ""
            if family not in typed:
                typed.add(family)
                lines.append(f"# TYPE {family} summary")
            for q in quantiles:
                key = f"{q * 100:.4f}".rstrip("0").rstrip(".")
                value = entry[f"p{key}"]
                lines.append(
                    f'{family}{{{lstr}{sep}quantile="{q:g}"}} {value}'
                )
            if lstr:
                lines.append(f"{family}_count{{{lstr}}} {entry['count']}")
                lines.append(f"{family}_sum{{{lstr}}} {entry['sum']}")
            else:
                lines.append(f"{family}_count {entry['count']}")
                lines.append(f"{family}_sum {entry['sum']}")
    if not lines:
        return b""
    return ("\n".join(lines) + "\n").encode()


class PrometheusEndpoint:
    """Pull endpoint: subscribes to processed metrics, caches the latest
    interval, and serves it at GET /metrics.

    With ``wheel=`` (a window.TimeWheel) each scrape also serves
    wheel-backed sliding-window quantiles (`<metric>_w5m{quantile=...}`)
    computed at scrape time, so the pull side sees live window tails,
    not just last-interval values."""

    def __init__(
        self,
        metric_system: MetricSystem,
        port: int = 9464,
        host: str = "0.0.0.0",
        wheel=None,
        windows: tuple[float, ...] = (300.0,),
        window_quantiles: tuple[float, ...] = (0.5, 0.9, 0.99),
    ):
        self._ms = metric_system
        self._addr = (host, port)
        self._wheel = wheel
        self._windows = tuple(windows)
        self._window_quantiles = tuple(window_quantiles)
        if wheel is not None and hasattr(wheel, "pin_window"):
            # materialize the scrape windows as commit-time snapshot
            # views, so a scrape serves from the latest snapshot epoch
            # (and repeat scrapes within one interval serve the cached
            # payload with zero device work)
            for w in self._windows:
                wheel.pin_window(w)
        self._windowed_cache: Optional[tuple] = None  # (epoch, payload)
        self._sub: Optional[ResilientSubscription] = None
        self._latest: bytes = b"# no interval collected yet\n"
        self._latest_lock = threading.Lock()
        self._server: Optional[http.server.ThreadingHTTPServer] = None
        self._threads: list[threading.Thread] = []

    def _windowed_payload(self) -> bytes:
        if self._wheel is None:
            return b""
        try:
            # serve the serialized payload straight from the latest
            # snapshot epoch: when no interval has committed since the
            # last scrape, the bytes are returned as-is — zero dispatch,
            # zero reserialization.  A wheel without snapshots (or
            # before its first commit) reports epoch None and falls
            # through to a fresh computation every scrape, as before.
            snap = getattr(self._wheel, "snapshot", None)
            epoch = snap.epoch if snap is not None else None
            cached = self._windowed_cache
            if cached is not None and epoch is not None \
                    and cached[0] == epoch:
                return cached[1]
            payload = windowed_exposition(
                self._wheel, self._windows, self._window_quantiles
            )
            if epoch is not None:
                self._windowed_cache = (epoch, payload)
            return payload
        except Exception:
            logger.exception("windowed exposition failed; serving "
                             "last-interval metrics only")
            return b""

    @property
    def port(self) -> int:
        return self._server.server_address[1] if self._server else 0

    def start(self) -> None:
        if self._server is not None:
            return
        endpoint = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (http.server API)
                import urllib.parse

                path = urllib.parse.urlsplit(self.path).path.rstrip("/")
                if path == "/healthz":
                    self._serve_healthz()
                    return
                if path == "/fleetz":
                    self._serve_fleetz()
                    return
                if path not in ("", "/metrics"):
                    self.send_error(404)
                    return
                with endpoint._latest_lock:
                    payload = endpoint._latest
                payload += endpoint._windowed_payload()
                self.send_response(200)
                self.send_header(
                    "Content-Type", "text/plain; version=0.0.4"
                )
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def _serve_healthz(self):
                """Machine-readable pipeline health (ISSUE 9): the
                watchdog's HealthReport as JSON.  503 when stalled so
                orchestrator liveness probes fail without parsing;
                degraded stays 200 (serving, with reasons attached)."""
                import json

                watchdog = getattr(endpoint._ms, "health", None)
                if watchdog is None:
                    doc = {
                        "status": "unknown",
                        "ok": True,
                        "reasons": [{
                            "code": "no_watchdog",
                            "detail": (
                                "observability is not enabled on this "
                                "system (TPUMetricSystem(observability"
                                "=ObsConfig(...)))"
                            ),
                            "value": 0.0,
                        }],
                    }
                    status = 200
                else:
                    report = watchdog.report()
                    doc = report.as_dict()
                    status = 503 if report.status == "stalled" else 200
                payload = json.dumps(doc).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def _serve_fleetz(self):
                """Fleet health rollup (ISSUE 12): the federation
                receiver's per-emitter report as JSON — top-K slowest /
                laggiest / flappiest emitters, starvation and clock-skew
                flags — beside /healthz's single-process view.  404 when
                the system has no federation tier."""
                import json

                fed = getattr(endpoint._ms, "federation", None)
                if fed is None or not hasattr(fed, "fleet_report"):
                    self.send_error(404, "no federation tier")
                    return
                doc = fed.fleet_report()
                payload = json.dumps(doc).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def log_message(self, *args):  # quiet
                pass

        self._server = http.server.ThreadingHTTPServer(self._addr, Handler)
        self._server.daemon_threads = True
        # survives strike-eviction (a starved updater whose channel the
        # reaper closes re-subscribes instead of serving stale data
        # forever) — shared recovery contract with Submitter/Journal
        self._sub = ResilientSubscription(
            self._ms.subscribe_to_processed_metrics,
            self._ms.unsubscribe_from_processed_metrics,
            8,
        )
        sub = self._sub

        def updater():
            while True:
                try:
                    pms = sub.get()
                except ChannelClosed:
                    return  # stop() closed the subscription
                payload = prometheus_exposition(pms)
                with self._latest_lock:
                    self._latest = payload

        self._threads = [
            threading.Thread(
                target=self._server.serve_forever, daemon=True,
                name="loghisto-prom-http",
            ),
            threading.Thread(
                target=updater, daemon=True, name="loghisto-prom-update"
            ),
        ]
        for t in self._threads:
            t.start()

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
        if self._sub is not None:
            self._sub.close()
            self._sub = None
        for t in self._threads:
            t.join(timeout=5.0)
        self._threads = []
