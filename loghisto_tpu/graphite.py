"""Graphite plaintext-protocol serializer (reference layer L4).

Wire format (reference graphite.go:40-47): one line per metric,

    cockroach.<host>.<metric with _ -> .> <value> <unix_ts>\n

The hardcoded ``cockroach.`` prefix is part of the reference's observed
behavior; here it is the *default* of a configurable prefix (the reference
has a TODO for custom tags/prefixes).  Values are rendered with ``%f``
exactly like Go's ``fmt.Sprintf("%f")`` (six decimal places) so the wire
bytes match.
"""

from __future__ import annotations

import socket

from loghisto_tpu.metrics import ProcessedMetricSet


def graphite_protocol(
    metric_set: ProcessedMetricSet,
    prefix: str = "cockroach",
    hostname: str | None = None,
) -> bytes:
    """Serialize a ProcessedMetricSet for a Graphite Carbon instance."""
    if hostname is None:
        hostname = socket.gethostname() or "unknown"
    ts = int(metric_set.time.timestamp())
    lines = [
        "%s.%s.%s %f %d\n"
        % (prefix, hostname, metric.replace("_", "."), value, ts)
        for metric, value in metric_set.metrics.items()
    ]
    return "".join(lines).encode()


# Reference-style alias: usable directly as a Submitter serializer.
GraphiteProtocol = graphite_protocol
