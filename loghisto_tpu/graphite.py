"""Graphite plaintext-protocol serializer (reference layer L4).

Wire format (reference graphite.go:40-47): one line per metric,

    cockroach.<host>.<metric with _ -> .> <value> <unix_ts>\n

The hardcoded ``cockroach.`` prefix is part of the reference's observed
behavior; here it is the *default* of a configurable prefix (the
reference has a TODO for custom tags/prefixes — resolved here).  Values
are rendered with ``%f`` exactly like Go's ``fmt.Sprintf("%f")`` (six
decimal places) so the wire bytes match.

Tag support follows the Graphite 1.1+ tagged-series form: a static
``tags`` mapping renders as ``;key=value`` appended to the metric path,
sorted by key for a deterministic wire format:

    cockroach.<host>.<metric> ;dc=us-east;env=prod <value> <ts>\n

(without the space — ``path;k=v <value> <ts>``).  The default (no tags,
``cockroach`` prefix) is byte-identical to the historical output, which
tests/test_export.py pins.
"""

from __future__ import annotations

import socket
from typing import Mapping, Optional

from loghisto_tpu.metrics import ProcessedMetricSet


def _render_tags(tags: Optional[Mapping[str, str]]) -> str:
    if not tags:
        return ""
    for k, v in tags.items():
        if not k or any(c in ";! " for c in k) or ";" in str(v):
            # the tagged-series grammar reserves ';' (and a leading '!'
            # / empty names); a malformed static tag is a config error
            raise ValueError(f"invalid graphite tag {k!r}={v!r}")
    return "".join(f";{k}={tags[k]}" for k in sorted(tags))


def graphite_protocol(
    metric_set: ProcessedMetricSet,
    prefix: str = "cockroach",
    hostname: str | None = None,
    tags: Optional[Mapping[str, str]] = None,
) -> bytes:
    """Serialize a ProcessedMetricSet for a Graphite Carbon instance."""
    if hostname is None:
        hostname = socket.gethostname() or "unknown"
    ts = int(metric_set.time.timestamp())
    tag_str = _render_tags(tags)
    lines = [
        "%s.%s.%s%s %f %d\n"
        % (prefix, hostname, metric.replace("_", "."), tag_str, value, ts)
        for metric, value in metric_set.metrics.items()
    ]
    return "".join(lines).encode()


def make_graphite_serializer(
    prefix: str = "cockroach",
    hostname: str | None = None,
    tags: Optional[Mapping[str, str]] = None,
):
    """Bind a custom prefix / static tag set into a serializer usable
    directly as a Submitter serializer (the constructor-configuration
    the reference's TODO asked for).  Tags are validated once here, not
    per interval."""
    _render_tags(tags)  # fail fast on malformed tags
    def serialize(metric_set: ProcessedMetricSet) -> bytes:
        return graphite_protocol(metric_set, prefix, hostname, tags)
    return serialize


def push_graphite(
    address: tuple[str, int],
    metric_set: ProcessedMetricSet,
    prefix: str = "cockroach",
    hostname: str | None = None,
    tags: Optional[Mapping[str, str]] = None,
    attempts: int = 3,
    backoff=None,
) -> Optional[Exception]:
    """Serialize and deliver one metric set to a Carbon instance with
    the shared capped-exponential-backoff retry policy
    (resilience/backoff.py).  Returns the last error or None — the
    one-shot push path that previously had to hand-roll its own retry
    loop around send_once."""
    from loghisto_tpu.resilience.backoff import send_with_backoff

    payload = graphite_protocol(metric_set, prefix, hostname, tags)
    return send_with_backoff(
        "tcp", address, payload, attempts=attempts, backoff=backoff
    )


# Reference-style alias: usable directly as a Submitter serializer.
GraphiteProtocol = graphite_protocol
