"""Graphite plaintext-protocol serializer (reference layer L4).

Wire format (reference graphite.go:40-47): one line per metric,

    cockroach.<host>.<metric with _ -> .> <value> <unix_ts>\n

The hardcoded ``cockroach.`` prefix is part of the reference's observed
behavior; here it is the *default* of a configurable prefix (the
reference has a TODO for custom tags/prefixes — resolved here).  Values
are rendered with ``%f`` exactly like Go's ``fmt.Sprintf("%f")`` (six
decimal places) so the wire bytes match.

Tag support follows the Graphite 1.1+ tagged-series form: a static
``tags`` mapping renders as ``;key=value`` appended to the metric path,
sorted by key for a deterministic wire format:

    cockroach.<host>.<metric> ;dc=us-east;env=prod <value> <ts>\n

(without the space — ``path;k=v <value> <ts>``).  The default (no tags,
``cockroach`` prefix) is byte-identical to the historical output, which
tests/test_export.py pins.

``labeled_tags=True`` (ISSUE 16) additionally re-renders canonical
labeled metric names (``http.latency;route=/api`` + processed suffix)
as native tagged series: the label pairs move out of the path and into
``;k=v`` tags merged over the static set, so Graphite sees
``cockroach.<host>.http.latency.99;route=/api``.  Off by default — the
flat wire format stays byte-identical.
"""

from __future__ import annotations

import socket
from typing import Mapping, Optional

from loghisto_tpu.labels.model import split_processed
from loghisto_tpu.metrics import ProcessedMetricSet


def _render_tags(tags: Optional[Mapping[str, str]]) -> str:
    if not tags:
        return ""
    for k, v in tags.items():
        if not k or any(c in ";! " for c in k) or ";" in str(v):
            # the tagged-series grammar reserves ';' (and a leading '!'
            # / empty names); a malformed static tag is a config error
            raise ValueError(f"invalid graphite tag {k!r}={v!r}")
    return "".join(f";{k}={tags[k]}" for k in sorted(tags))


def graphite_protocol(
    metric_set: ProcessedMetricSet,
    prefix: str = "cockroach",
    hostname: str | None = None,
    tags: Optional[Mapping[str, str]] = None,
    labeled_tags: bool = False,
) -> bytes:
    """Serialize a ProcessedMetricSet for a Graphite Carbon instance.
    With ``labeled_tags`` labeled metric names render their label pairs
    as per-line tagged-series tags (label values override a clashing
    static tag — the row-level value is the more specific one)."""
    if hostname is None:
        hostname = socket.gethostname() or "unknown"
    ts = int(metric_set.time.timestamp())
    tag_str = _render_tags(tags)
    lines = []
    for metric, value in metric_set.metrics.items():
        line_tags = tag_str
        if labeled_tags:
            sp = split_processed(metric)
            if sp is not None:
                base, pairs, suffix = sp
                merged = dict(tags or {})
                merged.update(pairs)
                line_tags = "".join(
                    f";{k}={merged[k]}" for k in sorted(merged)
                )
                metric = base + suffix
        lines.append(
            "%s.%s.%s%s %f %d\n"
            % (prefix, hostname, metric.replace("_", "."), line_tags,
               value, ts)
        )
    return "".join(lines).encode()


def make_graphite_serializer(
    prefix: str = "cockroach",
    hostname: str | None = None,
    tags: Optional[Mapping[str, str]] = None,
    labeled_tags: bool = False,
):
    """Bind a custom prefix / static tag set into a serializer usable
    directly as a Submitter serializer (the constructor-configuration
    the reference's TODO asked for).  Tags are validated once here, not
    per interval."""
    _render_tags(tags)  # fail fast on malformed tags
    def serialize(metric_set: ProcessedMetricSet) -> bytes:
        return graphite_protocol(
            metric_set, prefix, hostname, tags, labeled_tags
        )
    return serialize


def push_graphite(
    address: tuple[str, int],
    metric_set: ProcessedMetricSet,
    prefix: str = "cockroach",
    hostname: str | None = None,
    tags: Optional[Mapping[str, str]] = None,
    attempts: int = 3,
    backoff=None,
    labeled_tags: bool = False,
) -> Optional[Exception]:
    """Serialize and deliver one metric set to a Carbon instance with
    the shared capped-exponential-backoff retry policy
    (resilience/backoff.py).  Returns the last error or None — the
    one-shot push path that previously had to hand-roll its own retry
    loop around send_once."""
    from loghisto_tpu.resilience.backoff import send_with_backoff

    payload = graphite_protocol(
        metric_set, prefix, hostname, tags, labeled_tags
    )
    return send_with_backoff(
        "tcp", address, payload, attempts=attempts, backoff=backoff
    )


# Reference-style alias: usable directly as a Submitter serializer.
GraphiteProtocol = graphite_protocol
