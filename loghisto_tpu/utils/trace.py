"""Tracing/profiling hooks (SURVEY.md §5.1).

The reference *is* its own profiling tool (timers -> latency histograms);
the rebuild keeps that surface and adds optional capture of device traces
around aggregation steps:

  * ``profile_region("ingest")`` — a context manager that wraps a block in
    a ``jax.profiler.TraceAnnotation`` so it shows up named in TensorBoard
    / Perfetto traces.
  * ``capture(path)`` — records a full ``jax.profiler`` trace of the
    enclosed block to `path`.
  * Setting ``LOGHISTO_TRACE_DIR`` makes TPUAggregator.collect() capture
    its device program automatically (zero code changes for users).
"""

from __future__ import annotations

import contextlib
import os
from typing import Iterator


@contextlib.contextmanager
def profile_region(name: str) -> Iterator[None]:
    import jax.profiler

    with jax.profiler.TraceAnnotation(name):
        yield


@contextlib.contextmanager
def capture(path: str) -> Iterator[None]:
    """Record a jax.profiler trace of the enclosed block to `path`."""
    import jax.profiler

    jax.profiler.start_trace(path)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


@contextlib.contextmanager
def maybe_capture(region: str) -> Iterator[None]:
    """Capture a trace when LOGHISTO_TRACE_DIR is set; otherwise just
    annotate the region."""
    trace_dir = os.environ.get("LOGHISTO_TRACE_DIR")
    if trace_dir:
        with capture(os.path.join(trace_dir, region)):
            yield
    else:
        with profile_region(region):
            yield
