"""Raw-interval journal: persist RawMetricSets as JSON lines and replay
them later.

The reference streams intervals to subscribers and the data is gone; the
journal is the durable third option next to live broadcast and
checkpointing: every interval's sparse histograms/counters/rates/gauges
append to a JSONL file, and `replay()` reconstructs RawMetricSets that
feed anything the live stream feeds — `MetricSystem.process_metrics`,
`merge_raw_metric_sets`, or `TPUAggregator.merge_raw` (e.g. re-running
device aggregation over yesterday's intervals with different
percentiles).

The format is line-delimited JSON (one interval per line, append-only,
crash-tolerant: a torn final line is skipped on replay with a warning).
"""

from __future__ import annotations

import datetime as _dt
import json
import logging
import threading
from typing import Iterator, Optional

from loghisto_tpu.channel import ChannelClosed, ResilientSubscription
from loghisto_tpu.metrics import MetricSystem, RawMetricSet

logger = logging.getLogger("loghisto_tpu")

FORMAT_VERSION = 1

# process-wide corrupt-line ledger behind the journal.CorruptLines gauge
_corrupt_lock = threading.Lock()
_corrupt_lines = 0


def corrupt_lines_total() -> int:
    """Corrupt/torn journal lines skipped by replay() process-wide."""
    with _corrupt_lock:
        return _corrupt_lines


def _note_corrupt_line() -> None:
    global _corrupt_lines
    with _corrupt_lock:
        _corrupt_lines += 1


class JournalCorruptError(Exception):
    """A corrupt NON-final journal line under replay(strict=True) —
    mid-file corruption means lost data that a torn final line (crash
    mid-append) does not, so strict consumers get to refuse it."""


class JournalVersionError(Exception):
    """The journal was written by an incompatible format version — raised
    from replay() rather than silently skipping every line."""


def dump_line(raw: RawMetricSet) -> str:
    obj = {
        "v": FORMAT_VERSION,
        "time": raw.time.timestamp(),
        "counters": raw.counters,
        "rates": raw.rates,
        # JSON keys are strings; bucket indices round-trip via int()
        "histograms": {
            name: {str(b): c for b, c in buckets.items()}
            for name, buckets in raw.histograms.items()
        },
        "gauges": raw.gauges,
    }
    # interval duration (seconds): rates are per-interval deltas, so
    # replay-time rate/burn-rate math needs the real denominator instead
    # of assuming the replaying system's live interval.  Optional key —
    # same format version, and old lines replay with duration=None.
    if raw.duration is not None:
        obj["interval"] = raw.duration
    # interval sequence number (observability correlation id): lets a
    # replayed interval line up with span records / Perfetto flows from
    # the run that wrote it.  Optional key like "interval" — same format
    # version, and old lines replay with seq=None (the committer mints a
    # local seq for them).
    if raw.seq is not None:
        obj["seq"] = raw.seq
    return json.dumps(obj, separators=(",", ":"))


def parse_line(line: str) -> RawMetricSet:
    obj = json.loads(line)
    if not isinstance(obj, dict):
        raise ValueError(f"journal line is not an object: {type(obj)}")
    if obj.get("v") != FORMAT_VERSION:
        raise JournalVersionError(
            f"unsupported journal version {obj.get('v')}"
        )
    return RawMetricSet(
        time=_dt.datetime.fromtimestamp(obj["time"], tz=_dt.timezone.utc),
        counters={k: int(v) for k, v in obj["counters"].items()},
        rates={k: int(v) for k, v in obj["rates"].items()},
        histograms={
            name: {int(b): int(c) for b, c in buckets.items()}
            for name, buckets in obj["histograms"].items()
        },
        # coerced like the other fields so a corrupt gauges value fails
        # HERE (inside replay's skip-and-warn net), not at the consumer
        gauges={k: float(v) for k, v in obj["gauges"].items()},
        duration=(
            float(obj["interval"]) if obj.get("interval") is not None
            else None
        ),
        seq=(
            int(obj["seq"]) if obj.get("seq") is not None else None
        ),
    )


def replay(path: str, strict: bool = False) -> Iterator[RawMetricSet]:
    """Yield every interval in the journal.  A format-version mismatch
    raises JournalVersionError either way — a newer-format journal must
    not silently replay as empty.

    Corrupt lines split two ways by position.  A torn FINAL line is the
    expected crash-mid-append artifact and is always skipped with a
    warning.  Corrupt lines with valid lines after them mean real data
    loss: with ``strict=False`` (default) they are skipped with a
    counted warning (the ``journal.CorruptLines`` gauge); with
    ``strict=True`` they raise JournalCorruptError instead."""
    # a corrupt line is only provably non-final once a later non-empty
    # line shows up, so the error is held pending until then
    pending: Optional[tuple[int, Exception]] = None
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            if pending is not None:
                p_lineno, p_err = pending
                pending = None
                _note_corrupt_line()
                if strict:
                    raise JournalCorruptError(
                        f"journal {path} line {p_lineno} corrupt mid-file"
                        f" ({p_err})"
                    ) from p_err
                logger.warning(
                    "journal %s line %d unreadable (%s); skipping",
                    path, p_lineno, p_err,
                )
            try:
                yield parse_line(line)
            except JournalVersionError:
                raise
            except (json.JSONDecodeError, AttributeError, KeyError,
                    TypeError, ValueError) as e:
                pending = (lineno, e)
    if pending is not None:
        # torn final line: tolerated in both modes (crash mid-append)
        p_lineno, p_err = pending
        _note_corrupt_line()
        logger.warning(
            "journal %s line %d unreadable (%s); skipping torn tail",
            path, p_lineno, p_err,
        )


class RawJournal:
    """A raw-metrics subscriber that appends every interval to a JSONL
    file.  Subject to the same strike-eviction contract as any
    subscriber; writing happens on its own thread, never in the reaper."""

    def __init__(
        self,
        metric_system: MetricSystem,
        path: str,
        channel_capacity: int = 16,
    ):
        self.path = path
        self._ms = metric_system
        self._capacity = channel_capacity
        self._ch: Optional[ResilientSubscription] = None
        self._thread: Optional[threading.Thread] = None
        # chaos hook: mangles serialized lines (torn/corrupt injection)
        self.fault_injector = None

    def start(self) -> None:
        """Open the file and subscribe.  Subscription happens HERE, not in
        __init__ — a constructed-but-unstarted journal must never sit on
        the broadcast accruing strikes.  An unopenable path raises to the
        caller instead of silently killing the writer thread."""
        if self._thread is not None:
            return
        f = open(self.path, "a+")
        # a crash mid-append can leave a torn final line with no newline;
        # terminate it now so the next record starts on its own line
        # (otherwise BOTH the torn line and the first new record are lost)
        f.seek(0, 2)
        if f.tell() > 0:
            f.seek(f.tell() - 1)
            if f.read(1) != "\n":
                f.write("\n")
        # survives strike-eviction: a durability journal that dies
        # permanently after one transient stall defeats its purpose
        self._ch = ResilientSubscription(
            self._ms.subscribe_to_raw_metrics,
            self._ms.unsubscribe_from_raw_metrics,
            self._capacity,
        )
        self._thread = threading.Thread(
            target=self._run, args=(f, self._ch), daemon=True,
            name="loghisto-journal",
        )
        self._thread.start()

    def _run(self, f, ch: ResilientSubscription) -> None:
        with f:
            while True:
                try:
                    raw = ch.get()
                except ChannelClosed:
                    return
                try:
                    line = dump_line(raw) + "\n"
                    inj = self.fault_injector
                    if inj is not None:
                        line = inj.mangle("journal.append", line)
                    f.write(line)
                    f.flush()
                except OSError:
                    logger.exception("journal write failed; interval lost")

    def stop(self) -> None:
        if self._ch is not None:
            self._ch.close()
            self._ch = None
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None


class FrameJournal:
    """Binary framed journal: append-only ``(kind, payload)`` records in
    the SAME frame format the federation wire ships (ops/codec.py:
    versioned header + length prefix + CRC32) — one codec, two
    consumers, per the ISSUE 11 satellite.  The federation receiver
    write-aheads every applied frame here so a receiver restart replays
    to bit-identical aggregator state.

    Replay is torn-tolerant like the JSONL journal: a frame cut short at
    end-of-file is the expected crash-mid-append artifact (skipped with
    a counted warning); CORRUPT bytes mid-file stop the replay there —
    a byte stream offers no resync point past a bad length field — with
    the remainder counted as one corrupt record (``strict=True`` raises
    JournalCorruptError instead).  Both paths feed the same
    ``journal.CorruptLines`` ledger as the JSONL tier."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        self._f = open(path, "ab")
        self.frames_appended = 0

    def append(self, kind: int, payload: bytes) -> None:
        from loghisto_tpu.ops.codec import encode_frame

        frame = encode_frame(kind, payload)
        with self._lock:
            self._f.write(frame)
            self._f.flush()
            self.frames_appended += 1

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                self._f.close()
                self._f = None

    @staticmethod
    def replay(path: str, strict: bool = False):
        """Yield every ``(kind, payload)`` in the journal file (see the
        class docstring for the torn/corrupt contract)."""
        from loghisto_tpu.ops.codec import (
            FrameError, FrameTruncated, decode_frame,
        )

        with open(path, "rb") as f:
            buf = f.read()
        offset = 0
        while offset < len(buf):
            try:
                kind, payload, offset = decode_frame(buf, offset)
            except FrameTruncated as e:
                _note_corrupt_line()
                logger.warning(
                    "frame journal %s torn at offset %d (%s); skipping "
                    "tail", path, offset, e,
                )
                return
            except FrameError as e:
                _note_corrupt_line()
                if strict:
                    raise JournalCorruptError(
                        f"frame journal {path} corrupt at offset {offset}"
                        f" ({e})"
                    ) from e
                logger.warning(
                    "frame journal %s corrupt at offset %d (%s); "
                    "abandoning the remaining %d B (no resync point in "
                    "a binary stream)", path, offset, e, len(buf) - offset,
                )
                return
            yield kind, payload
