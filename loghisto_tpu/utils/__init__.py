"""Auxiliary subsystems: process/TPU gauges, checkpointing, tracing."""
