"""Checkpoint / resume for metric state (SURVEY.md §5.4).

The reference has no persistence: its lifetime stores die with the process
(metrics.go:111-126).  Long-running TPU aggregation wants better — the
dense bucket tensor plus lifetime scalars fully determine the statistics,
and both serialize trivially.

Format: a single .npz with JSON-encoded name tables, written atomically
(temp file + rename) so a crash mid-write can't corrupt the last good
snapshot.  Covers the host MetricSystem (lifetime counter store +
histogram aggregate store) and the TPUAggregator (dense accumulator,
registry names, lifetime aggregates).  Interval caches are deliberately
NOT persisted: in-flight samples of a crashed interval follow the
shed-don't-block philosophy of the reference.
"""

from __future__ import annotations

import json
import os
import tempfile
from typing import Optional

import numpy as np

from loghisto_tpu.metrics import MetricSystem

# v2: optional interval-seq watermark rides the payload so crash
# recovery can replay ONLY journal intervals past the snapshotted state
# (resilience/recovery.py).  v1 files load fine — watermark None.
# v3: paged aggregators (PR 14) snapshot portably — `agg_acc` carries
# the canonical dense decode of the page pool + host spill (so any
# storage mode restores any save), and `pg_codec_names` records each
# row's codec choice so a paged restore re-pins resolutions instead of
# re-deriving them from the first post-restore interval.  v1/v2 files
# load fine — codecs None.  The same two legs make v3 files
# MESH-SHAPE-portable (PR 18): decode_dense gathers the sharded pool
# to one host tensor on save, and restore replays through the target
# store's own translate/commit, which assigns pages against the
# target mesh's per-shard arenas — a 2x4 save restores onto 1x8, an
# unsharded store, or a dense aggregator, codec choices intact.
FORMAT_VERSION = 3


def save(
    path: str,
    metric_system: Optional[MetricSystem] = None,
    aggregator=None,
    lifecycle=None,
    anomaly=None,
    seq_watermark: Optional[int] = None,
    fault_injector=None,
) -> None:
    """Atomically snapshot lifetime state to `path` (.npz).

    ``seq_watermark`` stamps the snapshot with the last committed
    interval seq folded into this state; ``fault_injector`` exposes the
    two crash-window hook sites ("checkpoint.write" before the payload
    lands, "checkpoint.rename" after fsync but before the atomic
    rename) for the chaos harness.

    ``lifecycle`` (a lifecycle.LifecycleManager) additionally persists
    the activity vector, the lifetime churn counters, and the registry
    generation.  Overflow metric state needs no special handling — the
    catch-all series are ordinary named rows, so they ride the
    accumulator / lifetime-aggregate payloads like any other metric
    (tests/test_checkpoint.py round-trips this).

    ``anomaly`` (an anomaly.AnomalyManager) persists the EWMA baseline
    banks (profile + weight mass) so drift detection resumes warm
    after a restart instead of re-learning every baseline; rows are
    remapped by NAME on restore like every other per-row payload."""
    payload = {"version": np.int64(FORMAT_VERSION)}
    if seq_watermark is not None:
        payload["seq_watermark"] = np.int64(seq_watermark)

    if metric_system is not None:
        with metric_system._store_lock:
            counters = dict(metric_system._counter_store)
            agg = {
                name: (entry[0], entry[1])
                for name, entry in metric_system._histogram_agg_store.items()
            }
        payload["ms_counter_names"] = _names_arr(counters.keys())
        payload["ms_counter_values"] = np.array(
            list(counters.values()), dtype=np.uint64
        )
        payload["ms_agg_names"] = _names_arr(agg.keys())
        payload["ms_agg_sums"] = np.array(
            [v[0] for v in agg.values()], dtype=np.float64
        )
        payload["ms_agg_counts"] = np.array(
            [v[1] for v in agg.values()], dtype=np.uint64
        )
        if agg and all(isinstance(v[0], int) for v in agg.values()):
            # go_compat sums are exact uint64s that float64 would clip
            # above 2^53; keep the exact form alongside
            payload["ms_agg_sums_u64"] = np.array(
                [v[0] & 0xFFFFFFFFFFFFFFFF for v in agg.values()],
                dtype=np.uint64,
            )

    if aggregator is not None:
        # force: the preagg transport holds cells in a host store between
        # interval boundaries, and a cooling-down device gates non-forced
        # raw flushes — either way a plain flush() could silently omit
        # staged samples from the snapshot
        aggregator.flush(force=True)
        with aggregator._dev_lock:
            if getattr(aggregator, "paged", None) is not None:
                # canonical dense decode of pool + host spill: the
                # snapshot is storage-portable (a dense aggregator
                # restores a paged save and vice versa); codec choices
                # ride alongside so a paged restore re-pins resolutions
                acc = aggregator.paged.decode_dense(include_spill=True)
                payload["pg_codec_names"] = _names_arr(
                    aggregator.paged.codec_names()
                )
            else:
                # canonical dense layout: snapshots stay portable across
                # ingest_path choices (multirow's lane padding is
                # stripped)
                acc = np.asarray(
                    aggregator._finalize_acc(aggregator._acc)
                )
                # a spilled interval keeps part of its counts in the
                # host int64 fold — snapshotting only the device tensor
                # would silently lose them; the combined snapshot is
                # int64
                if aggregator._spill is not None:
                    acc = acc.astype(np.int64) + aggregator._spill
        with aggregator._agg_lock:
            agg_items = sorted(aggregator._agg.items())
        payload["agg_acc"] = acc
        # freed lifecycle slots serialize as JSON null and restore as
        # holes (their rows are zero — eviction folds then clears them)
        payload["agg_names"] = _names_arr(aggregator.registry.names())
        payload["agg_registry_generation"] = np.int64(
            getattr(aggregator.registry, "generation", 0)
        )
        payload["agg_ids"] = np.array([k for k, _ in agg_items], dtype=np.int64)
        payload["agg_sums"] = np.array(
            [v[0] for _, v in agg_items], dtype=np.float64
        )
        payload["agg_counts"] = np.array(
            [v[1] for _, v in agg_items], dtype=np.uint64
        )

    if lifecycle is not None:
        st = lifecycle.state_dict()
        payload["lc_last_active"] = st["last_active"]
        payload["lc_counters"] = np.array(
            [
                st["evicted_series"],
                st["overflowed_samples"],
                st["evictions"],
                st["compactions"],
            ],
            dtype=np.int64,
        )

    if anomaly is not None:
        st = anomaly.state_dict()
        payload["an_prof"] = st["prof"]
        payload["an_wsum"] = st["wsum"]
        payload["an_counters"] = np.array(
            [st["scored_intervals"]], dtype=np.int64
        )

    directory = os.path.dirname(os.path.abspath(path)) or "."
    fd, tmp = tempfile.mkstemp(dir=directory, suffix=".tmp")
    try:
        if fault_injector is not None:
            fault_injector.check("checkpoint.write")
        with os.fdopen(fd, "wb") as f:
            np.savez_compressed(f, **payload)
            f.flush()
            os.fsync(f.fileno())  # data durable before the rename
        if fault_injector is not None:
            fault_injector.check("checkpoint.rename")
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def restore(
    path: str,
    metric_system: Optional[MetricSystem] = None,
    aggregator=None,
    lifecycle=None,
    anomaly=None,
) -> Optional[int]:
    """Restore lifetime state saved by save().  Loads into the provided
    objects (merging over their current lifetime state).  With
    ``lifecycle``, the saved activity vector is remapped through the
    same by-name row mapping as the accumulator and the churn counters
    are restored; the target registry's generation is advanced to at
    least the saved one, so caches keyed on (generation, length) from a
    pre-restore world can never serve post-restore ids.

    Returns the interval-seq watermark the snapshot was stamped with
    (v2), or None for v1 files / unstamped saves — existing callers
    ignore the return value, recovery replay keys on it."""
    with np.load(path, allow_pickle=False) as data:
        version = int(data["version"])
        if version > FORMAT_VERSION:
            raise ValueError(f"unsupported checkpoint version {version}")
        seq_watermark = (
            int(data["seq_watermark"]) if "seq_watermark" in data else None
        )

        if metric_system is not None and "ms_counter_names" in data:
            names = _arr_names(data["ms_counter_names"])
            values = data["ms_counter_values"]
            agg_names = _arr_names(data["ms_agg_names"])
            sums = data["ms_agg_sums"]
            counts = data["ms_agg_counts"]
            # go_compat stores need INT sums (the uint64 mask would
            # TypeError on floats); prefer the exact u64 sidecar
            go_compat = metric_system.config.go_compat
            if go_compat and "ms_agg_sums_u64" in data:
                sums = data["ms_agg_sums_u64"]
            with metric_system._store_lock:
                for name, value in zip(names, values):
                    metric_system._counter_store[name] = int(value)
                for name, s, c in zip(agg_names, sums, counts):
                    metric_system._histogram_agg_store[name] = [
                        int(s) if go_compat else float(s), int(c)
                    ]

        if aggregator is not None and "agg_acc" in data:
            import jax.numpy as jnp

            acc = data["agg_acc"]
            # snapshots carry the canonical dense layout regardless of the
            # saving aggregator's ingest_path; the target may have MORE
            # rows than the snapshot (on_registry_full="grow")
            if (
                acc.shape[1] != aggregator.config.num_buckets
                or acc.shape[0] > aggregator.num_metrics
            ):
                raise ValueError(
                    f"checkpoint accumulator shape {acc.shape} does not "
                    "fit the aggregator's configuration "
                    f"({aggregator.num_metrics}, "
                    f"{aggregator.config.num_buckets})"
                )
            # Remap by NAME, not by row id: the target registry may already
            # hold other names at the checkpoint's ids.  Saved rows are
            # added into the target's rows for their re-registered ids.
            # Registration goes through the aggregator's _id_for so the
            # on_registry_full="grow" policy applies to restores exactly as
            # it does to live ingestion; a shed name (-1, past max_metrics)
            # drops that row with a warning rather than aborting mid-way.
            saved_names = _arr_names(data["agg_names"])
            row_map = []
            for saved_id, name in enumerate(saved_names):
                if name is None:
                    # lifecycle-freed slot: its row was folded into an
                    # overflow metric and zeroed before the save
                    continue
                new_id = aggregator._id_for(name)
                if new_id < 0:
                    import logging

                    logging.getLogger("loghisto_tpu").warning(
                        "restore: metric %r shed (registry at max_metrics)",
                        name,
                    )
                    continue
                row_map.append((saved_id, new_id))
            # Rows populated via record_batch with raw ids that were never
            # registered carry no name; map them identity (same row id) so
            # their counts survive the round trip — but ONLY when that row
            # is not claimed by a named metric (in the target registry or
            # by the named remap above): merging an unnamed row into a
            # named metric would silently corrupt its histogram.
            named_rows = {saved_id for saved_id, _ in row_map}
            named_targets = {new_id for _, new_id in row_map}
            target_named_rows = len(aggregator.registry)
            for saved_id in range(acc.shape[0]):
                if saved_id in named_rows or not acc[saved_id].any():
                    continue
                if saved_id in named_targets or saved_id < target_named_rows:
                    import logging

                    logging.getLogger("loghisto_tpu").warning(
                        "restore: dropping unnamed checkpoint row %d — its "
                        "row id is owned by a named metric in the target; "
                        "register names before saving to keep such rows",
                        saved_id,
                    )
                    continue
                row_map.append((saved_id, saved_id))
            remapped = np.zeros(
                (aggregator.num_metrics, acc.shape[1]), dtype=acc.dtype
            )
            for saved_id, new_id in row_map:
                remapped[new_id] += acc[saved_id]
            if getattr(aggregator, "paged", None) is not None:
                pg = aggregator.paged
                with aggregator._dev_lock:
                    # re-pin the saved codec choices first (by the same
                    # by-name row map), so the recommit below encodes
                    # each row at its saved resolution instead of
                    # re-deriving from this one delta's occupancy
                    if "pg_codec_names" in data:
                        saved_codecs = _arr_names(data["pg_codec_names"])
                        for saved_id, new_id in row_map:
                            if (
                                saved_id < len(saved_codecs)
                                and saved_codecs[saved_id] is not None
                            ):
                                pg.set_row_codec(
                                    new_id, saved_codecs[saved_id]
                                )
                    rows, cols = np.nonzero(remapped)
                    weights = remapped[rows, cols].astype(np.int64)
                    # same headroom rule as the dense branch: restored
                    # counts never increment _interval_ingested, so big
                    # deltas take the store's exact host spill
                    live_max = pg.max_cell()
                    if (
                        int(weights.max(initial=0))
                        + live_max
                        + aggregator.spill_threshold
                        + aggregator.batch_size
                    ) >= 2**31:
                        pg.spill_cells(
                            rows.astype(np.int64),
                            cols.astype(np.int64),
                            weights,
                        )
                    else:
                        packed = np.empty((len(rows), 3), dtype=np.int32)
                        packed[:, 0] = rows
                        packed[:, 1] = (
                            cols.astype(np.int64)
                            - aggregator.config.bucket_limit
                        )
                        packed[:, 2] = weights
                        pg.commit(packed)
            else:
                with aggregator._dev_lock:
                    _restore_dense_delta(aggregator, remapped)
            id_remap = dict(row_map)
            with aggregator._agg_lock:
                agg_compat = aggregator.config.go_compat
                for mid, s, c in zip(
                    data["agg_ids"], data["agg_sums"], data["agg_counts"]
                ):
                    new_id = id_remap.get(int(mid))
                    if new_id is None:
                        continue
                    entry = aggregator._agg.setdefault(new_id, [0, 0])
                    # int sums under go_compat (the uint64 mask applied at
                    # collect would TypeError on floats)
                    entry[0] += int(s) if agg_compat else float(s)
                    entry[1] += int(c)
            if "agg_registry_generation" in data:
                saved_gen = int(data["agg_registry_generation"])
                reg = aggregator.registry
                with reg._lock:
                    reg._generation = max(reg._generation, saved_gen)
            if lifecycle is not None and "lc_last_active" in data:
                saved_la = np.asarray(
                    data["lc_last_active"], dtype=np.int32
                )
                la = np.zeros(aggregator.num_metrics, dtype=np.int32)
                for saved_id, new_id in id_remap.items():
                    if saved_id < len(saved_la) and new_id < len(la):
                        la[new_id] = saved_la[saved_id]
                counters = data["lc_counters"]
                lifecycle.load_state({
                    "last_active": la,
                    "evicted_series": int(counters[0]),
                    "overflowed_samples": int(counters[1]),
                    "evictions": int(counters[2]),
                    "compactions": int(counters[3]),
                })
            if anomaly is not None and "an_prof" in data:
                # bank rows remap through the same by-name id map as
                # the accumulator — a baseline never lands on a row its
                # name doesn't own in the target registry
                saved_prof = np.asarray(data["an_prof"], dtype=np.float32)
                saved_wsum = np.asarray(data["an_wsum"], dtype=np.float32)
                k, ms_rows, b = saved_prof.shape
                m = aggregator.num_metrics
                prof = np.zeros((k, m, b), dtype=np.float32)
                wsum = np.zeros((k, m), dtype=np.float32)
                for saved_id, new_id in id_remap.items():
                    if saved_id < ms_rows and new_id < m:
                        prof[:, new_id] = saved_prof[:, saved_id]
                        wsum[:, new_id] = saved_wsum[:, saved_id]
                counters = data["an_counters"]
                anomaly.load_state({
                    "prof": prof,
                    "wsum": wsum,
                    "scored_intervals": int(counters[0]),
                })
    return seq_watermark


def _restore_dense_delta(aggregator, remapped: np.ndarray) -> None:
    """Merge a remapped canonical-dense delta into a dense aggregator
    (caller holds _dev_lock).  int64 snapshots (taken mid-spill) or
    counts too large for the int32 device tensor merge into the host
    spill instead — collect() folds spill + device exactly.  The live
    accumulator's hottest cell joins the headroom check: restored
    counts never increment _interval_ingested, so successive restores
    (merging several worker checkpoints) would otherwise stack toward
    2^31 unseen by the spill trigger."""
    import jax.numpy as jnp

    live_max = int(
        jnp.max(aggregator._finalize_acc(aggregator._acc))
    )
    if (
        int(remapped.max(initial=0))
        + live_max
        + aggregator.spill_threshold
        + aggregator.batch_size
    ) >= 2**31:
        if aggregator._spill is None:
            aggregator._spill = remapped.astype(np.int64)
        else:
            aggregator._spill += remapped.astype(np.int64)
    else:
        live_cols = aggregator._acc.shape[1]
        dense = remapped.astype(np.int32)
        if live_cols != dense.shape[1]:
            # re-pad the canonical dense rows into the live
            # (lane-padded) layout
            padded = np.zeros(
                (aggregator.num_metrics, live_cols), dtype=np.int32
            )
            padded[:, :dense.shape[1]] = dense
            dense = padded
        # re-shard the host rows onto the live accumulator's layout
        # first: checkpoints save gathered host arrays, so a snapshot
        # taken on one mesh shape restores onto any other (or none)
        delta = jnp.asarray(dense)
        live_sharding = getattr(aggregator._acc, "sharding", None)
        if (
            getattr(aggregator, "mesh", None) is not None
            and live_sharding is not None
        ):
            import jax

            delta = jax.device_put(delta, live_sharding)
        aggregator._acc = aggregator._acc + delta


def _names_arr(names) -> np.ndarray:
    return np.frombuffer(
        json.dumps(list(names)).encode(), dtype=np.uint8
    ).copy()


def _arr_names(arr: np.ndarray) -> list[str]:
    return json.loads(arr.tobytes().decode())
