"""Built-in process gauges — the analog of the reference's Go runtime gauges.

The reference registers four gauges when ``sysStats`` is on
(metrics.go:172-193): ``sys.Alloc`` (heap bytes), ``sys.NumGC``,
``sys.PauseTotalNs`` and ``sys.NumGoroutine``.  The Python/TPU equivalents:

  sys.Alloc        -> current RSS bytes (/proc/self/statm)
  sys.NumGC        -> cumulative CPython gc collections (all generations)
  sys.PauseTotalNs -> cumulative wall time spent inside CPython gc passes,
                      measured via gc callbacks (closest analog of Go's
                      stop-the-world pause total)
  sys.NumGoroutine -> live thread count

Device gauges (registered by the TPU aggregator, see parallel/aggregator.py):
``tpu.HbmBytesInUse``, ``tpu.LastAggregationUs``.
"""

from __future__ import annotations

import gc
import os
import threading
import time
from typing import Callable, Dict

try:
    _PAGE_SIZE = os.sysconf("SC_PAGE_SIZE")
except (AttributeError, ValueError, OSError):
    _PAGE_SIZE = 4096


def rss_bytes() -> float:
    try:
        with open("/proc/self/statm") as f:
            return float(int(f.read().split()[1]) * _PAGE_SIZE)
    except (OSError, ValueError, IndexError):
        return 0.0


def num_gc() -> float:
    return float(sum(s["collections"] for s in gc.get_stats()))


class _GcPauseTracker:
    """Accumulates wall time spent in gc passes via gc.callbacks."""

    def __init__(self):
        self._lock = threading.Lock()
        self._total_ns = 0
        self._start_ns: int | None = None
        self._installed = False

    def _cb(self, phase: str, info: dict) -> None:
        if phase == "start":
            self._start_ns = time.perf_counter_ns()
        elif phase == "stop" and self._start_ns is not None:
            with self._lock:
                self._total_ns += time.perf_counter_ns() - self._start_ns
            self._start_ns = None

    def install(self) -> None:
        with self._lock:
            if not self._installed:
                gc.callbacks.append(self._cb)
                self._installed = True

    def total_ns(self) -> float:
        with self._lock:
            return float(self._total_ns)


_pause_tracker = _GcPauseTracker()


def pause_total_ns() -> float:
    _pause_tracker.install()
    return _pause_tracker.total_ns()


def num_threads() -> float:
    return float(threading.active_count())


def default_gauges() -> Dict[str, Callable[[], float]]:
    """The gauge set registered when sys_stats=True; names kept identical to
    the reference so dashboards and PrintBenchmark output line up."""
    _pause_tracker.install()
    return {
        "sys.Alloc": rss_bytes,
        "sys.NumGC": num_gc,
        "sys.PauseTotalNs": pause_total_ns,
        "sys.NumGoroutine": num_threads,
    }
