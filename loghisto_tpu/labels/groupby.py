"""group_by rollup results + the host-side merge oracle.

``TimeWheel.query_group_by(selector, by=["route"])`` merges every
matching labeled row into one histogram per distinct value-tuple of
the ``by`` keys, ON DEVICE: one jitted gather + segment-sum + rank
search (``ops.stats.make_group_query_fn``).  This module holds the
host-facing result type, the group-key assignment (pure string work
over canonical names), and the float64 merge oracle the parity tests
compare the device rollup against.

Merging is exact because log-bucket histograms merge by bucket-count
addition (the same property the wheel's tier promotion relies on):
no sketch error is introduced by grouping — per-group answer quality
is bounded by the bucket width alone, and an equi-depth summary of a
merged group is just its percentiles at ranks j/depth (equi-depth
boundaries ARE quantiles), which is how ``depth=`` rides the same
device dispatch as the percentile list.

jax-free except for lazy oracle imports: the result/key helpers are
importable next to the selector layer without touching an accelerator
stack.
"""

from __future__ import annotations

import dataclasses
import datetime as _dt
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

from .model import parse_canonical

GroupKey = Tuple[str, ...]


@dataclasses.dataclass
class GroupStats:
    """Result of one group_by rollup.  ``groups`` maps the value-tuple
    of the ``by`` keys (missing label -> "") to the merged stat dict
    ({"count", "sum", "avg", "p50", ..., optionally "edges"}); ``sizes``
    records how many rows merged into each group."""

    time: _dt.datetime
    window_s: float
    covered_s: float
    tier: int
    slots: int
    by: Tuple[str, ...]
    groups: Dict[GroupKey, Dict[str, object]]
    sizes: Dict[GroupKey, int]


def group_key_for(name: str, by: Sequence[str]) -> GroupKey:
    """The group a canonical name rolls into: its label values at the
    ``by`` keys, missing labels reading as "" (Prometheus semantics —
    the flat base row groups under ("", ..., "")), so group_by is total
    over every selected row."""
    labels = dict(parse_canonical(name)[1])
    return tuple(labels.get(k, "") for k in by)


def assign_groups(
    matches: Sequence[Tuple[int, str]], by: Sequence[str]
) -> Tuple[List[GroupKey], List[int]]:
    """Deterministically number the groups of ``matches``: returns
    (ordered distinct group keys, per-match group index).  Keys are
    ordered by first appearance of ascending mid, so the device gids
    and the host oracle agree without a sort."""
    keys: List[GroupKey] = []
    index: Dict[GroupKey, int] = {}
    gids: List[int] = []
    for _mid, name in matches:
        gk = group_key_for(name, by)
        gi = index.get(gk)
        if gi is None:
            gi = len(keys)
            index[gk] = gi
            keys.append(gk)
        gids.append(gi)
    return keys, gids


def equidepth_ranks(depth: int) -> Tuple[float, ...]:
    """The interior quantile ranks of an equi-depth summary: ``depth``
    equal-count bins need the ``depth - 1`` boundaries at j/depth."""
    if depth < 2:
        raise ValueError("equi-depth summaries need depth >= 2")
    return tuple(j / depth for j in range(1, depth))


def merge_groups_host(
    histograms: Mapping[str, Mapping[int, int]],
    by: Sequence[str],
    ps: Sequence[float],
    precision: int,
    value_of=None,
) -> Dict[GroupKey, Dict[str, float]]:
    """Float64 merge oracle: group the sparse per-name interval
    histograms (name -> {codec bucket: count}) by ``by``, merge bucket
    counts per group, and answer count/sum/percentiles via the host
    reference selection rule (first bucket where float64(cum)/total >=
    p, endpoints at first/last populated bucket — the same rule
    ``percentiles_sparse`` implements).  The device group_by must pick
    the SAME BUCKET for every (group, p) for dense-codec rows.

    ``value_of(buckets) -> values`` maps codec bucket indices to
    representative values; defaults to the host float64 decompress.
    Parity tests pass the device's own float32 rep table
    (``lambda b: np.asarray(bucket_representatives(bl, prec))[b + bl]``)
    so bucket-identical selection becomes bit-identical float equality.
    """
    import numpy as np

    from loghisto_tpu.ops.codec import decompress_np

    if value_of is None:
        value_of = lambda b: decompress_np(b, precision)  # noqa: E731

    merged: Dict[GroupKey, Dict[int, int]] = {}
    for name, buckets in histograms.items():
        gk = group_key_for(name, by)
        dst = merged.setdefault(gk, {})
        for b, c in buckets.items():
            dst[b] = dst.get(b, 0) + c
    ps_arr = np.asarray(ps, dtype=np.float64)
    out: Dict[GroupKey, Dict[str, float]] = {}
    for gk, buckets in merged.items():
        if not buckets:
            continue
        barr = np.asarray(sorted(buckets.keys()), dtype=np.int64)
        carr = np.asarray(
            [buckets[int(b)] for b in barr], dtype=np.int64
        )
        total_count = int(carr.sum())
        if total_count == 0:
            continue
        values = np.asarray(value_of(barr), dtype=np.float64)
        total_sum = float(np.dot(values, carr.astype(np.float64)))
        cdf = np.cumsum(carr)
        cdfn = cdf.astype(np.float64) / float(total_count)
        pos = np.minimum(
            np.searchsorted(cdfn, ps_arr, side="left"), len(barr) - 1
        )
        idx = np.where(
            ps_arr <= 0, 0, np.where(ps_arr >= 1, len(barr) - 1, pos)
        )
        entry: Dict[str, float] = {
            "count": float(total_count),
            "sum": total_sum,
            "avg": total_sum / total_count,
        }
        for p, v in zip(ps, values[idx]):
            entry[_pct_key(float(p))] = float(v)
        out[gk] = entry
    return out


def _pct_key(q: float) -> str:
    # local copy of window.store.pct_key to keep this module import-light
    s = f"{q * 100:.4f}".rstrip("0").rstrip(".")
    return f"p{s}"
