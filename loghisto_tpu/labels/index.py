"""Host-side inverted label index over the metric registry.

Maps ``label key=value`` -> row-id set and ``base name`` -> row-id set
so a selector query (``http.latency{route=/api,code=~5..}``) compiles
down to the id list the existing sparse-gather query path already
consumes — the device never learns labels exist.

Generation keying mirrors the wheel's glob cache exactly (the cache
this subsystem was modelled on — see ``TimeWheel._resolve_glob``): the
index is valid for one ``(registry.generation, high_water)`` pair.

  * same generation, grown high water  -> incremental TAIL SCAN of the
    new rows (pure appends never change existing ids, per the registry
    contract), so steady-state label-set creation costs O(new rows),
    not O(live rows);
  * generation bump (evict / free-slot reuse / compaction)  -> full
    rebuild + selector-cache flush.  This is the stale-id safety
    property the churn tests pin: an id resolved under generation g is
    NEVER served once the registry moves past g.

Serving hot path: ``select`` first tries a LOCK-FREE cache probe — it
reads ``(generation, len(registry))`` (two O(1) reads, no name-table
copy, no index lock) and returns the cached id tuple when both the
cache entry and the index were built at exactly that version.  Under
the sustained-QPS benchmark this is what keeps 8+ serving threads from
convoying on one mutex while the commit thread appends rows: misses
serialize on the lock, but every repeat selector between two registry
changes is a dictionary probe.  jax-free by design.
"""

from __future__ import annotations

import fnmatch
import threading
from typing import Dict, List, Optional, Set, Tuple, Union

from .model import parse_canonical
from .selector import Selector, parse_selector

# one resolved selector: ((rgen, hw, max_id) it was computed at, matches)
_CacheEntry = Tuple[Tuple[int, int, Optional[int]], Tuple[Tuple[int, str], ...]]

_SEL_CACHE_CAP = 256


class LabelIndex:
    """Inverted index: base -> ids, (key, value) -> ids, id -> parsed
    labels.  One instance per registry; shared by the wheel's query
    path, ``query_group_by``, and the ``labels.*`` gauges."""

    def __init__(self, registry) -> None:
        self.registry = registry
        self._lock = threading.Lock()
        # version the structures below were built at; None = never built
        self._gen: Optional[Tuple[int, int]] = None
        self._rows: Dict[int, Tuple[str, str, Dict[str, str]]] = {}
        self._by_base: Dict[str, Set[int]] = {}
        self._by_label: Dict[Tuple[str, str], Set[int]] = {}
        self._sel_cache: Dict[str, _CacheEntry] = {}
        # self-metrics (read by the labels.* gauges and debug_dump)
        self.sel_cache_hits = 0
        self.sel_cache_misses = 0
        self.rebuilds = 0
        self.tail_scans = 0

    # ------------------------------------------------------------------
    # build / refresh

    def _current_version(self) -> Tuple[int, int]:
        """O(1), lock-free read of (generation, high_water).  Re-reads
        the generation to guard the torn case where an evict lands
        between the two loads — a torn pair could otherwise validate a
        cache entry built pre-evict against a post-evict high water."""
        reg = self.registry
        while True:
            g0 = reg.generation
            hw = len(reg)
            if reg.generation == g0:
                return (g0, hw)

    def _index_row(self, mid: int, name: str) -> None:
        base, pairs = parse_canonical(name)
        labels = dict(pairs)
        self._rows[mid] = (name, base, labels)
        self._by_base.setdefault(base, set()).add(mid)
        for kv in pairs:
            self._by_label.setdefault(kv, set()).add(mid)

    def _refresh_locked(self) -> Tuple[int, int]:
        """Bring the index up to the registry's current version (caller
        holds ``self._lock``).  Returns the version indexed."""
        reg = self.registry
        while True:
            g0 = reg.generation
            names = reg.names()  # consistent copy under registry lock
            if reg.generation == g0:
                break
        gen = (g0, len(names))
        if self._gen == gen:
            return gen
        if self._gen is not None and self._gen[0] == gen[0] \
                and gen[1] >= self._gen[1]:
            # pure appends since last refresh: scan only the new tail
            self.tail_scans += 1
            for mid in range(self._gen[1], gen[1]):
                name = names[mid]
                if name is not None:
                    self._index_row(mid, name)
        else:
            # generation bump: every cached id is suspect — rebuild
            self.rebuilds += 1
            self._rows.clear()
            self._by_base.clear()
            self._by_label.clear()
            self._sel_cache.clear()
            for mid, name in enumerate(names):
                if name is not None:
                    self._index_row(mid, name)
        self._gen = gen
        return gen

    # ------------------------------------------------------------------
    # query

    def select(
        self,
        selector: Union[str, Selector],
        max_id: Optional[int] = None,
    ) -> Tuple[Tuple[int, int], Tuple[Tuple[int, str], ...]]:
        """Resolve a selector to ``(version, ((mid, name), ...))`` with
        mids ascending.  ``version`` is the (generation, high_water)
        pair the answer is valid for — result caches key on it the same
        way they key on the glob cache's generation.  ``max_id`` bounds
        ids to a consumer's row space (the wheel passes its
        ``num_metrics``)."""
        sel = parse_selector(selector) if isinstance(selector, str) \
            else selector
        ckey = sel.text
        ver = self._current_version()
        want = (ver[0], ver[1], max_id)
        # lock-free fast path: entry AND index both at the live version
        ent = self._sel_cache.get(ckey)
        if ent is not None and ent[0] == want \
                and self._gen == (want[0], want[1]):
            self.sel_cache_hits += 1
            return (want[0], want[1]), ent[1]
        with self._lock:
            gen = self._refresh_locked()
            want = (gen[0], gen[1], max_id)
            ent = self._sel_cache.get(ckey)
            if ent is not None and ent[0] == want:
                self.sel_cache_hits += 1
                return gen, ent[1]
            self.sel_cache_misses += 1
            matches = self._select_locked(sel, max_id)
            if len(self._sel_cache) >= _SEL_CACHE_CAP:
                self._sel_cache.clear()
            self._sel_cache[ckey] = (want, matches)
            return gen, matches

    def _select_locked(
        self, sel: Selector, max_id: Optional[int]
    ) -> Tuple[Tuple[int, str], ...]:
        # candidate narrowing: postings for exact k=v clauses (rows
        # missing the label can't match a non-empty exact value), then
        # the base posting(s); full matcher evaluation runs only over
        # the narrowed set.
        candidates: Optional[Set[int]] = None
        for m in sel.exact_matchers():
            posting = self._by_label.get((m.key, m.value), set())
            candidates = posting if candidates is None \
                else candidates & posting
            if not candidates:
                return ()
        if sel.base_is_glob:
            base_ids: Set[int] = set()
            for base, ids in self._by_base.items():
                if fnmatch.fnmatchcase(base, sel.base):
                    base_ids |= ids
        else:
            base_ids = self._by_base.get(sel.base, set())
        candidates = base_ids if candidates is None \
            else candidates & base_ids
        out: List[Tuple[int, str]] = []
        for mid in candidates:
            if max_id is not None and mid >= max_id:
                continue
            name, _base, labels = self._rows[mid]
            if sel.match_labels(labels):
                out.append((mid, name))
        out.sort()
        return tuple(out)

    # ------------------------------------------------------------------
    # introspection

    def stats(self) -> Dict[str, object]:
        with self._lock:
            self._refresh_locked()
            labeled = sum(
                1 for (_n, _b, labels) in self._rows.values() if labels
            )
            return {
                "generation": self._gen,
                "rows": len(self._rows),
                "labeled_rows": labeled,
                "bases": len(self._by_base),
                "postings": len(self._by_label),
                "posting_ids": sum(
                    len(s) for s in self._by_label.values()
                ),
                "selector_cache_entries": len(self._sel_cache),
                "selector_cache_hits": self.sel_cache_hits,
                "selector_cache_misses": self.sel_cache_misses,
                "rebuilds": self.rebuilds,
                "tail_scans": self.tail_scans,
            }

    def cardinality_by_prefix(self) -> Dict[str, int]:
        """Live label-set count per first-dot prefix of the base name —
        the operator's view of which subsystem is exploding (the same
        prefix grain the lifecycle budgets use)."""
        with self._lock:
            self._refresh_locked()
            out: Dict[str, int] = {}
            for (_name, base, labels) in self._rows.values():
                if not labels:
                    continue
                prefix = base.split(".", 1)[0]
                out[prefix] = out.get(prefix, 0) + 1
            return dict(sorted(out.items()))

    def register_gauges(self, ms) -> None:
        """Publish the labels.* self-metrics on a MetricSystem."""
        ms.register_gauge_func(
            "labels.LiveLabelSets",
            lambda: self.stats()["labeled_rows"],
        )
        ms.register_gauge_func(
            "labels.IndexPostings",
            lambda: self.stats()["posting_ids"],
        )
        ms.register_gauge_func(
            "labels.SelectorCacheHits", lambda: self.sel_cache_hits
        )
        ms.register_gauge_func(
            "labels.SelectorCacheMisses", lambda: self.sel_cache_misses
        )
        ms.register_gauge_func(
            "labels.IndexRebuilds", lambda: self.rebuilds
        )
