"""Label data model: canonical encoding of dimensioned metric names.

A labeled metric (``http.latency{route=/api,code=500}``) is NOT a new
storage concept — it is exactly one registry row under a canonical flat
encoding:

    http.latency;code=500;route=/api

Keys are sorted, so every permutation of the same label set produces
the SAME canonical name — one registry row, one device histogram row,
one federation dictionary entry.  Everything underneath the name layer
(staged ingest, fused commit, paged storage, lifecycle folds,
checkpoints, the wire dictionary) already operates on opaque flat
names and therefore works on labeled metrics unchanged; the entire
label subsystem lives host-side, above the registry.

Grammar (validated at record time, the only place a label set enters
the system):

  * base name — any non-empty string without ``;`` (the pair
    separator), ``{``/``}`` (reserved for selector syntax), or
    newlines.
  * label key — ``[A-Za-z_][A-Za-z0-9_.]*`` (Prometheus-style, dots
    allowed; exporters sanitize per their own grammar).
  * label value — any string (including empty) free of the structural
    characters ``; = , { } "`` and whitespace/newlines, so canonical
    names survive every wire format in the tree (graphite tagged
    series, OpenTSDB tag maps, the federation name dictionary) without
    escaping.

This module is deliberately jax-free: the federation emitter
canonicalizes labels at record time in processes that must never
import an accelerator stack (tests pin the emitter's import graph).
"""

from __future__ import annotations

import functools
import re
from typing import Dict, Mapping, Optional, Tuple

LABEL_SEP = ";"

_KEY_RE = re.compile(r"[A-Za-z_][A-Za-z0-9_.]*\Z")
# structural characters no canonical value may carry (selector syntax,
# pair separators, exposition quoting, whitespace of any kind)
_BAD_VALUE_RE = re.compile(r"[;=,{}\"\s]")

# suffixes the processing layer appends AFTER the label tail
# (metrics.py naming scheme: <name>_count/_sum/_avg, lifetime _agg_*,
# counter _rate, percentile labels <name>_<NN>).  Longest first so
# ``_agg_count`` never half-matches as ``_count``.
_PROCESSED_SUFFIXES = (
    "_agg_count", "_agg_avg", "_agg_sum", "_count", "_rate", "_avg",
    "_sum", "_min", "_max",
)
_QUANTILE_TAIL_RE = re.compile(r"_(\d+(?:\.\d+)?)\Z")


class LabelError(ValueError):
    """A name or label set that violates the canonical grammar."""


@functools.lru_cache(maxsize=65536)
def _checked_pair(key: str, value: str) -> str:
    """Validate one (key, value) pair and return its ``;k=v`` fragment.
    Cached: hot ingest paths re-send the same few pairs forever."""
    if not _KEY_RE.match(key):
        raise LabelError(
            f"invalid label key {key!r}: keys must match "
            "[A-Za-z_][A-Za-z0-9_.]*"
        )
    if _BAD_VALUE_RE.search(value):
        raise LabelError(
            f"invalid label value {value!r} for key {key!r}: values may "
            "not contain ';', '=', ',', '{', '}', '\"', or whitespace"
        )
    return f"{LABEL_SEP}{key}={value}"


def check_base_name(name: str) -> str:
    if not name or LABEL_SEP in name or "{" in name or "}" in name \
            or "\n" in name:
        raise LabelError(
            f"invalid metric base name {name!r}: must be non-empty and "
            "free of ';', '{', '}', and newlines"
        )
    return name


def canonical_name(name: str, labels: Optional[Mapping[str, str]]) -> str:
    """``("http.latency", {"route": "/api", "code": "500"})`` ->
    ``"http.latency;code=500;route=/api"``.  Sorted keys make the
    encoding canonical: every insertion order of the same label set is
    ONE registry row.  ``labels`` empty/None returns the flat name
    unchanged (a labeled API call with no labels IS the flat metric)."""
    if not labels:
        return name
    check_base_name(name)
    items = sorted(labels.items())
    return name + "".join(
        _checked_pair(k, str(v)) for k, v in items
    )


class LabelSet:
    """An interned, sorted label set.  Equality/hash are by canonical
    encoding, so two LabelSets built from permuted dicts are the same
    object key.  Use ``canonical_name`` directly on hot paths — this
    class exists for callers that hold a label set as a value."""

    __slots__ = ("pairs", "_encoded")

    def __init__(self, labels: Optional[Mapping[str, str]] = None):
        items = sorted((labels or {}).items())
        self.pairs: Tuple[Tuple[str, str], ...] = tuple(
            (k, str(v)) for k, v in items
        )
        self._encoded = "".join(
            _checked_pair(k, v) for k, v in self.pairs
        )

    def encode(self) -> str:
        """The ``;k=v;k2=v2`` canonical tail ('' for the empty set)."""
        return self._encoded

    def apply(self, base: str) -> str:
        """The full canonical name for this set under ``base``."""
        check_base_name(base)
        return base + self._encoded

    def as_dict(self) -> Dict[str, str]:
        return dict(self.pairs)

    def __eq__(self, other) -> bool:
        return isinstance(other, LabelSet) and self.pairs == other.pairs

    def __hash__(self) -> int:
        return hash(self.pairs)

    def __repr__(self) -> str:
        return f"LabelSet({dict(self.pairs)!r})"


def is_labeled(name: str) -> bool:
    """True when ``name`` is a canonical labeled name."""
    return LABEL_SEP in name


@functools.lru_cache(maxsize=65536)
def parse_canonical(
    name: str,
) -> Tuple[str, Tuple[Tuple[str, str], ...]]:
    """Canonical name -> ``(base, ((key, value), ...))``.  A flat name
    returns ``(name, ())``.  Tolerant of foreign names that merely
    contain ``;`` without forming valid pairs: those parse as a flat
    name (the label layer must never make an unlabeled registry row
    unqueryable).  Cached — the inverted index and exporters re-parse
    the same live names every generation."""
    if LABEL_SEP not in name:
        return name, ()
    base, _, tail = name.partition(LABEL_SEP)
    pairs = []
    for frag in tail.split(LABEL_SEP):
        key, eq, value = frag.partition("=")
        if not eq or not _KEY_RE.match(key):
            return name, ()  # not a canonical tail; treat as flat
        pairs.append((key, value))
    return base, tuple(pairs)


def labels_of(name: str) -> Dict[str, str]:
    """The label mapping of a canonical name ({} for flat names)."""
    return dict(parse_canonical(name)[1])


def base_of(name: str) -> str:
    """The base (unlabeled) metric name of a canonical name."""
    return parse_canonical(name)[0]


def split_processed(
    name: str,
) -> Optional[Tuple[str, Tuple[Tuple[str, str], ...], str]]:
    """Parse a PROCESSED metric name that carries a label tail:
    ``http.latency;code=200;route=/api_99`` ->
    ``("http.latency", (("code","200"),("route","/api")), "_99")``.

    The processing layer appends its suffix (``_count``, ``_99``, ...)
    AFTER the canonical tail, so the suffix rides the last label value;
    this is the one place that seam is undone, shared by every exporter
    (Prometheus exposition, graphite tagged series, OpenTSDB tag maps).
    Known suffixes are matched longest-first; a purely numeric ``_NN``
    tail is treated as a percentile suffix.  Returns None when ``name``
    has no label separator or its tail is not canonical.  Limitation
    (documented): a label value that itself ends in a known suffix
    (e.g. ``stage=pre_count``) is mis-split — don't name values after
    the processing suffixes.
    """
    if LABEL_SEP not in name:
        return None
    suffix = ""
    body = name
    for s in _PROCESSED_SUFFIXES:
        if body.endswith(s):
            suffix = s
            body = body[: -len(s)]
            break
    else:
        m = _QUANTILE_TAIL_RE.search(body)
        if m:
            suffix = m.group(0)
            body = body[: m.start()]
    base, pairs = parse_canonical(body)
    if not pairs:
        return None
    return base, pairs, suffix
