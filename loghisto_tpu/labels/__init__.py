"""Label/tag data model over the flat registry (ISSUE 16).

A labeled metric is one registry row under the canonical flat encoding
``name;k1=v1;k2=v2`` (sorted keys) — ingest, fused commit, paged
storage, lifecycle, checkpoints, and the federation dictionary all
work unchanged underneath.  This package is the host-side layer on
top: canonical encoding (``model``), the selector query language
(``selector``), the generation-keyed inverted index that compiles
selectors to row ids (``index``), and group_by rollup plumbing
(``groupby``; imported lazily by consumers that need it — its oracle
helpers reach into ops/stats).

Everything exported here is jax-free, so the federation emitter can
canonicalize labels at record time without an accelerator stack.
"""

from .model import (
    LabelError,
    LabelSet,
    base_of,
    canonical_name,
    is_labeled,
    labels_of,
    parse_canonical,
    split_processed,
)
from .selector import (
    Matcher,
    Selector,
    SelectorError,
    is_selector,
    parse_selector,
)
from .index import LabelIndex

__all__ = [
    "LabelError",
    "LabelSet",
    "base_of",
    "canonical_name",
    "is_labeled",
    "labels_of",
    "parse_canonical",
    "split_processed",
    "Matcher",
    "Selector",
    "SelectorError",
    "is_selector",
    "parse_selector",
    "LabelIndex",
]
