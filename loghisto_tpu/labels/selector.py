"""Selector query language over canonical labeled names.

Syntax (PromQL-flavoured, minus the parts the registry can't answer):

    http.latency{route=/api,code=~5..}
    http.*{region!=eu,az!~us-(east|west).*}
    http.latency{}            # every label set of the base (and the
                              # flat base row itself, if registered)
    http.latency              # no braces: plain glob, handled by the
                              # wheel's existing fnmatch path

  * base — a literal base name or an fnmatch glob over base names
    (``*``/``?``/``[...]``, same dialect as the wheel's query globs).
  * matcher ops — ``=`` exact, ``!=`` negated exact, ``=~`` regex
    (fullmatch), ``!~`` negated regex.
  * values — bare tokens up to the next ``,``/``}``, or quoted
    ``"..."`` with ``\\"`` and ``\\\\`` escapes for values/regexes that
    need a comma or brace.

Missing-label semantics follow Prometheus: a row without label ``k``
behaves as ``k=""``.  So ``{code!=500}`` matches rows that have no
``code`` label at all, and ``{code=~".+"}`` is the idiom for "has a
code label".  This keeps selector algebra closed under negation and
means the flat base row participates in ``base{}`` queries.

Matching is pure host-side string work on canonical names — the
compiled form is consumed by ``labels.index.LabelIndex`` which turns a
selector into a row-id list for the existing sparse-gather query path.
jax-free by design (the federation emitter's import graph is pinned).
"""

from __future__ import annotations

import dataclasses
import fnmatch
import functools
import re
from typing import Dict, Mapping, Optional, Tuple

from .model import LabelError, parse_canonical

# longest-first so "!=" never lexes as "!" + "="
_OPS = ("=~", "!=", "!~", "=")


class SelectorError(ValueError):
    """A selector string that does not parse."""


@dataclasses.dataclass(frozen=True)
class Matcher:
    """One ``key <op> value`` clause.  For regex ops, ``pattern`` holds
    the compiled regex (fullmatch semantics, like PromQL)."""

    key: str
    op: str  # "=", "!=", "=~", "!~"
    value: str
    pattern: Optional[re.Pattern] = None

    def match(self, got: str) -> bool:
        if self.op == "=":
            return got == self.value
        if self.op == "!=":
            return got != self.value
        hit = self.pattern.fullmatch(got) is not None
        return hit if self.op == "=~" else not hit


@dataclasses.dataclass(frozen=True)
class Selector:
    """A parsed selector: base pattern + matcher clauses.

    ``base_is_glob`` is True when the base contains fnmatch
    metacharacters; the index falls back to scanning base names then.
    """

    text: str
    base: str
    matchers: Tuple[Matcher, ...]

    @property
    def base_is_glob(self) -> bool:
        return any(c in self.base for c in "*?[")

    def match_base(self, base: str) -> bool:
        if self.base_is_glob:
            return fnmatch.fnmatchcase(base, self.base)
        return base == self.base

    def match_labels(self, labels: Mapping[str, str]) -> bool:
        """Prometheus semantics: a missing label reads as ''."""
        for m in self.matchers:
            if not m.match(labels.get(m.key, "")):
                return False
        return True

    def match_name(self, name: str) -> bool:
        """Test a canonical (or flat) registry name directly — the
        oracle the inverted index must agree with, and the predicate
        the locked recompute path uses when no snapshot is live."""
        base, pairs = parse_canonical(name)
        return self.match_base(base) and self.match_labels(dict(pairs))

    def exact_matchers(self) -> Tuple[Matcher, ...]:
        """The ``k=v`` clauses with non-empty values — the ones the
        inverted index can answer from postings (``k=""`` means "label
        absent", which postings don't carry)."""
        return tuple(
            m for m in self.matchers if m.op == "=" and m.value != ""
        )


def is_selector(pattern: str) -> bool:
    """True when ``pattern`` uses selector syntax (brace block) rather
    than the wheel's plain name-glob syntax."""
    return "{" in pattern


def _lex_value(s: str, i: int) -> Tuple[str, int]:
    """Read one matcher value starting at ``i``; returns (value, next).
    Quoted values may contain anything (with backslash escapes); bare
    values run to the next ``,`` or ``}``."""
    if i < len(s) and s[i] == '"':
        out = []
        i += 1
        while i < len(s):
            c = s[i]
            if c == "\\" and i + 1 < len(s):
                out.append(s[i + 1])
                i += 2
                continue
            if c == '"':
                return "".join(out), i + 1
            out.append(c)
            i += 1
        raise SelectorError("unterminated quoted value")
    j = i
    while j < len(s) and s[j] not in ",}":
        j += 1
    return s[i:j].strip(), j


@functools.lru_cache(maxsize=4096)
def parse_selector(text: str) -> Selector:
    """Parse ``base{m1,m2,...}`` into a Selector.  Cached — serving
    threads re-issue the same few dashboard selectors at QPS."""
    brace = text.find("{")
    if brace < 0:
        raise SelectorError(
            f"not a selector (no '{{' in {text!r}); plain globs take "
            "the wheel's fnmatch path"
        )
    if not text.endswith("}"):
        raise SelectorError(f"selector {text!r} must end with '}}'")
    base = text[:brace].strip()
    if not base:
        raise SelectorError(f"selector {text!r} has an empty base name")
    if ";" in base or "}" in base:
        raise SelectorError(f"invalid base {base!r} in selector")
    body = text[brace + 1 : -1]
    matchers = []
    i = 0
    n = len(body)
    while i < n:
        while i < n and body[i] in ", \t":
            i += 1
        if i >= n:
            break
        # key
        j = i
        while j < n and (body[j].isalnum() or body[j] in "_."):
            j += 1
        key = body[i:j]
        if not key:
            raise SelectorError(
                f"expected label key at offset {i} in {text!r}"
            )
        while j < n and body[j] in " \t":
            j += 1
        for op in _OPS:
            if body.startswith(op, j):
                j += len(op)
                break
        else:
            raise SelectorError(
                f"expected one of =, !=, =~, !~ after {key!r} in {text!r}"
            )
        while j < n and body[j] in " \t":
            j += 1
        value, j = _lex_value(body, j)
        pattern = None
        if op in ("=~", "!~"):
            try:
                pattern = re.compile(value)
            except re.error as e:
                raise SelectorError(
                    f"bad regex {value!r} in {text!r}: {e}"
                ) from e
        matchers.append(Matcher(key, op, value, pattern))
        i = j
    try:
        sel = Selector(text=text, base=base, matchers=tuple(matchers))
    except LabelError as e:  # pragma: no cover - defensive
        raise SelectorError(str(e)) from e
    return sel
