"""Configuration for the distribution drift engine.

Pure host-side dataclass, mirroring ``lifecycle.policy.LifecycleConfig``:
all device behavior (bank shapes, decay, floors, dispatch tier) is
parameterized here and validated at construction, so a bad knob fails at
``TPUMetricSystem(anomaly=...)`` time, not intervals later on the bridge
thread.
"""

from __future__ import annotations

import datetime as _dt
from dataclasses import dataclass
from typing import Callable, Optional


def hourly_bank(t: _dt.datetime) -> int:
    """Example ``bank_of`` for seasonal traffic: one baseline per UTC
    hour of day (use with ``banks=24``)."""
    return t.hour


@dataclass(frozen=True)
class AnomalyConfig:
    """Knobs for the drift engine.

    banks         — number of EWMA baseline profiles kept per metric row
                    (1 = a single global baseline; 24 + ``bank_of=
                    hourly_bank`` = per-hour seasonal baselines)
    bank_of       — interval time -> bank index (clamped mod ``banks``);
                    None always uses bank 0
    decay         — EWMA retain factor in [0, 1): baseline_{t+1} =
                    decay * baseline_t + (1-decay) * interval_pmf.  0.9
                    means an interval's shape decays to ~35% influence
                    after 10 intervals
    min_samples   — rows with fewer interval samples neither update
                    their baseline nor score (the min-sample floor: a
                    quiet metric must not page on noise)
    check_every   — score every N committed intervals (1 = every
                    interval; scoring is one fused dispatch either way)
    tier          — retention tier whose snapshot views feed scoring
    window        — trailing window (seconds) to score against; None
                    scores the tier's full covered span.  The manager
                    pins it so the commit path materializes the view
    divergence_path — "auto" | "jnp" | "pallas" scoring kernel tier
                    (auto: Pallas only single-device on real TPU)
    export_glob   — metrics matching this glob export per-metric
                    ``anomaly.<name>.{ks,jsd,emd}`` gauges (None
                    disables per-metric gauges; the family counters
                    always export)
    max_export    — cap on per-metric gauge registrations (gauge
                    funcs are never unregistered, so unbounded export
                    under name churn would leak)
    """

    banks: int = 1
    bank_of: Optional[Callable[[_dt.datetime], int]] = None
    decay: float = 0.9
    min_samples: int = 64
    check_every: int = 1
    tier: int = 0
    window: Optional[float] = None
    divergence_path: str = "auto"
    export_glob: Optional[str] = "*"
    max_export: int = 256

    def __post_init__(self):
        if self.banks < 1:
            raise ValueError("banks must be >= 1")
        if not 0.0 <= self.decay < 1.0:
            raise ValueError("decay must be in [0, 1)")
        if self.min_samples < 1:
            # 0 would let the all-zero warmup histogram "update" the
            # baseline toward an empty profile
            raise ValueError("min_samples must be >= 1")
        if self.check_every < 1:
            raise ValueError("check_every must be >= 1")
        if self.tier < 0:
            raise ValueError("tier must be >= 0")
        if self.window is not None and self.window <= 0:
            raise ValueError("window must be positive")
        if self.max_export < 0:
            raise ValueError("max_export must be >= 0")
