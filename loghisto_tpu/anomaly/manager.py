"""AnomalyManager: the runtime that owns the EWMA baseline banks, runs
the per-interval divergence scoring, and serves drift scores to rules
and exporters.

Threading model (same as ``lifecycle.LifecycleManager``): the manager
piggybacks on the IntervalCommitter's bridge thread.  The committer
threads the donated carries — the interval histogram ``ihist`` and the
baseline banks ``(prof, wsum)`` — through the fused commit programs
(``ensure_capacity_locked`` / ``store_carry_locked``, called with the
aggregator's ``_dev_lock`` held, like the activity vector they sit
beside), then calls ``on_interval()`` with no locks held BEFORE the
wheel's hooks run, so ``distribution_drift`` rules evaluate against the
interval that just landed.  Scoring reads the wheel's published
snapshot handle (immutable, never donated) and the bank carries, and
runs ONE jitted dispatch (``ops.anomaly.make_divergence_fn``) — the
drift engine's entire per-interval device cost beyond the fused commit
the banks already ride.

Score serving is generation-keyed, mirroring the query engine's dead-id
contract: ``scores_for(name)`` resolves the name through the registry
and returns None when the registry generation moved since the scores
were computed (eviction, slot reuse, compaction) or the name's id has
no scored row — a dead or reused id can never serve a stale series'
drift score (tests/test_anomaly.py pins this).

Lifecycle integration: the LifecycleManager calls
``on_evicted_locked`` / ``apply_permutation_locked`` inside its device
critical sections so bank rows are zeroed with their victims and follow
their survivors through compaction; a freed row's next tenant always
starts with a cold baseline.
"""

from __future__ import annotations

import fnmatch
import logging
import threading
from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from loghisto_tpu.anomaly.config import AnomalyConfig
from loghisto_tpu.obs.spans import NULL_RECORDER
from loghisto_tpu.ops.anomaly import (
    make_bank_compact_fn,
    make_bank_evict_fn,
    make_divergence_fn,
    resolve_divergence_path,
)
from loghisto_tpu.parallel.mesh import (
    acc_sharding,
    bank_weight_sharding,
    ring_sharding,
)

logger = logging.getLogger("loghisto_tpu")

SCORE_KEYS = ("ks", "jsd", "emd")


class AnomalyManager:
    """Drift-engine runtime for a (TPUAggregator, TimeWheel) pair.
    Built by TPUMetricSystem when ``anomaly=AnomalyConfig(...)`` is
    passed; standalone construction is supported for tests."""

    def __init__(
        self,
        aggregator,
        wheel,
        config: AnomalyConfig,
        metric_system=None,
    ):
        if wheel is None:
            raise ValueError(
                "the drift engine needs a retention wheel: baselines "
                "ride the fused interval commit and scoring consumes "
                "the commit-time snapshot CDFs"
            )
        if not wheel.snapshots_enabled:
            raise ValueError(
                "the drift engine needs commit-time snapshots "
                "(TimeWheel snapshots=True): scoring consumes the "
                "published window CDF views"
            )
        if config.tier >= len(wheel._tiers):
            raise ValueError(
                f"anomaly tier {config.tier} out of range "
                f"({len(wheel._tiers)} tiers)"
            )
        self.aggregator = aggregator
        self.wheel = wheel
        self.config = config
        self.metric_system = metric_system
        platform = jax.default_backend()
        self.divergence_path = resolve_divergence_path(
            config.divergence_path, platform, aggregator.mesh is not None
        )
        self._div = make_divergence_fn(self.divergence_path)
        self._evict = make_bank_evict_fn()
        self._compact = make_bank_compact_fn()
        if config.window is not None:
            # materialize the scoring window as a snapshot view so each
            # pass gathers a commit-time CDF instead of recomputing
            wheel.pin_window(config.window)

        # donated device carries, guarded by aggregator._dev_lock like
        # the accumulator/activity vector they commit beside.  Under a
        # mesh each carry is metric-row-sharded in the layout the
        # sharded fused commit requires (parallel/mesh.py helpers)
        mesh = aggregator.mesh
        self._ihist_sharding = acc_sharding(mesh) if mesh is not None else None
        self._prof_sharding = ring_sharding(mesh) if mesh is not None else None
        self._wsum_sharding = (
            bank_weight_sharding(mesh) if mesh is not None else None
        )
        self._prof: Optional[jnp.ndarray] = None   # f32 [K, M, B]
        self._wsum: Optional[jnp.ndarray] = None   # f32 [K, M]
        self._ihist: Optional[jnp.ndarray] = None  # int32 [M, B]

        # latest host scores + the registry generation they were
        # computed under (the staleness key for dead/reused ids)
        self._scores_lock = threading.Lock()
        self._scores: Optional[Dict[str, np.ndarray]] = None
        self._scores_gen = -1
        self._scores_epoch = -1

        self._intervals_seen = 0
        self.scored_intervals = 0
        self.skipped_intervals = 0  # no snapshot / no baselines yet

        # lazy per-metric gauge export (anomaly.<name>.{ks,jsd,emd})
        self._export_key = None  # (generation, registry high-water)
        self._exported: set = set()

        # observability (ISSUE 9): scoring-cadence spans; swapped for a
        # real ring by TPUMetricSystem(observability=...)
        self.obs_recorder = NULL_RECORDER

    # -- traced scalar operands for the fused programs ------------------- #

    @property
    def decay32(self) -> np.float32:
        return np.float32(self.config.decay)

    @property
    def min_count32(self) -> np.int32:
        return np.int32(self.config.min_samples)

    def bank_for(self, t) -> np.int32:
        """Active bank index for an interval timestamp (datetime or
        None).  Clamped mod ``banks`` so a sloppy ``bank_of`` can never
        write out of range."""
        cfg = self.config
        if cfg.bank_of is None or t is None:
            return np.int32(0)
        try:
            return np.int32(int(cfg.bank_of(t)) % cfg.banks)
        except Exception:  # pragma: no cover - defensive
            logger.exception("anomaly bank_of failed; using bank 0")
            return np.int32(0)

    # -- carry protocol (callers hold aggregator._dev_lock) -------------- #

    def _place(self, x: jnp.ndarray, sharding) -> jnp.ndarray:
        """Pin a rebuilt/grown/restored carry to its mesh layout (no-op
        single-device).  Row growth under a mesh happens in metric-axis
        units, so the result always shards evenly."""
        return x if sharding is None else jax.device_put(x, sharding)

    def ensure_capacity_locked(self, m: int):
        """The drift carries, padded to ``m`` rows (new rows start cold:
        zero profile, zero weight — they score 0 until their baseline
        establishes).  Returns ``(ihist, (prof, wsum))`` in the fused
        programs' operand shapes."""
        k = self.config.banks
        b = self.wheel.config.num_buckets
        if self._ihist is None:
            self._ihist = self._place(
                jnp.zeros((m, b), dtype=jnp.int32), self._ihist_sharding
            )
        elif self._ihist.shape[0] < m:
            self._ihist = self._place(jnp.concatenate([
                self._ihist,
                jnp.zeros((m - self._ihist.shape[0], b), dtype=jnp.int32),
            ]), self._ihist_sharding)
        if self._prof is None:
            self._prof = self._place(
                jnp.zeros((k, m, b), dtype=jnp.float32),
                self._prof_sharding,
            )
            self._wsum = self._place(
                jnp.zeros((k, m), dtype=jnp.float32), self._wsum_sharding
            )
        elif self._prof.shape[1] < m:
            gap = m - self._prof.shape[1]
            self._prof = self._place(jnp.concatenate([
                self._prof,
                jnp.zeros((k, gap, b), dtype=jnp.float32),
            ], axis=1), self._prof_sharding)
            self._wsum = self._place(jnp.concatenate([
                self._wsum,
                jnp.zeros((k, gap), dtype=jnp.float32),
            ], axis=1), self._wsum_sharding)
        return self._ihist, (self._prof, self._wsum)

    def store_carry_locked(self, ihist, banks) -> None:
        self._ihist = ihist
        self._prof, self._wsum = banks

    def on_device_failure_locked(self) -> None:
        """A fused dispatch died mid-donation: any consumed carry is
        rebuilt cold (zeros).  Losing baselines only DELAYS detection —
        scores stay floored until the EWMA re-establishes, which is the
        safe failure direction for an alerting signal."""
        def dead(x):
            return x is not None and getattr(
                x, "is_deleted", lambda: False
            )()

        if dead(self._ihist):
            self._ihist = None
        if dead(self._prof) or dead(self._wsum):
            self._prof = None
            self._wsum = None

    # -- lifecycle integration (both device locks held) ------------------ #

    def on_evicted_locked(self, victim_ids: np.ndarray) -> None:
        """Zero the victims' bank rows (every bank) and interval
        histogram in one donated dispatch — a reused slot must build its
        baseline from scratch, never inherit the dead series' shape.
        ``victim_ids`` may be pow2-padded with DROP sentinels."""
        if self._prof is None:
            return
        self._prof, self._wsum, self._ihist = self._evict(
            self._prof, self._wsum, self._ihist, victim_ids
        )

    def apply_permutation_locked(self, perm: np.ndarray) -> None:
        """Repack the bank carries with the lifecycle's survivor
        permutation (``perm[new] = old``) so baselines follow their rows
        and freed rows come back cold."""
        if self._prof is None:
            return
        self._prof, self._wsum, self._ihist = self._compact(
            self._prof, self._wsum, self._ihist, perm
        )

    # -- scoring ---------------------------------------------------------- #

    def on_interval(self, raw) -> None:
        """Called by the committer after each committed interval (its
        thread, no locks held), BEFORE the wheel's hooks — rules see
        this interval's scores."""
        self._intervals_seen += 1
        if self._intervals_seen % self.config.check_every:
            return
        try:
            with self.obs_recorder.span("anomaly.score", raw.seq):
                self.score_now(raw.time)
        except Exception:  # pragma: no cover - defensive
            logger.exception("anomaly scoring failed")

    def _view(self, snap):
        ts = snap.tiers[self.config.tier]
        view = None
        if self.config.window is not None:
            view = ts.view_for(self.config.window)
        if view is None:
            # full covered span — always materialized as views[0]
            view = ts.views[0]
        return view

    def score_now(self, now=None) -> Optional[Dict[str, np.ndarray]]:
        """One scoring pass: live view CDF vs the active baseline bank,
        ONE fused device dispatch.  Returns the host score arrays (or
        None when there is nothing to score yet)."""
        snap = self.wheel.snapshot  # atomic ref; handle is immutable
        if snap is None:
            self.skipped_intervals += 1
            return None
        with self.aggregator._dev_lock:
            if self._prof is None:
                self.skipped_intervals += 1
                return None
            prof, wsum = self._prof, self._wsum
            gen = self.aggregator.registry.generation
        view = self._view(snap)
        bank = self.bank_for(now)
        scores = self._div(
            view.cdf, view.counts, prof, wsum, bank, self.min_count32
        )
        host = {k: np.asarray(v) for k, v in scores.items()}
        with self._scores_lock:
            self._scores = host
            self._scores_gen = gen
            self._scores_epoch = snap.epoch
            self.scored_intervals += 1
        self._refresh_export()
        return host

    def scores_for(self, name: str) -> Optional[Dict[str, float]]:
        """Latest drift scores for a metric, or None when the metric has
        no scored row.  Generation-keyed: any registry mutation that can
        change an id's meaning (eviction, reuse, compaction) invalidates
        the whole score vector, so a dead or reused id never serves a
        stale series' score."""
        reg = self.aggregator.registry
        with self._scores_lock:
            scores = self._scores
            gen = self._scores_gen
        if scores is None or reg.generation != gen:
            return None
        mid = reg.lookup(name)
        if mid is None or mid >= len(scores["ks"]):
            return None
        return {k: float(scores[k][mid]) for k in SCORE_KEYS}

    # -- checkpoint ------------------------------------------------------- #

    def state_dict(self) -> dict:
        """Host-serializable bank state for utils/checkpoint.py.  The
        interval histogram is deliberately NOT persisted: it is
        in-flight interval state, shed on restart like every other
        interval cache."""
        with self.aggregator._dev_lock:
            k = self.config.banks
            b = self.wheel.config.num_buckets
            prof = (
                np.asarray(self._prof) if self._prof is not None
                else np.zeros((k, 0, b), dtype=np.float32)
            )
            wsum = (
                np.asarray(self._wsum) if self._wsum is not None
                else np.zeros((k, 0), dtype=np.float32)
            )
        return {
            "prof": prof,
            "wsum": wsum,
            "scored_intervals": self.scored_intervals,
        }

    def load_state(self, state: dict) -> None:
        prof = np.asarray(state["prof"], dtype=np.float32)
        wsum = np.asarray(state["wsum"], dtype=np.float32)
        if prof.shape[0] != self.config.banks:
            raise ValueError(
                f"checkpoint has {prof.shape[0]} banks, config has "
                f"{self.config.banks}"
            )
        # checkpoints carry host arrays; restore re-shards onto THIS
        # manager's mesh layout, keeping checkpoints mesh-shape-portable
        with self.aggregator._dev_lock:
            if prof.shape[1]:
                self._prof = self._place(jnp.asarray(prof),
                                         self._prof_sharding)
                self._wsum = self._place(jnp.asarray(wsum),
                                         self._wsum_sharding)
        self.scored_intervals = int(state.get("scored_intervals", 0))

    # -- gauges ------------------------------------------------------------ #

    def _gauge(self, name: str, key: str) -> Callable[[], float]:
        def value() -> float:
            s = self.scores_for(name)
            return s[key] if s is not None else 0.0
        return value

    def _refresh_export(self) -> None:
        """Register ``anomaly.<metric>.{ks,jsd,emd}`` gauges for names
        matching ``export_glob`` (capped at ``max_export``).  Keyed on
        (generation, high-water) so a pass with no registry changes is
        two integer compares."""
        ms = self.metric_system
        cfg = self.config
        if ms is None or cfg.export_glob is None:
            return
        reg = self.aggregator.registry
        key = (reg.generation, len(reg))
        if key == self._export_key:
            return
        self._export_key = key
        for name in reg.names():
            if name is None or name in self._exported:
                continue
            if len(self._exported) >= cfg.max_export:
                break
            if not fnmatch.fnmatch(name, cfg.export_glob):
                continue
            self._exported.add(name)
            for k in SCORE_KEYS:
                ms.register_gauge_func(
                    f"anomaly.{name}.{k}", self._gauge(name, k)
                )

    def register_gauges(self, ms) -> None:
        """Export the drift-engine self-metric family through the normal
        gauge pipeline (same shape as commit.* / lifecycle.*)."""
        ms.register_gauge_func(
            "anomaly.ScoredIntervals",
            lambda: float(self.scored_intervals),
        )
        ms.register_gauge_func(
            "anomaly.SkippedIntervals",
            lambda: float(self.skipped_intervals),
        )
        ms.register_gauge_func(
            "anomaly.ExportedMetrics",
            lambda: float(len(self._exported)),
        )
        ms.register_gauge_func(
            "anomaly.Banks", lambda: float(self.config.banks)
        )
