"""Distribution drift engine: EWMA baseline banks maintained inside the
fused interval commit, one fused divergence dispatch per interval
(KS / JSD / bucket-space EMD), and generation-keyed score serving for
``distribution_drift`` rules and per-metric gauges.

See ``ops.anomaly`` for the device programs and ``AnomalyManager`` for
the host runtime; wired via ``TPUMetricSystem(anomaly=AnomalyConfig())``.
"""

from loghisto_tpu.anomaly.config import AnomalyConfig, hourly_bank
from loghisto_tpu.anomaly.manager import AnomalyManager

__all__ = ["AnomalyConfig", "AnomalyManager", "hourly_bank"]
