"""Automatic ingest-path selection (VERDICT r1 item 6).

Three bit-identical device accumulation kernels exist (scatter / one-hot
MXU matmul / metric-tiled Pallas multirow); they differ only in speed per
(num_metrics, num_buckets, platform) configuration.  The crossover rule in
ops/matmul_hist.py ("use when num_metrics*num_buckets <= ~2^21") is made
real here: ``TPUAggregator(ingest_path="auto")`` — the default — calls
``choose_ingest_path`` at construction (platform is known then; this is
NOT a trace-time probe).

Thresholds are provisional pending the real-TPU measurement table from
benchmarks/device_paths.py (benchmarks/tpu_watch.sh captures it); refresh
the constants below when BENCH_r02 lands.  On CPU the scatter path wins
everywhere measured (BENCH_r01 table), so auto == scatter there.
"""

from __future__ import annotations

# Dense one-hot matmul materializes an [N, B] one-hot per tile; profitable
# only while the whole [M, B] accumulator is MXU-tile sized.  Above this
# the scatter path wins (and is the only mesh-shardable formulation).
MATMUL_MAX_CELLS = 1 << 21


def choose_ingest_path(
    num_metrics: int, num_buckets: int, platform: str
) -> str:
    """Pick the measured-fastest ingest kernel for a configuration.

    The Pallas multirow kernel stays opt-in until hardware validation
    (benchmarks/pallas_parity.py) demotes or promotes it — "auto" never
    selects an unproven kernel.
    """
    if platform == "tpu" and num_metrics * num_buckets <= MATMUL_MAX_CELLS:
        return "matmul"
    return "scatter"
