"""Automatic ingest/storage/commit path selection (VERDICT r1 item 6,
unified capability table r17).

Six bit-identical device accumulation kernels exist (scatter / sort-dedup
scatter / scan-based sort-dedup ("sortscan") / one-hot MXU matmul /
Pallas row / Pallas multirow, plus the hot-row hybrid); they differ
only in speed per (num_metrics, num_buckets, platform) configuration.
``TPUAggregator(ingest_path="auto")`` — the default — calls
``choose_ingest_path`` at construction (platform is known then; this is
NOT a trace-time probe).

Thresholds come from the real-TPU measurement table captured in
TPU_CAPTURE_r2/device_paths.json (benchmarks/device_paths.py on a
v5 lite chip, batch 2^22, 8193 buckets):

    M=1:      pallas 8.2M/s > sort 6.7M > matmul 4.3M > scatter 3.4M
    M=16:     scatter 5.8M > multirow 5.0M > matmul 4.1M > sort 3.4M
    M=256:    scatter 4.8M > matmul 4.7M > sort 4.0M > multirow 3.6M
    M=10000:  sort 3.4M > scatter 2.5M > multirow 2.3M

(Absolute rates in that capture are tunnel-latency-skewed; the
within-row ranking is the signal.)  Duplicate-heavy scatters serialize
on TPU, which is why sort-dedup wins back the lead at high metric
cardinality where Zipf batches concentrate on hot rows, and why the
fused Pallas row kernel wins the single-metric case outright.  On CPU
the scatter path wins everywhere measured (BENCH_r01 table), so auto ==
scatter there.

Capability table (r17)
----------------------

Through r16 this module grew three independent contender ladders —
``fused_ingest_incapability`` (ingest), ``paged_storage_incapability``
(storage), and ``mesh_commit_incapability`` (commit) — each a
copy-pasted walk of if-return-reason checks.  The r17 direct-to-paged
fused kernel would have been a fourth.  They are now rows of ONE
``CAPABILITY_TABLE``: each (axis, contender) maps to an ordered tuple
of edges, each edge a named check returning its human-readable reason
string (or None), with policy edges (amortization crossovers — things
an explicit selection is allowed to override) flagged so
``crossover=False`` skips exactly those.  The public
``*_incapability`` functions are thin views over the table — every
pre-r17 reason string survives verbatim (tests pin them) — and
``resolve_full_path`` walks the single ``DEGRADATION_ORDER`` to
resolve a complete (transport, ingest, storage, commit) path with the
per-edge reasons of everything it declined along the way.
"""

from __future__ import annotations

import dataclasses
import json as _json
import os as _os
from typing import Callable, Dict, NamedTuple, Optional, Tuple

# Measured crossover (device_paths.json): sort-dedup overtakes plain
# scatter between M=256 and M=10000; the conservative switch point keeps
# scatter through the mid range it dominates.  Baked FALLBACK — a
# committed capture-derived table (below) overrides it.
SORT_MIN_METRICS = 4096

# Whether auto picks the fused Pallas row kernel at M=1 on TPU.  NOTE
# (ADVICE r2): the r2 capture ranked the UNMASKED no-ids row form
# (8.2M/s); the masked pallas_row_ingest_batch form auto actually
# dispatches carries an extra VMEM mask stream and has not been
# hardware-ranked yet — this default is an extrapolation until a capture
# ranks "pallasb" (analyze_capture.py flags the comparison).
PALLAS_SINGLE_METRIC = True

# Which sort-dedup formulation auto uses at high cardinality: "sort"
# (jnp.unique-based) or "sortscan" (sort + reverse min-scan, 3x on CPU,
# awaiting a hardware ranking).  Capture-overridable like the rest.
HIGH_CARDINALITY_KERNEL = "sort"

# Whether auto considers the r13 fused sample->scatter Pallas kernel
# (ops/fused_ingest.py: codec on the VPU inside the kernel, one
# dispatch, no HBM bucket-index array) at high metric cardinality on
# TPU.  It replaces the sort-dedup pick where capable; when
# fused_ingest_incapability names a blocker (mesh-embedded step, row
# tile, dtype, batch too small/unknown) auto degrades to the pre-r13
# winner.  Capture-overridable.
FUSED_INGEST = True

# Whether auto considers the r17 direct-to-paged fused kernel
# (ops/fused_ingest.fused_paged_ingest_batch: compress -> log-bucket ->
# codec-encode -> page-translate -> scatter-add straight into the
# donated page pool, ONE dispatch per batch, no dense [M, B] tensor and
# no host fold on the hot path).  Only meaningful when storage resolves
# to "paged"; when fused_paged_incapability names a blocker the paged
# path degrades to the pre-r17 two-stage route (host fold + translate +
# packed pool commit).  Capture-overridable.
FUSED_PAGED = True

# Minimum batch the fused kernel's XLA sort+layout preprocess amortizes
# over: below this the plain scatter's per-sample random access is
# cheaper than sorting the batch and padding block segments to
# SAMPLE_TILE boundaries.  Baked FALLBACK from the r13 CPU-host
# calibration sweep (benchmarks/fused_ingest_bench.py, FUSED_INGEST_r13
# "crossover" section); a hardware capture retunes it via the committed
# JSON like every other threshold.
FUSED_MIN_BATCH = 1 << 17

# Per-platform measured crossover overrides for FUSED_MIN_BATCH
# (r17 satellite): the r13 CPU-interpret sweep is NOT trustworthy for
# the TPU default, so calibration writes a platform-scoped entry
# ("fused_min_batch_by_platform": {"cpu": ..., "tpu": ...}) and the
# capability check consults the running platform's entry, falling back
# to the baked FUSED_MIN_BATCH when the platform was never measured.
FUSED_MIN_BATCH_BY_PLATFORM: Dict[str, int] = {}

# Metric rows per fused-kernel accumulator block; mirrored from
# fused_ingest.ROWS_TILE without importing jax (this module must stay
# importable without jax — analyze_capture.py depends on that).
FUSED_ROWS_TILE = 8

# Dense one-hot matmul materializes an [N, B] one-hot per tile; the r2
# table shows it never beating scatter on hardware at >=16 metrics, and
# losing to the Pallas row kernel at M=1 — it remains available for
# explicit selection but auto no longer picks it.
MATMUL_MAX_CELLS = 1 << 21

# Whether auto commits intervals through the fused single-dispatch
# program (ops/commit.py: aggregator fold + all retention tiers in one
# donated-carry launch) instead of the per-consumer fan-out.  The fused
# program is pure XLA scatter composition — bit-identical to the fan-out
# by construction (tests/test_commit.py) — so it defaults on; a hardware
# capture that ever ranks the fan-out faster flips this via the same
# committed-JSON machinery as the ingest thresholds.
FUSED_COMMIT = True

# Host->device transport crossover (r6): "auto" transport folds each
# raw flush on host and measures cell density = unique_cells / samples.
# At or below this crossover the batch is skewed enough that shipping
# packed [n,3] triples (transport="sparse", 12B/cell) beats shipping
# every sample (8B/sample) — both on wire bytes and on device work
# (weighted scatter over cells vs per-sample compress+scatter).  Above
# it the fold overhead isn't paid back and raw stays.  0.5 is the
# conservative break-even from the wire-bytes ratio alone
# (12*density < 8 => density < 2/3, minus fold-cost margin); a capture
# retunes it via the committed-JSON table like every other threshold.
SPARSE_DENSITY_CROSSOVER = 0.5

# Which device tier the sparse transport's packed-triple scatter uses:
# "jnp" (XLA weighted scatter-add) or "pallas" (per-cell DMA row
# round-trip, ops/sparse_ingest.py).  The Pallas tier is bit-identical
# but not yet hardware-ranked, so auto stays on jnp until a capture
# flips this.
SPARSE_KERNEL = "jnp"

# Whether storage="auto" considers the r14 paged bucket backend
# (ops/paged_store.py + loghisto_tpu/paging.py): the dense [M, B]
# accumulator replaced by a page pool + page table so HBM and commit
# H2D track OCCUPIED buckets.  Auto only switches at high metric
# cardinality — below the crossover the dense tensor fits HBM trivially
# and its donated in-place commit beats the translate step's host work.
PAGED_STORAGE = True

# Metric-row crossover for storage="auto": the dense accumulator at
# M=2^16 x B=8193 x 4B is ~2.1 GiB of HBM and the page pool wins
# outright on sparse occupancy (PAGED_STORE_r14); below it dense wins
# on simplicity.  Baked FALLBACK, capture-overridable like the rest.
PAGED_MIN_METRICS = 1 << 16

# Buckets per pool page; mirrored from ops/paged_store.PAGE_SIZE
# without importing jax (this module must stay importable without jax).
PAGE_SIZE = 256

# Fixed paged-commit launch width; mirrored from
# ops/paged_store.COMMIT_CHUNK without importing jax.  The mesh edges
# below check the stream axis divides it (the sharded paged commit
# splits the padded triple wire over the stream axis).
PAGED_COMMIT_CHUNK = 1 << 14

# Capture-derived threshold table (VERDICT r2 item 7): refreshing the
# dispatch policy after a hardware capture is a committed JSON (emitted
# by ``benchmarks/analyze_capture.py --emit-thresholds``), not a code
# edit.  Lives next to this module; absent or unreadable -> the baked
# constants above stand.  Stdlib-only so the module stays importable
# without jax (analyze_capture.py depends on that).
THRESHOLDS_FILE = _os.path.join(
    _os.path.dirname(_os.path.abspath(__file__)), "dispatch_thresholds.json"
)
THRESHOLDS_SOURCE = "baked-in defaults"


def _load_thresholds() -> None:
    global SORT_MIN_METRICS, PALLAS_SINGLE_METRIC, THRESHOLDS_SOURCE
    global HIGH_CARDINALITY_KERNEL, FUSED_COMMIT
    global SPARSE_DENSITY_CROSSOVER, SPARSE_KERNEL
    global FUSED_INGEST, FUSED_MIN_BATCH, FUSED_MIN_BATCH_BY_PLATFORM
    global PAGED_STORAGE, PAGED_MIN_METRICS, FUSED_PAGED
    try:
        with open(THRESHOLDS_FILE) as f:
            table = _json.load(f)
    except (OSError, ValueError):
        return
    if not isinstance(table, dict):
        return
    applied = False
    smm = table.get("sort_min_metrics")
    if isinstance(smm, int) and smm > 1:
        SORT_MIN_METRICS = smm
        applied = True
    psm = table.get("pallas_single_metric")
    if isinstance(psm, bool):
        PALLAS_SINGLE_METRIC = psm
        applied = True
    hck = table.get("high_cardinality_kernel")
    if hck in ("sort", "sortscan"):
        HIGH_CARDINALITY_KERNEL = hck
        applied = True
    fc = table.get("fused_commit")
    if isinstance(fc, bool):
        FUSED_COMMIT = fc
        applied = True
    sdc = table.get("sparse_density_crossover")
    # bool is an int subclass; a stray true/false must not become 1.0/0.0
    if (
        isinstance(sdc, (int, float))
        and not isinstance(sdc, bool)
        and 0.0 <= sdc <= 1.0
    ):
        SPARSE_DENSITY_CROSSOVER = float(sdc)
        applied = True
    sk = table.get("sparse_kernel")
    if sk in ("jnp", "pallas"):
        SPARSE_KERNEL = sk
        applied = True
    fi = table.get("fused_ingest")
    if isinstance(fi, bool):
        FUSED_INGEST = fi
        applied = True
    fmb = table.get("fused_min_batch")
    if isinstance(fmb, int) and not isinstance(fmb, bool) and fmb >= 1:
        FUSED_MIN_BATCH = fmb
        applied = True
    fmbp = table.get("fused_min_batch_by_platform")
    if isinstance(fmbp, dict):
        clean = {
            str(k): v
            for k, v in fmbp.items()
            if isinstance(v, int) and not isinstance(v, bool) and v >= 1
        }
        if clean:
            FUSED_MIN_BATCH_BY_PLATFORM = clean
            applied = True
    fp = table.get("fused_paged")
    if isinstance(fp, bool):
        FUSED_PAGED = fp
        applied = True
    pst = table.get("paged_storage")
    if isinstance(pst, bool):
        PAGED_STORAGE = pst
        applied = True
    pmm = table.get("paged_min_metrics")
    if isinstance(pmm, int) and not isinstance(pmm, bool) and pmm > 1:
        PAGED_MIN_METRICS = pmm
        applied = True
    if applied:  # never cite a table that contributed nothing
        THRESHOLDS_SOURCE = str(table.get("source", THRESHOLDS_FILE))


_load_thresholds()


def fused_min_batch_for(platform: Optional[str]) -> int:
    """The effective fused-kernel batch crossover for a platform: the
    calibrated per-platform entry when a measured sweep wrote one
    (bench.py's calibration stage / a hardware capture), else the baked
    FUSED_MIN_BATCH fallback.  ``platform=None`` (callers that never
    learned the backend) always gets the fallback."""
    if platform is not None:
        v = FUSED_MIN_BATCH_BY_PLATFORM.get(platform)
        if isinstance(v, int) and not isinstance(v, bool) and v >= 1:
            return v
    return FUSED_MIN_BATCH


# -- the capability table -------------------------------------------- #

@dataclasses.dataclass(frozen=True)
class PathContext:
    """Everything a capability edge may inspect — one context shape for
    every axis, so edges compose across contenders (the fused_paged row
    reuses the fused-ingest and paged-storage edges verbatim)."""

    num_metrics: int = 0
    num_buckets: Optional[int] = None
    platform: Optional[str] = None
    batch_size: Optional[int] = None
    mesh: bool = False
    mesh_obj: object = None  # the Mesh, when the caller has one
    transport: str = "sparse"
    acc_dtype: str = "int32"
    fused_ok: bool = False  # a capable fused_paged path relaxes edges


class CapabilityEdge(NamedTuple):
    """One named check of one contender.  ``policy=True`` marks
    performance policy (amortization crossovers, platform preferences)
    that ``crossover=False`` — an explicit operator selection — may
    override; ``policy=False`` edges are correctness and always apply.
    ``check(ctx)`` returns the human-readable reason string (what the
    operator sees in the auto-degrade log or the explicit-path raise)
    or None when the edge passes."""

    name: str
    policy: bool
    check: Callable[[PathContext], Optional[str]]


# -- ingest:fused edges (r13 strings, preserved verbatim) --


def _ck_fused_mesh(ctx: PathContext) -> Optional[str]:
    if ctx.mesh:
        return (
            "mesh shape: the fused kernel does not run inside a "
            "shard_map-embedded step (pallas_call under shard_map is not "
            "hardware-validated; the sharded path keeps its dispatched "
            "local fold)"
        )
    return None


def _ck_fused_rows_tile(ctx: PathContext) -> Optional[str]:
    if ctx.num_metrics % FUSED_ROWS_TILE:
        return (
            f"mesh shape: num_metrics={ctx.num_metrics} does not divide by "
            f"the fused kernel's {FUSED_ROWS_TILE}-row metric tile"
        )
    return None


def _ck_fused_dtype(ctx: PathContext) -> Optional[str]:
    if ctx.acc_dtype != "int32":
        return (
            f"dtype: accumulator dtype {ctx.acc_dtype} is not int32 — the "
            "fused kernel's per-tile f32 one-hot accumulation is "
            "integer-exact only against the int32 dense layout"
        )
    return None


def _ck_fused_batch(ctx: PathContext) -> Optional[str]:
    min_batch = fused_min_batch_for(ctx.platform)
    if ctx.batch_size is None:
        return (
            "batch too small: batch size unknown, cannot prove the "
            f"sort+layout preprocess amortizes (needs >= {min_batch} "
            "samples/batch)"
        )
    if ctx.batch_size < min_batch:
        return (
            f"batch too small: {ctx.batch_size} samples/batch does not "
            "amortize the fused kernel's sort+layout preprocess "
            f"(measured crossover {min_batch})"
        )
    return None


# -- storage:paged edges (r14 strings, preserved verbatim) --


def _ck_paged_mesh(ctx: PathContext) -> Optional[str]:
    # r18: the page pool is no longer a single-device arena — each
    # metric shard owns its own page arena and the paged commit runs
    # shard-local inside one shard_map (ops/paged_store.
    # make_sharded_paged_commit_fn).  The edge now declines only the
    # mesh SHAPES the sharded arenas genuinely cannot take.
    if not ctx.mesh:
        return None
    mesh = ctx.mesh_obj
    if mesh is None:
        # bool-only callers carry no shape to inspect: admitted here;
        # the same shape edges re-run wherever the Mesh is in hand
        # (resolve_full_path, PagedStore's constructor backstop)
        return None
    from loghisto_tpu.parallel.mesh import METRIC_AXIS, STREAM_AXIS

    axes = tuple(getattr(mesh, "axis_names", ()))
    if STREAM_AXIS not in axes or METRIC_AXIS not in axes:
        return (
            f"mesh shape: mesh axes {axes!r} are not the "
            f"('{STREAM_AXIS}', '{METRIC_AXIS}') layout the per-shard "
            "page arenas partition over"
        )
    n_metric = mesh.shape[METRIC_AXIS]
    if ctx.num_metrics and ctx.num_metrics % n_metric:
        return (
            f"mesh shape: num_metrics={ctx.num_metrics} rows don't "
            f"shard evenly over the {n_metric}-way metric axis, so the "
            "page arenas cannot split per shard"
        )
    n_stream = mesh.shape[STREAM_AXIS]
    if PAGED_COMMIT_CHUNK % n_stream:
        return (
            f"mesh shape: the {PAGED_COMMIT_CHUNK}-triple paged commit "
            f"chunk does not split over the {n_stream}-way stream axis"
        )
    return None


def _ck_paged_transport(ctx: PathContext) -> Optional[str]:
    allowed = ("sparse", "auto", "raw") if ctx.fused_ok else ("sparse", "auto")
    if ctx.transport not in allowed:
        return (
            f"transport: paged storage commits through the packed "
            f"[n,3] sparse-triple fold (transport='sparse'); "
            f"transport={ctx.transport!r} ships whole batches with no host "
            "fold, so there is no translate step to route cells through "
            "the page table"
        )
    return None


def _ck_paged_bucket_axis(ctx: PathContext) -> Optional[str]:
    if ctx.num_buckets is not None and ctx.num_buckets < PAGE_SIZE:
        return (
            f"bucket axis: num_buckets={ctx.num_buckets} is smaller than "
            f"one {PAGE_SIZE}-bucket page — the dense row is already "
            "cheaper than any page table"
        )
    return None


def _ck_paged_crossover(ctx: PathContext) -> Optional[str]:
    if ctx.num_metrics < PAGED_MIN_METRICS:
        return (
            f"below crossover: {ctx.num_metrics} metric rows — the dense "
            f"accumulator fits HBM trivially below {PAGED_MIN_METRICS} "
            "rows and its donated in-place commit wins (PAGED_STORE_r14)"
        )
    return None


# -- commit:fused edges (mesh strings, preserved verbatim) --


def _ck_commit_axes(ctx: PathContext) -> Optional[str]:
    mesh = ctx.mesh_obj
    if mesh is None:
        return None
    from loghisto_tpu.parallel.mesh import METRIC_AXIS, STREAM_AXIS

    axes = tuple(getattr(mesh, "axis_names", ()))
    if STREAM_AXIS not in axes or METRIC_AXIS not in axes:
        return (
            f"mesh axes {axes!r} are not the ('{STREAM_AXIS}', "
            f"'{METRIC_AXIS}') commit layout"
        )
    return None


def _ck_commit_rows(ctx: PathContext) -> Optional[str]:
    mesh = ctx.mesh_obj
    if mesh is None:
        return None
    from loghisto_tpu.parallel.mesh import METRIC_AXIS, STREAM_AXIS

    axes = tuple(getattr(mesh, "axis_names", ()))
    if STREAM_AXIS not in axes or METRIC_AXIS not in axes:
        return None  # the axes edge already declined
    n_metric = mesh.shape[METRIC_AXIS]
    if ctx.num_metrics and ctx.num_metrics % n_metric:
        return (
            f"num_metrics={ctx.num_metrics} rows don't shard evenly over "
            f"the {n_metric}-way metric axis"
        )
    return None


# -- ingest:fused_paged edges (r17) --


def _ck_fused_paged_switch(ctx: PathContext) -> Optional[str]:
    if not FUSED_PAGED:
        return (
            "disabled: fused_paged is off in the threshold table "
            f"({THRESHOLDS_SOURCE})"
        )
    return None


def _ck_fused_paged_transport(ctx: PathContext) -> Optional[str]:
    if ctx.transport not in ("raw", "auto"):
        return (
            "transport: the direct-to-paged fused kernel ingests RAW "
            "samples (compress, codec-encode, and page-translate all "
            f"happen on device in one dispatch); transport="
            f"{ctx.transport!r} folds cells on host first, leaving the "
            "one-dispatch path nothing to fuse — the folded route keeps "
            "the translate + packed pool commit"
        )
    return None


def _ck_fused_paged_mesh(ctx: PathContext) -> Optional[str]:
    # Unlike the dense fused kernel (pallas_call under shard_map is not
    # hardware-validated — _ck_fused_mesh stands), the sharded
    # direct-to-paged step runs its scatter on the jnp tier inside
    # shard_map (ops/fused_ingest.make_sharded_fused_paged_ingest_fn),
    # so a mesh only declines on batch split shape.
    if not ctx.mesh:
        return None
    mesh = ctx.mesh_obj
    if mesh is None:
        return None
    from loghisto_tpu.parallel.mesh import STREAM_AXIS

    axes = tuple(getattr(mesh, "axis_names", ()))
    if STREAM_AXIS not in axes:
        return None  # the pool_mesh edge names the axis-layout reason
    n_stream = mesh.shape[STREAM_AXIS]
    if ctx.batch_size is not None and ctx.batch_size % n_stream:
        return (
            f"mesh shape: batch_size={ctx.batch_size} samples don't "
            f"split over the {n_stream}-way stream axis for the "
            "shard_map-embedded direct-to-paged step"
        )
    return None


def _ck_fused_paged_platform(ctx: PathContext) -> Optional[str]:
    if ctx.platform is not None and ctx.platform != "tpu":
        return (
            f"platform: {ctx.platform} — auto only picks the direct-to-"
            "paged fused kernel on TPU (the interpret-mode Pallas tier is "
            "parity-only; explicit selection remains the opt-in)"
        )
    return None


# The table: (axis, contender) -> ordered edges.  The fused_paged row is
# COMPOSED from the fused-ingest and paged-storage edges plus its own —
# the refactor's point: a new contender is a new row, not a fourth
# copy-pasted ladder.  Note what it does NOT inherit: the rows_tile and
# dtype edges (the paged kernel is per-sample gather + per-cell DMA —
# no ROWS_TILE accumulator blocks, and the pool is int32 by
# construction), and the sparse-transport edge (it exists to ingest raw
# batches directly).
CAPABILITY_TABLE: Dict[Tuple[str, str], Tuple[CapabilityEdge, ...]] = {
    ("ingest", "fused"): (
        CapabilityEdge("mesh", False, _ck_fused_mesh),
        CapabilityEdge("rows_tile", False, _ck_fused_rows_tile),
        CapabilityEdge("dtype", False, _ck_fused_dtype),
        CapabilityEdge("batch", True, _ck_fused_batch),
    ),
    ("storage", "paged"): (
        CapabilityEdge("mesh", False, _ck_paged_mesh),
        CapabilityEdge("transport", False, _ck_paged_transport),
        CapabilityEdge("bucket_axis", False, _ck_paged_bucket_axis),
        CapabilityEdge("crossover", True, _ck_paged_crossover),
    ),
    ("commit", "fused"): (
        CapabilityEdge("mesh_axes", False, _ck_commit_axes),
        CapabilityEdge("rows", False, _ck_commit_rows),
    ),
    ("ingest", "fused_paged"): (
        CapabilityEdge("switch", True, _ck_fused_paged_switch),
        CapabilityEdge("mesh", False, _ck_fused_paged_mesh),
        CapabilityEdge("pool_mesh", False, _ck_paged_mesh),
        CapabilityEdge("bucket_axis", False, _ck_paged_bucket_axis),
        CapabilityEdge("transport", False, _ck_fused_paged_transport),
        CapabilityEdge("platform", True, _ck_fused_paged_platform),
        CapabilityEdge("batch", True, _ck_fused_batch),
    ),
}

# The single degradation order per axis — the ladder every "auto"
# resolution walks, most-capable contender first.  (The ingest ladder's
# sort entry is HIGH_CARDINALITY_KERNEL at resolve time; "scatter" is
# the unconditional floor on every axis where it appears.)
DEGRADATION_ORDER: Dict[str, Tuple[str, ...]] = {
    "ingest": ("fused_paged", "fused", "sort", "scatter"),
    "storage": ("paged", "dense"),
    "commit": ("fused", "fanout"),
    "transport": ("sparse", "raw"),
}


def incapability(
    axis: str,
    contender: str,
    ctx: PathContext,
    include_policy: bool = True,
) -> Optional[Tuple[str, str]]:
    """Walk one table row: the first failing edge as ``(edge_name,
    reason)``, or None when the contender is capable.  This is the ONE
    reason-string walk behind every ``*_incapability`` view —
    ``include_policy=False`` is what the explicit-selection
    ``crossover=False`` contract maps onto."""
    for edge in CAPABILITY_TABLE[(axis, contender)]:
        if edge.policy and not include_policy:
            continue
        reason = edge.check(ctx)
        if reason is not None:
            return edge.name, reason
    return None


# -- public incapability views (pre-r17 signatures, table-backed) ----- #


def fused_ingest_incapability(
    num_metrics: int,
    batch_size: int | None = None,
    mesh: bool = False,
    acc_dtype: str = "int32",
    crossover: bool = True,
    platform: str | None = None,
) -> str | None:
    """Why a configuration genuinely cannot (or should not) run the r13
    fused sample->scatter kernel, as a human-readable reason string — or
    None when it can.  Mirrors ``mesh_commit_incapability``'s shape:
    "auto" degrades silently on a reason, an EXPLICIT
    ``ingest_path="fused"`` surfaces the same string in its raise, so
    the operator always learns WHY fused ingest was declined.

    ``crossover=False`` skips the amortization checks (batch unknown /
    batch too small) — those are performance policy, not correctness,
    and an explicit selection is allowed to eat the preprocess cost.
    ``platform``, when known, selects the calibrated per-platform batch
    crossover (fused_min_batch_for)."""
    ctx = PathContext(
        num_metrics=num_metrics, batch_size=batch_size, mesh=mesh,
        acc_dtype=acc_dtype, platform=platform,
    )
    hit = incapability("ingest", "fused", ctx, include_policy=crossover)
    return None if hit is None else hit[1]


def fused_paged_incapability(
    num_metrics: int,
    num_buckets: int | None = None,
    batch_size: int | None = None,
    mesh: bool = False,
    transport: str = "auto",
    platform: str | None = None,
    crossover: bool = True,
    mesh_obj=None,
) -> str | None:
    """Why a configuration cannot (or should not) take the r17
    direct-to-paged fused ingest — the one-dispatch compress -> encode
    -> page-translate -> pool-scatter kernel.  Same contract as its
    siblings: auto degrades (to the host-fold translate + packed pool
    commit) with the reason, an explicit ``ingest_path="fused"`` on a
    paged store raises it; ``crossover=False`` skips the policy edges
    (platform preference, batch amortization, threshold switch).
    ``mesh_obj`` (the Mesh, when in hand) lets the r18 mesh edges check
    the actual shard shape instead of blanket-declining."""
    ctx = PathContext(
        num_metrics=num_metrics, num_buckets=num_buckets,
        batch_size=batch_size, mesh=mesh, transport=transport,
        platform=platform, mesh_obj=mesh_obj,
    )
    hit = incapability("ingest", "fused_paged", ctx, include_policy=crossover)
    return None if hit is None else hit[1]


def paged_storage_incapability(
    num_metrics: int,
    num_buckets: int | None = None,
    mesh: bool = False,
    transport: str = "sparse",
    crossover: bool = True,
    fused_ok: bool = False,
    mesh_obj=None,
) -> str | None:
    """Why a configuration genuinely cannot (or should not) run the r14
    paged bucket backend, as a human-readable reason string — or None
    when it can.  Same contract as ``fused_ingest_incapability``:
    storage="auto" degrades silently on a reason, an EXPLICIT
    ``storage="paged"`` surfaces the same string in its raise.

    ``crossover=False`` skips the metric-cardinality check — that is
    capacity policy, not correctness, and an explicit selection is
    allowed to page a small deployment (the tests do).  ``fused_ok=True``
    (the r17 direct-to-paged fused kernel is capable) relaxes the
    transport edge: raw batches then ingest straight into the pool with
    no host fold, so "raw" no longer disqualifies paged storage."""
    ctx = PathContext(
        num_metrics=num_metrics, num_buckets=num_buckets, mesh=mesh,
        transport=transport, fused_ok=fused_ok, mesh_obj=mesh_obj,
    )
    hit = incapability("storage", "paged", ctx, include_policy=crossover)
    return None if hit is None else hit[1]


def mesh_commit_incapability(mesh, num_metrics=None) -> str | None:
    """Why a sharded configuration genuinely cannot run the fused
    commit under ``shard_map``, as a human-readable reason string — or
    None when it can (including ``mesh=None``: single-device state is
    always capable).  The checks mirror what the sharded program
    actually requires:

      * the mesh must carry the ("stream", "metric") commit layout —
        the program psums cell deltas over the stream axis and keeps
        every carry metric-row-sharded;
      * ``num_metrics`` (when known) must split evenly over the metric
        axis, or the carries cannot take their ``P(metric)`` row
        sharding at all.
    """
    ctx = PathContext(
        num_metrics=num_metrics or 0, mesh=mesh is not None, mesh_obj=mesh
    )
    hit = incapability("commit", "fused", ctx)
    return None if hit is None else hit[1]


# -- resolution ------------------------------------------------------- #


def choose_ingest_path(
    num_metrics: int, num_buckets: int, platform: str
) -> str:
    """Pick the measured-fastest ingest kernel for a configuration.

    The Pallas multirow kernel stays opt-in: hardware-validated for
    parity (TPU_CAPTURE_r2/pallas_parity.json) but never the fastest at
    any measured config, so "auto" does not select it.  The Pallas row
    kernel (winner at M=1) participates via its masked
    pallas_row_ingest_batch form, which has the standard (ids, values)
    contract (see PALLAS_SINGLE_METRIC note on the extrapolation).  At
    high cardinality on TPU the r13 fused sample->scatter kernel is the
    preferred pick (one dispatch, codec on-chip); resolve_ingest_path
    degrades it to HIGH_CARDINALITY_KERNEL when
    ``fused_ingest_incapability`` names a blocker.
    """
    if platform == "tpu" and num_metrics == 1 and PALLAS_SINGLE_METRIC:
        # the fused Pallas row kernel wins the single-metric config
        # outright (r2 hardware table); its masked (ids, values) form
        # makes it contract-compatible with the other paths
        return "pallas"
    if platform == "tpu" and num_metrics >= SORT_MIN_METRICS:
        if FUSED_INGEST:
            return "fused"
        return HIGH_CARDINALITY_KERNEL
    return "scatter"


def resolve_ingest_path(
    path: str,
    num_metrics: int,
    num_buckets: int,
    platform: str,
    guard_metrics: int | None = None,
    batch_size: int | None = None,
    mesh: bool = False,
) -> str:
    """Resolve "auto" and enforce per-path shape preconditions — THE
    dispatch-guard policy, shared by TPUAggregator, the firehose, and the
    bench so the benchmarked default can never drift from the product
    default.  Auto never picks a kernel the shape invalidates (falls back
    to scatter), while an EXPLICIT choice the shape cannot support raises
    here — at selection time — instead of silently corrupting histograms
    inside the traced kernel (the sort and matmul paths' combined int32
    cell keys wrap negative past 2^31 cells).

    ``guard_metrics`` is the row count to validate shapes against when it
    exceeds ``num_metrics`` — TPUAggregator passes its growth cap
    (max_metrics) so auto cannot pick a kernel that registry growth would
    later invalidate.  ``batch_size``, when known, guards hybrid's
    float32 hot-head exactness bound (per-batch counts < 2^24); auto
    refuses to pick "pallas" when the bound is UNKNOWN (batch_size=None)
    — the precondition would otherwise surface as a trace-time raise
    inside a shard_map step (ADVICE r2).  ``mesh=True`` marks a
    shard_map-embedded resolve: auto additionally skips "pallas" there
    (pallas_call inside shard_map is not hardware-validated; explicit
    selection remains available as the opt-in)."""
    from loghisto_tpu.ops.sort_ingest import validate_flat_cell_shape

    guard = max(num_metrics, guard_metrics or 0)
    batch_too_big = batch_size is not None and batch_size >= 1 << 24
    if path == "auto":
        # auto never raises for a precondition: it just doesn't pick the
        # kernel the shape/batch would invalidate
        path = choose_ingest_path(num_metrics, num_buckets, platform)
        if path == "fused" and fused_ingest_incapability(
            guard, batch_size=batch_size, mesh=mesh, platform=platform
        ) is not None:
            # degrade to the pre-r13 high-cardinality winner, which then
            # takes its own shape validation below
            path = HIGH_CARDINALITY_KERNEL
        if path in ("sort", "sortscan"):
            try:
                validate_flat_cell_shape(guard, num_buckets, path)
            except ValueError:
                path = "scatter"
        elif path == "pallas" and (
            guard != 1 or batch_size is None or batch_too_big or mesh
        ):
            # registry growth can widen the row space past the
            # single-row kernel; auto must not pick it unless the cap
            # pins M=1 (explicit "pallas" instead swaps kernels on grow),
            # the batch bound is known to satisfy the float32-exactness
            # precondition, and the step is not shard_map-embedded
            path = "scatter"
        return path
    if path == "fused":
        # explicit selection: correctness blockers raise with the reason
        # string; the crossover (a perf policy) is the operator's call
        reason = fused_ingest_incapability(
            guard, batch_size=batch_size, mesh=mesh, crossover=False
        )
        if reason is not None:
            raise ValueError(f"fused ingest unavailable: {reason}")
    if path in ("sort", "sortscan", "matmul"):
        validate_flat_cell_shape(guard, num_buckets, path)
    elif path in ("hybrid", "pallas") and batch_too_big:
        raise ValueError(
            f"{path} ingest batches must stay < 2^24 samples (float32 "
            f"accumulation exactness); got batch_size={batch_size}"
        )
    if path == "pallas" and num_metrics != 1:
        raise ValueError(
            "ingest_path='pallas' is the single-metric row kernel; got "
            f"num_metrics={num_metrics} (growth past 1 row swaps kernels "
            "automatically, but the starting shape must be [1, B])"
        )
    return path


def resolve_sparse_kernel(kernel: str) -> str:
    """Resolve the sparse transport's device tier ("auto" follows the
    capture-overridable SPARSE_KERNEL switch)."""
    if kernel == "auto":
        return SPARSE_KERNEL
    if kernel not in ("jnp", "pallas"):
        raise ValueError(
            f"unknown sparse kernel {kernel!r}: expected 'auto', 'jnp', "
            "or 'pallas'"
        )
    return kernel


def choose_transport(
    platform: str, density: float | None = None, native_ok: bool = True
) -> str:
    """Pick the host->device transport for transport="auto".

    ``density`` is the measured unique-cell / samples ratio of a probe
    flush (None before any probe has run).  The policy: start on "raw"
    (zero host fold cost, always correct), and switch to "sparse" once a
    probe shows the load is skewed enough that shipping packed triples
    wins (density <= SPARSE_DENSITY_CROSSOVER).  "preagg" is never
    auto-picked: it trades flush latency for record()-time fold work,
    which only pays off when the *recording* threads are the bottleneck
    — a workload property no flush-side probe can see — so it stays an
    explicit opt-in.  ``native_ok=False`` (no compiler AND numpy tier
    unavailable — today never, the numpy tier always exists) pins raw.
    """
    del platform  # crossover is wire/fold-cost driven, not device-driven
    if not native_ok:
        return "raw"
    if density is not None and density <= SPARSE_DENSITY_CROSSOVER:
        return "sparse"
    return "raw"


def resolve_storage_path(
    storage: str,
    num_metrics: int,
    num_buckets: int,
    platform: str,
    mesh: bool = False,
    transport: str = "sparse",
    fused_ok: bool = False,
    mesh_obj=None,
) -> tuple[str, str | None]:
    """Resolve the accumulator storage backend: "dense" (the donated
    [M, B] tensor) or "paged" (page pool + page table + per-row codecs,
    r14).  Mirrors ``resolve_commit_path``: "auto" degrades to dense
    with the reason (returned, so TPUAggregator can surface it as
    ``storage_reason``), an explicit "paged" a capability blocker
    invalidates raises the same string, and unknown names raise.

    Returns ``(resolved, reason)`` — reason is None unless auto
    declined paged.

    ``fused_ok=True`` marks a capable r17 direct-to-paged fused ingest:
    the transport edge then admits "raw" (see
    ``paged_storage_incapability``).

    Labeled metrics (ISSUE 16): ``num_metrics`` counts REGISTRY ROWS,
    and under the canonical label encoding every distinct label set of
    a base name (``http.latency;code=500;route=/api``) is its own row —
    so label cardinality, not base-name count, is what drives this
    crossover.  A service with 50 base names and 10k live label sets is
    a 10k-row deployment and typically wants paged storage; see
    ``TPUMetricSystem.debug_dump()["labels"]["cardinality_by_prefix"]``
    for the live per-prefix label population.
    """
    del platform  # both backends run on every platform (interpret tier)
    if storage == "auto":
        if not PAGED_STORAGE:
            return "dense", "paged storage disabled by threshold table"
        reason = paged_storage_incapability(
            num_metrics, num_buckets, mesh=mesh, transport=transport,
            fused_ok=fused_ok, mesh_obj=mesh_obj,
        )
        if reason is not None:
            return "dense", reason
        return "paged", None
    if storage not in ("dense", "paged"):
        raise ValueError(
            f"unknown storage {storage!r}: expected 'auto', 'dense', or "
            "'paged'"
        )
    if storage == "paged":
        reason = paged_storage_incapability(
            num_metrics, num_buckets, mesh=mesh, transport=transport,
            crossover=False, fused_ok=fused_ok, mesh_obj=mesh_obj,
        )
        if reason is not None:
            raise ValueError(f"paged storage unavailable: {reason}")
    return storage, None


def resolve_commit_path(
    path: str, platform: str, mesh=None, num_metrics: int | None = None
) -> str:
    """Resolve the interval-commit path: "fused" (one donated-carry
    program for the aggregator fold + every retention tier,
    ops/commit.py) or "fanout" (the per-consumer bridge-merge +
    per-tier-scatter launches).  "auto" follows the capture-overridable
    FUSED_COMMIT switch — the same threshold machinery as the ingest
    kernels, so a hardware capture retunes this with a committed JSON,
    not a code edit.

    ``mesh`` takes the ("stream", "metric") mesh object when the state
    is sharded (or None).  Resolution is capability-based, not a
    blanket downgrade: sharded state runs the fused path under
    ``shard_map`` unless ``mesh_commit_incapability`` reports a shape
    that genuinely cannot shard (wrong axis layout, rows not divisible
    by the metric axis) — "auto" then degrades to the fan-out, and an
    explicit "fused" raises with the reason string.  A legacy boolean
    ``mesh=True`` (no mesh object to inspect) is treated as a capable
    sharded configuration.

    ``num_metrics`` here too counts registry rows under the canonical
    label encoding (one row per live label set, see
    loghisto_tpu/labels/model.py) — a labeled deployment's divisibility
    and sizing checks run against label cardinality, not base names."""
    mesh_obj = None if isinstance(mesh, bool) or mesh is None else mesh
    reason = mesh_commit_incapability(mesh_obj, num_metrics)
    if path == "auto":
        if reason is not None:
            return "fanout"
        return "fused" if FUSED_COMMIT else "fanout"
    if path not in ("fused", "fanout"):
        raise ValueError(
            f"unknown commit path {path!r}: expected 'auto', 'fused', or "
            "'fanout'"
        )
    if path == "fused" and reason is not None:
        raise ValueError(f"fused commit unavailable on this mesh: {reason}")
    return path


class FullPath(NamedTuple):
    """One resolved end-to-end dispatch: which wire the samples ride
    (transport), which kernel consumes them (ingest), which layout
    accumulates them (storage), and which program closes the interval
    (commit) — plus every reason the walk declined a more-capable
    contender, keyed "axis:contender"."""

    transport: str
    ingest: str
    storage: str
    commit: str
    reasons: Dict[str, str]


def resolve_full_path(
    num_metrics: int,
    num_buckets: int,
    platform: str,
    ingest: str = "auto",
    storage: str = "auto",
    transport: str = "auto",
    commit: str = "auto",
    batch_size: int | None = None,
    mesh=None,
    guard_metrics: int | None = None,
    density: float | None = None,
) -> FullPath:
    """THE composed resolver (r17): one walk of the capability table's
    degradation orders that answers all four axes together, because the
    axes are NOT independent — paged storage without the fused kernel
    pins the sparse transport (the translate step rides the host fold),
    while a capable fused_paged contender inverts that (raw samples
    ingest straight into the pool and the host fold disappears).  The
    per-edge reasons of every declined contender come back in
    ``reasons`` so callers (TPUAggregator's ``storage_reason`` /
    ``fused_paged_reason``, the bench's path table) surface WHY, with
    the same strings the explicit paths raise."""
    reasons: Dict[str, str] = {}
    mesh_flag = mesh is not None and mesh is not False
    mesh_obj = None if isinstance(mesh, bool) or mesh is None else mesh

    # 1. the fused_paged contender's capability gates BOTH the storage
    #    transport edge and the ingest ladder's top rung
    fp_reason = fused_paged_incapability(
        num_metrics, num_buckets, batch_size=batch_size, mesh=mesh_flag,
        transport=transport, platform=platform,
        crossover=(ingest == "auto"), mesh_obj=mesh_obj,
    )
    fused_ok = fp_reason is None and ingest in ("auto", "fused")
    if fp_reason is not None:
        reasons["ingest:fused_paged"] = fp_reason

    # 2. storage (may raise on explicit-invalid, same as before)
    storage_res, s_reason = resolve_storage_path(
        storage, num_metrics, num_buckets, platform, mesh=mesh_flag,
        transport=transport, fused_ok=fused_ok, mesh_obj=mesh_obj,
    )
    if s_reason is not None:
        reasons["storage:paged"] = s_reason

    # 3. ingest + transport, jointly
    if storage_res == "paged" and fused_ok:
        if ingest == "fused" and fp_reason is not None:
            raise ValueError(f"fused paged ingest unavailable: {fp_reason}")
        ingest_res = "fused_paged"
        transport_res = "raw"  # the batch IS the wire; no host fold
    elif storage_res == "paged":
        if ingest == "fused" and fp_reason is not None:
            raise ValueError(f"fused paged ingest unavailable: {fp_reason}")
        # pre-r17 paged route: host fold -> translate -> packed commit;
        # no per-sample ingest kernel runs at all
        ingest_res = "packed"
        transport_res = "sparse"
    else:
        ingest_res = resolve_ingest_path(
            ingest, num_metrics, num_buckets, platform,
            guard_metrics=guard_metrics, batch_size=batch_size,
            mesh=mesh_flag,
        )
        if transport == "auto":
            transport_res = choose_transport(platform, density=density)
        else:
            transport_res = transport

    # 4. commit
    commit_reason = mesh_commit_incapability(mesh_obj, num_metrics)
    if commit_reason is not None:
        reasons["commit:fused"] = commit_reason
    commit_res = resolve_commit_path(
        commit, platform, mesh=mesh if mesh_obj is not None else mesh_flag,
        num_metrics=num_metrics,
    )
    return FullPath(transport_res, ingest_res, storage_res, commit_res,
                    reasons)


def ingest_step_fn(path: str):
    """The pure per-batch accumulation function for a named path, with the
    uniform ``f(acc, ids, values, bucket_limit, precision) -> acc``
    contract (scatter / sort / sortscan / hybrid / matmul / pallas — the
    paths whose dense accumulator layout is interchangeable; pallas
    additionally requires acc shape [1, B]).  Used wherever a traced step
    needs the dispatched kernel inline (firehose generation loop, bench
    interval loop) rather than the TPUAggregator's jitted wrappers.
    The r17 "fused_paged" contender is NOT here: its accumulator is the
    page pool + LUT operands, a different contract
    (ops/fused_ingest.fused_paged_ingest_batch)."""
    if path == "sort":
        from loghisto_tpu.ops.sort_ingest import sort_ingest_batch

        return sort_ingest_batch
    if path == "sortscan":
        from loghisto_tpu.ops.sort_ingest import sortscan_ingest_batch

        return sortscan_ingest_batch
    if path == "hybrid":
        from loghisto_tpu.ops.hybrid_hist import ingest_batch_hybrid

        return ingest_batch_hybrid
    if path == "matmul":
        from loghisto_tpu.ops.matmul_hist import ingest_batch_matmul

        return ingest_batch_matmul
    if path == "pallas":
        from loghisto_tpu.ops.pallas_kernels import pallas_row_ingest_batch

        return pallas_row_ingest_batch
    if path == "fused":
        from loghisto_tpu.ops.fused_ingest import fused_ingest_batch

        return fused_ingest_batch
    if path != "scatter":
        raise ValueError(
            f"no pure step form for ingest_path {path!r}: expected "
            "'scatter', 'sort', 'sortscan', 'hybrid', 'matmul', "
            "'pallas', or 'fused'"
        )
    from loghisto_tpu.ops.ingest import ingest_batch

    return ingest_batch
