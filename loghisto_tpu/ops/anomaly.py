"""Device programs for the distribution drift engine (ISSUE 7):
EWMA baseline-bank maintenance and fused divergence scoring.

The paper's log-bucket histograms keep the ENTIRE distribution losslessly
— yet scalar rules throw that away at the last step.  These kernels put
the distribution back into alerting:

  * ``ewma_bank_update`` — the baseline side.  Per metric row, a bank of
    EWMA-decayed bucket *profiles* (normalized histograms) tracks "what
    this metric's distribution usually looks like"; configurable banks
    (e.g. one global + per-hour banks) absorb seasonality.  The update
    runs INSIDE the fused commit's donated-carry program
    (ops/commit.py ``track_baseline``) over the interval histogram the
    commit is already scattering — zero extra dispatches, the identical
    fusion economics as the lifecycle's activity stamp.
  * ``make_divergence_fn`` — the scoring side.  ONE fused dispatch per
    interval compares each live window CDF (the commit-time snapshot
    payload the query engine already materializes for free) against its
    baseline bank: Kolmogorov–Smirnov distance, Jensen–Shannon
    divergence (base-2, bounded [0, 1]), and bucket-space earth-mover's
    distance.  A jnp tier and a Pallas tier share one row-math helper,
    so the two are bit-identical (tests/test_anomaly.py pins this).
  * ``make_bank_evict_fn`` / ``make_bank_compact_fn`` — lifecycle
    integration: evicted rows zero their baselines (a reused slot must
    start cold, never inherit the dead series' shape) and compaction
    applies the same survivor permutation as every other carry.

Mesh-sharded state (PR 8): ``ewma_bank_update`` is row-elementwise, so
the sharded fused commit calls it shard-local inside its ``shard_map``
program on metric-row-sharded banks — same-order float ops per row,
hence bit-identical to the single-device path.  The divergence scorer
and the bank evict/compact programs jit over the sharded carries and
let GSPMD place the (row-parallel) math; scores read back replicated.

Divergence definitions, all in dense bucket space (axis index b = codec
bucket b - bucket_limit; log buckets make one step ~= precision% in
value space):

  ks  = max_b |F_live(b) - F_base(b)|            in [0, 1]
  emd = sum_b |F_live(b) - F_base(b)|            bucket-index units
  jsd = JS divergence of the pmfs, log base 2    in [0, 1]

Rows below the min-sample floor (live count < min_samples) or without an
established baseline (bank weight == 0) score exactly 0 — noise and
cold starts must not page.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from loghisto_tpu.ops.lifecycle import _sanitize_perm
from loghisto_tpu.ops.backend import default_interpret

ROWS_TILE = 8  # f32/int32 sublane tile, same as the window merge


# ---------------------------------------------------------------------- #
# baseline bank maintenance (runs inside the fused commit program)
# ---------------------------------------------------------------------- #


def ewma_bank_update(banks, ihist, bank, decay, min_count):
    """One EWMA step of the active baseline bank from a completed
    interval histogram.  Pure traceable math — ops/commit.py inlines it
    into the final-chunk fused program, so it costs zero dispatches.

      banks     (prof f32 [K, M, B], wsum f32 [K, M]) — donated carries
      ihist     int32 [M, B] — the interval's merged histogram
      bank      traced int32 scalar — active bank index (time-of-day
                selection happens host-side)
      decay     traced f32 scalar — EWMA retain factor in [0, 1)
      min_count traced int32 scalar — rows with fewer interval samples
                keep their baseline untouched (a quiet interval must not
                wash the profile toward zero)

    ``prof`` rows are EWMA mixes of per-interval *pmfs* and ``wsum`` is
    the matching EWMA weight mass (``decay*w + (1-decay)`` whenever the
    row updates), so ``prof/wsum`` is always a bias-corrected pmf — a
    young baseline after one update compares exactly, not attenuated by
    the EWMA warm-up.
    """
    prof, wsum = banks
    counts = jnp.sum(ihist, axis=1)                       # int32 [M]
    upd = counts >= min_count                             # bool  [M]
    tot = jnp.maximum(counts, 1).astype(jnp.float32)[:, None]
    pmf = ihist.astype(jnp.float32) / tot                 # [M, B]
    old_p = prof[bank]
    old_w = wsum[bank]
    gain = jnp.float32(1.0) - decay
    new_p = jnp.where(upd[:, None], decay * old_p + gain * pmf, old_p)
    new_w = jnp.where(upd, decay * old_w + gain, old_w)
    return prof.at[bank].set(new_p), wsum.at[bank].set(new_w)


# ---------------------------------------------------------------------- #
# divergence scoring
# ---------------------------------------------------------------------- #


def _row_divergence(cdf, counts, prof, w):
    """Raw per-row divergence scores (no floor mask): cdf int32 [R, B],
    counts int32 [R], prof f32 [R, B], w f32 [R] -> (ks, jsd, emd), each
    f32 [R].  Row-independent elementwise math + axis-1 reductions ONLY
    — this is what makes the jnp and Pallas tiers bit-identical (the
    Pallas kernel applies the same function per 8-row tile)."""
    total = jnp.maximum(counts, 1).astype(jnp.float32)[:, None]
    live_cdf = cdf.astype(jnp.float32) / total
    # exact integer bin counts first, divide after — differencing the
    # float CDF would lose low-order bits
    bins = cdf - jnp.concatenate(
        [jnp.zeros_like(cdf[:, :1]), cdf[:, :-1]], axis=1
    )
    live_pmf = bins.astype(jnp.float32) / total
    # bias-corrected baseline pmf; w == 0 rows are masked by the caller,
    # the epsilon only keeps the division finite for them
    base_pmf = prof / jnp.maximum(w, jnp.float32(1e-30))[:, None]
    base_cdf = jnp.cumsum(base_pmf, axis=1)
    diff = jnp.abs(live_cdf - base_cdf)
    ks = jnp.max(diff, axis=1)
    emd = jnp.sum(diff, axis=1)
    mid = jnp.float32(0.5) * (live_pmf + base_pmf)

    def kl_to_mid(p):
        # 0*log(0) := 0; where p > 0, mid >= p/2 > 0 so the ratio is
        # finite — the unselected lanes' NaNs are discarded by where
        return jnp.sum(
            jnp.where(p > 0, p * jnp.log2(p / mid), jnp.float32(0.0)),
            axis=1,
        )

    jsd = jnp.float32(0.5) * (kl_to_mid(live_pmf) + kl_to_mid(base_pmf))
    return ks, jsd, emd


def _div_kernel(cdf_ref, cnt_ref, prof_ref, w_ref,
                ks_ref, jsd_ref, emd_ref):
    ks, jsd, emd = _row_divergence(
        cdf_ref[...], cnt_ref[...][:, 0], prof_ref[...], w_ref[...][:, 0]
    )
    ks_ref[...] = ks[:, None]
    jsd_ref[...] = jsd[:, None]
    emd_ref[...] = emd[:, None]


def divergence_pallas(cdf, counts, prof, w, interpret=None):
    """Pallas tier of the raw divergence: grid over metric tiles, each
    [ROWS_TILE, B] live/baseline block resident in VMEM while its three
    scores reduce — HBM traffic is the two operand tensors in + 3 floats
    per row out, the bandwidth floor.  Row padding is score-neutral
    (padded rows are sliced off) and the per-row math is the SAME
    function the jnp tier runs, so results are bit-identical."""
    if interpret is None:
        interpret = default_interpret()
    m, b = cdf.shape
    m_pad = (m + ROWS_TILE - 1) // ROWS_TILE * ROWS_TILE
    if m_pad != m:
        gap = m_pad - m
        cdf = jnp.pad(cdf, ((0, gap), (0, 0)))
        counts = jnp.pad(counts, (0, gap))
        prof = jnp.pad(prof, ((0, gap), (0, 0)))
        w = jnp.pad(w, (0, gap))
    grid = (m_pad // ROWS_TILE,)
    row_spec = pl.BlockSpec((ROWS_TILE, b), lambda i: (i, 0))
    col_spec = pl.BlockSpec((ROWS_TILE, 1), lambda i: (i, 0))
    out = pl.pallas_call(
        _div_kernel,
        grid=grid,
        in_specs=[row_spec, col_spec, row_spec, col_spec],
        out_specs=(col_spec, col_spec, col_spec),
        out_shape=tuple(
            jax.ShapeDtypeStruct((m_pad, 1), jnp.float32) for _ in range(3)
        ),
        interpret=interpret,
    )(cdf, counts[:, None], prof, w[:, None])
    return tuple(o[:m, 0] for o in out)


def resolve_divergence_path(path: str, platform: str, mesh: bool) -> str:
    """Dispatch policy for the divergence tier, mirroring
    resolve_merge_path: "auto" picks Pallas only single-device on real
    TPU (Pallas under shard_map is off the table; interpret mode off-TPU
    is strictly slower than the jnp form)."""
    if path not in ("auto", "jnp", "pallas"):
        raise ValueError(
            f"divergence_path={path!r}: expected 'auto', 'jnp', or "
            "'pallas'"
        )
    if path == "auto":
        return "pallas" if (platform == "tpu" and not mesh) else "jnp"
    if path == "pallas" and mesh:
        raise ValueError("divergence_path='pallas' is single-device; use "
                         "jnp with a mesh")
    return path


def divergence_scores(cdf, counts, prof, wsum, bank, min_samples,
                      path: str = "jnp"):
    """Full scoring pass: live window CDF vs the active baseline bank.

      cdf         int32 [M, B] — snapshot view CDF (commit-time payload)
      counts      int32 [M]    — snapshot view totals
      prof/wsum   f32 [K, Mb, B] / f32 [K, Mb] — the baseline bank
      bank        traced int32 scalar — bank to compare against
      min_samples traced int32 scalar — the min-sample floor

    Returns {"ks", "jsd", "emd"}: f32 [M] each, exactly 0 for rows below
    the floor or without an established baseline (wsum == 0 — including
    every row past the bank's high-water when the accumulator grew).
    The bank gather, both tiers' row math, and the floor mask all trace
    into ONE jitted program: one device dispatch per scoring pass.
    """
    m = cdf.shape[0]
    bprof = prof[bank]
    bw = wsum[bank]
    mb = bprof.shape[0]
    if mb < m:
        # the accumulator/wheel grew past the bank (rare, between carry
        # growth points): new rows have no baseline — masked below
        bprof = jnp.pad(bprof, ((0, m - mb), (0, 0)))
        bw = jnp.pad(bw, (0, m - mb))
    else:
        bprof = bprof[:m]
        bw = bw[:m]
    if path == "pallas":
        ks, jsd, emd = divergence_pallas(cdf, counts, bprof, bw)
    else:
        ks, jsd, emd = _row_divergence(cdf, counts, bprof, bw)
    valid = (counts >= min_samples) & (bw > 0)
    zero = jnp.float32(0.0)
    return {
        "ks": jnp.where(valid, ks, zero),
        "jsd": jnp.where(valid, jsd, zero),
        "emd": jnp.where(valid, emd, zero),
    }


@functools.lru_cache(maxsize=None)
def make_divergence_fn(path: str = "jnp"):
    """Jitted ``div(cdf, counts, prof, wsum, bank, min_samples) ->
    {"ks","jsd","emd"}`` — the drift engine's single per-interval
    dispatch.  Cached per path; bank and min_samples are traced, so bank
    rotation (time-of-day) never recompiles.  Snapshot payloads are
    never donated (they back the lock-free query handles), so neither
    are the operands here."""

    @jax.jit
    def div(cdf, counts, prof, wsum, bank, min_samples):
        return divergence_scores(
            cdf, counts, prof, wsum, bank, min_samples, path
        )

    return div


# ---------------------------------------------------------------------- #
# lifecycle integration: bank eviction + compaction
# ---------------------------------------------------------------------- #


@functools.lru_cache(maxsize=None)
def make_bank_evict_fn():
    """``evict(prof, wsum, ihist, victims) -> (prof, wsum, ihist)``:
    zero the victims' baselines and interval-histogram rows in one
    donated dispatch (DROP_ID pads shed).  A freed row's next tenant
    must build its baseline from scratch — leaking the dead series'
    shape would score the newcomer against a stranger's history."""

    @functools.partial(jax.jit, donate_argnums=(0, 1, 2))
    def evict(prof, wsum, ihist, victims):
        prof = prof.at[:, victims].set(0.0, mode="drop")
        wsum = wsum.at[:, victims].set(0.0, mode="drop")
        ihist = ihist.at[victims].set(0, mode="drop")
        return prof, wsum, ihist

    return evict


@functools.lru_cache(maxsize=None)
def make_bank_compact_fn():
    """``compact(prof, wsum, ihist, perm) -> (prof, wsum, ihist)``:
    apply the lifecycle's survivor permutation (``perm[new] = old``,
    DROP sentinel = empty -> zeros) to every bank carry — the same
    one-gather-per-structure repack as ops.lifecycle.make_compact_fn,
    so baselines follow their rows and freed rows come back cold."""

    @functools.partial(jax.jit, donate_argnums=(0, 1, 2))
    def compact(prof, wsum, ihist, perm):
        mb = prof.shape[1]
        sp = _sanitize_perm(perm[:mb], mb)
        prof = jnp.take(prof, sp, axis=1, mode="fill", fill_value=0)
        wsum = jnp.take(wsum, sp, axis=1, mode="fill", fill_value=0)
        mi = ihist.shape[0]
        ihist = jnp.take(
            ihist, _sanitize_perm(perm[:mi], mi), axis=0,
            mode="fill", fill_value=0,
        )
        return prof, wsum, ihist

    return compact
