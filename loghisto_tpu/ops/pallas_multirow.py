"""Multi-row Pallas TPU ingest: metric-tiled histogram accumulation.

The general [num_metrics, num_buckets] scatter-add gives XLA little to
tile.  This kernel restructures the batch so the MXU does the work:

  1. (XLA preprocess, all static shapes) bucket the samples, group them
     by *metric row block* (rows_tile consecutive rows) with a sort, and
     lay them out so every SAMPLE_TILE-sized tile contains samples of
     exactly one block — block segments are padded up to tile boundaries
     with filler entries (row index == rows_tile, which the one-hot
     drops).
  2. (Pallas kernel) grid over sample tiles; a scalar-prefetched
     `tile_block` array routes each tile's accumulator block: the aliased
     acc block (rows_tile, padded_buckets) stays resident in VMEM across
     the consecutive tiles of one block, each tile adding a
     [rows_tile*H, 128] one-hot matmul (MXU) of its samples.

HBM traffic per batch is the sorted sample layout in + each touched
block in/out once — compare scatter's per-sample random access.  The
sort itself is XLA's (fast on TPU), and the layout padding overhead is
bounded by one tile per block.

The accumulator lives in a lane-padded layout [M, H*128] (H =
ceil(num_buckets/128)); `finalize` slices back to [M, num_buckets].
Unlike the single-row kernel (whose f32 scratch spans the whole call),
per-tile f32 accumulation here is bounded by SAMPLE_TILE before the int32
cast, so exactness is limited only by int32 per-cell overflow at 2^31 —
the same contract as the scatter path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from loghisto_tpu.config import PRECISION
from loghisto_tpu.ops.ingest import bucket_indices
from loghisto_tpu.ops.backend import default_interpret
from loghisto_tpu.ops.pallas_kernels import LANES, SAMPLE_TILE


def preprocess(
    ids: jnp.ndarray,
    values: jnp.ndarray,
    num_metrics: int,
    rows_tile: int,
    bucket_limit: int,
    precision: int = PRECISION,
    sample_tile: int = SAMPLE_TILE,
):
    """Sort and block-pad one batch.

    Returns (layout_rows [G*T], layout_bidx [G*T], tile_block [G]) where
    G = ceil(N/T) + n_blocks (static): every tile's samples belong to one
    block, filler entries carry row == rows_tile.
    """
    n = ids.shape[0]
    t = sample_tile
    n_blocks = num_metrics // rows_tile
    g = (n + t - 1) // t + n_blocks

    bidx = bucket_indices(values, bucket_limit, precision)
    valid = (ids >= 0) & (ids < num_metrics)
    block = jnp.where(valid, ids // rows_tile, n_blocks - 1)
    row_in_block = jnp.where(
        valid, ids - block * rows_tile, rows_tile  # filler drops in one-hot
    )

    order = jnp.argsort(block)
    sorted_block = block[order]
    sorted_row = row_in_block[order]
    sorted_bidx = bidx[order]

    counts = jnp.bincount(sorted_block, length=n_blocks)
    tiles_per_block = (counts + t - 1) // t
    start_tile = jnp.concatenate(
        [jnp.zeros(1, dtype=tiles_per_block.dtype),
         jnp.cumsum(tiles_per_block)[:-1]]
    )
    padded_start = start_tile * t  # sample-slot offset of each block
    sample_start = jnp.concatenate(
        [jnp.zeros(1, dtype=counts.dtype), jnp.cumsum(counts)[:-1]]
    )
    rank = jnp.arange(n) - sample_start[sorted_block]
    dest = padded_start[sorted_block] + rank

    layout_rows = jnp.full(g * t, rows_tile, dtype=jnp.int32)
    layout_bidx = jnp.zeros(g * t, dtype=jnp.int32)
    layout_rows = layout_rows.at[dest].set(sorted_row.astype(jnp.int32))
    layout_bidx = layout_bidx.at[dest].set(sorted_bidx.astype(jnp.int32))

    # tile -> block routing; tiles beyond the used range park on the last
    # block (their entries are all filler)
    tile_ids = jnp.arange(g)
    tile_block = (
        jnp.searchsorted(start_tile, tile_ids, side="right") - 1
    ).astype(jnp.int32)
    tile_block = jnp.clip(tile_block, 0, n_blocks - 1)
    return layout_rows, layout_bidx, tile_block


def _kernel(tile_block_ref, rows_ref, bidx_ref, acc_in_ref, acc_out_ref, *,
            rows_tile: int, h: int):
    i = pl.program_id(0)
    rows = rows_ref[0, :]
    bidx = bidx_ref[0, :]
    hi = bidx // LANES
    lo = bidx % LANES
    col = rows * h + hi  # filler rows land at >= rows_tile*h -> one-hot 0
    onehot_col = jax.nn.one_hot(col, rows_tile * h, dtype=jnp.bfloat16)
    onehot_lo = jax.nn.one_hot(lo, LANES, dtype=jnp.bfloat16)
    partial = jax.lax.dot_general(
        onehot_col, onehot_lo,
        dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ).reshape(rows_tile, h * LANES).astype(jnp.int32)

    # Consecutive tiles of one block keep the output block resident; the
    # aliased INPUT block may be re-fetched stale on revisits, so it is
    # only read on the block's first tile — afterwards accumulate in the
    # resident output block.
    first_visit = jnp.logical_or(
        i == 0, tile_block_ref[i] != tile_block_ref[jnp.maximum(i - 1, 0)]
    )

    @pl.when(first_visit)
    def _init():
        acc_out_ref[:] = acc_in_ref[:] + partial

    @pl.when(jnp.logical_not(first_visit))
    def _accumulate():
        acc_out_ref[:] = acc_out_ref[:] + partial


def make_multirow_ingest(
    num_metrics: int,
    bucket_limit: int,
    precision: int = PRECISION,
    rows_tile: int = 8,
    interpret: bool | None = None,
):
    """Build (init, ingest, finalize) for the metric-tiled Pallas path.

      init() -> padded acc int32 [num_metrics, H*128]
      ingest(acc, ids, values) -> acc     (jitted, donated acc)
      finalize(acc) -> int32 [num_metrics, 2*bucket_limit+1]
    """
    if num_metrics % rows_tile:
        raise ValueError(
            f"num_metrics={num_metrics} must divide by rows_tile={rows_tile}"
        )
    if interpret is None:
        interpret = default_interpret()
    num_buckets = 2 * bucket_limit + 1
    h = (num_buckets + LANES - 1) // LANES
    b_pad = h * LANES

    def init():
        return jnp.zeros((num_metrics, b_pad), dtype=jnp.int32)

    kernel = functools.partial(_kernel, rows_tile=rows_tile, h=h)

    @functools.partial(jax.jit, donate_argnums=0)
    def ingest(acc, ids, values):
        rows, bidx, tile_block = preprocess(
            ids, values, num_metrics, rows_tile, bucket_limit, precision
        )
        g = tile_block.shape[0]
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(g,),
            in_specs=[
                # lane-axis grid over a [1, G*T] layout: Mosaic rejects
                # block [1, T] on a [G, T] array (dim -2 must be
                # 8-divisible or equal the array dim — see pallas_kernels)
                pl.BlockSpec((1, SAMPLE_TILE), lambda i, tb: (0, i)),
                pl.BlockSpec((1, SAMPLE_TILE), lambda i, tb: (0, i)),
                pl.BlockSpec((rows_tile, b_pad), lambda i, tb: (tb[i], 0)),
            ],
            out_specs=pl.BlockSpec(
                (rows_tile, b_pad), lambda i, tb: (tb[i], 0)
            ),
        )
        return pl.pallas_call(
            kernel,
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((num_metrics, b_pad), jnp.int32),
            # flattened input index incl. the scalar-prefetch operand:
            # 0=tile_block, 1=rows, 2=bidx, 3=acc
            input_output_aliases={3: 0},
            interpret=interpret,
        )(
            tile_block,
            rows.reshape(1, g * SAMPLE_TILE),
            bidx.reshape(1, g * SAMPLE_TILE),
            acc,
        )

    def finalize(acc):
        return acc[:, :num_buckets]

    return init, ingest, finalize
