"""Device programs for the metric lifecycle subsystem (ISSUE 4):
activity touch, evict-fold, and the gather-compact repack.

The paper's lossless-counting claim only survives name churn if series
can be RETIRED without losing their counts: log-bucket histograms merge
exactly by elementwise addition, so an evicted row folds into a
catch-all overflow row with zero information loss at the bucket level.
These kernels keep the whole lifecycle on-device over the same donated
carries the fused commit owns:

  * ``make_touch_fn`` — per-interval activity scatter for the fan-out
    path (the fused commit embeds the same update at zero extra
    dispatches; see ops/commit.py ``track_activity``).
  * ``make_fold_evict_fn`` — gather each victim row, scatter-add it
    into its overflow target, zero the victim, stamp ``last_active`` —
    one dispatch for the accumulator and every tier ring together.
  * ``make_compact_fn`` — repack every structure over a survivor
    permutation (``perm[new] = old`` row, DROP sentinel = empty) in one
    gather per structure; jnp ``take`` tier plus a Pallas
    scalar-prefetch tier where the permutation itself drives the block
    index_map, so each output row is read and written exactly once.

Out-of-range handling follows the house convention: DROP_ID pads
(ops/commit.py) vanish via ``mode="drop"`` scatters and zero-fill
gathers, so every program is shape-stable under jit — pad widths are
pow-2 bucketed by the callers to bound executable counts.

Mesh-sharded state (PR 8): these programs run unchanged on
metric-row-sharded carries.  Victim decisions stay host-side (the
manager gathers the activity vector, which is tiny), and the fold /
compact programs jit over the sharded arrays — the victim gathers and
permutation ``take``s address GLOBAL row ids, so GSPMD inserts the
cross-shard collectives where a victim's overflow target lives on a
different shard.  Only the per-interval hot path (the activity stamp
inside the fused commit) is hand-placed under ``shard_map``
(ops/commit.py); eviction and compaction are rare, so auto-partitioning
is the right trade there.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from loghisto_tpu.ops.commit import DROP_ID
from loghisto_tpu.ops.backend import default_interpret


@functools.lru_cache(maxsize=None)
def make_touch_fn():
    """Jitted activity stamp for the fan-out commit path:
    ``touch(last_active, ids, epoch) -> last_active`` sets
    ``last_active[ids] = max(last_active[ids], epoch)`` with DROP_ID
    pads shedding.  The fused commit performs the identical update
    inside its own program; this standalone form exists for paths that
    cannot fuse (spill fallback, mesh fan-out)."""

    @functools.partial(jax.jit, donate_argnums=(0,))
    def touch(last_active, ids, epoch):
        return last_active.at[ids].max(epoch, mode="drop")

    return touch


@functools.lru_cache(maxsize=None)
def make_fold_evict_fn(num_tiers: int, with_acc: bool = True):
    """Build the evict-fold program for ``num_tiers`` retention tiers.

    ``with_acc=False`` is the paged-storage variant (r18): the lifetime
    accumulator lives in the page pool, whose fold is a host translate +
    pool commit (PagedStore.fold_rows_into) — so the device program
    folds only the tier rings and stamps the activity vector:
    ``fold(rings, last_active, victims, targets, epoch) -> (rings,
    last_active)``.  Victim-count accounting moves to the pool fold's
    exact host return value.

    With ``with_acc=True`` (dense):
    ``fold(acc, rings, last_active, victims, targets, epoch) ->
    (acc, rings, last_active, victim_counts)`` where

      acc         int32 [M, B]        — aggregator accumulator (donated)
      rings       tuple int32 [S,M_t,B] — tier rings (donated)
      last_active int32 [M]           — activity epochs (donated)
      victims     int32 [E]           — rows being evicted (DROP_ID pad)
      targets     int32 [E]           — overflow row for each victim
      epoch       int32 scalar        — stamped on the freed rows so a
                                        reused slot starts fresh

    Per structure: gather the victim rows (out-of-range -> zero), ONE
    scatter-add into the overflow targets (duplicate targets accumulate
    — integer scatter-adds are order-independent, so folding E victims
    is bit-identical to E sequential merges), then zero the victims.
    Victims whose id exceeds a ring's row space simply never had window
    state there; targets beyond it drop, which loses only *windowed*
    visibility of the overflow — the lifetime fold into ``acc`` is the
    lossless one.  ``victim_counts`` (int32 [E], bucket-sum per victim)
    feeds the lifecycle gauges; exact lifetime accounting is the host
    ``_agg`` fold in lifecycle/manager.py, which uses Python ints.

    Targets must never themselves be victims (the policy layer protects
    overflow names), so add-then-zero ordering is safe.
    """

    def _fold_rings(rings, last_active, victims, targets, epoch):
        new_rings = []
        for t in range(num_tiers):
            ring = rings[t]
            rrows = jnp.take(ring, victims, axis=1, mode="fill",
                             fill_value=0)
            ring = ring.at[:, targets].add(rrows, mode="drop")
            ring = ring.at[:, victims].set(0, mode="drop")
            new_rings.append(ring)
        last_active = last_active.at[victims].set(epoch, mode="drop")
        return tuple(new_rings), last_active

    if not with_acc:

        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def fold_paged(rings, last_active, victims, targets, epoch):
            return _fold_rings(rings, last_active, victims, targets, epoch)

        return fold_paged

    @functools.partial(jax.jit, donate_argnums=(0, 1, 2))
    def fold(acc, rings, last_active, victims, targets, epoch):
        rows = jnp.take(acc, victims, axis=0, mode="fill", fill_value=0)
        victim_counts = jnp.sum(rows, axis=1)
        acc = acc.at[targets].add(rows, mode="drop")
        acc = acc.at[victims].set(0, mode="drop")
        new_rings, last_active = _fold_rings(
            rings, last_active, victims, targets, epoch
        )
        return acc, new_rings, last_active, victim_counts

    return fold


# -- gather-compact ------------------------------------------------------ #


def _sanitize_perm(perm: jnp.ndarray, m: int) -> jnp.ndarray:
    """Map every out-of-range entry (DROP_ID pad OR explicit -1 hole) to
    the positive DROP sentinel: jnp's ``mode="fill"`` wraps negative
    indices BEFORE its bounds check, so a raw -1 would gather the last
    row instead of filling zero."""
    return jnp.where(
        (perm >= 0) & (perm < m), perm.astype(jnp.int32), DROP_ID
    )


def compact_rows(arr: jnp.ndarray, perm: jnp.ndarray) -> jnp.ndarray:
    """jnp tier of the row repack: ``out[new] = arr[perm[new]]``, zeros
    where ``perm[new]`` is out of range (DROP_ID = empty row).  One
    gather; XLA partitions it row-parallel under a metric-sharded
    mesh."""
    return jnp.take(
        arr, _sanitize_perm(perm, arr.shape[0]), axis=0,
        mode="fill", fill_value=0,
    )


def _compact_kernel(perm_ref, in_ref, out_ref):
    i = pl.program_id(0)

    # the index_map clamped an empty row's source to 0; zero it here
    out_ref[:] = jnp.where(perm_ref[i] >= 0, in_ref[:], 0)


def compact_rows_pallas(
    arr: jnp.ndarray,
    perm: jnp.ndarray,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Pallas tier: the survivor permutation rides scalar prefetch and
    drives the input BlockSpec's index_map directly, so the repack reads
    each survivor row from HBM once and writes each output row once —
    the same bandwidth-floor structure as window_merge_pallas, with the
    gather hidden in block indexing instead of a device-side take.
    Empty rows (negative / DROP sentinel) clamp to row 0 for the fetch
    and are zeroed in the kernel."""
    if interpret is None:
        interpret = default_interpret()
    m, b = arr.shape
    n = perm.shape[0]
    # sanitize the sentinel into -1 so the kernel's sign test works for
    # both DROP_ID pads and explicit -1 holes
    perm32 = jnp.where(
        (perm >= 0) & (perm < m), perm.astype(jnp.int32), -1
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n,),
        in_specs=[
            pl.BlockSpec((1, b), lambda i, pr: (jnp.maximum(pr[i], 0), 0)),
        ],
        out_specs=pl.BlockSpec((1, b), lambda i, pr: (i, 0)),
    )
    return pl.pallas_call(
        _compact_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((n, b), arr.dtype),
        interpret=interpret,
    )(perm32, arr)


def resolve_compact_path(path: str, platform: str, mesh: bool) -> str:
    """Dispatch policy for the repack, mirroring resolve_merge_path:
    "auto" picks the Pallas tier only single-device on real TPU (Pallas
    under shard_map is off the table; interpret mode off-TPU is strictly
    slower than the jnp gather)."""
    if path not in ("auto", "jnp", "pallas"):
        raise ValueError(
            f"compact_path={path!r}: expected 'auto', 'jnp', or 'pallas'"
        )
    if path == "auto":
        return "pallas" if (platform == "tpu" and not mesh) else "jnp"
    if path == "pallas" and mesh:
        raise ValueError("compact_path='pallas' is single-device; use "
                         "jnp with a mesh")
    return path


@functools.lru_cache(maxsize=None)
def make_compact_fn(num_tiers: int, path: str = "jnp",
                    with_acc: bool = True):
    """Build the full-repack program: one donated-carry dispatch that
    reorders the accumulator, every tier ring, and the activity vector
    over the survivor permutation.

    ``with_acc=False`` is the paged-storage variant (r18): the pool
    repacks on host (PagedStore.apply_permutation permutes page-table
    ROWS — zero device data movement), so the device program handles
    only the rings and the activity vector:
    ``compact(rings, last_active, perm, epoch) -> (rings, last_active)``.

    ``compact(acc, rings, last_active, perm, epoch) ->
    (acc, rings, last_active)`` where ``perm`` is int32 [M] with
    ``perm[new] = old`` row (DROP sentinel = empty).  Shapes never
    change — compaction re-DENSIFIES rows toward the front so the
    registry free-list hands out low ids again; HBM stays bounded
    because rows are reused, not because arrays shrink mid-flight.
    Every output row is a pure copy of one input row (or zeros), so
    survivor histograms — and therefore every percentile derived from
    them — are bit-identical across the repack (tests/test_lifecycle.py
    pins this against a pre-compaction oracle).  Freed rows get
    ``last_active = epoch`` so reuse starts fresh.
    """

    def repack(arr2d, perm):
        if path == "pallas":
            return compact_rows_pallas(arr2d, perm)
        return compact_rows(arr2d, perm)

    def _compact_rings(rings, last_active, perm, epoch):
        new_rings = []
        for t in range(num_tiers):
            ring = rings[t]
            m_t = ring.shape[1]
            if path == "pallas":
                ring = jax.vmap(compact_rows_pallas,
                                in_axes=(0, None))(ring, perm[:m_t])
            else:
                ring = jnp.take(
                    ring, _sanitize_perm(perm[:m_t], m_t), axis=1,
                    mode="fill", fill_value=0,
                )
            new_rings.append(ring)
        la = jnp.take(
            last_active, _sanitize_perm(perm, last_active.shape[0]),
            axis=0, mode="fill", fill_value=0,
        )
        empty = (perm < 0) | (perm >= last_active.shape[0])
        last_active = jnp.where(empty, epoch, la)
        return tuple(new_rings), last_active

    if not with_acc:

        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def compact_paged(rings, last_active, perm, epoch):
            return _compact_rings(rings, last_active, perm, epoch)

        return compact_paged

    @functools.partial(jax.jit, donate_argnums=(0, 1, 2))
    def compact(acc, rings, last_active, perm, epoch):
        acc = repack(acc, perm)
        new_rings, last_active = _compact_rings(
            rings, last_active, perm, epoch
        )
        return acc, new_rings, last_active

    return compact


def pad_pow2_ids(ids, min_width: int = 8):
    """Pad a host id vector to the next pow-2 width with DROP_ID, so the
    evict/compact programs compile one executable per width bucket
    instead of one per victim count (same policy as
    QueryPlanCache.pad_ids)."""
    import numpy as np

    n = len(ids)
    width = max(min_width, 1 << max(0, (int(n) - 1).bit_length()))
    out = np.full(width, DROP_ID, dtype=np.int32)
    out[:n] = np.asarray(ids, dtype=np.int32)
    return out
