"""Fused sample->scatter Pallas TPU ingest: raw values + metric ids to
dense [M, B] int32 accumulator in ONE device dispatch.

Every prior multi-metric path splits the work in two: a compress stage
that materializes a bucket-index array in HBM, then a scatter (or
one-hot matmul) stage that consumes it.  The circllhist observation
(PAPERS.md) is that log-linear bucket selection is pure bit/exponent
arithmetic — VPU work that belongs in the SAME kernel as the
accumulate, like SNIPPETS.md [2]'s histogram kernel which avoids
``searchsorted`` for exactly this reason.  This module fuses the whole
pipeline:

  1. (XLA preprocess, all static shapes, fused into the same jitted
     program) group samples by *metric row block* (rows_tile consecutive
     rows) with one sort, and lay the RAW values out so every
     SAMPLE_TILE-sized tile holds samples of exactly one block — the
     ``pallas_multirow.py`` tiling idiom, except no bucket index is ever
     computed here: the layout carries float32 values, not bucket ids.
  2. (Pallas kernel) grid over sample tiles routed by a
     scalar-prefetched ``tile_block`` map.  Each tile compresses its
     values on the VPU (``bucket_indices`` — the same codec function as
     the scatter path, so the contract can never diverge), forms the
     one-hots in VMEM, and accumulates a [rows_tile*H, 128] matmul on
     the MXU straight into the aliased accumulator block.

Compared to the multirow kernel this (a) moves the codec on-chip — the
bucket-index array never exists in HBM — and (b) drops the lane-padded
accumulator layout: the acc block is (rows_tile, B) with B equal to the
array's own minor dim, which Mosaic accepts (a block dim may equal the
array dim instead of being 8/128-divisible), so the kernel aliases the
product's [M, B] accumulator directly and plugs into the uniform
``f(acc, ids, values, bucket_limit, precision)`` dispatch contract.

Exactness contract (same as every other path): per-tile f32 one-hot
accumulation is bounded by SAMPLE_TILE < 2^24 before the int32 cast;
cross-tile accumulation is integer; per-cell overflow at 2^31 is the
caller's spill bound.  Invalid ids (< 0 or >= M) take the filler row,
which the one-hot drops — bit-identical to sanitize_ids + mode="drop".

The jnp fallback for CPU/GPU is ``ops.ingest.ingest_batch`` itself —
re-exported as ``fused_ingest_reference`` — because that scatter
composition IS the semantics the kernel must reproduce bit-for-bit
(tests/test_fused_ingest.py pins the parity across denormals, negative
values, inf/NaN sanitization, row-boundary ids, and empty batches).

Direct-to-paged fusion (r17)
----------------------------

``fused_paged_ingest_batch`` extends the fusion all the way into the
paged backend (ops/paged_store.py): through r16, paged mode paid a
host fold (raw batch -> packed triples) plus a host page-table
translate before its pool commit dispatch — and combining the r13
kernel with paged storage would have materialized the dense [M, B]
accumulator only to re-encode and recommit it.  Here the whole
pipeline runs in ONE donated jitted program per batch:

  1. (XLA preprocess, same program) compress every raw value with the
     shared ``bucket_indices`` codec, gather the row's codec *encode*
     LUT (``enc_luts[row_codec[row], dense]`` — the circllhist
     log-linear / polytail layouts reduced to LUTs by
     loghisto_tpu/paging.py), gather the device page-table mirror to a
     flat pool cell (slot * page_size + offset), and fold duplicate
     cells with one sort + segment-sum — all static [N] shapes, no
     [M, B] tensor ever exists.  Invalid ids (and cells whose page the
     host declined) park on the sentinel flat index, sort to the end,
     and become the dropped filler cell; the reserved slot-0 zero page
     stays the unmapped-read mask and is never written.
  2. (Pallas kernel — the ONE pallas_call of the program) the folded
     (slot, offset, count) cells take the sparse-ingest per-cell DMA
     scatter with pool pages as the rows (``pallas_paged_scatter``):
     serial grid, int32 adds — exact cross-tile accumulation by
     construction.

The host half (PagedStore.prepare_batch) stays off the dispatch path:
codec assignment and page allocation for everything a batch touches
happen in one vectorized pass on the transfer worker BEFORE the upload,
so the page table never blocks the dispatch.  Bit-identity oracle: jnp
encode + ``paged_scatter_batch`` over per-sample triples
(tests/test_fused_paged.py pins it across all three codecs).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from loghisto_tpu.config import PRECISION
from loghisto_tpu.ops.backend import default_interpret
from loghisto_tpu.ops.ingest import bucket_indices
from loghisto_tpu.ops.ingest import ingest_batch as fused_ingest_reference  # noqa: F401
from loghisto_tpu.ops.paged_store import (
    ZERO_SLOT,
    paged_scatter_batch,
    pallas_paged_scatter,
)
from loghisto_tpu.ops.pallas_kernels import LANES, SAMPLE_TILE

# Metric rows per accumulator block resident in VMEM.  8 matches the
# multirow kernel (and the sublane tile), keeps the one-hot column space
# rows_tile*H narrow enough for VMEM at 8k buckets, and is what
# TPUAggregator._grow_row_unit preserves under registry growth.
ROWS_TILE = 8


def preprocess_values(
    ids: jnp.ndarray,
    values: jnp.ndarray,
    num_metrics: int,
    rows_tile: int = ROWS_TILE,
    sample_tile: int = SAMPLE_TILE,
):
    """Sort and block-pad one RAW batch (no bucketing happens here).

    Returns (layout_rows [G*T] int32, layout_vals [G*T] float32,
    tile_block [G] int32) with G = ceil(N/T) + n_blocks (static): every
    tile's samples belong to one metric block, filler entries carry
    row == rows_tile (dropped by the kernel's one-hot) and value 0.0.
    The searchsorted below routes TILES to blocks (an O(G) map over
    static shapes) — per-sample bucket selection stays on the VPU
    inside the kernel.
    """
    n = ids.shape[0]
    t = sample_tile
    n_blocks = num_metrics // rows_tile
    g = (n + t - 1) // t + n_blocks

    values = values.astype(jnp.float32)
    valid = (ids >= 0) & (ids < num_metrics)
    block = jnp.where(valid, ids // rows_tile, n_blocks - 1)
    row_in_block = jnp.where(
        valid, ids - block * rows_tile, rows_tile  # filler drops in one-hot
    )

    order = jnp.argsort(block)
    sorted_block = block[order]
    sorted_row = row_in_block[order]
    sorted_vals = values[order]

    counts = jnp.bincount(sorted_block, length=n_blocks)
    tiles_per_block = (counts + t - 1) // t
    start_tile = jnp.concatenate(
        [jnp.zeros(1, dtype=tiles_per_block.dtype),
         jnp.cumsum(tiles_per_block)[:-1]]
    )
    padded_start = start_tile * t
    sample_start = jnp.concatenate(
        [jnp.zeros(1, dtype=counts.dtype), jnp.cumsum(counts)[:-1]]
    )
    rank = jnp.arange(n) - sample_start[sorted_block]
    dest = padded_start[sorted_block] + rank

    layout_rows = jnp.full(g * t, rows_tile, dtype=jnp.int32)
    layout_vals = jnp.zeros(g * t, dtype=jnp.float32)
    layout_rows = layout_rows.at[dest].set(sorted_row.astype(jnp.int32))
    layout_vals = layout_vals.at[dest].set(sorted_vals)

    tile_ids = jnp.arange(g)
    tile_block = (
        jnp.searchsorted(start_tile, tile_ids, side="right") - 1
    ).astype(jnp.int32)
    tile_block = jnp.clip(tile_block, 0, n_blocks - 1)
    return layout_rows, layout_vals, tile_block


def _kernel(tile_block_ref, rows_ref, vals_ref, acc_in_ref, acc_out_ref, *,
            rows_tile: int, h: int, num_buckets: int, bucket_limit: int,
            precision: int):
    i = pl.program_id(0)
    rows = rows_ref[0, :]
    v = vals_ref[0, :]
    # the fused step: codec on the VPU, inside the kernel — shared with
    # the scatter path so sign mirroring, NaN->bucket 0, and saturation
    # can never diverge (filler values are 0.0; their row drops them)
    bucket = bucket_indices(v, bucket_limit, precision)
    hi = bucket // LANES
    lo = bucket % LANES
    col = rows * h + hi  # filler rows land at >= rows_tile*h -> one-hot 0
    onehot_col = jax.nn.one_hot(col, rows_tile * h, dtype=jnp.bfloat16)
    onehot_lo = jax.nn.one_hot(lo, LANES, dtype=jnp.bfloat16)
    partial = jax.lax.dot_general(
        onehot_col, onehot_lo,
        dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ).reshape(rows_tile, h * LANES).astype(jnp.int32)[:, :num_buckets]

    # Consecutive tiles of one block keep the output block resident; the
    # aliased INPUT block may be re-fetched stale on revisits, so it is
    # only read on the block's first tile (see pallas_multirow._kernel).
    first_visit = jnp.logical_or(
        i == 0, tile_block_ref[i] != tile_block_ref[jnp.maximum(i - 1, 0)]
    )

    @pl.when(first_visit)
    def _init():
        acc_out_ref[:] = acc_in_ref[:] + partial

    @pl.when(jnp.logical_not(first_visit))
    def _accumulate():
        acc_out_ref[:] = acc_out_ref[:] + partial


def fused_ingest_batch(
    acc: jnp.ndarray,
    ids: jnp.ndarray,
    values: jnp.ndarray,
    bucket_limit: int,
    precision: int = PRECISION,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Uniform-contract fused step: acc int32 [M, B] (B = 2*bl+1,
    M % ROWS_TILE == 0), f(acc, ids, values) -> acc, ONE pallas_call and
    zero scatter dispatches (tests pin the jaxpr).  f64 values are cast
    to f32 at entry — the same canonicalization every other path gets.
    """
    if interpret is None:
        interpret = default_interpret()
    if acc.ndim != 2:
        raise ValueError(f"acc must be [M, B]; got shape {tuple(acc.shape)}")
    num_metrics, num_buckets = acc.shape
    if num_buckets != 2 * bucket_limit + 1:
        raise ValueError(
            f"acc has {num_buckets} buckets but bucket_limit={bucket_limit} "
            f"implies {2 * bucket_limit + 1}"
        )
    if num_metrics % ROWS_TILE:
        raise ValueError(
            f"fused ingest needs num_metrics % {ROWS_TILE} == 0; got "
            f"{num_metrics} (dispatch declines this shape before tracing)"
        )
    h = (num_buckets + LANES - 1) // LANES

    rows, vals, tile_block = preprocess_values(
        ids, values, num_metrics, ROWS_TILE
    )
    g = tile_block.shape[0]
    kernel = functools.partial(
        _kernel, rows_tile=ROWS_TILE, h=h, num_buckets=num_buckets,
        bucket_limit=bucket_limit, precision=precision,
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(g,),
        in_specs=[
            # lane-axis grid over [1, G*T] layouts (see pallas_multirow:
            # Mosaic rejects block [1, T] on a [G, T] array)
            pl.BlockSpec((1, SAMPLE_TILE), lambda i, tb: (0, i)),
            pl.BlockSpec((1, SAMPLE_TILE), lambda i, tb: (0, i)),
            # acc block minor dim == array minor dim: legal without lane
            # padding, so the product accumulator aliases directly
            pl.BlockSpec((ROWS_TILE, num_buckets), lambda i, tb: (tb[i], 0)),
        ],
        out_specs=pl.BlockSpec(
            (ROWS_TILE, num_buckets), lambda i, tb: (tb[i], 0)
        ),
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((num_metrics, num_buckets), jnp.int32),
        # flattened input index incl. the scalar-prefetch operand:
        # 0=tile_block, 1=rows, 2=vals, 3=acc
        input_output_aliases={3: 0},
        interpret=interpret,
    )(
        tile_block,
        rows.reshape(1, g * SAMPLE_TILE),
        vals.reshape(1, g * SAMPLE_TILE),
        acc,
    )


def make_fused_ingest_fn(
    bucket_limit: int,
    precision: int = PRECISION,
    interpret: bool | None = None,
):
    """Jitted, donated-accumulator fused step:
    f(acc [M, B], ids [N], values [N]) -> acc, one device dispatch."""

    @functools.partial(jax.jit, donate_argnums=0)
    def ingest(acc, ids, values):
        return fused_ingest_batch(
            acc, ids, values, bucket_limit, precision, interpret=interpret
        )

    return ingest


# Sentinel flat pool cell for samples that must drop (invalid id, row
# without a codec, page the host declined to map, zero-page hit).  One
# past the largest index validate_pool_shape admits, so the scatter's
# bounds guard discards it — the same "park past the end" idiom as
# paged_scatter_batch's mode="drop" filler.
_DROP_CELL = 2**31 - 2


def fused_paged_ingest_batch(
    pool: jnp.ndarray,
    ids: jnp.ndarray,
    values: jnp.ndarray,
    row_codec: jnp.ndarray,
    enc_luts: jnp.ndarray,
    page_table: jnp.ndarray,
    bucket_limit: int,
    precision: int = PRECISION,
    interpret: bool | None = None,
    kernel: str = "pallas",
) -> jnp.ndarray:
    """Direct-to-paged fused step: raw (ids, values) -> donated pool
    [P, page_size] int32 in ONE Pallas dispatch.

    ``kernel="jnp"`` swaps the final scatter for the XLA tier
    (``paged_scatter_batch``) — bit-identical by the paged-store parity
    pin, and legal inside shard_map where the per-cell DMA kernel is
    not (the resolve_compact_path policy: Pallas stays the
    single-device tier).

    The codec encode and page translate that paging.py performs on the
    host for the packed-commit path run here as three gathers on static
    [N] shapes; duplicate cells fold with one sort + segment-sum so the
    scatter sees each touched cell once per batch (per-cell DMA cost
    tracks UNIQUE cells, not samples).  Operands beyond the batch are
    the PagedStore device mirrors (``PagedStore.device_luts``):

      row_codec   int32 [M]        codec id per row (-1 = unassigned —
                                   those samples drop; the host assigns
                                   codecs in prepare_batch BEFORE the
                                   dispatch, so a -1 here means the host
                                   chose to spill the row)
      enc_luts    int32 [C, B]     per-codec dense->storage encode LUTs
      page_table  int32 [M, ppr]   pool slot per (row, storage page),
                                   -1 = unmapped (drops)

    Exactness: every count is an int32 add into the pool (the f32 path
    exists only inside bucket_indices, identical to every other tier);
    the segment fold is integer; ordering never matters.  Slot 0 (the
    reserved zero page) is excluded by the valid mask here AND shifted
    out of range by pallas_paged_scatter — double-guarded like the
    translate step.
    """
    pages, page_size = pool.shape
    if page_table.ndim != 2:
        raise ValueError(
            f"page_table must be [M, pages_per_row]; got {page_table.shape}"
        )
    if enc_luts.ndim != 2 or enc_luts.shape[1] != 2 * bucket_limit + 1:
        raise ValueError(
            f"enc_luts must be [codecs, {2 * bucket_limit + 1}]; got "
            f"{tuple(enc_luts.shape)}"
        )
    n = ids.shape[0]
    if n == 0:
        return pool
    num_metrics = page_table.shape[0]

    # -- XLA preprocess: compress -> encode -> translate -> fold, all
    #    static [N] shapes (no [M, B] array exists on this path) --
    dense = bucket_indices(values.astype(jnp.float32), bucket_limit, precision)
    valid = (ids >= 0) & (ids < num_metrics)
    row = jnp.where(valid, ids, 0).astype(jnp.int32)
    codec = row_codec[row]
    valid &= codec >= 0
    storage = enc_luts[jnp.maximum(codec, 0), dense]
    page_idx = storage // page_size
    offset = storage - page_idx * page_size
    slot = page_table[row, page_idx]
    valid &= slot > ZERO_SLOT
    flat = jnp.where(
        valid, slot * page_size + offset, jnp.int32(_DROP_CELL)
    )

    # fold duplicates: sort parks dropped samples at the end, then each
    # run of equal cells collapses to (cell, run length) on its first
    # position — everything else becomes a slot -1 filler triple
    sorted_flat = jnp.sort(flat)
    is_start = jnp.concatenate(
        [jnp.ones(1, dtype=bool), sorted_flat[1:] != sorted_flat[:-1]]
    )
    seg = jnp.cumsum(is_start.astype(jnp.int32)) - 1
    seg_counts = jnp.zeros(n, dtype=jnp.int32).at[seg].add(1)
    keep = is_start & (sorted_flat != _DROP_CELL)
    slots = jnp.where(keep, sorted_flat // page_size, jnp.int32(-1))
    offs = jnp.where(keep, sorted_flat % page_size, 0)
    counts = jnp.where(keep, seg_counts[seg], 0)
    packed = jnp.stack([slots, offs, counts], axis=1).astype(jnp.int32)

    if kernel == "jnp":
        return paged_scatter_batch(pool, packed)
    # -- the ONE pallas_call of the program --
    return pallas_paged_scatter(pool, packed, interpret=interpret)


def make_fused_paged_ingest_fn(
    bucket_limit: int,
    precision: int = PRECISION,
    interpret: bool | None = None,
):
    """Jitted, donated-pool direct-to-paged step: f(pool [P, page_size],
    ids [N], values [N], row_codec [M], enc_luts [C, B],
    page_table [M, ppr]) -> pool.  One executable per (pool shape, batch
    length, table shape); the aggregator fixes the batch length to its
    staging chunk and PagedStore re-makes the fn on table growth."""

    @functools.partial(jax.jit, donate_argnums=0)
    def ingest(pool, ids, values, row_codec, enc_luts, page_table):
        return fused_paged_ingest_batch(
            pool, ids, values, row_codec, enc_luts, page_table,
            bucket_limit, precision, interpret=interpret,
        )

    return ingest


def make_sharded_fused_paged_ingest_fn(
    mesh,
    rows_per_shard: int,
    shard_pages: int,
    bucket_limit: int,
    precision: int = PRECISION,
):
    """Mesh tier of the direct-to-paged step — same operand contract as
    ``make_fused_paged_ingest_fn`` (pool, ids, values, row_codec,
    enc_luts, page_table) with the pool laid out as per-metric-shard
    page arenas and the batch split over the stream axis.

    Inside one shard_map each device (a) keeps the ids its metric shard
    owns (re-based to local rows; foreign ids take the dropped filler),
    (b) localizes its page-table slice's GLOBAL slots to arena-local
    slots (rows only ever map pages from their own shard's arena —
    PagedStore's allocation invariant — so this is a pure re-base; the
    defensive mask drops anything else), (c) runs the whole
    compress->encode->translate->fold->scatter body on its [N/n_stream]
    batch slice with the jnp scatter tier, and (d) merges deltas with
    ONE stream-axis psum.  int32 adds commute and every sample is owned
    by exactly one metric shard, so the result is bit-identical to the
    single-device fused ingest over the same batch.

    ids.shape[0] must divide by the stream axis (the capability table
    screens batch sizes); rows_per_shard bakes into the executable, so
    PagedStore drops its cached fn on grow().
    """
    from jax.sharding import PartitionSpec as P

    from loghisto_tpu.parallel.mesh import METRIC_AXIS, STREAM_AXIS, shard_map

    def _local(pool_local, ids, values, row_codec_local, enc_luts, tbl_local):
        shard = jax.lax.axis_index(METRIC_AXIS)
        local_ids = ids - shard * rows_per_shard
        local_ids = jnp.where(
            (local_ids >= 0) & (local_ids < rows_per_shard),
            local_ids,
            jnp.int32(-1),
        )
        local_tbl = tbl_local - shard * shard_pages
        local_tbl = jnp.where(
            (tbl_local >= 0)
            & (local_tbl > ZERO_SLOT)
            & (local_tbl < shard_pages),
            local_tbl,
            jnp.int32(-1),
        )
        delta = fused_paged_ingest_batch(
            jnp.zeros_like(pool_local),
            local_ids,
            values,
            row_codec_local,
            enc_luts,
            local_tbl,
            bucket_limit,
            precision,
            kernel="jnp",
        )
        delta = jax.lax.psum(delta, STREAM_AXIS)
        return pool_local + delta

    sharded = shard_map(
        _local,
        mesh=mesh,
        in_specs=(
            P(METRIC_AXIS, None),
            P(STREAM_AXIS),
            P(STREAM_AXIS),
            P(METRIC_AXIS),
            P(),
            P(METRIC_AXIS, None),
        ),
        out_specs=P(METRIC_AXIS, None),
    )

    @functools.partial(jax.jit, donate_argnums=0)
    def ingest(pool, ids, values, row_codec, enc_luts, page_table):
        return sharded(pool, ids, values, row_codec, enc_luts, page_table)

    return ingest
