"""Fused sample->scatter Pallas TPU ingest: raw values + metric ids to
dense [M, B] int32 accumulator in ONE device dispatch.

Every prior multi-metric path splits the work in two: a compress stage
that materializes a bucket-index array in HBM, then a scatter (or
one-hot matmul) stage that consumes it.  The circllhist observation
(PAPERS.md) is that log-linear bucket selection is pure bit/exponent
arithmetic — VPU work that belongs in the SAME kernel as the
accumulate, like SNIPPETS.md [2]'s histogram kernel which avoids
``searchsorted`` for exactly this reason.  This module fuses the whole
pipeline:

  1. (XLA preprocess, all static shapes, fused into the same jitted
     program) group samples by *metric row block* (rows_tile consecutive
     rows) with one sort, and lay the RAW values out so every
     SAMPLE_TILE-sized tile holds samples of exactly one block — the
     ``pallas_multirow.py`` tiling idiom, except no bucket index is ever
     computed here: the layout carries float32 values, not bucket ids.
  2. (Pallas kernel) grid over sample tiles routed by a
     scalar-prefetched ``tile_block`` map.  Each tile compresses its
     values on the VPU (``bucket_indices`` — the same codec function as
     the scatter path, so the contract can never diverge), forms the
     one-hots in VMEM, and accumulates a [rows_tile*H, 128] matmul on
     the MXU straight into the aliased accumulator block.

Compared to the multirow kernel this (a) moves the codec on-chip — the
bucket-index array never exists in HBM — and (b) drops the lane-padded
accumulator layout: the acc block is (rows_tile, B) with B equal to the
array's own minor dim, which Mosaic accepts (a block dim may equal the
array dim instead of being 8/128-divisible), so the kernel aliases the
product's [M, B] accumulator directly and plugs into the uniform
``f(acc, ids, values, bucket_limit, precision)`` dispatch contract.

Exactness contract (same as every other path): per-tile f32 one-hot
accumulation is bounded by SAMPLE_TILE < 2^24 before the int32 cast;
cross-tile accumulation is integer; per-cell overflow at 2^31 is the
caller's spill bound.  Invalid ids (< 0 or >= M) take the filler row,
which the one-hot drops — bit-identical to sanitize_ids + mode="drop".

The jnp fallback for CPU/GPU is ``ops.ingest.ingest_batch`` itself —
re-exported as ``fused_ingest_reference`` — because that scatter
composition IS the semantics the kernel must reproduce bit-for-bit
(tests/test_fused_ingest.py pins the parity across denormals, negative
values, inf/NaN sanitization, row-boundary ids, and empty batches).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from loghisto_tpu.config import PRECISION
from loghisto_tpu.ops.backend import default_interpret
from loghisto_tpu.ops.ingest import bucket_indices
from loghisto_tpu.ops.ingest import ingest_batch as fused_ingest_reference  # noqa: F401
from loghisto_tpu.ops.pallas_kernels import LANES, SAMPLE_TILE

# Metric rows per accumulator block resident in VMEM.  8 matches the
# multirow kernel (and the sublane tile), keeps the one-hot column space
# rows_tile*H narrow enough for VMEM at 8k buckets, and is what
# TPUAggregator._grow_row_unit preserves under registry growth.
ROWS_TILE = 8


def preprocess_values(
    ids: jnp.ndarray,
    values: jnp.ndarray,
    num_metrics: int,
    rows_tile: int = ROWS_TILE,
    sample_tile: int = SAMPLE_TILE,
):
    """Sort and block-pad one RAW batch (no bucketing happens here).

    Returns (layout_rows [G*T] int32, layout_vals [G*T] float32,
    tile_block [G] int32) with G = ceil(N/T) + n_blocks (static): every
    tile's samples belong to one metric block, filler entries carry
    row == rows_tile (dropped by the kernel's one-hot) and value 0.0.
    The searchsorted below routes TILES to blocks (an O(G) map over
    static shapes) — per-sample bucket selection stays on the VPU
    inside the kernel.
    """
    n = ids.shape[0]
    t = sample_tile
    n_blocks = num_metrics // rows_tile
    g = (n + t - 1) // t + n_blocks

    values = values.astype(jnp.float32)
    valid = (ids >= 0) & (ids < num_metrics)
    block = jnp.where(valid, ids // rows_tile, n_blocks - 1)
    row_in_block = jnp.where(
        valid, ids - block * rows_tile, rows_tile  # filler drops in one-hot
    )

    order = jnp.argsort(block)
    sorted_block = block[order]
    sorted_row = row_in_block[order]
    sorted_vals = values[order]

    counts = jnp.bincount(sorted_block, length=n_blocks)
    tiles_per_block = (counts + t - 1) // t
    start_tile = jnp.concatenate(
        [jnp.zeros(1, dtype=tiles_per_block.dtype),
         jnp.cumsum(tiles_per_block)[:-1]]
    )
    padded_start = start_tile * t
    sample_start = jnp.concatenate(
        [jnp.zeros(1, dtype=counts.dtype), jnp.cumsum(counts)[:-1]]
    )
    rank = jnp.arange(n) - sample_start[sorted_block]
    dest = padded_start[sorted_block] + rank

    layout_rows = jnp.full(g * t, rows_tile, dtype=jnp.int32)
    layout_vals = jnp.zeros(g * t, dtype=jnp.float32)
    layout_rows = layout_rows.at[dest].set(sorted_row.astype(jnp.int32))
    layout_vals = layout_vals.at[dest].set(sorted_vals)

    tile_ids = jnp.arange(g)
    tile_block = (
        jnp.searchsorted(start_tile, tile_ids, side="right") - 1
    ).astype(jnp.int32)
    tile_block = jnp.clip(tile_block, 0, n_blocks - 1)
    return layout_rows, layout_vals, tile_block


def _kernel(tile_block_ref, rows_ref, vals_ref, acc_in_ref, acc_out_ref, *,
            rows_tile: int, h: int, num_buckets: int, bucket_limit: int,
            precision: int):
    i = pl.program_id(0)
    rows = rows_ref[0, :]
    v = vals_ref[0, :]
    # the fused step: codec on the VPU, inside the kernel — shared with
    # the scatter path so sign mirroring, NaN->bucket 0, and saturation
    # can never diverge (filler values are 0.0; their row drops them)
    bucket = bucket_indices(v, bucket_limit, precision)
    hi = bucket // LANES
    lo = bucket % LANES
    col = rows * h + hi  # filler rows land at >= rows_tile*h -> one-hot 0
    onehot_col = jax.nn.one_hot(col, rows_tile * h, dtype=jnp.bfloat16)
    onehot_lo = jax.nn.one_hot(lo, LANES, dtype=jnp.bfloat16)
    partial = jax.lax.dot_general(
        onehot_col, onehot_lo,
        dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ).reshape(rows_tile, h * LANES).astype(jnp.int32)[:, :num_buckets]

    # Consecutive tiles of one block keep the output block resident; the
    # aliased INPUT block may be re-fetched stale on revisits, so it is
    # only read on the block's first tile (see pallas_multirow._kernel).
    first_visit = jnp.logical_or(
        i == 0, tile_block_ref[i] != tile_block_ref[jnp.maximum(i - 1, 0)]
    )

    @pl.when(first_visit)
    def _init():
        acc_out_ref[:] = acc_in_ref[:] + partial

    @pl.when(jnp.logical_not(first_visit))
    def _accumulate():
        acc_out_ref[:] = acc_out_ref[:] + partial


def fused_ingest_batch(
    acc: jnp.ndarray,
    ids: jnp.ndarray,
    values: jnp.ndarray,
    bucket_limit: int,
    precision: int = PRECISION,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Uniform-contract fused step: acc int32 [M, B] (B = 2*bl+1,
    M % ROWS_TILE == 0), f(acc, ids, values) -> acc, ONE pallas_call and
    zero scatter dispatches (tests pin the jaxpr).  f64 values are cast
    to f32 at entry — the same canonicalization every other path gets.
    """
    if interpret is None:
        interpret = default_interpret()
    if acc.ndim != 2:
        raise ValueError(f"acc must be [M, B]; got shape {tuple(acc.shape)}")
    num_metrics, num_buckets = acc.shape
    if num_buckets != 2 * bucket_limit + 1:
        raise ValueError(
            f"acc has {num_buckets} buckets but bucket_limit={bucket_limit} "
            f"implies {2 * bucket_limit + 1}"
        )
    if num_metrics % ROWS_TILE:
        raise ValueError(
            f"fused ingest needs num_metrics % {ROWS_TILE} == 0; got "
            f"{num_metrics} (dispatch declines this shape before tracing)"
        )
    h = (num_buckets + LANES - 1) // LANES

    rows, vals, tile_block = preprocess_values(
        ids, values, num_metrics, ROWS_TILE
    )
    g = tile_block.shape[0]
    kernel = functools.partial(
        _kernel, rows_tile=ROWS_TILE, h=h, num_buckets=num_buckets,
        bucket_limit=bucket_limit, precision=precision,
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(g,),
        in_specs=[
            # lane-axis grid over [1, G*T] layouts (see pallas_multirow:
            # Mosaic rejects block [1, T] on a [G, T] array)
            pl.BlockSpec((1, SAMPLE_TILE), lambda i, tb: (0, i)),
            pl.BlockSpec((1, SAMPLE_TILE), lambda i, tb: (0, i)),
            # acc block minor dim == array minor dim: legal without lane
            # padding, so the product accumulator aliases directly
            pl.BlockSpec((ROWS_TILE, num_buckets), lambda i, tb: (tb[i], 0)),
        ],
        out_specs=pl.BlockSpec(
            (ROWS_TILE, num_buckets), lambda i, tb: (tb[i], 0)
        ),
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((num_metrics, num_buckets), jnp.int32),
        # flattened input index incl. the scalar-prefetch operand:
        # 0=tile_block, 1=rows, 2=vals, 3=acc
        input_output_aliases={3: 0},
        interpret=interpret,
    )(
        tile_block,
        rows.reshape(1, g * SAMPLE_TILE),
        vals.reshape(1, g * SAMPLE_TILE),
        acc,
    )


def make_fused_ingest_fn(
    bucket_limit: int,
    precision: int = PRECISION,
    interpret: bool | None = None,
):
    """Jitted, donated-accumulator fused step:
    f(acc [M, B], ids [N], values [N]) -> acc, one device dispatch."""

    @functools.partial(jax.jit, donate_argnums=0)
    def ingest(acc, ids, values):
        return fused_ingest_batch(
            acc, ids, values, bucket_limit, precision, interpret=interpret
        )

    return ingest
