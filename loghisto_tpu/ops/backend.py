"""Shared accelerator-backend probe for the Pallas kernel modules.

Every Pallas entry point in this repo picks compiled-vs-interpret mode
from the same question — "is the default JAX backend a real TPU?" — and
until r13 each module (ops/pallas_kernels.py, ops/sparse_ingest.py, and
the dispatch-adjacent callers) carried its own copy-pasted probe.  One
probe lives here now, with an env override so CI can pin the answer:

  LOGHISTO_FORCE_INTERPRET=1   every kernel runs in Pallas interpret
                               mode regardless of the detected platform
                               — deterministic CPU CI, and a TPU
                               debugging escape hatch.

The probe is intentionally exception-swallowing: ``jax.devices()`` can
raise during interpreter teardown or before a distributed runtime is
initialized, and "couldn't probe" must degrade to the safe answer
(interpret mode) rather than crash an import chain.
"""

from __future__ import annotations

import os

import jax

ENV_FORCE_INTERPRET = "LOGHISTO_FORCE_INTERPRET"
_TRUTHY = ("1", "true", "yes", "on")


def force_interpret() -> bool:
    """True when the env override pins interpret mode on."""
    raw = os.environ.get(ENV_FORCE_INTERPRET)
    return raw is not None and raw.strip().lower() in _TRUTHY


def on_tpu() -> bool:
    """True when kernels should compile for a real TPU.

    False on every other platform AND whenever LOGHISTO_FORCE_INTERPRET
    is set truthy — callers use ``interpret = not on_tpu()`` so the
    override flips every kernel to interpret mode in one place.
    """
    if force_interpret():
        return False
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


def default_interpret() -> bool:
    """The ``interpret=`` default for every pallas_call in this repo."""
    return not on_tpu()
