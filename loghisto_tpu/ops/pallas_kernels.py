"""Pallas TPU kernel: fused compress -> one-hot -> MXU-accumulate
histogram for a single metric row.

The XLA variant (ops/matmul_hist.py) materializes the two one-hot
matrices [N, H] and [N, 128] in HBM between fusion boundaries; this kernel
keeps everything on-chip: each grid step loads one sample tile into VMEM,
compresses it on the VPU, forms the one-hots in registers/VMEM, runs the
[H, T] x [T, 128] matmul on the MXU into a float32 VMEM scratch
accumulator, and only on the last step adds the scratch into the int32
output row.  HBM traffic is exactly `N * 4` bytes in + `B * 4` bytes out —
the information-theoretic minimum for this op.

This is the hot-op kernel for the reference's headline single-metric
benchmark (readme.md:27: ~20M samples/s/process in Go; the MXU sustains
~2 samples/cycle at 8k buckets).  The multi-metric scatter path stays on
XLA (see ops/ingest.py); per-metric-tile generalization is future work.

Falls back to interpret mode automatically off-TPU so CI exercises the
same code path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from loghisto_tpu.config import PRECISION
# shared backend probe (ops/backend.py); every kernel module now calls
# backend.default_interpret() directly (r14 probe dedup) — the `_on_tpu`
# alias stays importable for external callers only
from loghisto_tpu.ops.backend import default_interpret
from loghisto_tpu.ops.backend import on_tpu as _on_tpu  # noqa: F401
from loghisto_tpu.ops.ingest import bucket_indices

LANES = 128
SAMPLE_TILE = 2048
# float32 scratch accumulation is exact only below 2^24 per cell; bound the
# whole call so no cell can saturate silently.
MAX_SAMPLES_PER_CALL = 1 << 24


def _hist_kernel(values_ref, acc_ref, out_ref, scratch_ref, *,
                 bucket_limit: int, precision: int, h: int):
    """One grid step: accumulate one sample tile into the VMEM scratch."""
    i = pl.program_id(0)
    n_steps = pl.num_programs(0)

    @pl.when(i == 0)
    def _init():
        scratch_ref[:] = jnp.zeros_like(scratch_ref)

    v = values_ref[0, :]  # [T] float32
    # fused codec (VPU): shared with the scatter path so the contract
    # (sign mirroring, NaN->bucket 0, saturation) can never diverge
    bucket = bucket_indices(v, bucket_limit, precision)

    hi = bucket // LANES  # [T] in [0, h)
    lo = bucket % LANES

    # one-hots in VMEM; iota comparisons are VPU-native
    hi_iota = jax.lax.broadcasted_iota(jnp.int32, (v.shape[0], h), 1)
    lo_iota = jax.lax.broadcasted_iota(jnp.int32, (v.shape[0], LANES), 1)
    onehot_hi = (hi[:, None] == hi_iota).astype(jnp.bfloat16)  # [T, H]
    onehot_lo = (lo[:, None] == lo_iota).astype(jnp.bfloat16)  # [T, 128]

    # [H, T] x [T, 128] on the MXU, exact f32 integer accumulation
    partial = jax.lax.dot_general(
        onehot_hi, onehot_lo,
        dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    scratch_ref[:] += partial

    @pl.when(i == n_steps - 1)
    def _finalize():
        out_ref[:] = acc_ref[:] + scratch_ref[:].astype(jnp.int32)


def pallas_histogram_row(
    acc_row: jnp.ndarray,
    values: jnp.ndarray,
    bucket_limit: int,
    precision: int = PRECISION,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Accumulate `values` into a single dense histogram row.

    acc_row: int32 [num_buckets]; values: float32 [N] with N a multiple of
    SAMPLE_TILE (for ragged N or an (ids, values) contract use
    pallas_row_ingest_batch below, whose mask drops padding and non-zero
    ids).  Returns the updated row.
    """
    if interpret is None:
        interpret = default_interpret()
    b = acc_row.shape[0]
    h = (b + LANES - 1) // LANES
    b_pad = h * LANES
    n = values.shape[0]
    if n % SAMPLE_TILE:
        raise ValueError(f"N={n} must be a multiple of {SAMPLE_TILE}")
    if n >= MAX_SAMPLES_PER_CALL:
        raise ValueError(
            f"N={n} >= 2^24: the float32 scratch would silently saturate; "
            "split the batch across calls"
        )
    g = n // SAMPLE_TILE

    acc2d = jnp.zeros((h, LANES), dtype=jnp.int32)
    acc2d = acc2d.reshape(-1).at[:b].set(acc_row).reshape(h, LANES)
    # Mosaic requires each of a block's last two dims to be 8/128-divisible
    # OR equal to the array dim — so grid the LANE axis of a [1, N] layout
    # (block [1, T]: dim -2 equals the array's 1, dim -1 is 128-divisible);
    # a [g, T] layout with block [1, T] is rejected on hardware.
    values2d = values.reshape(1, n)

    kernel = functools.partial(
        _hist_kernel, bucket_limit=bucket_limit, precision=precision, h=h
    )
    out = pl.pallas_call(
        kernel,
        grid=(g,),
        in_specs=[
            pl.BlockSpec((1, SAMPLE_TILE), lambda i: (0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((h, LANES), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((h, LANES), lambda i: (0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((h, LANES), jnp.int32),
        scratch_shapes=[pltpu.VMEM((h, LANES), jnp.float32)],
        interpret=interpret,
    )(values2d, acc2d)
    return out.reshape(-1)[:b]


def make_pallas_row_ingest(
    num_buckets: int,
    bucket_limit: int,
    precision: int = PRECISION,
    interpret: bool | None = None,
):
    """Jitted single-row ingest: f(acc_row, values[N]) -> acc_row."""

    @functools.partial(jax.jit, donate_argnums=0)
    def ingest(acc_row, values):
        return pallas_histogram_row(
            acc_row, values, bucket_limit, precision, interpret=interpret
        )

    return ingest


def _hist_kernel_masked(values_ref, mask_ref, acc_ref, out_ref, scratch_ref,
                        *, bucket_limit: int, precision: int, h: int):
    """Masked variant of _hist_kernel: samples whose mask is 0 contribute
    nothing (their one-hot row is zeroed) — this is what gives the row
    kernel a drop semantics for invalid ids and arbitrary-N padding."""
    i = pl.program_id(0)
    n_steps = pl.num_programs(0)

    @pl.when(i == 0)
    def _init():
        scratch_ref[:] = jnp.zeros_like(scratch_ref)

    v = values_ref[0, :]
    m = mask_ref[0, :] != 0  # [T] bool
    bucket = bucket_indices(v, bucket_limit, precision)
    hi = bucket // LANES
    lo = bucket % LANES
    hi_iota = jax.lax.broadcasted_iota(jnp.int32, (v.shape[0], h), 1)
    lo_iota = jax.lax.broadcasted_iota(jnp.int32, (v.shape[0], LANES), 1)
    onehot_hi = (
        (hi[:, None] == hi_iota) & m[:, None]
    ).astype(jnp.bfloat16)
    onehot_lo = (lo[:, None] == lo_iota).astype(jnp.bfloat16)
    partial = jax.lax.dot_general(
        onehot_hi, onehot_lo,
        dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    scratch_ref[:] += partial

    @pl.when(i == n_steps - 1)
    def _finalize():
        out_ref[:] = acc_ref[:] + scratch_ref[:].astype(jnp.int32)


def pallas_row_ingest_batch(
    acc: jnp.ndarray,
    ids: jnp.ndarray,
    values: jnp.ndarray,
    bucket_limit: int,
    precision: int = PRECISION,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Uniform-contract form of the row kernel: acc int32 [1, B],
    f(acc, ids, values) -> acc, bit-identical to the scatter path for a
    single-metric accumulator (samples with ids != 0 are dropped via the
    mask; ragged N is padded with masked-out samples).  This is what
    lets ``ingest_path="auto"``/"pallas" reach the measured-fastest M=1
    kernel through the same dispatch table as every other path."""
    if interpret is None:
        interpret = default_interpret()
    if acc.ndim != 2 or acc.shape[0] != 1:
        raise ValueError(
            f"pallas row path needs a single-metric [1, B] accumulator; "
            f"got shape {tuple(acc.shape)}"
        )
    b = acc.shape[1]
    h = (b + LANES - 1) // LANES
    n = values.shape[0]
    mask = (ids == 0).astype(jnp.int32)
    pad = (-n) % SAMPLE_TILE
    if n + pad >= MAX_SAMPLES_PER_CALL:
        raise ValueError(
            f"N={n} >= 2^24: the float32 scratch would silently saturate; "
            "split the batch across calls"
        )
    if pad:
        values = jnp.concatenate(
            [values, jnp.zeros(pad, values.dtype)]
        )
        mask = jnp.concatenate([mask, jnp.zeros(pad, mask.dtype)])
    g = (n + pad) // SAMPLE_TILE

    acc2d = jnp.zeros((h, LANES), dtype=jnp.int32)
    acc2d = acc2d.reshape(-1).at[:b].set(acc[0]).reshape(h, LANES)
    kernel = functools.partial(
        _hist_kernel_masked, bucket_limit=bucket_limit,
        precision=precision, h=h,
    )
    out = pl.pallas_call(
        kernel,
        grid=(g,),
        in_specs=[
            pl.BlockSpec((1, SAMPLE_TILE), lambda i: (0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, SAMPLE_TILE), lambda i: (0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((h, LANES), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((h, LANES), lambda i: (0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((h, LANES), jnp.int32),
        scratch_shapes=[pltpu.VMEM((h, LANES), jnp.float32)],
        interpret=interpret,
    )(values.reshape(1, -1), mask.reshape(1, -1), acc2d)
    return out.reshape(-1)[:b][None, :]
