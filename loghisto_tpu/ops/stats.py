"""Histogram statistics: percentiles, sum/count/avg (reference layer L3 math).

The reference extracts each percentile by sorting the sparse bucket list and
walking the CDF — once per percentile per histogram per interval, with an
acknowledged TODO to batch them (metrics.go:406-418).  Here the scan is a
prefix sum + ``searchsorted`` computing *all* percentiles in one pass:

  * Bucket indices are monotonic in value (the codec is sign-mirrored and
    monotonic), so sorting by bucket index == sorting by representative value,
    and for the dense tensor the buckets are *already* sorted — no sort at all.
  * The reference's selection rule is "first representative where
    float64(cum)/float64(total) >= p" (metrics.go:411-414).  The host
    (NumPy) tier replicates the same float64 division before comparison so
    edge cases round identically (e.g. p=.99 over 10_000 samples must hit
    cum==9900 exactly).  The device tier keeps the cumsum exact in int32 and
    performs the division in float32 (TPUs have no fast float64): selection
    is bit-identical to the reference for per-metric interval counts up to
    2^24 and within one bucket (i.e. within the 1% accuracy contract)
    beyond; min (p=0) and max (p=1) are computed by exact populated-bucket
    selection at any count.

The jnp variants operate on a dense ``[num_metrics, num_buckets]`` count
tensor where bucket axis index b represents codec bucket ``b - bucket_limit``;
sums become a matvec against the representative values (MXU-friendly) and
percentile selection is a two-level hierarchical rank search: one pass of
128-lane block sums, a tiny block-level cumsum, then an in-block resolve —
every threshold served from a single pass over the data (no full-width
cumsum, which lowers as ~log2(B) whole-array passes).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from loghisto_tpu.config import PRECISION
from loghisto_tpu.ops.codec import decompress, decompress_np


def percentiles_sparse(
    buckets: np.ndarray, counts: np.ndarray, ps: np.ndarray,
    precision: int = PRECISION,
) -> np.ndarray:
    """Percentiles from a sparse (bucket, count) histogram (host tier).

    Args:
      buckets: int bucket indices, any order, each count > 0.
      counts: occurrence counts per bucket.
      ps: quantiles in [0, 1] (caller validates; reference glog-and-drops
        out-of-range requests, metrics.go:378-385).

    Returns bucket representative values, one per p.  An empty histogram
    returns zeros for every p (consistent with dense_stats' empty-metric
    behavior; the reference never processes empty histograms because names
    only exist in its sparse map once a sample lands).
    """
    if len(np.asarray(buckets)) == 0:
        return np.zeros(len(np.asarray(ps)))
    order = np.argsort(buckets, kind="stable")
    values = decompress_np(np.asarray(buckets)[order], precision)
    cdf = np.cumsum(np.asarray(counts, dtype=np.uint64)[order])
    total = float(cdf[-1])
    # Same operation order as the reference: float(cum)/float(total) >= p.
    cdfn = cdf.astype(np.float64) / total
    idx = np.searchsorted(cdfn, np.asarray(ps, dtype=np.float64), side="left")
    idx = np.minimum(idx, len(values) - 1)
    return values[idx]


def summarize_sparse(
    buckets: np.ndarray, counts: np.ndarray, precision: int = PRECISION,
) -> tuple[float, int]:
    """(sum of representatives * counts, total count) — metrics.go:342-347."""
    values = decompress_np(np.asarray(buckets), precision)
    counts = np.asarray(counts, dtype=np.float64)
    return float(np.dot(values, counts)), int(counts.sum())


def bucket_representatives(
    bucket_limit: int, precision: int = PRECISION, dtype=jnp.float32
) -> jnp.ndarray:
    """Representative value of every dense-axis bucket: index b maps to codec
    bucket b - bucket_limit."""
    idx = jnp.arange(2 * bucket_limit + 1, dtype=jnp.int32) - bucket_limit
    return decompress(idx, precision).astype(dtype)


def sparse_cells_stats(
    rows: np.ndarray,
    dense_idx: np.ndarray,
    counts: np.ndarray,
    num_metrics: int,
    ps: np.ndarray,
    bucket_limit: int,
    precision: int = PRECISION,
) -> dict[str, np.ndarray]:
    """dense_stats_np over a sparse cell list: O(occupied cells) host
    work, never a dense ``[M, B]`` materialization — the collect() tier
    of the paged backend (loghisto_tpu/paging.py).

    Args:
      rows / dense_idx / counts: parallel cell arrays — metric row,
        dense-axis bucket index (codec bucket + bucket_limit), int64
        count.  Duplicate (row, bucket) cells are allowed and fold.

    Selection is identical to dense_stats_np (first bucket where
    float(cum)/float(total) >= p over int64-exact cumsums; endpoints
    are the first/last populated bucket), so percentiles of a sparse
    view are BIT-IDENTICAL to the dense oracle over the same histogram.
    Sums reduce in occupied-bucket order, which can differ from the
    dense matvec in the final float64 ulp.
    """
    rows = np.asarray(rows, dtype=np.int64)
    dense_idx = np.asarray(dense_idx, dtype=np.int64)
    cell_counts = np.asarray(counts, dtype=np.int64)
    ps = np.asarray(ps, dtype=np.float64)
    m, p_n = int(num_metrics), len(ps)
    out_counts = np.zeros(m, dtype=np.int64)
    out_sums = np.zeros(m, dtype=np.float64)
    out_pct = np.zeros((m, p_n), dtype=np.float64)
    if not len(rows):
        return {
            "counts": out_counts, "sums": out_sums, "percentiles": out_pct,
        }
    # fold duplicates and order cells by (row, bucket) in one pass
    order = np.lexsort((dense_idx, rows))
    rows, dense_idx, cell_counts = (
        rows[order], dense_idx[order], cell_counts[order]
    )
    keys = rows * (2 * bucket_limit + 2) + dense_idx
    uniq, inverse = np.unique(keys, return_inverse=True)
    folded = np.zeros(len(uniq), dtype=np.int64)
    np.add.at(folded, inverse, cell_counts)
    first = np.searchsorted(keys, uniq, side="left")
    rows, dense_idx, cell_counts = rows[first], dense_idx[first], folded

    reps = decompress_np(dense_idx - bucket_limit, precision)
    starts = np.searchsorted(rows, np.arange(m), side="left")
    ends = np.searchsorted(rows, np.arange(m), side="right")
    for r in range(m):
        lo, hi = starts[r], ends[r]
        if lo == hi:
            continue
        c = cell_counts[lo:hi]
        cdf = np.cumsum(c)
        total = cdf[-1]
        out_counts[r] = total
        out_sums[r] = np.dot(reps[lo:hi], c.astype(np.float64))
        cdfn = cdf.astype(np.float64) / float(total)
        pos = np.minimum(
            np.searchsorted(cdfn, ps, side="left"), hi - lo - 1
        )
        idx = np.where(ps <= 0, 0, np.where(ps >= 1, hi - lo - 1, pos))
        out_pct[r] = reps[lo:hi][idx]
    return {"counts": out_counts, "sums": out_sums, "percentiles": out_pct}


def dense_stats_np(
    acc: np.ndarray,
    ps: np.ndarray,
    bucket_limit: int,
    precision: int = PRECISION,
) -> dict[str, np.ndarray]:
    """Host (NumPy, int64) mirror of dense_stats for intervals whose
    counts exceed what the int32 device accumulator can hold — the
    overflow-spill path (SURVEY.md §7 hard part (b)).  Exact at any
    count < 2^53 (float64 integer exactness), same selection rule as
    percentiles_sparse: first bucket where float(cum)/float(total) >= p.
    """
    acc = np.asarray(acc, dtype=np.int64)
    ps = np.asarray(ps, dtype=np.float64)
    reps = decompress_np(
        np.arange(-bucket_limit, bucket_limit + 1, dtype=np.int64), precision
    )
    cdf = np.cumsum(acc, axis=1)
    counts = cdf[:, -1]
    sums = acc.astype(np.float64) @ reps
    m, b = acc.shape
    idx = np.zeros((m, len(ps)), dtype=np.int64)
    for row in range(m):
        total = counts[row]
        if total == 0:
            continue
        cdfn = cdf[row].astype(np.float64) / float(total)
        pos = np.minimum(np.searchsorted(cdfn, ps, side="left"), b - 1)
        populated = np.nonzero(acc[row])[0]
        lo, hi = populated[0], populated[-1]
        idx[row] = np.where(ps <= 0, lo, np.where(ps >= 1, hi, pos))
    pct = reps[idx]
    pct[counts == 0] = 0.0
    return {"counts": counts, "sums": sums, "percentiles": pct}


def dense_stats(
    acc: jnp.ndarray,
    ps: jnp.ndarray,
    bucket_limit: int,
    precision: int = PRECISION,
) -> dict[str, jnp.ndarray]:
    """Full per-metric statistics from a dense [M, B] count tensor.

    Returns dict with:
      counts [M] int32 — per-metric total sample count (this interval; kept
        integer so counts above 2^24 stay exact)
      sums   [M]   — sum of bucket representatives weighted by counts
      percentiles [M, P] — representative value at each quantile in ps

    Percentile rule matches the reference exactly (see module docstring);
    empty metrics (count == 0) return 0 for all stats, mirroring the
    reference where empty histograms simply don't exist in the sparse map.
    """
    num_buckets = acc.shape[1]
    acc_f = acc.astype(jnp.float32)
    reps = bucket_representatives(bucket_limit, precision)
    sums = acc_f @ reps  # matvec on the MXU
    # Hierarchical CDF: a full [M, B] cumsum lowers as ~log2(B) whole-
    # array passes (measured 0.9s of a 1.1s CPU stats call at 10k x 8193);
    # instead reduce to per-block sums in ONE pass (LANE-sized blocks — a
    # TPU vector register row), cumsum only the [M, B/LANE] block totals,
    # and resolve each rank threshold inside a single gathered block.
    # All integer arithmetic stays exact int32, same as the full cumsum.
    LANE = 128
    m = acc.shape[0]
    n_blocks = (num_buckets + LANE - 1) // LANE
    pad = n_blocks * LANE - num_buckets
    acc_pad = jnp.pad(acc, ((0, 0), (0, pad))) if pad else acc
    blocks = acc_pad.reshape(m, n_blocks, LANE)
    block_sums = blocks.sum(axis=2, dtype=jnp.int32)  # [M, nB]
    block_cdf = jnp.cumsum(block_sums, axis=1)  # [M, nB] — tiny
    counts = block_cdf[:, -1]

    ps = jnp.asarray(ps, dtype=jnp.float32)

    # Selection rule: first bucket with f32(cdf)/f32(total) >= p.  Instead
    # of materializing the [M, B] float CDF (a full extra array + division
    # per cell), derive the integer rank threshold k*[m, p] = the smallest
    # integer count satisfying the float division — an [M, P] computation —
    # and search the integer cumsum directly.  Monotonicity of k/total in
    # k makes the two formulations select identical buckets.
    # Exact below 2^24 (float32 integers are exact there, and the +/-1
    # window always brackets the crossover).  Above 2^24 float32 ulp
    # exceeds 1, so the window may contain no passing candidate; fall
    # back to k0 itself — within a few ulp of the true rank, i.e. a
    # relative rank error < 2^-22, far inside the within-one-bucket
    # contract.  Never use an out-of-int32 sentinel: its cast is
    # backend-defined.
    total_f = jnp.maximum(counts, 1).astype(jnp.float32)[:, None]  # [M,1]
    k0 = jnp.ceil(ps[None, :] * total_f)  # [M, P] first candidate
    cands = k0[:, :, None] + jnp.arange(-1.0, 2.0)  # [M, P, 3]
    ok = (cands / total_f[:, :, None] >= ps[None, :, None]) & (cands >= 1.0)
    best = jnp.min(jnp.where(ok, cands, jnp.inf), axis=2)
    k_star_f = jnp.where(jnp.isfinite(best), best, k0)
    # int32-representable float clamp BEFORE the cast (f32(2^31) itself
    # casts implementation-defined), then the exact integer clamp
    k_star_f = jnp.clip(k_star_f, 1.0, jnp.float32(2**31 - 256))
    k_star = jnp.minimum(
        k_star_f.astype(jnp.int32), jnp.maximum(counts, 1)[:, None]
    )

    # 0 < p < 1: first bucket whose integer cumsum reaches k*.  Two-level
    # search serving all P thresholds in one pass over the block totals
    # (metrics.go:408's TODO, answered at device scale):
    #   1. block level: j*[m,p] = count of blocks whose cumulative total
    #      is still below k* (vectorized count-below over [M, P, nB])
    #   2. lane level: gather block j* ([M, P, LANE] — tiny), cumsum its
    #      LANE lanes, count lanes below the residual threshold
    # Empty prefix buckets have cdf 0 < k*, so the hit lands on a
    # populated bucket — identical selection to a full-cumsum search.
    blk = jnp.sum(
        (block_cdf[:, None, :] < k_star[:, :, None]).astype(jnp.int32),
        axis=2,
    )
    blk = jnp.minimum(blk, n_blocks - 1)  # [M, P]
    # exclusive prefix before the selected block
    base = jnp.where(
        blk > 0,
        jnp.take_along_axis(block_cdf, jnp.maximum(blk - 1, 0), axis=1),
        0,
    )
    inner = jnp.take_along_axis(
        blocks, blk[:, :, None], axis=1
    )  # [M, P, LANE]
    inner_cdf = base[:, :, None] + jnp.cumsum(inner, axis=2)
    lane = jnp.sum(
        (inner_cdf < k_star[:, :, None]).astype(jnp.int32), axis=2
    )
    pos = jnp.minimum(blk * LANE + lane, num_buckets - 1)

    # Exact populated-bucket endpoints, immune to rounding, via the same
    # two-level structure: block_sums > 0 marks blocks with any count.
    # p == 0 / p == 1: the reference iterates only *populated* buckets, so
    # these mean first/last populated bucket — selected exactly.
    block_pop = block_sums > 0
    iota_b = jnp.arange(n_blocks, dtype=jnp.int32)[None, :]
    iota_l = jnp.arange(LANE, dtype=jnp.int32)[None, :]
    jb_min = jnp.argmax(block_pop, axis=1)  # first populated block
    jb_max = jnp.max(jnp.where(block_pop, iota_b, -1), axis=1)
    jb_max_c = jnp.maximum(jb_max, 0)
    first_blk = jnp.take_along_axis(
        blocks, jb_min[:, None, None], axis=1
    )[:, 0, :]
    last_blk = jnp.take_along_axis(
        blocks, jb_max_c[:, None, None], axis=1
    )[:, 0, :]
    idx_min = jb_min * LANE + jnp.argmax(first_blk > 0, axis=1)
    idx_max = jb_max_c * LANE + jnp.maximum(
        jnp.max(jnp.where(last_blk > 0, iota_l, -1), axis=1), 0
    )
    idx_max = jnp.minimum(idx_max, num_buckets - 1)

    idx = jnp.where(
        ps[None, :] <= 0,
        idx_min[:, None],
        jnp.where(ps[None, :] >= 1, idx_max[:, None], pos),
    )
    pct = reps[idx]
    nonempty = (counts > 0)[:, None]
    return {
        "counts": counts,
        "sums": sums,
        "percentiles": jnp.where(nonempty, pct, 0.0),
    }


# ---------------------------------------------------------------------- #
# Snapshot query engine: commit-time CDF + sparse row serving
# ---------------------------------------------------------------------- #
#
# dense_stats answers every metric at once, which is the right shape for
# the interval pipeline but the wrong one for serving: a scrape or a rule
# check re-pays the whole [M, B] scan per query.  The snapshot split
# moves the scan to COMMIT time: ``dense_cdf`` emits the exact int32
# bucket prefix sums (plus counts and the same f32 sums matvec) once per
# interval, and ``snapshot_row_stats`` turns a percentile query into a
# row gather + ``searchsorted`` over only the requested metrics.
#
# Selection parity: dense_stats picks "the number of buckets whose
# integer cumsum is < k*" (two-level block search).  For a nondecreasing
# CDF row, ``searchsorted(cdf, k*, side="left")`` returns exactly that
# count, and the endpoint rules collapse into the same primitive —
# first populated bucket == searchsorted(cdf, 1), last populated bucket
# == searchsorted(cdf, total) — so a snapshot query is bit-identical to
# dense_stats over the same histogram (tests/test_query_engine.py pins
# this), while reading back [n, P] floats instead of [M, P].


def dense_cdf(
    acc: jnp.ndarray,
    bucket_limit: int,
    precision: int = PRECISION,
) -> dict[str, jnp.ndarray]:
    """Commit-time snapshot payload for a dense [M, B] count tensor:

      cdf    int32 [M, B] — exact per-metric bucket prefix sums
      counts int32 [M]    — per-metric totals (cdf[:, -1])
      sums   f32   [M]    — the same representative matvec dense_stats
                            uses, precomputed so a query never touches
                            the full histogram
    """
    reps = bucket_representatives(bucket_limit, precision)
    cdf = jnp.cumsum(acc, axis=1, dtype=jnp.int32)
    return {
        "cdf": cdf,
        "counts": cdf[:, -1],
        "sums": acc.astype(jnp.float32) @ reps,
    }


def snapshot_row_stats(
    cdf_rows: jnp.ndarray,
    counts: jnp.ndarray,
    sums: jnp.ndarray,
    ps: jnp.ndarray,
    bucket_limit: int,
    precision: int = PRECISION,
) -> dict[str, jnp.ndarray]:
    """Statistics for gathered snapshot rows: cdf_rows int32 [n, B],
    counts int32 [n], sums f32 [n], ps f32 [P] -> counts/sums pass
    through, percentiles [n, P].  Same k* derivation as dense_stats
    (identical float32 operation order), then one searchsorted per row.
    """
    num_buckets = cdf_rows.shape[1]
    reps = bucket_representatives(bucket_limit, precision)
    ps = jnp.asarray(ps, dtype=jnp.float32)
    total_f = jnp.maximum(counts, 1).astype(jnp.float32)[:, None]  # [n,1]
    k0 = jnp.ceil(ps[None, :] * total_f)  # [n, P]
    cands = k0[:, :, None] + jnp.arange(-1.0, 2.0)  # [n, P, 3]
    ok = (cands / total_f[:, :, None] >= ps[None, :, None]) & (cands >= 1.0)
    best = jnp.min(jnp.where(ok, cands, jnp.inf), axis=2)
    k_star_f = jnp.where(jnp.isfinite(best), best, k0)
    k_star_f = jnp.clip(k_star_f, 1.0, jnp.float32(2**31 - 256))
    total_i = jnp.maximum(counts, 1)[:, None]
    k_star = jnp.minimum(k_star_f.astype(jnp.int32), total_i)
    # endpoints through the same searchsorted: rank 1 hits the first
    # populated bucket, rank == total the last populated bucket
    k = jnp.where(
        ps[None, :] <= 0,
        jnp.ones_like(k_star),
        jnp.where(ps[None, :] >= 1, total_i, k_star),
    )
    pos = jax.vmap(
        lambda row, kk: jnp.searchsorted(row, kk, side="left")
    )(cdf_rows, k)
    pos = jnp.minimum(pos, num_buckets - 1)
    pct = reps[pos]
    nonempty = (counts > 0)[:, None]
    return {
        "counts": counts,
        "sums": sums,
        "percentiles": jnp.where(nonempty, pct, 0.0),
    }


@functools.lru_cache(maxsize=None)
def make_snapshot_query_fn(
    bucket_limit: int, precision: int = PRECISION, mesh=None
):
    """Jitted sparse snapshot query ``f(cdf, counts, sums, ids, ps) ->
    stats for rows ids``: ONE gather + searchsorted dispatch, D2H
    traffic O(len(ids) * len(ps)).  Cached per bucket geometry so every
    wheel/aggregator with the same codec shares one jit object (and its
    per-shape executable cache — the plan cache's backing store); ids
    and ps are traced operands, so neither their values nor the commit
    epoch ever retrace.

    With ``mesh`` (metric-row-sharded snapshot views) the gather
    partitions under GSPMD: each requested row ships from its owning
    shard — sparse cross-shard traffic proportional to the matched ids,
    never a full CDF replication — and the small ``[n, P]`` results are
    pinned replicated so the host readback is a local copy on every
    process."""
    jit_kwargs = {}
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec

        jit_kwargs["out_shardings"] = NamedSharding(mesh, PartitionSpec())

    @functools.partial(jax.jit, **jit_kwargs)
    def query(cdf, counts, sums, ids, ps):
        return snapshot_row_stats(
            cdf[ids], counts[ids], sums[ids], ps, bucket_limit, precision
        )

    return query


@functools.lru_cache(maxsize=None)
def make_group_query_fn(
    bucket_limit: int, precision: int = PRECISION, mesh=None
):
    """Jitted group_by rollup ``f(cdf, counts, sums, ids, gids, ps,
    num_groups=G) -> stats per group``: gather the matched snapshot
    rows, segment-sum them into per-group merged histograms, then run
    the same row-stats selection as the sparse query — ONE dispatch for
    the whole rollup (labels layer, ISSUE 16).

    Merging is EXACT, not approximate: log-bucket histograms merge by
    bucket-count addition, and a prefix sum is linear, so the sum of
    CDF rows IS the CDF of the merged histogram (int32 exact; a merged
    group's total must stay within int32, the same wire contract as a
    single wheel slot).  Percentiles of the merged CDF therefore match
    a host-side sparse merge oracle bit-for-bit for dense-codec rows
    (tests/test_labels.py pins this).

    ``num_groups`` is static (segment_sum needs a static segment
    count); callers pad it to a power of two — padding ids point at row
    0 and padding gids at a reserved dump segment that is sliced off
    after readback — so drifting group counts reuse one executable per
    (n_ids-bucket, groups-bucket, P) shape, exactly like the plan-cache
    discipline of the sparse query path.  Sharding note: under a mesh
    the gather ships only matched rows off their owning shards and the
    tiny per-group results land replicated, same as the sparse query.
    """
    jit_kwargs = {}
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec

        jit_kwargs["out_shardings"] = NamedSharding(mesh, PartitionSpec())

    @functools.partial(
        jax.jit, static_argnames=("num_groups",), **jit_kwargs
    )
    def group_query(cdf, counts, sums, ids, gids, ps, *, num_groups):
        gcdf = jax.ops.segment_sum(
            cdf[ids], gids, num_segments=num_groups
        )
        gcounts = jax.ops.segment_sum(
            counts[ids], gids, num_segments=num_groups
        )
        gsums = jax.ops.segment_sum(
            sums[ids], gids, num_segments=num_groups
        )
        return snapshot_row_stats(
            gcdf, gcounts, gsums, ps, bucket_limit, precision
        )

    return group_query
