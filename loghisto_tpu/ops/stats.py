"""Histogram statistics: percentiles, sum/count/avg (reference layer L3 math).

The reference extracts each percentile by sorting the sparse bucket list and
walking the CDF — once per percentile per histogram per interval, with an
acknowledged TODO to batch them (metrics.go:406-418).  Here the scan is a
prefix sum + ``searchsorted`` computing *all* percentiles in one pass:

  * Bucket indices are monotonic in value (the codec is sign-mirrored and
    monotonic), so sorting by bucket index == sorting by representative value,
    and for the dense tensor the buckets are *already* sorted — no sort at all.
  * The reference's selection rule is "first representative where
    float64(cum)/float64(total) >= p" (metrics.go:411-414).  The host
    (NumPy) tier replicates the same float64 division before comparison so
    edge cases round identically (e.g. p=.99 over 10_000 samples must hit
    cum==9900 exactly).  The device tier keeps the cumsum exact in int32 and
    performs the division in float32 (TPUs have no fast float64): selection
    is bit-identical to the reference for per-metric interval counts up to
    2^24 and within one bucket (i.e. within the 1% accuracy contract)
    beyond; min (p=0) and max (p=1) are computed by exact populated-bucket
    selection at any count.

The jnp variants operate on a dense ``[num_metrics, num_buckets]`` count
tensor where bucket axis index b represents codec bucket ``b - bucket_limit``;
sums become a matvec against the representative values (MXU-friendly) and the
percentile scan a row-wise cumsum + vmapped searchsorted.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from loghisto_tpu.config import PRECISION
from loghisto_tpu.ops.codec import decompress, decompress_np


def percentiles_sparse(
    buckets: np.ndarray, counts: np.ndarray, ps: np.ndarray,
    precision: int = PRECISION,
) -> np.ndarray:
    """Percentiles from a sparse (bucket, count) histogram (host tier).

    Args:
      buckets: int bucket indices, any order, each count > 0.
      counts: occurrence counts per bucket.
      ps: quantiles in [0, 1] (caller validates; reference glog-and-drops
        out-of-range requests, metrics.go:378-385).

    Returns bucket representative values, one per p.  An empty histogram
    returns zeros for every p (consistent with dense_stats' empty-metric
    behavior; the reference never processes empty histograms because names
    only exist in its sparse map once a sample lands).
    """
    if len(np.asarray(buckets)) == 0:
        return np.zeros(len(np.asarray(ps)))
    order = np.argsort(buckets, kind="stable")
    values = decompress_np(np.asarray(buckets)[order], precision)
    cdf = np.cumsum(np.asarray(counts, dtype=np.uint64)[order])
    total = float(cdf[-1])
    # Same operation order as the reference: float(cum)/float(total) >= p.
    cdfn = cdf.astype(np.float64) / total
    idx = np.searchsorted(cdfn, np.asarray(ps, dtype=np.float64), side="left")
    idx = np.minimum(idx, len(values) - 1)
    return values[idx]


def summarize_sparse(
    buckets: np.ndarray, counts: np.ndarray, precision: int = PRECISION,
) -> tuple[float, int]:
    """(sum of representatives * counts, total count) — metrics.go:342-347."""
    values = decompress_np(np.asarray(buckets), precision)
    counts = np.asarray(counts, dtype=np.float64)
    return float(np.dot(values, counts)), int(counts.sum())


def bucket_representatives(
    bucket_limit: int, precision: int = PRECISION, dtype=jnp.float32
) -> jnp.ndarray:
    """Representative value of every dense-axis bucket: index b maps to codec
    bucket b - bucket_limit."""
    idx = jnp.arange(2 * bucket_limit + 1, dtype=jnp.int32) - bucket_limit
    return decompress(idx, precision).astype(dtype)


def dense_stats(
    acc: jnp.ndarray,
    ps: jnp.ndarray,
    bucket_limit: int,
    precision: int = PRECISION,
) -> dict[str, jnp.ndarray]:
    """Full per-metric statistics from a dense [M, B] count tensor.

    Returns dict with:
      counts [M] int32 — per-metric total sample count (this interval; kept
        integer so counts above 2^24 stay exact)
      sums   [M]   — sum of bucket representatives weighted by counts
      percentiles [M, P] — representative value at each quantile in ps

    Percentile rule matches the reference exactly (see module docstring);
    empty metrics (count == 0) return 0 for all stats, mirroring the
    reference where empty histograms simply don't exist in the sparse map.
    """
    num_buckets = acc.shape[1]
    acc_f = acc.astype(jnp.float32)
    reps = bucket_representatives(bucket_limit, precision)
    sums = acc_f @ reps  # matvec on the MXU
    # Integer cumsum stays exact for any per-interval count the int32
    # accumulator can hold; only the final division is float32.
    cdf = jnp.cumsum(acc.astype(jnp.int32), axis=1)
    counts = cdf[:, -1]
    # Normalize by the cumsum's own last column: cdfn[-1] == 1.0 exactly
    # (x/x in IEEE), so p=1.0 always lands inside the populated range.
    total = jnp.maximum(counts, 1)[:, None].astype(jnp.float32)
    cdfn = cdf.astype(jnp.float32) / total

    ps = jnp.asarray(ps, dtype=jnp.float32)

    # Exact populated-bucket endpoints, immune to division rounding:
    # min = first bucket with count > 0, max = last bucket with count > 0.
    populated = acc > 0
    idx_min = jnp.argmax(populated, axis=1)
    idx_max = (num_buckets - 1) - jnp.argmax(populated[:, ::-1], axis=1)

    # 0 < p < 1: first bucket where cdf/total >= p (empty prefix buckets
    # have cdf 0 < p, so the hit always lands on a populated bucket).
    # Two equivalent formulations of "first index with cdfn >= p":
    #   * TPU: an argmax reduction over a comparison — VPU-tiled vector
    #     work, one [M, B] pass per percentile (P is small and static);
    #     per-row binary search lowers poorly there.
    #   * CPU/GPU: vmapped searchsorted (binary search), ~3x cheaper than
    #     the full comparison passes.
    # p == 0 / p == 1: the reference iterates only *populated* buckets, so
    # these mean first/last populated bucket — selected exactly.
    on_tpu = jax.devices()[0].platform == "tpu"
    if on_tpu:
        cols = []
        for k in range(ps.shape[0]):
            p = ps[k]
            pos = jnp.argmax(cdfn >= p, axis=1)
            cols.append(
                jnp.where(p <= 0, idx_min, jnp.where(p >= 1, idx_max, pos))
            )
        idx = jnp.stack(cols, axis=1)
    else:
        def row_search(cdfn_row, lo, hi):
            pos = jnp.searchsorted(cdfn_row, ps, side="left")
            pos = jnp.minimum(pos, num_buckets - 1)
            return jnp.where(ps <= 0, lo, jnp.where(ps >= 1, hi, pos))

        idx = jax.vmap(row_search)(cdfn, idx_min, idx_max)
    pct = reps[idx]
    nonempty = (counts > 0)[:, None]
    return {
        "counts": counts,
        "sums": sums,
        "percentiles": jnp.where(nonempty, pct, 0.0),
    }
