"""Paged bucket storage: device programs whose cost tracks *occupied*
pages, not dense capacity.

The dense accumulator spends ``M x B x 4`` bytes of HBM (and commit
H2D bytes proportional to the rows it touches) regardless of how many
buckets a metric ever populated — INTERVAL_COMMIT_r6 shows H2D
dominating the 10k-metric commit, and at the ROADMAP's 1M-live-row
target the dense tensor alone (1M x 8193 x 4 ~= 32.8 GB) exceeds a
chip's HBM outright.  Real latency/size distributions are SPARSE in
bucket space: a metric that only ever sees 1-50ms latencies occupies a
few hundred adjacent log buckets out of 8193.

The paged layout replaces the dense ``[M, B]`` tensor with

  * a **page pool** ``[P, page_size]`` int32 — fixed-size bucket pages,
    allocated on demand, slot 0 reserved as the permanently-zero page so
    unmapped reads decode to zeros without a mask gather;
  * a host-side **page table** ``[M, pages_per_row]`` int32 mapping each
    (row, page-of-storage-axis) to a pool slot, -1 = unmapped.

The committed wire stays the packed sparse-triple format (PR 6); the
host translate step (loghisto_tpu/paging.py) rewrites each
``(row, codec_bucket, count)`` cell into ``(slot, offset, count)``
against the page table — allocation and spill policy are host decisions
(the host already folds every batch to triples, so it sees exactly
which cells an interval touches) — and the device program here is a
pure weighted scatter into the pool: O(occupied cells) H2D, O(mapped
pages) HBM, no codec work, no dense row materialization.

Two commit tiers, bit-identical by construction (the Pallas tier reuses
the sparse-ingest per-cell DMA row round-trip with pool pages as the
rows — a [1, page_size] row DMA is lane-aligned at the default 256,
unlike the 8193-wide dense rows):

  * "jnp"    — XLA weighted scatter-add over the flat pool view;
  * "pallas" — per-cell DMA page round-trip through a VMEM scratch
    (ops/sparse_ingest.py's kernel, parameterized by the pool shape).

Query serving gathers only a row's mapped pages and expands them
through the row's codec decode-LUT back onto the dense native bucket
axis — ``make_paged_query_fn`` then runs the exact
``snapshot_row_stats`` program of the dense snapshot engine, so a paged
query is bit-identical to a dense query over the same histogram for
identity-codec rows (tests/test_paged_store.py pins it).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from loghisto_tpu.ops.backend import default_interpret

# Buckets per page.  256 int32 = 1 KiB per page, two full TPU vector
# lanes rows — page DMAs are lane-aligned, and at B=8193 a dense row is
# 33 pages, so one hot latency band (a few hundred buckets) costs 1-3
# pages instead of a 32 KiB dense row.  Mirrored (without importing
# jax) as ops/dispatch.PAGE_SIZE for the thresholds machinery.
PAGE_SIZE = 256

# Reserved pool slot: permanently zero, never allocated, never written.
# Page-table entries of -1 clamp onto it at gather time, so reading an
# unmapped page needs no mask pass — the zero page IS the mask.
ZERO_SLOT = 0

# Fixed commit-launch width: every paged commit pads its translated
# triples to a multiple of this, so ONE compiled executable serves
# every interval (the _MERGE_CHUNK idea from the dense bridge merge).
COMMIT_CHUNK = 1 << 14


def validate_pool_shape(pool_pages: int, page_size: int) -> None:
    """Construction-time guard: the flat pool index (slot * page_size +
    offset) must stay inside int32, and pages must keep the TPU lane
    alignment that makes the Pallas page DMA legal."""
    if page_size < 128 or page_size % 128:
        raise ValueError(
            f"page_size must be a positive multiple of 128 (TPU lane "
            f"alignment); got {page_size}"
        )
    if pool_pages < 2:
        raise ValueError(
            f"pool needs >= 2 pages (slot 0 is the reserved zero page); "
            f"got {pool_pages}"
        )
    if pool_pages * page_size >= 2**31 - 2:
        raise ValueError(
            f"pool of {pool_pages} x {page_size} buckets overflows the "
            "flat int32 cell index; shrink the pool or the page"
        )


def paged_scatter_batch(pool: jnp.ndarray, packed: jnp.ndarray) -> jnp.ndarray:
    """Pure jnp tier: weighted scatter-add of translated ``(slot,
    offset, count)`` triples into the page pool.  Padding rows use slot
    -1 and drop; slot 0 (the zero page) is refused by the translate
    step, never here (a traced guard would silently clamp)."""
    if packed.ndim != 2 or packed.shape[1] != 3:
        raise ValueError(
            f"packed must be [n, 3] (slot, offset, count); got {packed.shape}"
        )
    pages, page_size = pool.shape
    slots = packed[:, 0]
    offs = jnp.clip(packed[:, 1], 0, page_size - 1)
    valid = (slots > ZERO_SLOT) & (slots < pages)
    # invalid rows park past the largest flat index validate_pool_shape
    # admits (pool cells < 2^31 - 2); mode="drop" discards them
    flat_idx = jnp.where(valid, slots * page_size + offs, jnp.int32(2**31 - 2))
    flat = pool.reshape(-1).at[flat_idx].add(packed[:, 2], mode="drop")
    return flat.reshape(pages, page_size)


def pallas_paged_scatter(
    pool: jnp.ndarray,
    packed: jnp.ndarray,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Pallas tier: same contract as paged_scatter_batch, executed as
    the sparse-ingest per-cell DMA round-trip with pool pages as the
    rows (one [1, page_size] VMEM scratch, serial grid => exact integer
    accumulation for duplicate cells)."""
    from loghisto_tpu.ops.sparse_ingest import TRIPLE_TILE, _pallas_kernel

    if interpret is None:
        interpret = default_interpret()
    if packed.ndim != 2 or packed.shape[1] != 3:
        raise ValueError(
            f"packed must be [n, 3] (slot, offset, count); got {packed.shape}"
        )
    pages, page_size = pool.shape
    n = packed.shape[0]
    g = max(1, (n + TRIPLE_TILE - 1) // TRIPLE_TILE)
    padded = g * TRIPLE_TILE
    if padded != n:
        pad = jnp.zeros((padded - n, 3), dtype=jnp.int32)
        pad = pad.at[:, 0].set(-1)
        packed = jnp.concatenate([packed, pad])
    slots = packed[:, 0]
    # the kernel bounds-guards ids to [0, rows); shift the zero page out
    # of range too so nothing can ever write it
    slots = jnp.where(slots <= ZERO_SLOT, jnp.int32(-1), slots)
    ids = slots.reshape(g, TRIPLE_TILE)
    offs = jnp.clip(packed[:, 1], 0, page_size - 1).reshape(g, TRIPLE_TILE)
    weights = packed[:, 2].reshape(g, TRIPLE_TILE)

    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    smem_spec = pl.BlockSpec(
        (1, TRIPLE_TILE), lambda i: (i, 0), memory_space=pltpu.SMEM
    )
    return pl.pallas_call(
        functools.partial(_pallas_kernel, num_metrics=pages),
        grid=(g,),
        in_specs=[
            smem_spec,
            smem_spec,
            smem_spec,
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        out_shape=jax.ShapeDtypeStruct(pool.shape, pool.dtype),
        scratch_shapes=[
            pltpu.VMEM((1, page_size), jnp.int32),
            pltpu.SemaphoreType.DMA(()),
            pltpu.SemaphoreType.DMA(()),
        ],
        input_output_aliases={3: 0},
        interpret=interpret,
    )(ids, offs, weights, pool)


def make_paged_commit_fn(kernel: str = "jnp"):
    """Jitted, donated-pool commit step ``f(pool, packed) -> pool`` with
    pool int32 [P, page_size] and packed int32 [n, 3] translated
    triples.  One executable per (pool shape, padded triple length) —
    the host side pads to COMMIT_CHUNK multiples so the set of lengths
    stays tiny."""
    step = pallas_paged_scatter if kernel == "pallas" else paged_scatter_batch

    @functools.partial(jax.jit, donate_argnums=0)
    def commit(pool, packed):
        return step(pool, packed)

    return commit


def make_sharded_paged_commit_fn(mesh, shard_pages: int):
    """Mesh tier of the paged commit: ``f(pool, packed) -> pool`` with
    pool int32 [n_metric * shard_pages, page_size] laid out as one
    contiguous page arena per metric shard (shard k owns global slots
    [k*shard_pages, (k+1)*shard_pages), slot k*shard_pages being that
    shard's local zero page), and packed [n, 3] GLOBAL-slot triples
    split over the stream axis.

    Inside one shard_map each device keeps only the triples whose slot
    falls in its metric shard's arena (re-based to local slots — the
    local zero page and every foreign slot drop), scatters them into a
    zero local delta, and ONE psum over the stream axis merges the
    deltas.  Every triple is owned by exactly one metric shard and
    int32 adds commute, so the result is bit-identical to the
    single-device ``make_paged_commit_fn`` over the same pool — the
    PR-8 sharded-commit recipe applied to pages instead of rows.  The
    scatter body is the jnp tier (shard_map-local XLA scatter); the
    Pallas per-cell DMA tier stays single-device, matching
    resolve_compact_path's policy.

    Host-side contract: the padded triple count must divide by the
    stream axis size (COMMIT_CHUNK is a power of two, so any pow-2
    stream axis works; paging.py guards this at construction).
    """
    from jax.sharding import PartitionSpec as P

    from loghisto_tpu.parallel.mesh import METRIC_AXIS, STREAM_AXIS, shard_map

    def _local(pool_local, packed):
        shard = jax.lax.axis_index(METRIC_AXIS)
        local = packed[:, 0] - shard * shard_pages
        own = (local > ZERO_SLOT) & (local < shard_pages)
        lp = jnp.stack(
            [jnp.where(own, local, jnp.int32(-1)), packed[:, 1], packed[:, 2]],
            axis=1,
        )
        delta = paged_scatter_batch(jnp.zeros_like(pool_local), lp)
        delta = jax.lax.psum(delta, STREAM_AXIS)
        return pool_local + delta

    sharded = shard_map(
        _local,
        mesh=mesh,
        in_specs=(P(METRIC_AXIS, None), P(STREAM_AXIS, None)),
        out_specs=P(METRIC_AXIS, None),
    )

    @functools.partial(jax.jit, donate_argnums=0)
    def commit(pool, packed):
        return sharded(pool, packed)

    return commit


def gather_storage_rows(
    pool: jnp.ndarray, table_rows: jnp.ndarray, storage_buckets: int
) -> jnp.ndarray:
    """Reassemble dense STORAGE-axis rows from mapped pages: table_rows
    int32 [n, pages_per_row] (pool slots, -1 unmapped) -> int32
    [n, storage_buckets].  Unmapped entries clamp onto the reserved
    zero page, so no mask pass is needed — D2H and FLOP cost is
    O(n * pages_per_row * page_size), independent of M."""
    pages = pool[jnp.maximum(table_rows, ZERO_SLOT)]  # [n, ppr, page]
    n, ppr, page = pages.shape
    return pages.reshape(n, ppr * page)[:, :storage_buckets]


@functools.lru_cache(maxsize=None)
def make_paged_query_fn(bucket_limit: int, precision: int):
    """Jitted paged snapshot query ``f(pool, table_rows, dec_lut, ps) ->
    stats``: gather the requested rows' mapped pages, expand each
    storage bucket onto its representative native bucket through the
    codec decode-LUT (a scatter-add — decode LUTs are injective, so
    this is exact), and run the SAME snapshot_row_stats program as the
    dense query engine.  dec_lut is a traced int32 [S] operand, so all
    rows of one codec share one executable and neither the table values
    nor the LUT retrace."""
    from loghisto_tpu.ops.stats import snapshot_row_stats

    num_buckets = 2 * bucket_limit + 1

    @jax.jit
    def query(pool, table_rows, dec_lut, ps):
        storage = gather_storage_rows(pool, table_rows, dec_lut.shape[0])
        n = storage.shape[0]
        native = jnp.zeros((n, num_buckets), dtype=jnp.int32)
        native = native.at[:, dec_lut].add(storage)
        cdf = jnp.cumsum(native, axis=1, dtype=jnp.int32)
        counts = cdf[:, -1]
        from loghisto_tpu.ops.stats import bucket_representatives

        reps = bucket_representatives(bucket_limit, precision)
        sums = native.astype(jnp.float32) @ reps
        return snapshot_row_stats(
            cdf, counts, sums, ps, bucket_limit, precision
        )

    return query
