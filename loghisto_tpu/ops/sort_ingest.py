"""Sort-deduplicated ingest: an alternative device accumulation kernel
built for TPU scatter semantics.

The plain scatter path (ops/ingest.py) hands XLA a batch with many
DUPLICATE (metric, bucket) indices — a Zipf workload concentrates most of
a 4M-sample batch on a few hot cells, and duplicate-index scatter-adds
serialize on TPU.  This path restructures the batch so every scattered
index is unique:

  1. fuse compress -> combined cell key  (id * num_buckets + bucket)
  2. static-shape dedup via jnp.unique(size=N) — one XLA sort plus
     run-length counts, padding confined to the tail
  3. one scatter-add of (unique cell, count) pairs with
     unique_indices=True, indices_are_sorted=True — the conflict-free
     form XLA can fully vectorize (dropped tail entries park at distinct
     ascending out-of-bounds rows so both promises hold literally)

Bit-identical to the scatter/matmul paths (tests/test_fast_paths.py);
ordering is irrelevant because bucket histograms are commutative.  The
combined key needs num_metrics * num_buckets < 2^31 - 2 (10k metrics x
8193 buckets ~= 8.2e7, three orders inside the bound; construction
validates it).

Selectable as TPUAggregator(ingest_path="sort"); "auto" prefers it at
high metric cardinality on TPU per the measured dispatch table
(ops/dispatch.py).  sortscan_ingest_batch below is a leaner second
formulation of the same idea (one sort + one scan + one conflict-free
scatter instead of jnp.unique's generic bookkeeping), selectable as
ingest_path="sortscan" and measured side by side in
benchmarks/device_paths.py.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from loghisto_tpu.config import PRECISION
from loghisto_tpu.ops.ingest import bucket_indices


def _cell_keys(acc, ids, values, bucket_limit, precision):
    """Combined int32 cell keys (id * num_buckets + bucket) for one
    batch, shared by both dedup formulations.  Invalid ids (negative or
    >= num_metrics) get the one-past-the-end key — the maximum, so they
    sort last and scatter-drop.  Returns (key, invalid_key)."""
    num_metrics, num_buckets = acc.shape
    bidx = bucket_indices(values, bucket_limit, precision)
    invalid_key = jnp.int32(num_metrics * num_buckets)
    valid = (ids >= 0) & (ids < num_metrics)
    return jnp.where(valid, ids * num_buckets + bidx, invalid_key), invalid_key


def _park_rows(n: int) -> jnp.ndarray:
    """Distinct out-of-bounds rows for dropped scatter entries (real rows
    stay below 2^30 because MAX_FLAT_CELLS bounds rows * buckets)."""
    return jnp.int32(2**30) + jnp.arange(n, dtype=jnp.int32)


def sort_ingest_batch(
    acc: jnp.ndarray,
    ids: jnp.ndarray,
    values: jnp.ndarray,
    bucket_limit: int,
    precision: int = PRECISION,
) -> jnp.ndarray:
    """Pure function: accumulate one (ids, values) batch into acc via the
    sort-dedup formulation."""
    num_buckets = acc.shape[1]
    n = ids.shape[0]
    key, invalid_key = _cell_keys(acc, ids, values, bucket_limit, precision)
    # static-shape dedup: unique keys ascending, padding (fill =
    # invalid_key, the maximum) confined to the TAIL, counts 0 for pads
    ukeys, counts = jnp.unique(
        key, return_counts=True, size=n, fill_value=invalid_key
    )
    row = ukeys // num_buckets
    col = jnp.where(ukeys == invalid_key, 0, ukeys % num_buckets)
    # park every dropped entry at a DISTINCT ascending out-of-bounds row,
    # so both scatter promises hold literally: indices stay sorted (the
    # park rows exceed every real row and only occupy the tail) and
    # unique (each park row is distinct)
    row = jnp.where(ukeys == invalid_key, _park_rows(n), row)
    return acc.at[row, col].add(
        counts.astype(acc.dtype),
        mode="drop",
        unique_indices=True,
        indices_are_sorted=True,
    )


# one shy of the sort path's invalid_key sentinel, which must itself fit
MAX_FLAT_CELLS = 2**31 - 2


def validate_flat_cell_shape(
    num_metrics: int, num_buckets: int, path: str = "sort"
) -> None:
    """Raise if a combined int32 cell key (id * num_buckets + bucket)
    cannot represent this shape — shared bound for every kernel that
    flattens (row, bucket) into one int32 (sort's dedup key, matmul's
    flat cell index).  Called at CONSTRUCTION/selection — a raise inside
    the traced ingest would be swallowed by flush's shed-don't-block
    failure handling and look like a permanently down device instead of
    a config error."""
    if num_metrics * num_buckets >= MAX_FLAT_CELLS:
        raise ValueError(
            f"{path} ingest needs num_metrics * num_buckets < 2^31 - 2 "
            f"for its combined int32 cell key; got "
            f"{num_metrics} x {num_buckets}"
        )




def sortscan_ingest_batch(
    acc: jnp.ndarray,
    ids: jnp.ndarray,
    values: jnp.ndarray,
    bucket_limit: int,
    precision: int = PRECISION,
) -> jnp.ndarray:
    """Leaner sort-dedup: one sort + one associative scan + one
    conflict-free scatter.

    ``jnp.unique(size=n, return_counts=True)`` (the "sort" path) lowers
    to one sort plus ~40 scatter and ~16 gather ops for its general
    inverse/index bookkeeping, none of which this kernel needs: after
    sorting the combined cell keys, segment STARTS are adjacent-diff
    flags, and each start's count is the distance to the next start —
    computable with a single reverse min-scan over start positions.  The
    scatter then writes (row, col, count) at the starts only; non-starts
    and invalid keys park at distinct out-of-bounds rows, so
    unique_indices holds literally.  Unlike sort_ingest_batch the park
    rows interleave with real rows (starts sit wherever the sorted keys
    put them), so indices_are_sorted must NOT be promised here — the
    conflict-free guarantee is the one that unlocks vectorization.
    Bit-identical to every other ingest kernel."""
    num_buckets = acc.shape[1]
    n = ids.shape[0]
    key, invalid_key = _cell_keys(acc, ids, values, bucket_limit, precision)

    sk = jnp.sort(key)  # invalid keys are the maximum: they sort last
    idx = jnp.arange(n, dtype=jnp.int32)
    flags = jnp.concatenate(
        [jnp.ones((1,), jnp.bool_), sk[1:] != sk[:-1]]
    )
    # next segment start strictly after i: reverse running-min of start
    # positions, shifted left by one (position n = "no further start")
    starts = jnp.where(flags, idx, jnp.int32(n))
    nxt = jax.lax.associative_scan(jnp.minimum, starts, reverse=True)
    nxt_after = jnp.concatenate([nxt[1:], jnp.full((1,), n, jnp.int32)])
    live = flags & (sk != invalid_key)
    cnt = jnp.where(live, nxt_after - idx, 0)
    row = jnp.where(live, sk // num_buckets, _park_rows(n))
    col = jnp.where(live, sk % num_buckets, 0)
    return acc.at[row, col].add(
        cnt.astype(acc.dtype),
        mode="drop",
        unique_indices=True,
    )


def make_sortscan_ingest_fn(bucket_limit: int, precision: int = PRECISION):
    """Jitted, donated-accumulator sortscan ingest with the standard
    f(acc, ids, values) -> new_acc contract."""

    @functools.partial(jax.jit, donate_argnums=0)
    def ingest(acc, ids, values):
        validate_flat_cell_shape(acc.shape[0], acc.shape[1], "sortscan")
        return sortscan_ingest_batch(
            acc, ids, values, bucket_limit, precision
        )

    return ingest


def make_sort_ingest_fn(bucket_limit: int, precision: int = PRECISION):
    """A jitted, donated-accumulator sort-dedup ingest step with the same
    f(acc, ids, values) -> new_acc contract as make_ingest_fn."""

    @functools.partial(jax.jit, donate_argnums=0)
    def ingest(acc, ids, values):
        validate_flat_cell_shape(acc.shape[0], acc.shape[1], "sort")
        return sort_ingest_batch(acc, ids, values, bucket_limit, precision)

    return ingest
