"""Sort-deduplicated ingest: an alternative device accumulation kernel
built for TPU scatter semantics.

The plain scatter path (ops/ingest.py) hands XLA a batch with many
DUPLICATE (metric, bucket) indices — a Zipf workload concentrates most of
a 4M-sample batch on a few hot cells, and duplicate-index scatter-adds
serialize on TPU.  This path restructures the batch so every scattered
index is unique:

  1. fuse compress -> combined cell key  (id * num_buckets + bucket)
  2. static-shape dedup via jnp.unique(size=N) — one XLA sort plus
     run-length counts, padding confined to the tail
  3. one scatter-add of (unique cell, count) pairs with
     unique_indices=True, indices_are_sorted=True — the conflict-free
     form XLA can fully vectorize (dropped tail entries park at distinct
     ascending out-of-bounds rows so both promises hold literally)

Bit-identical to the scatter/matmul paths (tests/test_fast_paths.py);
ordering is irrelevant because bucket histograms are commutative.  The
combined key needs num_metrics * num_buckets < 2^31 - 2 (10k metrics x
8193 buckets ~= 8.2e7, three orders inside the bound; construction
validates it).

Selectable as TPUAggregator(ingest_path="sort"); "auto" will prefer it
once the hardware table (benchmarks/device_paths.py) proves it.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from loghisto_tpu.config import PRECISION
from loghisto_tpu.ops.ingest import bucket_indices


def sort_ingest_batch(
    acc: jnp.ndarray,
    ids: jnp.ndarray,
    values: jnp.ndarray,
    bucket_limit: int,
    precision: int = PRECISION,
) -> jnp.ndarray:
    """Pure function: accumulate one (ids, values) batch into acc via the
    sort-dedup formulation."""
    num_metrics, num_buckets = acc.shape
    n = ids.shape[0]
    bidx = bucket_indices(values, bucket_limit, precision)
    # combined cell key; invalid ids (negative or >= num_metrics) get the
    # one-past-the-end key so they sort last and scatter-drop
    invalid_key = jnp.int32(num_metrics * num_buckets)
    valid = (ids >= 0) & (ids < num_metrics)
    key = jnp.where(valid, ids * num_buckets + bidx, invalid_key)
    # static-shape dedup: unique keys ascending, padding (fill =
    # invalid_key, the maximum) confined to the TAIL, counts 0 for pads
    ukeys, counts = jnp.unique(
        key, return_counts=True, size=n, fill_value=invalid_key
    )
    row = ukeys // num_buckets
    col = jnp.where(ukeys == invalid_key, 0, ukeys % num_buckets)
    # park every dropped entry at a DISTINCT ascending out-of-bounds row,
    # so both scatter promises hold literally: indices stay sorted (the
    # park rows exceed every real row and only occupy the tail) and
    # unique (each park row is distinct)
    park = jnp.int32(2**30) + jnp.arange(n, dtype=jnp.int32)
    row = jnp.where(ukeys == invalid_key, park, row)
    return acc.at[row, col].add(
        counts.astype(acc.dtype),
        mode="drop",
        unique_indices=True,
        indices_are_sorted=True,
    )


# one shy of the sort path's invalid_key sentinel, which must itself fit
MAX_FLAT_CELLS = 2**31 - 2


def validate_flat_cell_shape(
    num_metrics: int, num_buckets: int, path: str = "sort"
) -> None:
    """Raise if a combined int32 cell key (id * num_buckets + bucket)
    cannot represent this shape — shared bound for every kernel that
    flattens (row, bucket) into one int32 (sort's dedup key, matmul's
    flat cell index).  Called at CONSTRUCTION/selection — a raise inside
    the traced ingest would be swallowed by flush's shed-don't-block
    failure handling and look like a permanently down device instead of
    a config error."""
    if num_metrics * num_buckets >= MAX_FLAT_CELLS:
        raise ValueError(
            f"{path} ingest needs num_metrics * num_buckets < 2^31 - 2 "
            f"for its combined int32 cell key; got "
            f"{num_metrics} x {num_buckets}"
        )




def make_sort_ingest_fn(bucket_limit: int, precision: int = PRECISION):
    """A jitted, donated-accumulator sort-dedup ingest step with the same
    f(acc, ids, values) -> new_acc contract as make_ingest_fn."""

    @functools.partial(jax.jit, donate_argnums=0)
    def ingest(acc, ids, values):
        validate_flat_cell_shape(acc.shape[0], acc.shape[1], "sort")
        return sort_ingest_batch(acc, ids, values, bucket_limit, precision)

    return ingest
