"""Sparse-delta device program (transport="sparse"): donated scatter-add
over packed int32 [n, 3] (id, codec_bucket, count) triples.

The raw transport ships every sample and pays a per-sample device
compress; the sparse transport folds the batch on host first (_native
``fold_packed`` — parallel C tier or pure NumPy) and ships only the
unique cells, so the device program is a WEIGHTED scatter over O(cells)
rows with no codec work at all.  For Zipf-shaped load the cell count is
a small fraction of the sample count, which moves both the wire bytes
and the device FLOPs from O(samples) to O(unique cells).

Two tiers, bit-identical by construction (tests/test_ingest_transport.py
pins the parity):

  * "jnp"    — XLA scatter-add, identical math to ops.ingest's
    make_packed_ingest_fn; works on every platform and is what "auto"
    dispatches today.
  * "pallas" — a TPU Pallas kernel that keeps the accumulator in HBM and
    round-trips one bucket row per cell through a VMEM scratch via
    explicit DMA.  Exact (integer adds, serial grid), but NOT yet
    hardware-ranked against the XLA scatter — it exists so a capture can
    rank it (benchmarks/device_paths.py pattern); "auto" will not pick
    it until a committed threshold table says so (ops/dispatch.py
    SPARSE_KERNEL).  Off-TPU it runs in interpret mode so CI exercises
    the same code path.

Padding rows use id -1, which ``sanitize_ids`` (jnp tier) or the
explicit bounds guard (Pallas tier) drops; callers route counts >= 2^30
to the exact host spill first, so the int32 count column cannot
overflow (the _native drain's split rule caps every wire row below
that).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from loghisto_tpu.ops.backend import default_interpret
from loghisto_tpu.ops.ingest import sanitize_ids

# Triples per Pallas grid step: small enough that the SMEM operand
# blocks stay trivial, large enough to amortize grid overhead.
TRIPLE_TILE = 256


def sparse_ingest_batch(
    acc: jnp.ndarray, packed: jnp.ndarray, bucket_limit: int
) -> jnp.ndarray:
    """Pure jnp tier: weighted scatter-add of packed triples into the
    dense accumulator (the math of ops.ingest.make_packed_ingest_fn)."""
    if packed.ndim != 2 or packed.shape[1] != 3:
        raise ValueError(
            f"packed must be [n, 3] (id, bucket, count); got {packed.shape}"
        )
    idx = jnp.clip(packed[:, 1], -bucket_limit, bucket_limit) + bucket_limit
    return acc.at[sanitize_ids(packed[:, 0]), idx].add(
        packed[:, 2], mode="drop"
    )


def _pallas_kernel(ids_ref, idx_ref, w_ref, acc_in_ref, acc_out_ref,
                   row_ref, sem_in, sem_out, *, num_metrics: int):
    """One grid step: apply TRIPLE_TILE cells to the HBM accumulator.

    Per cell: DMA the target bucket row HBM->VMEM, integer-add the
    weight at the (dynamic) dense column, DMA the row back.  The TPU
    grid is sequential and each DMA pair completes before the next cell
    starts, so duplicate rows within or across tiles accumulate exactly
    — no atomics needed.  acc_in/acc_out alias (input_output_aliases),
    so all traffic goes through acc_out_ref and the input ref is only
    the donation anchor."""
    del acc_in_ref

    def body(j, carry):
        mid = ids_ref[0, j]

        @pl.when((mid >= 0) & (mid < num_metrics))
        def _apply():
            load = pltpu.make_async_copy(
                acc_out_ref.at[pl.ds(mid, 1)], row_ref, sem_in
            )
            load.start()
            load.wait()
            col = idx_ref[0, j]
            row_ref[0, col] += w_ref[0, j]
            store = pltpu.make_async_copy(
                row_ref, acc_out_ref.at[pl.ds(mid, 1)], sem_out
            )
            store.start()
            store.wait()

        return carry

    jax.lax.fori_loop(0, ids_ref.shape[1], body, 0)


def pallas_sparse_ingest(
    acc: jnp.ndarray, packed: jnp.ndarray, bucket_limit: int
) -> jnp.ndarray:
    """Pallas tier: same contract as sparse_ingest_batch.  packed length
    is padded to TRIPLE_TILE inside (pad id -1 drops)."""
    if packed.ndim != 2 or packed.shape[1] != 3:
        raise ValueError(
            f"packed must be [n, 3] (id, bucket, count); got {packed.shape}"
        )
    n = packed.shape[0]
    g = max(1, (n + TRIPLE_TILE - 1) // TRIPLE_TILE)
    padded = g * TRIPLE_TILE
    if padded != n:
        pad = jnp.zeros((padded - n, 3), dtype=jnp.int32)
        pad = pad.at[:, 0].set(-1)
        packed = jnp.concatenate([packed, pad])
    ids = packed[:, 0].reshape(g, TRIPLE_TILE)
    idx = (
        jnp.clip(packed[:, 1], -bucket_limit, bucket_limit) + bucket_limit
    ).reshape(g, TRIPLE_TILE)
    weights = packed[:, 2].reshape(g, TRIPLE_TILE)
    num_metrics, num_buckets = acc.shape

    smem_spec = pl.BlockSpec(
        (1, TRIPLE_TILE), lambda i: (i, 0), memory_space=pltpu.SMEM
    )
    return pl.pallas_call(
        functools.partial(_pallas_kernel, num_metrics=num_metrics),
        grid=(g,),
        in_specs=[
            smem_spec,
            smem_spec,
            smem_spec,
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        out_shape=jax.ShapeDtypeStruct(acc.shape, acc.dtype),
        scratch_shapes=[
            pltpu.VMEM((1, num_buckets), jnp.int32),
            pltpu.SemaphoreType.DMA(()),
            pltpu.SemaphoreType.DMA(()),
        ],
        input_output_aliases={3: 0},
        interpret=default_interpret(),
    )(ids, idx, weights, acc)


def make_sparse_ingest_fn(bucket_limit: int, kernel: str = "auto"):
    """Jitted, donated-accumulator sparse merge step:
    ``f(acc, packed) -> acc`` with acc int32 [M, B] and packed int32
    [n, 3].  ``kernel`` picks the tier ("auto" follows the
    capture-overridable ops.dispatch.SPARSE_KERNEL switch)."""
    from loghisto_tpu.ops.dispatch import resolve_sparse_kernel

    kernel = resolve_sparse_kernel(kernel)
    step = (
        pallas_sparse_ingest if kernel == "pallas" else sparse_ingest_batch
    )

    @functools.partial(jax.jit, donate_argnums=0)
    def ingest(acc, packed):
        return step(acc, packed, bucket_limit)

    return ingest
