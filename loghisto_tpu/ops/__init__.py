"""TPU compute kernels: codec, dense ingest, statistics, sketches.

The codec names re-export eagerly (codec.py is jax-free at import time);
the stats names resolve lazily via PEP 562 so that federation emitter
processes can reach the frame/bucket codecs without importing jax.
"""

from loghisto_tpu.ops.codec import (
    FrameError,
    FrameTruncated,
    compress,
    compress_np,
    compress_scalar,
    decode_frame,
    decompress,
    decompress_np,
    decompress_scalar,
    encode_frame,
    iter_frames,
)

_STATS_NAMES = (
    "bucket_representatives",
    "dense_stats",
    "percentiles_sparse",
    "summarize_sparse",
)

__all__ = [
    "FrameError",
    "FrameTruncated",
    "compress",
    "compress_np",
    "compress_scalar",
    "decode_frame",
    "decompress",
    "decompress_np",
    "decompress_scalar",
    "encode_frame",
    "iter_frames",
    *_STATS_NAMES,
]


def __getattr__(name):
    if name in _STATS_NAMES:
        from loghisto_tpu.ops import stats

        return getattr(stats, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
