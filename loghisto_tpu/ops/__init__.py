"""TPU compute kernels: codec, dense ingest, statistics, sketches."""

from loghisto_tpu.ops.codec import (
    compress,
    compress_np,
    compress_scalar,
    decompress,
    decompress_np,
    decompress_scalar,
)
from loghisto_tpu.ops.stats import (
    bucket_representatives,
    dense_stats,
    percentiles_sparse,
    summarize_sparse,
)

__all__ = [
    "compress",
    "compress_np",
    "compress_scalar",
    "decompress",
    "decompress_np",
    "decompress_scalar",
    "bucket_representatives",
    "dense_stats",
    "percentiles_sparse",
    "summarize_sparse",
]
