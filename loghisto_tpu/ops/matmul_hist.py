"""MXU-path histogram accumulation: one-hot matmul instead of scatter.

Scatter-add with data-dependent indices is the natural lowering of
histogram accumulation but makes poor use of a systolic array.  For
*small metric counts* (the reference's headline PrintBenchmark config is a
single metric, readme.md:27) there is an MXU-shaped alternative:

    flat   = id * num_buckets + bucket            (flat cell index)
    hi, lo = flat // 128, flat % 128              (tile decomposition)
    counts[hi, lo] += sum_n onehot(hi_n)[:, None] * onehot(lo_n)[None, :]

i.e. the whole batch becomes ONE matmul ``onehot_hi^T @ onehot_lo`` of
shape [H, N] x [N, 128] with exact 0/1 bfloat16 inputs and float32
accumulation (exact for per-batch cell counts < 2^24).  At one metric and
8193 buckets this sustains ~2 samples/cycle on the MXU — far beyond the
scatter path — at the cost of N*H*128 MACs, so it only wins while
``num_metrics * num_buckets / 128`` (H) stays modest.  Dispatchers should
use it when ``num_metrics * num_buckets <= ~2**21`` and fall back to
scatter otherwise (the 10k-metric config stays on scatter).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from loghisto_tpu.config import PRECISION
from loghisto_tpu.ops.ingest import bucket_indices, sanitize_ids

LANES = 128


def _flat_cells(ids, values, num_buckets, bucket_limit, precision):
    bidx = bucket_indices(values, bucket_limit, precision)
    ids = sanitize_ids(ids)
    return ids * num_buckets + bidx


def ingest_batch_matmul(
    acc: jnp.ndarray,
    ids: jnp.ndarray,
    values: jnp.ndarray,
    bucket_limit: int,
    precision: int = PRECISION,
) -> jnp.ndarray:
    """Accumulate one (ids, values) batch into acc[M, B] via one-hot
    matmuls.  Semantically identical to ops.ingest.ingest_batch for
    in-range ids; out-of-range ids are dropped."""
    m, b = acc.shape
    n = values.shape[0]
    flat = _flat_cells(ids, values, b, bucket_limit, precision)
    total = m * b
    h = (total + LANES - 1) // LANES
    valid = flat < total  # sanitize_ids pushed bad ids far out of range
    hi = jnp.where(valid, flat // LANES, h)  # h = one-past-end: drops
    lo = jnp.where(valid, flat % LANES, 0)

    onehot_hi = jax.nn.one_hot(hi, h, dtype=jnp.bfloat16)  # [N, H]
    onehot_lo = jax.nn.one_hot(lo, LANES, dtype=jnp.bfloat16)  # [N, 128]
    counts = jax.lax.dot_general(
        onehot_hi, onehot_lo,
        dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [H, 128], exact integers below 2^24
    counts = counts.astype(jnp.int32).reshape(-1)[:total].reshape(m, b)
    return acc + counts


def make_matmul_ingest_fn(bucket_limit: int, precision: int = PRECISION):
    @functools.partial(jax.jit, donate_argnums=0)
    def ingest(acc, ids, values):
        return ingest_batch_matmul(acc, ids, values, bucket_limit, precision)

    return ingest
