"""MXU-path histogram accumulation: one-hot matmul instead of scatter.

Scatter-add with data-dependent indices is the natural lowering of
histogram accumulation but makes poor use of a systolic array.  For
*small metric counts* (the reference's headline PrintBenchmark config is a
single metric, readme.md:27) there is an MXU-shaped alternative:

    flat   = id * num_buckets + bucket            (flat cell index)
    hi, lo = flat // 128, flat % 128              (tile decomposition)
    counts[hi, lo] += sum_n onehot(hi_n)[:, None] * onehot(lo_n)[None, :]

i.e. the whole batch becomes ONE matmul ``onehot_hi^T @ onehot_lo`` of
shape [H, N] x [N, 128] with exact 0/1 bfloat16 inputs and float32
accumulation (exact for per-batch cell counts < 2^24).  At one metric and
8193 buckets this sustains ~2 samples/cycle on the MXU — far beyond the
scatter path — at the cost of N*H*128 MACs, so it only wins while
``num_metrics * num_buckets / 128`` (H) stays modest.  Dispatchers should
use it when ``num_metrics * num_buckets <= ~2**21`` and fall back to
scatter otherwise (the 10k-metric config stays on scatter).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from loghisto_tpu.config import PRECISION
from loghisto_tpu.ops.ingest import bucket_indices, sanitize_ids

LANES = 128


def _flat_cells(ids, values, num_buckets, bucket_limit, precision):
    bidx = bucket_indices(values, bucket_limit, precision)
    ids = sanitize_ids(ids)
    return ids * num_buckets + bidx


CHUNK = 4096  # samples per one-hot matmul; bounds the [CHUNK, H] one-hot


def ingest_batch_matmul(
    acc: jnp.ndarray,
    ids: jnp.ndarray,
    values: jnp.ndarray,
    bucket_limit: int,
    precision: int = PRECISION,
) -> jnp.ndarray:
    """Accumulate one (ids, values) batch into acc[M, B] via one-hot
    matmuls.  Semantically identical to ops.ingest.ingest_batch for
    in-range ids; out-of-range ids are dropped.

    The batch is processed in CHUNK-sized pieces under lax.scan so the
    materialized one-hots stay [CHUNK, H] regardless of N; the float32
    count accumulator is exact for per-batch cell counts < 2^24."""
    m, b = acc.shape
    n = values.shape[0]
    flat = _flat_cells(ids, values, b, bucket_limit, precision)
    total = m * b
    h = (total + LANES - 1) // LANES
    valid = flat < total  # sanitize_ids pushed bad ids far out of range
    hi = jnp.where(valid, flat // LANES, h)  # h = one-past-end: drops
    lo = jnp.where(valid, flat % LANES, 0)

    pad = (n + CHUNK - 1) // CHUNK * CHUNK - n
    if pad:
        hi = jnp.concatenate([hi, jnp.full(pad, h, dtype=hi.dtype)])
        lo = jnp.concatenate([lo, jnp.zeros(pad, dtype=lo.dtype)])
    g = hi.shape[0] // CHUNK

    def body(counts, chunk):
        chi, clo = chunk
        onehot_hi = jax.nn.one_hot(chi, h, dtype=jnp.bfloat16)  # [C, H]
        onehot_lo = jax.nn.one_hot(clo, LANES, dtype=jnp.bfloat16)
        partial = jax.lax.dot_general(
            onehot_hi, onehot_lo,
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [H, 128]
        return counts + partial, None

    counts = jnp.zeros((h, LANES), dtype=jnp.float32)
    counts, _ = jax.lax.scan(
        body, counts, (hi.reshape(g, CHUNK), lo.reshape(g, CHUNK))
    )
    counts = counts.astype(jnp.int32).reshape(-1)[:total].reshape(m, b)
    return acc + counts


def make_matmul_ingest_fn(bucket_limit: int, precision: int = PRECISION):
    @functools.partial(jax.jit, donate_argnums=0)
    def ingest(acc, ids, values):
        return ingest_batch_matmul(acc, ids, values, bucket_limit, precision)

    return ingest
