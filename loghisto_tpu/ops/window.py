"""Fused window-merge + statistics kernels for the timewheel retention
store (window/store.py).

The log-bucket representation makes sliding windows almost free: interval
histograms merge *exactly* by elementwise addition (the same property the
mesh psum rides), so "p99 over the last W intervals" is ONE masked
reduction over the ring axis of a dense ``[slots, num_metrics,
num_buckets]`` tensor followed by the standard CDF scan of ops/stats.py —
no re-ingestion, no per-interval host loop, and a cost that depends on
the ring capacity, not the window length (which is what makes query
latency sublinear — effectively flat — in window size).

Two merge tiers:

  * ``window_merge`` — jnp masked ring-sum.  Works everywhere, and under
    a ("stream", "metric") mesh a metric-row-sharded ring partitions the
    reduction row-wise with zero collectives (the ring axis is local).
  * ``window_merge_pallas`` — metric-tiled Pallas kernel: grid over
    (metric tiles, ring slots) with the output block resident in VMEM
    across the slot sweep, so HBM traffic is ring-in + merged-out once —
    the bandwidth floor.  Single-device, TPU-targeted; interpret mode
    elsewhere so CI runs the same code path.

``window_stats`` composes either merge with ops/stats.py ``dense_stats``
into one jittable program: query(window) == one device dispatch.

Mesh-sharded state (PR 8): ``window_snapshot`` additionally runs
SHARD-LOCAL inside the sharded fused commit's ``shard_map`` program
(ops/commit.py) — the masked ring-sum and CDF scan are row-independent,
so each metric shard emits its own slice of the commit-time snapshot
payloads with zero collectives beyond the one cell-delta psum.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from loghisto_tpu.config import PRECISION
from loghisto_tpu.ops.backend import default_interpret
from loghisto_tpu.ops.stats import dense_cdf, dense_stats

ROWS_TILE = 8  # int32 sublane tile


def window_merge(ring: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
    """Merge the masked ring slots into one dense histogram.

    ring: int32 [slots, M, B]; mask: bool/int32 [slots].  Returns
    int32 [M, B] = sum over slots where mask is set — exact (histogram
    merges are elementwise adds).  One reduction over the ring axis;
    XLA partitions it row-parallel when the ring is metric-sharded.
    """
    keep = mask.astype(jnp.bool_)[:, None, None]
    return jnp.sum(jnp.where(keep, ring, 0), axis=0, dtype=jnp.int32)


def _merge_kernel(mask_ref, ring_ref, out_ref):
    k = pl.program_id(1)

    @pl.when(k == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    @pl.when(mask_ref[k] != 0)
    def _accumulate():
        out_ref[:] += ring_ref[0]


def window_merge_pallas(
    ring: jnp.ndarray,
    mask: jnp.ndarray,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Pallas tier of window_merge: identical result, VMEM-resident
    output blocks.  The grid sweeps ring slots innermost per metric tile,
    so each [ROWS_TILE, B] output block is written to HBM exactly once
    however long the window is."""
    if interpret is None:
        interpret = default_interpret()
    slots, m, b = ring.shape
    m_pad = (m + ROWS_TILE - 1) // ROWS_TILE * ROWS_TILE
    if m_pad != m:
        ring = jnp.pad(ring, ((0, 0), (0, m_pad - m), (0, 0)))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(m_pad // ROWS_TILE, slots),
        in_specs=[
            # block last dim == the array dim (B is rarely 128-divisible:
            # 2*bucket_limit+1 is odd), which Mosaic accepts — see the
            # layout note in ops/pallas_kernels.py
            pl.BlockSpec((1, ROWS_TILE, b), lambda i, k, mk: (k, i, 0)),
        ],
        out_specs=pl.BlockSpec((ROWS_TILE, b), lambda i, k, mk: (i, 0)),
    )
    out = pl.pallas_call(
        _merge_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((m_pad, b), jnp.int32),
        interpret=interpret,
    )(mask.astype(jnp.int32), ring)
    return out[:m]


def resolve_merge_path(path: str, platform: str, mesh: bool) -> str:
    """Shared dispatch policy for the window merge: "auto" picks the
    Pallas tier only single-device on real TPU hardware (the same
    constraint as ingest dispatch — Pallas inside shard_map is off the
    table, and interpret mode off-TPU is strictly slower than the jnp
    reduction)."""
    if path not in ("auto", "jnp", "pallas"):
        raise ValueError(
            f"merge_path={path!r}: expected 'auto', 'jnp', or 'pallas'"
        )
    if path == "auto":
        return "pallas" if (platform == "tpu" and not mesh) else "jnp"
    if path == "pallas" and mesh:
        raise ValueError("merge_path='pallas' is single-device; use jnp "
                         "with a mesh")
    return path


def window_stats(
    ring: jnp.ndarray,
    mask: jnp.ndarray,
    ps: jnp.ndarray,
    bucket_limit: int,
    precision: int = PRECISION,
    merge_path: str = "jnp",
) -> dict[str, jnp.ndarray]:
    """Fused window query: masked ring merge + full CDF-scan statistics
    in one traceable program — counts [M], sums [M], percentiles [M, P]
    for every metric over the selected window."""
    if merge_path == "pallas":
        merged = window_merge_pallas(ring, mask)
    else:
        merged = window_merge(ring, mask)
    return dense_stats(merged, ps, bucket_limit, precision)


def make_window_stats_fn(
    bucket_limit: int,
    precision: int = PRECISION,
    merge_path: str = "jnp",
):
    """Jitted f(ring, mask, ps) -> stats, one executable per ring shape
    (one tier = one shape, so a wheel compiles one program per tier)."""
    return jax.jit(
        functools.partial(
            window_stats,
            bucket_limit=bucket_limit,
            precision=precision,
            merge_path=merge_path,
        )
    )


def window_snapshot(
    ring: jnp.ndarray,
    masks: jnp.ndarray,
    bucket_limit: int,
    precision: int = PRECISION,
    merge_path: str = "jnp",
) -> dict[str, jnp.ndarray]:
    """Commit-time snapshot payloads for a tier: merge each masked view
    of the ring and take its exact bucket CDF in one program.

    ring: int32 [slots, M, B]; masks: bool [V, slots] — one row per
    snapshot view (the full written span plus any pinned windows).
    Returns cdf int32 [V, M, B], counts int32 [V, M], sums f32 [V, M].

    Because cumsum is linear, the CDF of a merged window equals the sum
    of per-slot CDFs — merging first is just the cheaper order.  The
    per-view merge reuses the same window_merge the query path jits, so
    snapshot contents are bit-identical to a direct recompute over the
    identical mask (the parity contract tests/test_query_engine.py pins).
    """

    def one_view(mask):
        if merge_path == "pallas":
            merged = window_merge_pallas(ring, mask)
        else:
            merged = window_merge(ring, mask)
        return dense_cdf(merged, bucket_limit, precision)

    out = jax.vmap(one_view)(masks.astype(jnp.bool_))
    return out


def make_window_snapshot_fn(
    bucket_limit: int,
    precision: int = PRECISION,
    merge_path: str = "jnp",
):
    """Jitted f(ring, masks) -> snapshot payload dict.  One executable
    per (ring shape, view count); view counts only change when a new
    window is pinned, so steady state never retraces."""
    return jax.jit(
        functools.partial(
            window_snapshot,
            bucket_limit=bucket_limit,
            precision=precision,
            merge_path=merge_path,
        )
    )
