"""Fused interval commit: one donated-carry program for the aggregator
fold plus every retention tier's open-slot scatter.

The paper's core claim is that log-bucket histograms merge by elementwise
addition, so every device consumer of an interval is payable with ONE
pass over the interval's sparse bucket cells.  Before this module each
committed interval fanned out into ~5+ separate dispatches — the
aggregator bridge's weighted scatter (parallel/aggregator.py) plus one
``_scatter_cells_jit`` launch per TimeWheel tier (window/store.py), each
behind its own lock and each re-uploading the same host-built cell
arrays.  ``make_fused_commit_fn`` collapses that to a single jitted
program over a donated carry pytree ``(aggregator_acc, ring_0..N)``:

  * the cell arrays ``(ids, idx, weights)`` are uploaded once,
  * the aggregator fold and every tier's open-slot scatter (plus the
    slot clear on ring wrap) execute in the same XLA program,
  * per-tier slot indices and keep factors arrive as TRACED int32
    operands (the jnp analog of Pallas scalar prefetch), so tier
    rotation across intervals never recompiles — one executable serves
    every interval for the lifetime of the shapes.

``CellStagingRing`` is the async H2D front end: a depth-2
double-buffered set of pinned host pad arrays whose ``stage()`` issues
``jax.device_put`` and returns immediately, so interval N+1's cell
transfer overlaps interval N's commit dispatch (the same super-chunk
overlap design as the aggregator's raw flush path, extended to the
bridge).  Depth 2 gives exactly one in-flight commit of slack: a slot's
host buffers are rewritten only after the commit dispatched against the
OTHER slot has been enqueued, which is the contract the overlap needs.

The orchestration (locks, spill policy, tier metadata) lives in
``loghisto_tpu.commit.IntervalCommitter``; this module stays pure
jax/numpy so it is importable and testable without the runtime classes.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from loghisto_tpu.config import PRECISION
from loghisto_tpu.ops.ingest import sanitize_ids
from loghisto_tpu.ops.paged_store import paged_scatter_batch
from loghisto_tpu.ops.stats import dense_cdf
from loghisto_tpu.ops.window import window_snapshot
from loghisto_tpu.parallel.mesh import METRIC_AXIS, STREAM_AXIS, shard_map

# Fixed commit launch width, matching the aggregator bridge's merge
# chunk: one compiled executable serves every interval; a typical
# interval is one launch, a 10k-metric worst case a handful.
COMMIT_CHUNK = 1 << 16

# Drop sentinel for pad (and shed) cells: far out of every row range, so
# each scatter's mode="drop" sheds it — same design as sanitize_ids and
# the wheel's _DROP_ID.
DROP_ID = np.int32(2**30)


@functools.lru_cache(maxsize=None)
def make_fused_commit_fn(
    num_tiers: int,
    track_activity: bool = False,
    track_baseline: bool = False,
):
    """Build the fused commit program for ``num_tiers`` retention tiers.
    Cached per (tier count, activity flag, baseline flag): the jitted
    program is shape-polymorphic, so every committer with the same
    signature shares one jit object (and its per-shape executable
    cache) instead of recompiling.

    Returns ``commit(acc, rings, slots, keeps, ids, idx, weights) ->
    (acc, rings)`` where

      acc     int32 [M, B]            — aggregator accumulator (donated)
      rings   tuple of int32 [S_t, M_t, B] — tier rings (donated)
      slots   int32 [T]               — each tier's open slot (traced,
                                        so rotation never recompiles)
      keeps   int32 [T]               — 0 to clear the open slot first
                                        (ring wrap), 1 to keep it
      ids     int32 [N]               — metric rows; DROP_ID pads/sheds
      idx     int32 [N]               — dense bucket column in [0, B)
      weights int32 [N]               — per-cell counts (0 on pads)

    All consumers add the SAME cells: the aggregator fold is
    ``acc[ids, idx] += weights`` and each tier's open-slot scatter is
    ``ring[slot, ids, idx] += weights`` after multiplying the slot by
    its keep factor (x1 = no-op, x0 = the ring-wrap clear, fused into
    the same program instead of a separate ``_open_slot_jit`` launch).
    Integer scatter-adds are order-independent, so the result is
    bit-identical to the fan-out path (tests/test_commit.py pins this).

    Out-of-range rows drop: the accumulator may have grown past a ring's
    row count (registry growth), in which case those cells land in the
    accumulator and fall off every ring — the same semantics the
    separate paths had.

    With ``track_activity`` the signature gains a donated int32 [M]
    ``last_active`` carry and a traced int32 ``epoch`` — inserted after
    ``rings`` and after ``weights`` respectively — and the program
    additionally stamps ``last_active[ids] = max(., epoch)`` over the
    interval's touched rows.  Same cells, same dispatch: the lifecycle
    subsystem's activity vector costs zero extra launches, the
    identical fusion economics as the snapshot variant's commit-time
    CDFs.

    With ``track_baseline`` the signature further gains a donated int32
    [M, B] ``ihist`` carry (after ``last_active``) and a trailing
    traced int32 ``ifirst``: the program folds the SAME cells into the
    interval histogram after multiplying it by ``ifirst`` (0 on an
    interval's first chunk — clearing the previous interval — 1 on
    later chunks).  The completed ``ihist`` feeds the drift engine's
    EWMA baseline update in the final-chunk snapshot variant; like the
    activity stamp, it rides the commit dispatch for free.

    Full ordering with both flags:
    ``commit(acc, rings, last_active, ihist, slots, keeps, ids, idx,
    weights, epoch, ifirst) -> (acc, rings, last_active, ihist)``.
    """
    donate = tuple(range(2 + int(track_activity) + int(track_baseline)))

    @functools.partial(jax.jit, donate_argnums=donate)
    def commit(*args):
        it = iter(args)
        acc = next(it)
        rings = next(it)
        last_active = next(it) if track_activity else None
        ihist = next(it) if track_baseline else None
        slots = next(it)
        keeps = next(it)
        ids = next(it)
        idx = next(it)
        weights = next(it)
        epoch = next(it) if track_activity else None
        ifirst = next(it) if track_baseline else None

        acc = acc.at[ids, idx].add(weights, mode="drop")
        new_rings = []
        for t in range(num_tiers):
            ring = rings[t]
            ring = ring.at[slots[t]].multiply(keeps[t], mode="drop")
            ring = ring.at[slots[t], ids, idx].add(weights, mode="drop")
            new_rings.append(ring)
        out = [acc, tuple(new_rings)]
        if track_activity:
            out.append(last_active.at[ids].max(epoch, mode="drop"))
        if track_baseline:
            ihist = ihist * ifirst
            out.append(ihist.at[ids, idx].add(weights, mode="drop"))
        return tuple(out)

    return commit


@functools.lru_cache(maxsize=None)
def make_fused_commit_snapshot_fn(
    num_tiers: int,
    bucket_limit: int,
    precision: int = PRECISION,
    merge_path: str = "jnp",
    track_activity: bool = False,
    track_baseline: bool = False,
):
    """The fused commit program's FINAL-chunk variant: same donated-carry
    fold as ``make_fused_commit_fn`` plus, in the SAME dispatch, the
    query engine's snapshot emission — per tier, the CDF/counts/sums of
    every materialized window view over the post-commit ring, and the
    aggregator accumulator's own CDF payload.

    Extra operand ``masks``: a tuple of bool ``[V, S_t]`` arrays, one per
    tier — the post-interval trailing-window slot masks (full span first,
    then pinned windows), computed host-side by simulating the slot
    close-out BEFORE dispatch.  Masks are traced, so slot rotation never
    recompiles; only a changed view count V (a new pinned window — rare)
    retraces.

    Returns ``(acc, rings, tier_payloads, acc_payload)`` where payload
    dicts carry cdf/counts/sums as in ``ops.window.window_snapshot`` /
    ``ops.stats.dense_cdf``.  The payload outputs are fresh (never
    donated), which is what lets the store publish them as a lock-free
    immutable handle while later commits keep donating the carries.

    ``track_activity`` threads the lifecycle's donated ``last_active``
    carry and traced ``epoch`` through exactly as in
    ``make_fused_commit_fn`` — the final chunk of an interval then pays
    the scatter fold, every snapshot payload, AND the activity stamp in
    one dispatch.

    ``track_baseline`` threads the drift engine's carries: the donated
    int32 [M, B] ``ihist`` interval histogram (as in
    ``make_fused_commit_fn``), a donated ``banks = (prof f32 [K, M, B],
    wsum f32 [K, M])`` EWMA baseline-bank pytree, and trailing traced
    scalars ``ifirst, bank, decay, min_count``.  Because this is the
    interval's FINAL chunk, the completed interval histogram decays
    into baseline bank ``bank`` here (``ops.anomaly.ewma_bank_update``;
    rows under ``min_count`` skip the update) — the whole EWMA baseline
    maintenance rides the commit dispatch, zero extra launches.

    Full ordering with both flags:
    ``commit(acc, rings, last_active, ihist, banks, slots, keeps, ids,
    idx, weights, epoch, masks, ifirst, bank, decay, min_count) ->
    (acc, rings, last_active, ihist, banks, tier_payloads,
    acc_payload)``.
    """
    if track_baseline:
        # Deferred: ops.anomaly -> ops.lifecycle -> ops.commit cycle.
        from loghisto_tpu.ops.anomaly import ewma_bank_update
    donate = tuple(range(2 + int(track_activity) + 2 * int(track_baseline)))

    @functools.partial(jax.jit, donate_argnums=donate)
    def commit(*args):
        it = iter(args)
        acc = next(it)
        rings = next(it)
        last_active = next(it) if track_activity else None
        ihist = next(it) if track_baseline else None
        banks = next(it) if track_baseline else None
        slots = next(it)
        keeps = next(it)
        ids = next(it)
        idx = next(it)
        weights = next(it)
        epoch = next(it) if track_activity else None
        masks = next(it)
        if track_baseline:
            ifirst = next(it)
            bank = next(it)
            decay = next(it)
            min_count = next(it)

        acc = acc.at[ids, idx].add(weights, mode="drop")
        new_rings = []
        payloads = []
        for t in range(num_tiers):
            ring = rings[t]
            ring = ring.at[slots[t]].multiply(keeps[t], mode="drop")
            ring = ring.at[slots[t], ids, idx].add(weights, mode="drop")
            new_rings.append(ring)
            payloads.append(
                window_snapshot(ring, masks[t], bucket_limit, precision,
                                merge_path)
            )
        out = [acc, tuple(new_rings)]
        if track_activity:
            out.append(last_active.at[ids].max(epoch, mode="drop"))
        if track_baseline:
            ihist = ihist * ifirst
            ihist = ihist.at[ids, idx].add(weights, mode="drop")
            out.append(ihist)
            out.append(ewma_bank_update(banks, ihist, bank, decay,
                                        min_count))
        acc_payload = dense_cdf(acc, bucket_limit, precision)
        out.extend((tuple(payloads), acc_payload))
        return tuple(out)

    return commit


def _shard_local_deltas(acc, rings, ids, idx, weights, track_activity):
    """Shard-local body shared by the sharded commit factories: scatter
    THIS stream-shard's cell slice into dense per-shard deltas, then run
    ONE ``psum`` over the stream axis so every downstream consumer
    (accumulator fold, tier scatters, activity stamp, interval
    histogram) works shard-local on the merged interval delta.

    Rows are translated to shard-local coordinates with the aggregator's
    proven idiom (parallel/aggregator.py): ``ids - shard * local_rows``,
    sanitized BEFORE the drop-mode scatter because JAX wraps negative
    indices ahead of the bounds check.  Cells owned by other shards land
    out of the local range and drop — exactly the single-device
    ``mode="drop"`` semantics, applied per shard.

    When a ring's row count differs from the accumulator's (registry
    growth past the wheel's fixed rows), shard k of the ring covers
    DIFFERENT global rows than shard k of the accumulator, so a second
    delta is built at the ring width; all deltas (and the activity
    touch-marker vector) merge in a single ``psum`` call, keeping the
    collective count at one per dispatch.

    Returns ``(acc_delta, {ring_rows: ring_delta}, touched_or_None)``.
    """
    shard = jax.lax.axis_index(METRIC_AXIS)
    acc_rows = acc.shape[0]
    acc_ids = sanitize_ids(ids - shard * acc_rows)
    parts = {
        "acc": jnp.zeros_like(acc).at[acc_ids, idx].add(weights,
                                                        mode="drop")
    }
    ring_rows = sorted({r.shape[1] for r in rings} - {acc_rows})
    for rows in ring_rows:
        rids = sanitize_ids(ids - shard * rows)
        parts[f"ring{rows}"] = (
            jnp.zeros((rows, acc.shape[1]), acc.dtype)
            .at[rids, idx].add(weights, mode="drop")
        )
    if track_activity:
        # the single-device path stamps every in-range id, even at
        # weight 0, so "delta != 0" is NOT a faithful activity signal;
        # a psum'd touch-marker vector is exactly equivalent
        parts["touched"] = (
            jnp.zeros((acc_rows,), jnp.int32)
            .at[acc_ids].max(1, mode="drop")
        )
    parts = jax.lax.psum(parts, STREAM_AXIS)
    return (
        parts["acc"],
        {rows: parts[f"ring{rows}"] for rows in ring_rows},
        parts.get("touched"),
    )


def _sharded_commit_specs(track_activity, track_baseline):
    """(carry in/out specs, carry count) shared by both sharded
    factories — the donated-carry prefix of the operand list."""
    specs = [P(METRIC_AXIS, None), P(None, METRIC_AXIS, None)]
    if track_activity:
        specs.append(P(METRIC_AXIS))
    if track_baseline:
        specs.append(P(METRIC_AXIS, None))
    return specs


@functools.lru_cache(maxsize=None)
def make_sharded_fused_commit_fn(
    mesh,
    num_tiers: int,
    track_activity: bool = False,
    track_baseline: bool = False,
):
    """``make_fused_commit_fn`` for metric-row-sharded carries under the
    ("stream", "metric") mesh: identical operand ordering, donation
    ranges, and results (integer scatter-adds and the int32 psum are
    order-independent, so the output is bit-identical to the
    single-device fused path — tests/test_mesh_commit.py pins this).

    The staged cell chunk arrives stream-sharded (``P(STREAM_AXIS)``,
    see ``CellStagingRing``): each device scatters its slice into dense
    shard-local deltas, ONE ``psum`` over the stream axis merges them,
    and the accumulator fold, every tier's open-slot scatter, the
    activity stamp, and the interval-histogram fold then execute
    shard-local on the ``P(METRIC_AXIS)``-rowed carries — one collective
    and one dispatch per chunk, preserving the <= 2-dispatches/interval
    budget.  Cached per (mesh, tiers, flags); shape-polymorphic like the
    single-device factory (per-shard row counts come from local operand
    shapes), so registry growth never needs a new cache entry."""
    donate = tuple(range(2 + int(track_activity) + int(track_baseline)))

    def commit(*args):
        it = iter(args)
        acc = next(it)
        rings = next(it)
        last_active = next(it) if track_activity else None
        ihist = next(it) if track_baseline else None
        slots = next(it)
        keeps = next(it)
        ids = next(it)
        idx = next(it)
        weights = next(it)
        epoch = next(it) if track_activity else None
        ifirst = next(it) if track_baseline else None

        delta, ring_deltas, touched = _shard_local_deltas(
            acc, rings, ids, idx, weights, track_activity
        )
        acc = acc + delta
        new_rings = []
        for t in range(num_tiers):
            ring = rings[t]
            rd = ring_deltas.get(ring.shape[1], delta)
            ring = ring.at[slots[t]].multiply(keeps[t], mode="drop")
            ring = ring.at[slots[t]].add(rd, mode="drop")
            new_rings.append(ring)
        out = [acc, tuple(new_rings)]
        if track_activity:
            out.append(jnp.where(touched > 0,
                                 jnp.maximum(last_active, epoch),
                                 last_active))
        if track_baseline:
            out.append(ihist * ifirst + delta)
        return tuple(out)

    carry_specs = _sharded_commit_specs(track_activity, track_baseline)
    in_specs = tuple(carry_specs) + (
        P(), P(), P(STREAM_AXIS), P(STREAM_AXIS), P(STREAM_AXIS),
    )
    if track_activity:
        in_specs += (P(),)      # epoch
    if track_baseline:
        in_specs += (P(),)      # ifirst
    return jax.jit(
        shard_map(
            commit, mesh=mesh,
            in_specs=in_specs, out_specs=tuple(carry_specs),
        ),
        donate_argnums=donate,
    )


@functools.lru_cache(maxsize=None)
def make_sharded_fused_commit_snapshot_fn(
    mesh,
    num_tiers: int,
    bucket_limit: int,
    precision: int = PRECISION,
    merge_path: str = "jnp",
    track_activity: bool = False,
    track_baseline: bool = False,
):
    """``make_fused_commit_snapshot_fn`` under the mesh: the sharded
    fold of ``make_sharded_fused_commit_fn`` plus, in the SAME dispatch,
    the EWMA baseline-bank decay and the snapshot emission — all
    shard-local after the single stream psum, because every emitted
    quantity (masked slot merge, row cumsum CDF, per-row sums matvec,
    per-row EWMA decay) is row-independent.  Payload outputs keep the
    metric-row sharding, so the published snapshot handle serves sparse
    per-row gathers from the owning shard without replicating the CDF
    tensors."""
    if track_baseline:
        # Deferred: ops.anomaly -> ops.lifecycle -> ops.commit cycle.
        from loghisto_tpu.ops.anomaly import ewma_bank_update
    donate = tuple(range(2 + int(track_activity) + 2 * int(track_baseline)))

    def commit(*args):
        it = iter(args)
        acc = next(it)
        rings = next(it)
        last_active = next(it) if track_activity else None
        ihist = next(it) if track_baseline else None
        banks = next(it) if track_baseline else None
        slots = next(it)
        keeps = next(it)
        ids = next(it)
        idx = next(it)
        weights = next(it)
        epoch = next(it) if track_activity else None
        masks = next(it)
        if track_baseline:
            ifirst = next(it)
            bank = next(it)
            decay = next(it)
            min_count = next(it)

        delta, ring_deltas, touched = _shard_local_deltas(
            acc, rings, ids, idx, weights, track_activity
        )
        acc = acc + delta
        new_rings = []
        payloads = []
        for t in range(num_tiers):
            ring = rings[t]
            rd = ring_deltas.get(ring.shape[1], delta)
            ring = ring.at[slots[t]].multiply(keeps[t], mode="drop")
            ring = ring.at[slots[t]].add(rd, mode="drop")
            new_rings.append(ring)
            payloads.append(
                window_snapshot(ring, masks[t], bucket_limit, precision,
                                merge_path)
            )
        out = [acc, tuple(new_rings)]
        if track_activity:
            out.append(jnp.where(touched > 0,
                                 jnp.maximum(last_active, epoch),
                                 last_active))
        if track_baseline:
            ihist = ihist * ifirst + delta
            out.append(ihist)
            out.append(ewma_bank_update(banks, ihist, bank, decay,
                                        min_count))
        acc_payload = dense_cdf(acc, bucket_limit, precision)
        out.extend((tuple(payloads), acc_payload))
        return tuple(out)

    carry_specs = _sharded_commit_specs(track_activity, track_baseline)
    bank_specs = (P(None, METRIC_AXIS, None), P(None, METRIC_AXIS))
    in_specs = tuple(carry_specs)
    if track_baseline:
        in_specs += (bank_specs,)
    in_specs += (P(), P(), P(STREAM_AXIS), P(STREAM_AXIS), P(STREAM_AXIS))
    if track_activity:
        in_specs += (P(),)      # epoch
    in_specs += (P(),)          # masks (prefix broadcast over the tuple)
    if track_baseline:
        in_specs += (P(), P(), P(), P())  # ifirst, bank, decay, min_count
    tier_payload_spec = {
        "cdf": P(None, METRIC_AXIS, None),
        "counts": P(None, METRIC_AXIS),
        "sums": P(None, METRIC_AXIS),
    }
    acc_payload_spec = {
        "cdf": P(METRIC_AXIS, None),
        "counts": P(METRIC_AXIS),
        "sums": P(METRIC_AXIS),
    }
    out_specs = tuple(carry_specs)
    if track_baseline:
        out_specs += (bank_specs,)
    out_specs += (
        tuple(dict(tier_payload_spec) for _ in range(num_tiers)),
        acc_payload_spec,
    )
    return jax.jit(
        shard_map(
            commit, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        ),
        donate_argnums=donate,
    )


@functools.lru_cache(maxsize=None)
def make_paged_fused_commit_fn(num_tiers: int, track_activity: bool = False):
    """The fused commit program for a PAGED aggregator (r18): the pool
    replaces the dense accumulator carry, and the interval's cells ride
    the dispatch twice — as dense ``(ids, idx, weights)`` for every
    tier's open-slot scatter (tier rings stay dense), and as
    host-translated ``(slot, offset, count)`` triples for the pool
    scatter (``paged_scatter_batch``; translation against the page
    table is a host decision, exactly as in ``PagedStore.commit``).

    Returns ``commit(pool, rings, [last_active], slots, keeps, ids,
    idx, weights, triples, [epoch]) -> (pool, rings, [last_active])``
    with the same donation, drop-sentinel, and traced-scalar contracts
    as ``make_fused_commit_fn`` — one dispatch still pays the
    aggregator fold, every tier, and the activity stamp."""
    donate = tuple(range(2 + int(track_activity)))

    @functools.partial(jax.jit, donate_argnums=donate)
    def commit(*args):
        it = iter(args)
        pool = next(it)
        rings = next(it)
        last_active = next(it) if track_activity else None
        slots = next(it)
        keeps = next(it)
        ids = next(it)
        idx = next(it)
        weights = next(it)
        triples = next(it)
        epoch = next(it) if track_activity else None

        pool = paged_scatter_batch(pool, triples)
        new_rings = []
        for t in range(num_tiers):
            ring = rings[t]
            ring = ring.at[slots[t]].multiply(keeps[t], mode="drop")
            ring = ring.at[slots[t], ids, idx].add(weights, mode="drop")
            new_rings.append(ring)
        out = [pool, tuple(new_rings)]
        if track_activity:
            out.append(last_active.at[ids].max(epoch, mode="drop"))
        return tuple(out)

    return commit


@functools.lru_cache(maxsize=None)
def make_paged_fused_commit_snapshot_fn(
    num_tiers: int,
    bucket_limit: int,
    precision: int = PRECISION,
    merge_path: str = "jnp",
    track_activity: bool = False,
):
    """Final-chunk variant of ``make_paged_fused_commit_fn``: the same
    fold plus every tier's window-snapshot emission.  Unlike the dense
    variant there is NO acc payload output — the pool's counts live
    behind per-row codecs, so accumulator stats are served by the paged
    query/stats engine (``PagedStore.query``), not a dense CDF ridden
    on the commit.  Ordering: ``commit(pool, rings, [last_active],
    slots, keeps, ids, idx, weights, triples, [epoch], masks) ->
    (pool, rings, [last_active], tier_payloads)``."""
    donate = tuple(range(2 + int(track_activity)))

    @functools.partial(jax.jit, donate_argnums=donate)
    def commit(*args):
        it = iter(args)
        pool = next(it)
        rings = next(it)
        last_active = next(it) if track_activity else None
        slots = next(it)
        keeps = next(it)
        ids = next(it)
        idx = next(it)
        weights = next(it)
        triples = next(it)
        epoch = next(it) if track_activity else None
        masks = next(it)

        pool = paged_scatter_batch(pool, triples)
        new_rings = []
        payloads = []
        for t in range(num_tiers):
            ring = rings[t]
            ring = ring.at[slots[t]].multiply(keeps[t], mode="drop")
            ring = ring.at[slots[t], ids, idx].add(weights, mode="drop")
            new_rings.append(ring)
            payloads.append(
                window_snapshot(ring, masks[t], bucket_limit, precision,
                                merge_path)
            )
        out = [pool, tuple(new_rings)]
        if track_activity:
            out.append(last_active.at[ids].max(epoch, mode="drop"))
        out.append(tuple(payloads))
        return tuple(out)

    return commit


def _paged_shard_local_deltas(
    pool, rings, last_active, ids, idx, weights, triples, shard_pages,
    track_activity,
):
    """Shard-local body shared by the sharded PAGED commit factories.
    The staged triples carry GLOBAL pool slots; re-basing by ``shard *
    shard_pages`` puts this shard's own arena at [1, shard_pages) (its
    local zero page at 0) and every other shard's triples out of range,
    so ``paged_scatter_batch``'s validity mask implements ownership for
    free.  Ring/activity deltas re-use the dense sharded idiom; ONE
    ``psum`` over the stream axis merges every part."""
    shard = jax.lax.axis_index(METRIC_AXIS)
    local = jnp.stack(
        [triples[:, 0] - shard * shard_pages, triples[:, 1], triples[:, 2]],
        axis=1,
    )
    parts = {"pool": paged_scatter_batch(jnp.zeros_like(pool), local)}
    for rows in sorted({r.shape[1] for r in rings}):
        rids = sanitize_ids(ids - shard * rows)
        parts[f"ring{rows}"] = (
            jnp.zeros((rows, rings[0].shape[2]), rings[0].dtype)
            .at[rids, idx].add(weights, mode="drop")
        )
    if track_activity:
        la_rows = last_active.shape[0]
        lids = sanitize_ids(ids - shard * la_rows)
        parts["touched"] = (
            jnp.zeros((la_rows,), jnp.int32).at[lids].max(1, mode="drop")
        )
    return jax.lax.psum(parts, STREAM_AXIS)


def _sharded_paged_commit_specs(track_activity):
    """Donated-carry prefix specs for the sharded paged factories:
    (pool arenas over metric, tier ring rows over metric, [activity
    rows over metric])."""
    specs = [P(METRIC_AXIS, None), P(None, METRIC_AXIS, None)]
    if track_activity:
        specs.append(P(METRIC_AXIS))
    return specs


@functools.lru_cache(maxsize=None)
def make_sharded_paged_fused_commit_fn(
    mesh, shard_pages: int, num_tiers: int, track_activity: bool = False
):
    """``make_paged_fused_commit_fn`` under the ("stream", "metric")
    mesh: identical operand ordering and results (int32 scatter-adds
    and the single stream psum are order-independent, so the committed
    pool is bit-identical to the single-device paged fused path).  The
    pool carry splits per metric-shard arena (``P(METRIC_AXIS, None)``,
    each shard's zero page at its arena base), staged cells and triples
    arrive stream-sharded, and everything downstream of the one psum is
    shard-local — one collective, one dispatch per chunk."""
    donate = tuple(range(2 + int(track_activity)))

    def commit(*args):
        it = iter(args)
        pool = next(it)
        rings = next(it)
        last_active = next(it) if track_activity else None
        slots = next(it)
        keeps = next(it)
        ids = next(it)
        idx = next(it)
        weights = next(it)
        triples = next(it)
        epoch = next(it) if track_activity else None

        parts = _paged_shard_local_deltas(
            pool, rings, last_active, ids, idx, weights, triples,
            shard_pages, track_activity,
        )
        pool = pool + parts["pool"]
        new_rings = []
        for t in range(num_tiers):
            ring = rings[t]
            rd = parts[f"ring{ring.shape[1]}"]
            ring = ring.at[slots[t]].multiply(keeps[t], mode="drop")
            ring = ring.at[slots[t]].add(rd, mode="drop")
            new_rings.append(ring)
        out = [pool, tuple(new_rings)]
        if track_activity:
            out.append(jnp.where(parts["touched"] > 0,
                                 jnp.maximum(last_active, epoch),
                                 last_active))
        return tuple(out)

    carry_specs = _sharded_paged_commit_specs(track_activity)
    in_specs = tuple(carry_specs) + (
        P(), P(), P(STREAM_AXIS), P(STREAM_AXIS), P(STREAM_AXIS),
        P(STREAM_AXIS, None),
    )
    if track_activity:
        in_specs += (P(),)      # epoch
    return jax.jit(
        shard_map(
            commit, mesh=mesh,
            in_specs=in_specs, out_specs=tuple(carry_specs),
        ),
        donate_argnums=donate,
    )


@functools.lru_cache(maxsize=None)
def make_sharded_paged_fused_commit_snapshot_fn(
    mesh,
    shard_pages: int,
    num_tiers: int,
    bucket_limit: int,
    precision: int = PRECISION,
    merge_path: str = "jnp",
    track_activity: bool = False,
):
    """``make_paged_fused_commit_snapshot_fn`` under the mesh: the
    sharded paged fold plus shard-local snapshot emission, payload
    outputs metric-row-sharded like the dense sharded variant."""
    donate = tuple(range(2 + int(track_activity)))

    def commit(*args):
        it = iter(args)
        pool = next(it)
        rings = next(it)
        last_active = next(it) if track_activity else None
        slots = next(it)
        keeps = next(it)
        ids = next(it)
        idx = next(it)
        weights = next(it)
        triples = next(it)
        epoch = next(it) if track_activity else None
        masks = next(it)

        parts = _paged_shard_local_deltas(
            pool, rings, last_active, ids, idx, weights, triples,
            shard_pages, track_activity,
        )
        pool = pool + parts["pool"]
        new_rings = []
        payloads = []
        for t in range(num_tiers):
            ring = rings[t]
            rd = parts[f"ring{ring.shape[1]}"]
            ring = ring.at[slots[t]].multiply(keeps[t], mode="drop")
            ring = ring.at[slots[t]].add(rd, mode="drop")
            new_rings.append(ring)
            payloads.append(
                window_snapshot(ring, masks[t], bucket_limit, precision,
                                merge_path)
            )
        out = [pool, tuple(new_rings)]
        if track_activity:
            out.append(jnp.where(parts["touched"] > 0,
                                 jnp.maximum(last_active, epoch),
                                 last_active))
        out.append(tuple(payloads))
        return tuple(out)

    carry_specs = _sharded_paged_commit_specs(track_activity)
    in_specs = tuple(carry_specs) + (
        P(), P(), P(STREAM_AXIS), P(STREAM_AXIS), P(STREAM_AXIS),
        P(STREAM_AXIS, None),
    )
    if track_activity:
        in_specs += (P(),)      # epoch
    in_specs += (P(),)          # masks (prefix broadcast over the tuple)
    tier_payload_spec = {
        "cdf": P(None, METRIC_AXIS, None),
        "counts": P(None, METRIC_AXIS),
        "sums": P(None, METRIC_AXIS),
    }
    out_specs = tuple(carry_specs) + (
        tuple(dict(tier_payload_spec) for _ in range(num_tiers)),
    )
    return jax.jit(
        shard_map(
            commit, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        ),
        donate_argnums=donate,
    )


class CellStagingRing:
    """Depth-D double-buffered H2D staging for interval cell arrays.

    Each slot owns reusable pinned host pad arrays ``(ids, idx,
    weights)`` of the fixed commit width; ``stage()`` writes one chunk
    into the next slot, pads the tail with drop sentinels, and issues an
    async ``jax.device_put`` — the transfer of the NEXT chunk/interval
    overlaps the commit dispatch of the previous one, because
    ``device_put`` and the jitted commit both return before the device
    work completes.

    Depth 2 (the default) is the minimum that makes the overlap safe:
    slot k's host buffers are only rewritten once a commit has been
    dispatched against slot k^1, so the copy engine is never racing the
    host writes of the transfer it is consuming.  Upload accounting
    (``uploads``, ``bytes_uploaded``) feeds the committer's
    H2D-bytes-per-interval gauge.
    """

    def __init__(self, depth: int = 2, width: int = COMMIT_CHUNK,
                 sharding=None):
        if depth < 2:
            raise ValueError("staging ring depth must be >= 2 (the "
                             "overlap contract needs one slot of slack)")
        self.depth = depth
        self.width = width
        # under a mesh the cell chunk uploads stream-sharded (each
        # device receives its slice of the staged pad arrays); the
        # sharded commit programs consume it as P(STREAM_AXIS) operands
        self.sharding = sharding
        self._slots = [
            (
                np.empty(width, dtype=np.int32),
                np.empty(width, dtype=np.int32),
                np.empty(width, dtype=np.int32),
            )
            for _ in range(depth)
        ]
        self._next = 0
        self.uploads = 0          # lifetime stage() calls
        self.bytes_uploaded = 0   # lifetime H2D bytes issued

    def stage(self, ids: np.ndarray, idx: np.ndarray, weights: np.ndarray):
        """Pad one cell chunk (len <= width) into the next host slot and
        start its async upload; returns the device arrays."""
        n = len(ids)
        if n > self.width:
            raise ValueError(f"chunk of {n} cells exceeds staging width "
                             f"{self.width}")
        hid, hidx, hw = self._slots[self._next]
        self._next = (self._next + 1) % self.depth
        hid[:n] = ids
        hid[n:] = DROP_ID
        hidx[:n] = idx
        hidx[n:] = 0
        hw[:n] = weights
        hw[n:] = 0
        dev = (
            jax.device_put((hid, hidx, hw), self.sharding)
            if self.sharding is not None
            else jax.device_put((hid, hidx, hw))
        )
        self.uploads += 1
        self.bytes_uploaded += 3 * self.width * 4
        return dev


class PagedTripleRing:
    """``CellStagingRing``'s twin for the paged committer's translated
    ``(slot, offset, count)`` triples: same depth/overlap contract,
    same fixed width (the commit chunk, so one executable serves every
    interval), pad sentinel slot -1 (``paged_scatter_batch`` drops it).
    Under a mesh the upload splits over the stream axis
    (``triple_sharding``), matching the sharded paged commit's
    ``P(STREAM_AXIS, None)`` operand spec."""

    def __init__(self, depth: int = 2, width: int = COMMIT_CHUNK,
                 sharding=None):
        if depth < 2:
            raise ValueError("staging ring depth must be >= 2 (the "
                             "overlap contract needs one slot of slack)")
        self.depth = depth
        self.width = width
        self.sharding = sharding
        self._slots = [
            np.empty((width, 3), dtype=np.int32) for _ in range(depth)
        ]
        self._next = 0
        self.uploads = 0
        self.bytes_uploaded = 0

    def stage(self, triples: np.ndarray):
        """Pad one translated triple chunk (len <= width) into the next
        host slot and start its async upload; returns the device array."""
        n = len(triples)
        if n > self.width:
            raise ValueError(f"chunk of {n} triples exceeds staging "
                             f"width {self.width}")
        buf = self._slots[self._next]
        self._next = (self._next + 1) % self.depth
        buf[:n] = triples
        buf[n:, 0] = -1
        buf[n:, 1] = 0
        buf[n:, 2] = 0
        if self.sharding is not None:
            # collective-free across real jax.distributed processes
            # (every process stages the identical translated chunk)
            from loghisto_tpu.parallel.multihost import global_put

            dev = global_put(buf, self.sharding)
        else:
            dev = jax.device_put(buf)
        self.uploads += 1
        self.bytes_uploaded += buf.nbytes
        return dev
