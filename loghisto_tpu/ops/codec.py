"""Log-bucket value<->index codec — the numeric core (layer L1).

Reference contract (metrics.go:316-332):

    compress(v)   = sign(v) * int16(precision * ln(1 + |v|) + 0.5)
    decompress(c) = sign(c) * (e^(|c| / precision) - 1)

With ``precision = 100`` the bucket boundary ratio is e^0.01 ~= 1.0100, so a
round trip stays within 1% of the true value for |v| >~ 1; below that the
worst-case relative error grows as ~0.005 * (1 + v) / v (reaching ~1.3% near
0.51 — the reference's "+/- 0.51" doc comment overstates the zone).
Documented failure modes (metrics.go:313-315): int16 overflow above ~1e142
and poor *relative* precision inside (-0.51, 0.51).  Zero maps to bucket 0 exactly;
negative values get mirrored negative buckets.

Where the reference compresses one scalar per call under a mutex, these are
vectorized: NumPy for the host tier, jnp for the device tier (the jnp version
is what the Pallas/XLA ingest kernels inline).  One deliberate deviation:
out-of-range buckets *saturate* to +/-32767 instead of wrapping the way Go's
int16 conversion does — saturation is strictly saner and the difference only
manifests beyond the documented ~1e142 failure point.
"""

from __future__ import annotations

import math

import jax.numpy as jnp
import numpy as np

from loghisto_tpu.config import INT16_BUCKET_LIMIT, PRECISION


def compress_scalar(value: float, precision: int = PRECISION) -> int:
    """Scalar compress with exact reference semantics (metrics.go:316-322).
    NaN pins to bucket 0, like every other tier."""
    if math.isnan(value):
        return 0
    if math.isinf(value):  # saturate like the vectorized tiers
        return -INT16_BUCKET_LIMIT if value < 0 else INT16_BUCKET_LIMIT
    i = int(precision * math.log1p(abs(value)) + 0.5)  # floor: arg is >= 0
    i = min(i, INT16_BUCKET_LIMIT)
    return -i if value < 0 else i


def decompress_scalar(bucket: int, precision: int = PRECISION) -> float:
    """Scalar decompress with exact reference semantics (metrics.go:326-332)."""
    f = math.exp(abs(bucket) / precision) - 1.0
    return -f if bucket < 0 else f


def compress_np(values: np.ndarray, precision: int = PRECISION) -> np.ndarray:
    """Vectorized compress -> int16 buckets (host tier).  NaN pins to
    bucket 0, like every other tier."""
    values = np.asarray(values, dtype=np.float64)
    values = np.where(np.isnan(values), 0.0, values)
    mag = np.floor(precision * np.log1p(np.abs(values)) + 0.5)
    mag = np.minimum(mag, INT16_BUCKET_LIMIT)
    return np.where(values < 0, -mag, mag).astype(np.int16)


def decompress_np(buckets: np.ndarray, precision: int = PRECISION) -> np.ndarray:
    """Vectorized decompress -> float64 bucket representatives (host tier)."""
    buckets = np.asarray(buckets)
    mag = np.exp(np.abs(buckets).astype(np.float64) / precision) - 1.0
    return np.where(buckets < 0, -mag, mag)


def compress(values: jnp.ndarray, precision: int = PRECISION) -> jnp.ndarray:
    """Vectorized compress on device (int32 buckets — int16 only matters for
    storage; the dense accumulator indexes with int32 anyway).  NaN pins
    to bucket 0, like every other tier."""
    values = jnp.asarray(values)
    values = jnp.where(jnp.isnan(values), 0.0, values)
    mag = jnp.floor(precision * jnp.log1p(jnp.abs(values)) + 0.5)
    mag = jnp.minimum(mag, float(INT16_BUCKET_LIMIT))
    return jnp.where(values < 0, -mag, mag).astype(jnp.int32)


def decompress(buckets: jnp.ndarray, precision: int = PRECISION) -> jnp.ndarray:
    """Vectorized decompress on device -> float32 bucket representatives."""
    buckets = jnp.asarray(buckets)
    mag = jnp.exp(jnp.abs(buckets).astype(jnp.float32) / precision) - 1.0
    return jnp.where(buckets < 0, -mag, mag)
